// Native host runtime for deeplearning4j_tpu.
//
// Role (SURVEY.md §2.1): the reference delegates its performance-critical
// paths to JVM-external native code (ND4J JNI -> BLAS).  In the TPU build
// the device math is XLA's, so the native seam moves to the HOST-bound hot
// paths that feed the chip: corpus tokenization/counting for vocab builds
// and skip-gram pair generation (the per-token Python loops dominate
// word2vec wall-clock otherwise).  Exposed as a C ABI for ctypes.
//
// Build: python -m deeplearning4j_tpu.native.build   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- tokenizer
// Tokenize text (sentences separated by '\n'), lowercasing and stripping
// non-alphanumeric bytes (ASCII fast path; multi-byte UTF-8 kept verbatim).
// Returns a malloc'd buffer "word\tcount\n..." and its length; caller frees
// via drt_free.
char* drt_count_tokens(const char* text, int64_t len, int64_t* out_len) {
    std::unordered_map<std::string, int64_t> counts;
    std::string cur;
    cur.reserve(32);
    for (int64_t i = 0; i <= len; ++i) {
        unsigned char c = (i < len) ? static_cast<unsigned char>(text[i]) : ' ';
        bool is_space = (c == ' ' || c == '\t' || c == '\n' || c == '\r' ||
                         c == '\v' || c == '\f');  // match Python \S+
        if (is_space) {
            if (!cur.empty()) {
                ++counts[cur];
                cur.clear();
            }
            continue;
        }
        // ASCII-only fast path (the ctypes binding routes any non-ASCII
        // corpus to the Python tokenizer so semantics never diverge):
        // keep [A-Za-z0-9_] lowercased — exactly Python's \w for ASCII.
        if (std::isalnum(c) || c == '_') {
            cur.push_back(static_cast<char>(std::tolower(c)));
        }
        // punctuation stripped
    }
    std::string out;
    out.reserve(counts.size() * 16);
    for (const auto& kv : counts) {
        out += kv.first;
        out += '\t';
        out += std::to_string(kv.second);
        out += '\n';
    }
    char* buf = static_cast<char*>(std::malloc(out.size()));
    std::memcpy(buf, out.data(), out.size());
    *out_len = static_cast<int64_t>(out.size());
    return buf;
}

void drt_free(void* p) { std::free(p); }

// ---------------------------------------------------------------- skipgram
// Generate skip-gram (center, context) pairs with per-position random
// window shrink (word2vec's `b = rand % window`).
// tokens: concatenated sentence word-indices; offsets: sentence starts
// (n_sentences+1 entries).  Returns number of pairs written; call first with
// centers=nullptr to get the required capacity.
int64_t drt_skipgram_pairs(const int32_t* tokens, const int64_t* offsets,
                           int64_t n_sentences, int32_t window, uint64_t seed,
                           int32_t* centers, int32_t* contexts,
                           int64_t capacity) {
    uint64_t state = seed ? seed : 0x9E3779B97F4A7C15ull;
    auto next_rand = [&state]() {
        state ^= state << 13;
        state ^= state >> 7;
        state ^= state << 17;
        return state;
    };
    int64_t n = 0;
    for (int64_t s = 0; s < n_sentences; ++s) {
        int64_t lo = offsets[s], hi = offsets[s + 1];
        int64_t len = hi - lo;
        for (int64_t pos = 0; pos < len; ++pos) {
            int32_t b = window > 0 ? static_cast<int32_t>(next_rand() % window) : 0;
            int32_t w = window - b;
            int64_t jlo = pos - w < 0 ? 0 : pos - w;
            int64_t jhi = pos + w + 1 > len ? len : pos + w + 1;
            for (int64_t j = jlo; j < jhi; ++j) {
                if (j == pos) continue;
                if (centers != nullptr) {
                    if (n >= capacity) return -1;  // caller under-allocated
                    centers[n] = tokens[lo + pos];
                    contexts[n] = tokens[lo + j];
                }
                ++n;
            }
        }
    }
    return n;
}

// ---------------------------------------------------------------- glove
// Window-weighted co-occurrence accumulation (GloVe's host-side hot loop:
// increment by 1/distance within the forward window, symmetrized).
// Returns a malloc'd packed buffer: int64 n, then n records of
// (int32 row, int32 col, float val).  Caller frees via drt_free.
char* drt_cooccurrence(const int32_t* tokens, const int64_t* offsets,
                       int64_t n_sentences, int32_t window,
                       int64_t* out_bytes) {
    std::unordered_map<int64_t, float> counts;
    for (int64_t s = 0; s < n_sentences; ++s) {
        int64_t lo = offsets[s], hi = offsets[s + 1];
        int64_t len = hi - lo;
        for (int64_t pos = 0; pos < len; ++pos) {
            int64_t jmax = pos + window + 1 < len ? pos + window + 1 : len;
            int32_t wi = tokens[lo + pos];
            for (int64_t j = pos + 1; j < jmax; ++j) {
                float inc = 1.0f / static_cast<float>(j - pos);
                int32_t wj = tokens[lo + j];
                counts[(static_cast<int64_t>(wi) << 32) |
                       static_cast<uint32_t>(wj)] += inc;
                counts[(static_cast<int64_t>(wj) << 32) |
                       static_cast<uint32_t>(wi)] += inc;
            }
        }
    }
    int64_t n = static_cast<int64_t>(counts.size());
    int64_t bytes = 8 + n * 12;
    char* buf = static_cast<char*>(std::malloc(bytes));
    std::memcpy(buf, &n, 8);
    char* p = buf + 8;
    for (const auto& kv : counts) {
        int32_t row = static_cast<int32_t>(kv.first >> 32);
        int32_t col = static_cast<int32_t>(kv.first & 0xFFFFFFFF);
        std::memcpy(p, &row, 4);
        std::memcpy(p + 4, &col, 4);
        std::memcpy(p + 8, &kv.second, 4);
        p += 12;
    }
    *out_bytes = bytes;
    return buf;
}

// ---------------------------------------------------------------- svmlight
// Parse svmlight text ("<label> <idx>:<val> ... # comment", 1-based indices)
// into dense row-major features + a label vector.  feats must be PRE-ZEROED
// (rows are sparse); text must be NUL-terminated (ctypes c_char_p is).
// Returns rows parsed; -1 on malformed input (caller falls back to the
// Python parser for exact error semantics); -2 when max_rows is too small.
// Indices beyond num_features are skipped and counted into *skipped (the
// Python caller turns that into its out-of-range warning).
int64_t drt_parse_svmlight(const char* text, int64_t len, int32_t nf,
                           float* feats, float* labels, int64_t max_rows,
                           int64_t* skipped) {
    int64_t row = 0;
    *skipped = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end) {
        const char* nl = static_cast<const char*>(std::memchr(p, '\n', end - p));
        const char* le = nl ? nl : end;
        const char* hash = static_cast<const char*>(std::memchr(p, '#', le - p));
        const char* ce = hash ? hash : le;     // parse stops at the comment
        const char* q = p;
        while (q < ce && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
        if (q >= ce) { p = le + 1; continue; } // blank / comment-only line
        if (row >= max_rows) return -2;
        char* nxt = nullptr;
        float lab = std::strtof(q, &nxt);      // stops at ' ', '#', '\n'
        if (nxt == q || nxt > ce) return -1;   // no leading label
        labels[row] = lab;
        float* frow = feats + row * static_cast<int64_t>(nf);
        q = nxt;
        while (q < ce) {
            while (q < ce && (*q == ' ' || *q == '\t' || *q == '\r')) ++q;
            if (q >= ce) break;
            char* c1 = nullptr;
            long idx = std::strtol(q, &c1, 10);
            if (c1 == q || c1 >= ce || *c1 != ':') return -1;
            // value must start right after ':' — strtof skips leading
            // whitespace (incl. '\n'), which would silently consume a
            // number from beyond the token/line; Python raises there
            const char* vs = c1 + 1;
            if (vs >= ce || *vs == ' ' || *vs == '\t' || *vs == '\r' ||
                *vs == '\n') return -1;
            char* c2 = nullptr;
            float v = std::strtof(vs, &c2);
            if (c2 == vs || c2 > ce) return -1;
            if (idx <= 0) return -1;           // svmlight text is 1-based
            if (idx <= nf) frow[idx - 1] = v;
            else ++*skipped;
            q = c2;
        }
        ++row;
        p = le + 1;
    }
    return row;
}

// ---------------------------------------------------------------- csv
// Parse a float CSV buffer into a dense row-major array. Returns rows
// written, or -1 on ragged rows. out must hold max_rows*n_cols floats.
int64_t drt_parse_csv_floats(const char* text, int64_t len, int32_t n_cols,
                             float* out, int64_t max_rows) {
    int64_t row = 0, col = 0;
    const char* p = text;
    const char* end = text + len;
    while (p < end && row < max_rows) {
        char c = *p;
        if (c == '\n') {  // newline handled BEFORE strtof (which would
                          // swallow it as leading whitespace)
            if (col != 0) {
                if (col != n_cols) return -1;
                col = 0;
                ++row;
            }
            ++p;
            continue;
        }
        if (c == ',' || c == ' ' || c == '\t' || c == '\r') {
            ++p;
            continue;
        }
        char* next = nullptr;
        float v = std::strtof(p, &next);
        if (next == p) return -1;  // non-numeric garbage
        if (col >= n_cols) return -1;
        out[row * n_cols + col] = v;
        ++col;
        p = next;
    }
    if (col == n_cols) ++row;
    else if (col != 0) return -1;
    return row;
}

}  // extern "C"
