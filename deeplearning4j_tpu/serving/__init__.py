"""Model serving: continuous-batching inference over trained checkpoints.

The inference half of the north star ("serves heavy traffic from millions
of users", ROADMAP.md): :class:`InferenceEngine` keeps a slot-pool KV
cache full of concurrently-decoding sequences, :class:`BatchScorer`
coalesces forward/score calls for ``MultiLayerNetwork``/zoo models,
:class:`RequestQueue` applies deadline-aware admission control with
bounded-queue backpressure, and :class:`ModelServer` exposes the whole
thing over stdlib HTTP with Prometheus metrics.  See DESIGN.md §13.
"""

from .batcher import (Completion, DeadlineExceeded, GenerateRequest,
                      PagePoolExhausted, PendingResult, QueueFull,
                      RequestQueue, ScoreRequest, ServingRejected)
from .client import ServingClient, ServingError
from .engine import BatchScorer, InferenceEngine, ServingConfig
from .paging import PagePool
from .server import ModelServer

__all__ = [
    "BatchScorer",
    "Completion",
    "DeadlineExceeded",
    "GenerateRequest",
    "InferenceEngine",
    "ModelServer",
    "PagePool",
    "PagePoolExhausted",
    "PendingResult",
    "QueueFull",
    "RequestQueue",
    "ScoreRequest",
    "ServingClient",
    "ServingConfig",
    "ServingError",
    "ServingRejected",
]
