"""K-means clustering.

Capability match of ``clustering/KMeansClustering.java:29,55-111``: k
centroids by Lloyd's algorithm.  TPU-first: the assignment+update sweep is
one jitted computation over the full (n, d) matrix — distance matrix on the
MXU — instead of the reference's per-point host loop.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import METRICS, trace


@partial(jax.jit, static_argnums=(2,))
def _lloyd_step(points, centroids, k):
    d2 = (jnp.sum(points ** 2, axis=1, keepdims=True)
          - 2.0 * points @ centroids.T
          + jnp.sum(centroids ** 2, axis=1)[None, :])
    assign = jnp.argmin(d2, axis=1)
    one_hot = jax.nn.one_hot(assign, k, dtype=points.dtype)
    counts = one_hot.sum(axis=0)
    sums = one_hot.T @ points
    new_centroids = jnp.where(counts[:, None] > 0,
                              sums / jnp.maximum(counts[:, None], 1.0),
                              centroids)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centroids, assign, inertia


class KMeansClustering:
    def __init__(self, k: int, max_iterations: int = 100, tol: float = 1e-6,
                 seed: int = 0):
        self.k = k
        self.max_iterations = max_iterations
        self.tol = tol
        self.seed = seed
        self.centroids: np.ndarray | None = None
        self.inertia: float = float("inf")

    def fit(self, points) -> "KMeansClustering":
        pts = jnp.asarray(np.asarray(points, np.float32))
        rng = np.random.default_rng(self.seed)
        init_idx = rng.choice(pts.shape[0], self.k, replace=False)
        centroids = pts[jnp.asarray(init_idx)]
        prev = float("inf")
        with trace.span("kmeans.fit", k=self.k, n=int(pts.shape[0])):
            for _ in range(self.max_iterations):
                centroids, _, inertia = _lloyd_step(pts, centroids, self.k)
                # the relative-tolerance early exit needs the host scalar
                # every sweep; Lloyd iterations are few and the sync IS the
                # convergence test  # graftlint: disable=HS01
                cur = float(inertia)
                METRICS.increment("kmeans.sweeps")
                if abs(prev - cur) < self.tol * max(1.0, abs(prev)):
                    break
                prev = cur
        # final assignment/inertia against the FINAL centroids (the loop's
        # values lag one update behind), so labels() agrees with predict()
        _, assign, inertia = _lloyd_step(pts, centroids, self.k)
        self.centroids = np.asarray(centroids)
        self.inertia = float(inertia)
        self._assign = np.asarray(assign)
        return self

    def predict(self, points) -> np.ndarray:
        pts = np.asarray(points, np.float32)
        d2 = ((pts[:, None, :] - self.centroids[None]) ** 2).sum(-1)
        return d2.argmin(axis=1)

    def labels(self) -> np.ndarray:
        return self._assign
