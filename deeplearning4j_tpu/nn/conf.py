"""Typed model configuration with JSON round-trip.

TPU-native equivalent of the reference's config layer
(``nn/conf/NeuralNetConfiguration.java:35-100`` hyperparameter bean with
fluent ``Builder`` at ``:903+``, per-layer overrides ``ConfOverride``/
``ListBuilder`` at ``:735-800``, and ``nn/conf/MultiLayerConfiguration.java``
with ``toJson/fromJson``).  Differences by design:

- configs are immutable frozen dataclasses (functional JAX style) rather than
  mutable beans; "override" produces new values instead of mutating;
- serde is plain dataclass->dict->JSON — no custom serializer classes needed
  because every field is data, not a live object (the reference needed custom
  Jackson (de)serializers for ActivationFunction/Distribution/RandomGenerator
  objects; here activations/losses/weight-inits are *names* resolved by
  registries and the RNG is a seed).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Mapping, Sequence

from ..ops.losses import LossFunction


class OptimizationAlgorithm(str, enum.Enum):
    """Mirrors ``nn/api/OptimizationAlgorithm.java`` (enum of solver kinds)."""

    GRADIENT_DESCENT = "gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    HESSIAN_FREE = "hessian_free"
    LBFGS = "lbfgs"
    ITERATION_GRADIENT_DESCENT = "iteration_gradient_descent"


class WeightInit(str, enum.Enum):
    """Mirrors ``nn/weights/WeightInit.java:7-16`` scheme names."""

    VI = "vi"                     # Glorot-like: uniform * sqrt(6)/sqrt(fan_in+fan_out+1)
    ZERO = "zero"
    SIZE = "size"
    DISTRIBUTION = "distribution"
    NORMALIZED = "normalized"
    UNIFORM = "uniform"


class Distribution(str, enum.Enum):
    """Weight distributions (reference: ``distributions/Distributions.java``)."""

    UNIFORM = "uniform"
    NORMAL = "normal"


class RBMVisibleUnit(str, enum.Enum):
    """Mirrors ``models/featuredetectors/rbm/RBM.java:54-62`` VisibleUnit."""

    BINARY = "binary"
    GAUSSIAN = "gaussian"
    SOFTMAX = "softmax"
    LINEAR = "linear"


class RBMHiddenUnit(str, enum.Enum):
    """Mirrors ``RBM.java:64-70`` HiddenUnit."""

    RECTIFIED = "rectified"
    BINARY = "binary"
    GAUSSIAN = "gaussian"
    SOFTMAX = "softmax"


# Layer kinds known to the layer registry (nn/layers/factory/LayerFactories
# equivalent — see nn/layers.py REGISTRY).
class LayerKind(str, enum.Enum):
    DENSE = "dense"
    OUTPUT = "output"
    RBM = "rbm"
    AUTOENCODER = "autoencoder"
    RECURSIVE_AUTOENCODER = "recursive_autoencoder"
    LSTM = "lstm"
    CONVOLUTION_DOWNSAMPLE = "convolution_downsample"
    # Beyond-v0 additions for the north-star models:
    CONV2D = "conv2d"
    MAXPOOL2D = "maxpool2d"
    BATCHNORM = "batchnorm"
    EMBEDDING = "embedding"
    ATTENTION = "attention"


@dataclass(frozen=True)
class NeuralNetConfiguration:
    """Per-layer hyperparameters.

    Field-for-field capability match of the reference's
    ``NeuralNetConfiguration`` bean (~35 knobs, ``NeuralNetConfiguration.java:
    35-100``); fields that only made sense for mutable Java objects (live rng,
    live dist object) are replaced by ``seed``/``dist`` names.
    """

    # core optimization knobs
    lr: float = 1e-1
    momentum: float = 0.5
    momentum_schedule: dict[int, float] = field(default_factory=dict)  # iteration -> momentum
    l2: float = 0.0
    use_regularization: bool = False
    dropout: float = 0.0
    sparsity: float = 0.0
    apply_sparsity: bool = False
    corruption_level: float = 0.3        # denoising AE input corruption
    num_iterations: int = 1000           # optimizer iterations (reference default 1000)
    optimization_algo: OptimizationAlgorithm = OptimizationAlgorithm.CONJUGATE_GRADIENT
    lr_score_based_decay: float = 0.0
    minimize: bool = False               # reference maximizes score by default (GradientAscent)
    constrain_gradient_to_unit_norm: bool = False
    use_adagrad: bool = True
    reset_adagrad_iterations: int = -1

    # shapes
    n_in: int = 0
    n_out: int = 0
    batch_size: int = 0                  # 0 = whole batch

    # layer semantics
    kind: LayerKind = LayerKind.DENSE
    activation: str = "sigmoid"
    loss: LossFunction = LossFunction.RECONSTRUCTION_CROSSENTROPY
    weight_init: WeightInit = WeightInit.VI
    dist: Distribution = Distribution.NORMAL
    dist_std: float = 1e-2               # std / half-width for DISTRIBUTION init
    seed: int = 123

    # pretrain (RBM) knobs
    k: int = 1                           # CD-k Gibbs steps
    visible_unit: RBMVisibleUnit = RBMVisibleUnit.BINARY
    hidden_unit: RBMHiddenUnit = RBMHiddenUnit.BINARY

    # conv knobs (reference: filterSize/stride/featureMapSize)
    filter_size: tuple[int, int] = (2, 2)
    stride: tuple[int, int] = (2, 2)
    num_filters: int = 1
    padding: str = "VALID"

    # recurrent knobs
    hidden_size: int = 0

    # misc
    render_weights_every_n: int = 0
    extra: dict[str, Any] = field(default_factory=dict)  # forward-compat knobs

    # ------------------------------------------------------------------ serde
    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, enum.Enum):
                d[k] = v.value
        d["momentum_schedule"] = {str(k): v for k, v in self.momentum_schedule.items()}
        d["filter_size"] = list(self.filter_size)
        d["stride"] = list(self.stride)
        return d

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "NeuralNetConfiguration":
        kw = dict(d)
        kw["optimization_algo"] = OptimizationAlgorithm(kw.get("optimization_algo", "conjugate_gradient"))
        kw["kind"] = LayerKind(kw.get("kind", "dense"))
        kw["loss"] = LossFunction(kw.get("loss", "reconstruction_crossentropy"))
        kw["weight_init"] = WeightInit(kw.get("weight_init", "vi"))
        kw["dist"] = Distribution(kw.get("dist", "normal"))
        kw["visible_unit"] = RBMVisibleUnit(kw.get("visible_unit", "binary"))
        kw["hidden_unit"] = RBMHiddenUnit(kw.get("hidden_unit", "binary"))
        kw["momentum_schedule"] = {int(k): float(v) for k, v in kw.get("momentum_schedule", {}).items()}
        kw["filter_size"] = tuple(kw.get("filter_size", (2, 2)))
        kw["stride"] = tuple(kw.get("stride", (2, 2)))
        known = {f.name for f in dataclasses.fields(cls)}
        kw = {k: v for k, v in kw.items() if k in known}
        return cls(**kw)

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "NeuralNetConfiguration":
        return cls.from_dict(json.loads(s))

    def replace(self, **kw) -> "NeuralNetConfiguration":
        return dataclasses.replace(self, **kw)

    def momentum_at(self, iteration: int) -> float:
        """Momentum with schedule lookup (``BaseOptimizer.java:75-84``)."""
        m = self.momentum
        if self.momentum_schedule:
            applicable = [it for it in self.momentum_schedule if it <= iteration]
            if applicable:
                m = self.momentum_schedule[max(applicable)]
        return m


# Type alias used across the package: a layer config IS a NeuralNetConfiguration.
LayerConfig = NeuralNetConfiguration


@dataclass(frozen=True)
class ConfOverride:
    """Per-layer field overrides applied by ``MultiLayerConfiguration.Builder``.

    Mirrors ``NeuralNetConfiguration.ConfOverride`` (``:735-785``) — the
    reference mutates layer i's conf in a callback; here it is a dict of
    field replacements for layer ``layer_index``.
    """

    layer_index: int
    overrides: dict[str, Any] = field(default_factory=dict)

    def apply(self, conf: NeuralNetConfiguration) -> NeuralNetConfiguration:
        kw = dict(self.overrides)
        # Allow enum names as strings in overrides.
        if "kind" in kw:
            kw["kind"] = LayerKind(kw["kind"])
        if "loss" in kw:
            kw["loss"] = LossFunction(kw["loss"])
        if "optimization_algo" in kw:
            kw["optimization_algo"] = OptimizationAlgorithm(kw["optimization_algo"])
        if "weight_init" in kw:
            kw["weight_init"] = WeightInit(kw["weight_init"])
        if "visible_unit" in kw:
            kw["visible_unit"] = RBMVisibleUnit(kw["visible_unit"])
        if "hidden_unit" in kw:
            kw["hidden_unit"] = RBMHiddenUnit(kw["hidden_unit"])
        return conf.replace(**kw)


@dataclass(frozen=True)
class MultiLayerConfiguration:
    """Whole-network configuration.

    Mirrors ``nn/conf/MultiLayerConfiguration.java:13-120``: a list of
    per-layer confs + network-level knobs (hidden sizes, pretrain flag,
    dropconnect, Hessian-free damping) + JSON round-trip.
    """

    confs: tuple[NeuralNetConfiguration, ...] = ()
    hidden_layer_sizes: tuple[int, ...] = ()
    pretrain: bool = True
    backprop: bool = True
    use_dropconnect: bool = False
    use_gauss_newton_vector_product_back_prop: bool = False
    damping_factor: float = 100.0        # HF damping default (MultiLayerConfiguration.java:22)
    use_rbm_propagation: bool = False    # propagate via sampled vs mean activations in pretrain
    # per-layer OutputPreProcessor map (reference: ``MultiLayerConfiguration``
    # processors + ``nn/conf/preprocessor/ReshapePreProcessor``): name of a
    # registered post-processing applied to layer i's OUTPUT before layer i+1.
    preprocessors: dict[int, str] = field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "confs", tuple(self.confs))
        object.__setattr__(self, "hidden_layer_sizes", tuple(self.hidden_layer_sizes))
        object.__setattr__(self, "preprocessors",
                           {int(k): v for k, v in dict(self.preprocessors).items()})

    @property
    def n_layers(self) -> int:
        return len(self.confs)

    def conf(self, i: int) -> NeuralNetConfiguration:
        return self.confs[i]

    # ------------------------------------------------------------------ serde
    def to_dict(self) -> dict:
        return {
            "confs": [c.to_dict() for c in self.confs],
            "hidden_layer_sizes": list(self.hidden_layer_sizes),
            "pretrain": self.pretrain,
            "backprop": self.backprop,
            "use_dropconnect": self.use_dropconnect,
            "use_gauss_newton_vector_product_back_prop": self.use_gauss_newton_vector_product_back_prop,
            "damping_factor": self.damping_factor,
            "use_rbm_propagation": self.use_rbm_propagation,
            "preprocessors": {str(k): v for k, v in self.preprocessors.items()},
        }

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "MultiLayerConfiguration":
        kw = dict(d)
        kw["confs"] = tuple(NeuralNetConfiguration.from_dict(c) for c in kw.get("confs", []))
        kw["hidden_layer_sizes"] = tuple(kw.get("hidden_layer_sizes", ()))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in kw.items() if k in known})

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "MultiLayerConfiguration":
        return cls.from_dict(json.loads(s))

    def replace(self, **kw) -> "MultiLayerConfiguration":
        return dataclasses.replace(self, **kw)


class ListBuilder:
    """Mirrors ``NeuralNetConfiguration.ListBuilder`` — expand one base conf
    into a per-layer list, sizing n_in/n_out from input size + hidden sizes,
    then apply ``ConfOverride``s."""

    def __init__(self, base: NeuralNetConfiguration, n_layers: int):
        self._base = base
        self._n_layers = n_layers
        self._overrides: list[ConfOverride] = []
        self._net_kw: dict[str, Any] = {}

    def override(self, layer_index: int, **overrides) -> "ListBuilder":
        self._overrides.append(ConfOverride(layer_index, overrides))
        return self

    def override_conf(self, ov: ConfOverride) -> "ListBuilder":
        self._overrides.append(ov)
        return self

    def hidden_layer_sizes(self, *sizes: int) -> "ListBuilder":
        self._net_kw["hidden_layer_sizes"] = tuple(sizes)
        return self

    def pretrain(self, flag: bool) -> "ListBuilder":
        self._net_kw["pretrain"] = flag
        return self

    def backprop(self, flag: bool) -> "ListBuilder":
        self._net_kw["backprop"] = flag
        return self

    def set(self, **net_kw) -> "ListBuilder":
        self._net_kw.update(net_kw)
        return self

    def build(self) -> MultiLayerConfiguration:
        confs = [self._base for _ in range(self._n_layers)]
        hidden = self._net_kw.get("hidden_layer_sizes", ())
        if hidden:
            # Size the chain: layer0 (n_in -> hidden[0]) ... last (hidden[-1] -> n_out).
            n_in, n_out = self._base.n_in, self._base.n_out
            sizes_in = [n_in] + list(hidden)
            sizes_out = list(hidden) + [n_out]
            confs = [
                c.replace(n_in=sizes_in[i], n_out=sizes_out[i], seed=c.seed + i)
                for i, c in enumerate(confs)
            ]
        for ov in self._overrides:
            confs[ov.layer_index] = ov.apply(confs[ov.layer_index])
        return MultiLayerConfiguration(confs=tuple(confs), **self._net_kw)


def list_builder(base: NeuralNetConfiguration, n_layers: int) -> ListBuilder:
    return ListBuilder(base, n_layers)


class Configuration(dict):
    """Untyped string key/value runtime configuration.

    Capability match of the Hadoop-derived ``nn/conf/Configuration.java:19``
    used by the scaleout layer for cluster knobs — here a thin dict with
    typed getters and ``${var}`` substitution.
    """

    def get_str(self, key: str, default: str | None = None) -> str | None:
        v = self.get(key, default)
        return self._subst(v) if isinstance(v, str) else v

    def get_int(self, key: str, default: int = 0) -> int:
        v = self.get(key)
        return int(self._subst(v)) if v is not None else default

    def get_float(self, key: str, default: float = 0.0) -> float:
        v = self.get(key)
        return float(self._subst(v)) if v is not None else default

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self.get(key)
        if v is None:
            return default
        if isinstance(v, bool):
            return v
        return str(self._subst(v)).strip().lower() in ("1", "true", "yes", "on")

    def _subst(self, v):
        if not isinstance(v, str):
            return v
        out, guard = v, 0
        while "${" in out and guard < 10:
            start = out.index("${")
            end = out.find("}", start)
            if end == -1:  # unclosed ${ — return verbatim rather than crash
                break
            var = out[start + 2:end]
            out = out[:start] + str(self.get(var, "")) + out[end + 1:]
            guard += 1
        return out

    def to_json(self) -> str:
        return json.dumps(self, indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Configuration":
        return cls(json.loads(s))
