"""The disaggregated serving front end (DESIGN.md §27).

:class:`DisaggScheduler` owns the pipeline: requests enter a bounded
prefill-tier queue, worker threads run prompt prefill on prefill-role
engines, the :class:`~.migrate.KVMigrator` moves the resulting pages to
the decode engine, and the decode engine's own continuous batch takes
it from there.  The scheduler exposes the SAME surface as an engine
(``generate``/``submit``/``stats``/``reload``/``start``/``stop``), so
an :class:`~..router.replicas.EngineReplica` can wrap one and the
``PrefixRouter`` routes to a disagg cell exactly as it routes to a
colocated engine — prefix affinity keeps warm pages near their decode
home with zero new router code.

Failure contract: a chaos-killed prefill worker
(``disagg.prefill_worker``) or a transient migration fault
(``disagg.migrate``) REQUEUES the request at the head of its tier —
never fails it, never corrupts decode state — and the worker respawns.
Requeues are capped; the cap exhausting is the only path from chaos to
a caller-visible error.  TTFT for a disagg request is measured from
scheduler entry (the queue stamps ``submitted_s`` once), so
``disagg.ttft`` is comparable to colocated ``serving.ttft``.
"""

from __future__ import annotations

import threading
import time

from ...observability import METRICS
from ...resilience.faults import FAULTS, TransientStepFault, WorkerKilled
from ..batcher import GenerateRequest, PendingResult, RequestQueue
from ..engine import MigrationRejected
from .migrate import KVMigrator

__all__ = ["DisaggScheduler"]


class DisaggScheduler:
    """Drive requests through prefill engines into one decode engine.

    ``prefill_engines`` must be paged, prefill-role (or at least
    serve-thread-less) engines sharing the decode engine's model
    weights and page geometry; ``decode_engine`` is a normal paged
    engine whose serve loop admits migrations between segments.
    """

    def __init__(self, prefill_engines, decode_engine, *,
                 max_queue: int = 256, max_batch_delay_ms: float = 2.0,
                 workers_per_engine: int = 1,
                 migrate_timeout_s: float = 30.0, max_requeues: int = 3):
        if not prefill_engines:
            raise ValueError("need at least one prefill engine")
        self.prefill_engines = list(prefill_engines)
        self.decode = decode_engine
        self.migrator = KVMigrator(decode_engine)
        self.workers_per_engine = int(workers_per_engine)
        self.migrate_timeout_s = float(migrate_timeout_s)
        self.max_requeues = int(max_requeues)
        self._queue = RequestQueue(
            max_queue, max_batch_delay_ms,
            depth_gauge="serving.queue.depth.prefill")
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []   # guarded-by: self._lock
        self._requeue_counts: dict[int, int] = {}    # guarded-by: self._lock

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "DisaggScheduler":
        self._stop_evt.clear()
        for eng in self.prefill_engines:
            if not eng.stats()["warmed"]:
                eng.start()
        if not self.decode.stats()["running"]:
            self.decode.start()
        with self._lock:
            have = len([t for t in self._threads if t.is_alive()])
        want = len(self.prefill_engines) * self.workers_per_engine
        for i in range(have, want):
            self._spawn(self.prefill_engines[i % len(self.prefill_engines)])
        return self

    def stop(self) -> None:
        self._stop_evt.set()
        self._queue.wake()
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join(timeout=10.0)
        for p in self._queue.drain():
            p._fail(MigrationRejected("disagg scheduler stopped"))
        for eng in self.prefill_engines:
            eng.stop()
        self.decode.stop()

    def _spawn(self, eng) -> None:
        t = threading.Thread(target=self._worker, args=(eng,),
                             daemon=True, name="disagg-prefill-worker")
        with self._lock:
            self._threads.append(t)
        t.start()

    # ------------------------------------------------------------ submission
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               seed: int = 0, eos_id: int | None = None,
               deadline_ms: float | None = None, tenant: str = "",
               priority: int = 0) -> PendingResult:
        """Validate + enqueue into the prefill tier; mirrors
        :meth:`InferenceEngine.submit`'s error contract (400 / 429)."""
        cfg = self.decode.model.cfg
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < cfg.vocab_size for t in prompt):
            raise ValueError(
                f"prompt token out of range [0, {cfg.vocab_size})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) exceeds max_len ({cfg.max_len})")
        req = GenerateRequest(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), seed=int(seed), eos_id=eos_id,
            deadline_s=(time.monotonic() + deadline_ms / 1e3
                        if deadline_ms is not None else None),
            priority=int(priority))
        return self._queue.submit(req)

    def generate(self, prompt, max_new_tokens: int,
                 temperature: float = 0.0, seed: int = 0,
                 eos_id: int | None = None,
                 deadline_ms: float | None = None, tenant: str = "",
                 priority: int = 0, timeout: float | None = None):
        p = self.submit(prompt, max_new_tokens, temperature=temperature,
                        seed=seed, eos_id=eos_id, deadline_ms=deadline_ms,
                        tenant=tenant, priority=priority)
        completion = p.result(timeout)
        if completion.ttft_s is not None:
            METRICS.observe_time("disagg.ttft", completion.ttft_s)
        return completion

    # ------------------------------------------------------------ workers
    def _worker(self, eng) -> None:
        while not self._stop_evt.is_set():
            got = self._queue.take(1, block_s=0.2)
            if not got:
                continue
            p = got[0]
            if not self._queue.claim(p):
                continue   # expired between take and claim — 504 already
            rec = None
            try:
                FAULTS.maybe_fire("disagg.prefill_worker")
                req = p.request
                rec = eng.prefill(req.prompt, req.max_new_tokens,
                                  temperature=req.temperature,
                                  seed=req.seed, eos_id=req.eos_id)
                # kill point with a live prefill record: the handler
                # below must release it — the chaos leg asserts the
                # prefill pool returns to its pre-request refcounts
                FAULTS.maybe_fire("disagg.prefill_worker")
                ticket, _plan = self.migrator.migrate(eng, rec, p)
                rec = None          # consumed by the migrator
                if ticket.wait(self.migrate_timeout_s):
                    with self._lock:
                        self._requeue_counts.pop(p.request.id, None)
                elif not p.done():
                    # admission rejected (weight generation moved):
                    # nothing leaked, nothing decoded — go again
                    self._requeue(p, ticket.reason or "admission rejected")
            except WorkerKilled as e:
                if rec is not None:
                    eng.release_prefill(rec)
                self._requeue(p, str(e))
                self._respawn(eng)
                return              # this worker is dead; a twin took over
            except (TransientStepFault, MigrationRejected, TimeoutError) as e:
                if rec is not None:
                    eng.release_prefill(rec)
                self._requeue(p, str(e))
            except BaseException as e:
                if rec is not None:
                    eng.release_prefill(rec)
                p._fail(e)

    def _respawn(self, eng) -> None:
        if not self._stop_evt.is_set():
            self._spawn(eng)

    def _requeue(self, p: PendingResult, reason: str) -> None:
        """Head-of-tier requeue with a cap — the ONLY way chaos reaches
        the caller is this cap exhausting."""
        if p.done():
            return
        with self._lock:
            n = self._requeue_counts.get(p.request.id, 0) + 1
            self._requeue_counts[p.request.id] = n
        METRICS.increment("disagg.requeues")
        if n > self.max_requeues:
            with self._lock:
                self._requeue_counts.pop(p.request.id, None)
            p._fail(MigrationRejected(
                f"gave up after {n - 1} requeues: {reason}"))
            return
        self._queue.unclaim(p)

    # ------------------------------------------------------------ surface
    def stats(self) -> dict:
        out = dict(self.decode.stats())
        prefill = [e.stats() for e in self.prefill_engines]
        out["role"] = "disagg"
        out["warmed"] = bool(out.get("warmed")) and all(
            s["warmed"] for s in prefill)
        out["prefill_engines"] = len(prefill)
        out["prefill_queue_depth"] = self._queue.depth()
        return out

    def reload(self, step: int):
        """Stage the checkpoint on BOTH tiers — prefill engines apply
        at their next prefill entry, the decode engine at its next
        all-slots-free fence; the migration generation check rejects
        any request whose pages straddle the swap."""
        out = self.decode.reload(step)
        for eng in self.prefill_engines:
            eng.reload(step)
        return out
