"""L8 — NLP stack (reference: ``deeplearning4j-nlp``, SURVEY.md §1 L8).

Host side: tokenization, sentence/document iteration, vocab building,
Huffman coding, co-occurrence counting, serialization — plain Python (with
native C++ acceleration where profiled).  Device side: batched skip-gram /
negative-sampling / GloVe updates as jitted segment ops on the TPU — the
per-pair BLAS axpy loops of the reference's ``InMemoryLookupTable`` become
one scatter-add per batch.
"""

from .tokenization import (
    DefaultTokenizer,
    DefaultTokenizerFactory,
    LowerCasePreProcessor,
    StripPunctuationPreProcess,
)
from .sentence import (
    CollectionSentenceIterator,
    FileSentenceIterator,
    LabelAwareListSentenceIterator,
    LineSentenceIterator,
)
from .vocab import Huffman, VocabCache, VocabWord, build_vocab
from .lm_dataset import LMCorpus, LMTokenBatchIterator
from .word2vec import Word2Vec
from .serializer import load_txt, save_txt, load_google_binary, save_google_binary
from .glove import Glove
from .paragraph_vectors import ParagraphVectors
from .vectorizers import BagOfWordsVectorizer, TfidfVectorizer

__all__ = [
    "DefaultTokenizer", "DefaultTokenizerFactory", "LowerCasePreProcessor",
    "StripPunctuationPreProcess",
    "CollectionSentenceIterator", "FileSentenceIterator",
    "LabelAwareListSentenceIterator", "LineSentenceIterator",
    "Huffman", "VocabCache", "VocabWord", "build_vocab",
    "LMCorpus", "LMTokenBatchIterator",
    "Word2Vec", "Glove", "ParagraphVectors",
    "load_txt", "save_txt", "load_google_binary", "save_google_binary",
    "BagOfWordsVectorizer", "TfidfVectorizer",
]
