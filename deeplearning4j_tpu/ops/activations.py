"""Activation functions and their derivatives.

TPU-native equivalent of the ND4J ``Activations`` factory consumed at e.g.
``nn/layers/BaseLayer.java:163`` and ``nn/layers/OutputLayer.java:129`` of the
reference.  Functions are elementwise jnp ops XLA fuses into surrounding
matmuls; ``softmax`` operates row-wise like the reference's
``Activations.softMaxRows``.

``apply_derivative`` mirrors ``ActivationFunction.applyDerivative``
(used by the hand-written backprop in ``MultiLayerNetwork.java:618,654``).
The real gradient path here is JAX autodiff; the explicit derivatives exist
for API parity and for tests that pin down the math.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

Activation = Callable[[jnp.ndarray], jnp.ndarray]

_REGISTRY: dict[str, Activation] = {}
_DERIVATIVES: dict[str, Activation] = {}
# Row-wise (non-elementwise) activations: the vmapped-grad fallback in
# apply_derivative is meaningless for these (a 1-element softmax row is
# constant), so they must either have an explicit derivative or reject.
_ROWWISE = {"logsoftmax"}


def register(name: str, fn: Activation, deriv: Activation | None = None):
    _REGISTRY[name] = fn
    if deriv is not None:
        _DERIVATIVES[name] = deriv
    return fn


def get(name: str) -> Activation:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown activation {name!r}; known: {sorted(_REGISTRY)}") from None


def names() -> list[str]:
    return sorted(_REGISTRY)


def apply(name: str, x: jnp.ndarray) -> jnp.ndarray:
    return get(name)(x)


def apply_derivative(name: str, x: jnp.ndarray) -> jnp.ndarray:
    """Elementwise derivative f'(x).

    For ``softmax`` this returns the diagonal approximation y*(1-y) the
    reference uses inside its delta chain (the full Jacobian is handled by
    autodiff in the real training path).
    """
    if name in _DERIVATIVES:
        return _DERIVATIVES[name](x)
    if name in _ROWWISE:
        raise ValueError(
            f"activation {name!r} is row-wise (not elementwise); its full "
            "Jacobian is handled by autodiff in the training path — "
            "apply_derivative has no elementwise meaning for it")
    fn = get(name)
    # Fallback: elementwise derivative via vmapped grad.
    flat = x.reshape(-1)
    d = jax.vmap(jax.grad(lambda v: fn(v.reshape(1))[0]))(flat)
    return d.reshape(x.shape)


def softmax(x: jnp.ndarray) -> jnp.ndarray:
    return jax.nn.softmax(x, axis=-1)


register("sigmoid", jax.nn.sigmoid, lambda x: jax.nn.sigmoid(x) * (1 - jax.nn.sigmoid(x)))
register("tanh", jnp.tanh, lambda x: 1 - jnp.tanh(x) ** 2)
register("relu", jax.nn.relu, lambda x: (x > 0).astype(x.dtype))
register("leakyrelu", lambda x: jax.nn.leaky_relu(x, 0.01),
         lambda x: jnp.where(x > 0, 1.0, 0.01).astype(x.dtype))
register("linear", lambda x: x, lambda x: jnp.ones_like(x))
register("identity", lambda x: x, lambda x: jnp.ones_like(x))
register("exp", jnp.exp, jnp.exp)
register("softsign", jax.nn.soft_sign, lambda x: 1.0 / (1.0 + jnp.abs(x)) ** 2)
register("softplus", jax.nn.softplus, jax.nn.sigmoid)
register("hardtanh", lambda x: jnp.clip(x, -1.0, 1.0),
         lambda x: ((x > -1.0) & (x < 1.0)).astype(x.dtype))
register("gelu", jax.nn.gelu)
register("softmax", softmax, lambda x: softmax(x) * (1 - softmax(x)))
register("logsoftmax", lambda x: jax.nn.log_softmax(x, axis=-1))
