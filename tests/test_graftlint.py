"""graftlint unit tests.

Every rule is demonstrated on a known-bad fixture snippet AND shown quiet
on the corresponding known-good rewrite — the shipped tree only exercises
a subset of the rules, so this file is where each rule's trigger contract
actually lives.  Also covers the suppression pragmas, the baseline
ledger, the metrics gauges, and the ``tools.graftlint`` CLI.
"""

import json
import textwrap

import pytest

from deeplearning4j_tpu.analysis import (
    ACTIVE,
    BASELINED,
    SUPPRESSED,
    Analyzer,
    Baseline,
    active,
    all_rules,
    emit_metrics,
)


def lint(source, only=None, baseline=None, path="snippet.py"):
    """Analyze one dedented snippet; ``only`` restricts to a single rule
    so known-good assertions aren't polluted by a *different* rule firing
    on the same fixture."""
    rules = [all_rules()[only]] if only else None
    analyzer = Analyzer(rules=rules, baseline=baseline)
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return findings


def rules_hit(findings):
    return {f.rule for f in findings if f.status == ACTIVE}


# --------------------------------------------------------------------------- HS01

HS01_BAD = """
    import jax

    step = jax.jit(lambda p, x: p * x)

    def fit(p, xs):
        total = 0.0
        for x in xs:
            loss = step(p, x)
            total += float(loss)
        return total
"""


def test_hs01_fires_on_float_in_loop():
    findings = [f for f in lint(HS01_BAD) if f.rule == "HS01"]
    assert len(findings) == 1
    assert "float(loss)" in findings[0].code
    assert "drain" in findings[0].message


def test_hs01_fires_in_loop_free_per_call_function():
    src = """
        import jax

        step = jax.jit(lambda p, x: p * x)

        def apply_step(p, x):
            loss = step(p, x)
            return float(loss)
    """
    findings = [f for f in lint(src) if f.rule == "HS01"]
    assert len(findings) == 1
    assert "loop-free" in findings[0].message


def test_hs01_quiet_on_post_loop_fence():
    src = """
        import jax

        step = jax.jit(lambda p, x: p * x)

        def fit(p, xs):
            loss = None
            for x in xs:
                loss = step(p, x)
            return float(loss)
    """
    assert lint(src, only="HS01") == []


def test_hs01_quiet_on_untainted_values():
    src = """
        def fit(xs):
            total = 0.0
            for x in xs:
                total += float(x)
            return total
    """
    assert lint(src, only="HS01") == []


# --------------------------------------------------------------------------- RC01

def test_rc01_fires_on_param_dependent_shape():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def embed(n, x):
            return jnp.arange(n) + x
    """
    findings = [f for f in lint(src) if f.rule == "RC01"]
    assert len(findings) == 1
    assert "'n'" in findings[0].message


def test_rc01_quiet_on_shape_derived_sizes():
    src = """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def embed(x):
            return jnp.arange(x.shape[0]) + x
    """
    assert lint(src, only="RC01") == []


def test_rc01_fires_on_list_literal_at_static_position():
    src = """
        import jax

        agg = jax.jit(lambda x, dims: x, static_argnums=(1,))

        def call(x):
            return agg(x, [1, 2])
    """
    findings = [f for f in lint(src) if f.rule == "RC01"]
    assert len(findings) == 1
    assert "hashable" in findings[0].message


def test_rc01_quiet_on_tuple_at_static_position():
    src = """
        import jax

        agg = jax.jit(lambda x, dims: x, static_argnums=(1,))

        def call(x):
            return agg(x, (1, 2))
    """
    assert lint(src, only="RC01") == []


# --------------------------------------------------------------------------- RNG01

def test_rng01_fires_on_sequential_reuse():
    src = """
        import jax

        def sample(key):
            a = jax.random.normal(key)
            b = jax.random.uniform(key)
            return a + b
    """
    findings = [f for f in lint(src) if f.rule == "RNG01"]
    assert len(findings) == 1
    assert "correlated" in findings[0].message


def test_rng01_fires_on_cross_iteration_reuse():
    src = """
        import jax

        def roll(key, n):
            out = []
            for _ in range(n):
                out.append(jax.random.normal(key))
            return out
    """
    findings = [f for f in lint(src) if f.rule == "RNG01"]
    assert len(findings) == 1
    assert "every" in findings[0].message


def test_rng01_quiet_on_split_keys():
    src = """
        import jax

        def sample(key):
            k1, k2 = jax.random.split(key)
            a = jax.random.normal(k1)
            b = jax.random.uniform(k2)
            return a + b
    """
    assert lint(src, only="RNG01") == []


def test_rng01_quiet_on_per_iteration_fold_in():
    src = """
        import jax

        def roll(key, n):
            out = []
            for i in range(n):
                key = jax.random.fold_in(key, i)
                out.append(jax.random.normal(key))
            return out
    """
    # key is rebound in the loop body — and the fold_in/normal pair within
    # one iteration draws from DIFFERENT values of the rebound name
    assert lint(src, only="RNG01") == []


def test_rng01_quiet_across_exclusive_branches():
    src = """
        import jax

        def pick(key, flag):
            if flag:
                return jax.random.normal(key)
            return jax.random.uniform(key)
    """
    assert lint(src, only="RNG01") == []


# --------------------------------------------------------------------------- DON01

DON01_PRELUDE = """
    import jax

    step = jax.jit(lambda p, x: p + x, donate_argnums=(0,))
"""


def test_don01_fires_on_read_after_donation():
    src = DON01_PRELUDE + """
    def train(p, x):
        q = step(p, x)
        y = p + 1
        return q, y
    """
    findings = [f for f in lint(src) if f.rule == "DON01"]
    assert len(findings) == 1
    assert "donated" in findings[0].message


def test_don01_fires_on_unrebound_donation_in_loop():
    src = DON01_PRELUDE + """
    def train(p, xs):
        q = None
        for x in xs:
            q = step(p, x)
        return q
    """
    findings = [f for f in lint(src) if f.rule == "DON01"]
    assert len(findings) == 1
    assert "next iteration" in findings[0].message


def test_don01_quiet_when_rebound_from_result():
    src = DON01_PRELUDE + """
    def train(p, xs):
        for x in xs:
            p = step(p, x)
        return p
    """
    assert lint(src, only="DON01") == []


# --------------------------------------------------------------------------- TB01

def test_tb01_fires_on_python_if_over_traced_param():
    src = """
        import jax

        @jax.jit
        def relu(x):
            if x > 0:
                return x
            return 0.0
    """
    findings = [f for f in lint(src) if f.rule == "TB01"]
    assert len(findings) == 1
    assert "lax.cond" in findings[0].message


def test_tb01_quiet_on_static_attribute_tests():
    src = """
        import jax

        @jax.jit
        def maybe_pad(x):
            if x.shape[0] > 2:
                return x
            return x * 2.0
    """
    assert lint(src, only="TB01") == []


def test_tb01_quiet_on_is_none_tests():
    src = """
        import jax

        @jax.jit
        def f(x, key):
            if key is None:
                return x
            return x + 1
    """
    assert lint(src, only="TB01") == []


def test_tb01_quiet_outside_traced_functions():
    src = """
        def plain(x):
            if x > 0:
                return x
            return 0.0
    """
    assert lint(src, only="TB01") == []


# --------------------------------------------------------------------------- HOT02

HOT02_BAD = """
    import jax

    step = jax.jit(lambda p: p * 2)

    def run(p, n):
        for _ in range(n):
            p = step(p)
        return p
"""


def test_hot02_fires_on_uninstrumented_dispatch_loop():
    findings = [f for f in lint(HOT02_BAD) if f.rule == "HOT02"]
    assert len(findings) == 1
    assert "instrumentation" in findings[0].message


def test_hot02_quiet_with_metrics_counter_in_loop():
    src = """
        import jax

        step = jax.jit(lambda p: p * 2)

        def run(p, n):
            for _ in range(n):
                p = step(p)
                METRICS.increment("run.steps")
            return p
    """
    assert lint(src, only="HOT02") == []


def test_hot02_quiet_with_span_around_loop():
    src = """
        import jax

        step = jax.jit(lambda p: p * 2)

        def run(p, n):
            with trace.span("run", steps=n):
                for _ in range(n):
                    p = step(p)
            return p
    """
    assert lint(src, only="HOT02") == []


def test_hot02_quiet_on_host_only_loops():
    src = """
        def run(xs):
            out = []
            for x in xs:
                out.append(x * 2)
            return out
    """
    assert lint(src, only="HOT02") == []


# --------------------------------------------------------------------------- EXC01

EXC01_BAD = """
    def retry(fn, attempts=3):
        for _ in range(attempts):
            try:
                return fn()
            except:
                continue
"""


def test_exc01_fires_on_bare_except():
    findings = [f for f in lint(EXC01_BAD) if f.rule == "EXC01"]
    assert len(findings) == 1
    assert "SystemExit" in findings[0].message


def test_exc01_quiet_on_typed_handlers():
    src = """
        def retry(fn, attempts=3, retry_on=(Exception,)):
            for _ in range(attempts):
                try:
                    return fn()
                except retry_on:
                    continue
                except Exception:
                    raise
    """
    assert lint(src, only="EXC01") == []


# --------------------------------------------------------------------------- PL01

PL01_BAD = """
    from jax.experimental import pallas as pl

    def call(kernel, x, spec):
        return pl.pallas_call(kernel, out_shape=x, in_specs=[spec])(x)
"""


def test_pl01_fires_on_pallas_call_without_interpret():
    findings = [f for f in lint(PL01_BAD) if f.rule == "PL01"]
    assert len(findings) == 1
    assert "interpret" in findings[0].message


def test_pl01_quiet_when_interpret_is_threaded():
    src = """
        from jax.experimental import pallas as pl

        def call(kernel, x, spec, interpret):
            return pl.pallas_call(kernel, out_shape=x, in_specs=[spec],
                                  interpret=interpret)(x)
    """
    assert lint(src, only="PL01") == []


# --------------------------------------------------------------------------- ZR01

ZR01_BAD = """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def init_state(self, params):
        stage = self.zero_stage
        tstate = self.transform.init(params)
        tstate = jax.device_put(tstate, NamedSharding(self.mesh, P()))
        return tstate
"""

ZR01_BAD_TREE_MAP = """
    import jax

    def restore(self, template):
        stage = self.zero_stage
        tstate = jax.tree_util.tree_map(
            lambda x: jax.device_put(x, self._rep_sh), template.tstate)
        return tstate
"""

ZR01_GOOD_GATED = """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def init_state(self, params):
        if self.zero_stage >= 1:
            tstate = self.init_sharded(params)
        else:
            tstate = jax.device_put(self.transform.init(params),
                                    NamedSharding(self.mesh, P()))
        return tstate
"""

ZR01_GOOD_EARLY_RETURN = """
    import jax

    def restore(self, template):
        if self.zero_stage >= 1:
            return self._restore_zero(template)
        return jax.device_put(template.tstate, self._rep_sh)
"""

ZR01_GOOD_NOT_ZERO_AWARE = """
    import jax

    def init_state(self, params):
        # stage-0-only trainer: replicating state is the correct layout
        tstate = self.transform.init(params)
        return jax.device_put(tstate, self._rep_sh)
"""


def test_zr01_fires_on_ungated_replicated_tstate_put():
    findings = [f for f in lint(ZR01_BAD) if f.rule == "ZR01"]
    assert len(findings) == 1
    assert "zero_stage" in findings[0].message
    assert "1/ndp" in findings[0].message


def test_zr01_fires_on_tree_map_device_put_form():
    findings = [f for f in lint(ZR01_BAD_TREE_MAP) if f.rule == "ZR01"]
    assert len(findings) == 1


def test_zr01_quiet_when_gated_by_zero_stage_branch():
    assert lint(ZR01_GOOD_GATED, only="ZR01") == []


def test_zr01_quiet_after_early_returning_zero_stage_guard():
    assert lint(ZR01_GOOD_EARLY_RETURN, only="ZR01") == []


def test_zr01_quiet_in_functions_that_never_read_zero_stage():
    assert lint(ZR01_GOOD_NOT_ZERO_AWARE, only="ZR01") == []


def test_zr01_quiet_on_dp_sharded_placement():
    src = """
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        def init_state(self, params):
            stage = self.zero_stage
            tstate = self.transform.init(params)
            return jax.device_put(tstate, NamedSharding(self.mesh, P("dp")))
    """
    assert lint(src, only="ZR01") == []


# --------------------------------------------------------------------------- suppressions

def test_same_line_pragma_suppresses_one_rule():
    src = HS01_BAD.replace(
        "total += float(loss)",
        "total += float(loss)  # graftlint: disable=HS01")
    findings = [f for f in lint(src) if f.rule == "HS01"]
    assert len(findings) == 1
    assert findings[0].status == SUPPRESSED
    assert active(findings) == []


def test_comment_line_pragma_applies_to_next_statement():
    src = HS01_BAD.replace(
        "total += float(loss)",
        "# deliberate per-step read  # graftlint: disable=HS01\n"
        "            total += float(loss)")
    findings = [f for f in lint(src) if f.rule == "HS01"]
    assert [f.status for f in findings] == [SUPPRESSED]


def test_file_wide_pragma():
    src = "# graftlint: disable-file=HS01\n" + textwrap.dedent(HS01_BAD)
    findings = [f for f in lint(src) if f.rule == "HS01"]
    assert [f.status for f in findings] == [SUPPRESSED]


def test_bare_disable_silences_every_rule_on_the_line():
    src = HS01_BAD.replace(
        "total += float(loss)",
        "total += float(loss)  # graftlint: disable")
    findings = [f for f in lint(src) if f.rule == "HS01"]
    assert [f.status for f in findings] == [SUPPRESSED]


def test_pragma_for_other_rule_does_not_suppress():
    src = HS01_BAD.replace(
        "total += float(loss)",
        "total += float(loss)  # graftlint: disable=RC01")
    assert "HS01" in rules_hit(lint(src))


# --------------------------------------------------------------------------- baseline

def test_baseline_roundtrip_and_matching(tmp_path):
    findings = active(lint(HS01_BAD))
    assert findings
    bl = Baseline.from_findings(findings, justification="legacy hot path")
    path = tmp_path / "baseline.json"
    bl.save(str(path))

    loaded = Baseline.load(str(path))
    assert loaded.entries == bl.entries
    assert all(loaded.contains(f) for f in findings)

    # with the baseline applied the same findings classify as baselined
    refound = lint(HS01_BAD, baseline=loaded)
    assert [f.status for f in refound if f.rule == "HS01"] == [BASELINED]
    assert active([f for f in refound if f.rule == "HS01"]) == []


def test_baseline_is_line_number_free(tmp_path):
    bl = Baseline.from_findings(active(lint(HS01_BAD)))
    # shift every line down: the (rule, path, code) key still matches
    shifted = "\n# padding\n# padding\n" + textwrap.dedent(HS01_BAD)
    findings = Analyzer(baseline=bl).analyze_source(shifted, "snippet.py")
    assert [f.status for f in findings if f.rule == "HS01"] == [BASELINED]


def test_baseline_invalidated_by_editing_the_flagged_line():
    bl = Baseline.from_findings(active(lint(HS01_BAD)))
    edited = HS01_BAD.replace("total += float(loss)",
                              "total += 2.0 * float(loss)")
    findings = lint(edited, baseline=bl)
    assert "HS01" in rules_hit(findings)  # forced a fresh look


def test_baseline_dedupes_identical_code_lines():
    src = """
        import jax

        step = jax.jit(lambda p, x: p * x)

        def fit_a(p, xs):
            for x in xs:
                loss = step(p, x)
                print(float(loss))

        def fit_b(p, xs):
            for x in xs:
                loss = step(p, x)
                print(float(loss))
    """
    findings = [f for f in active(lint(src)) if f.rule == "HS01"]
    assert len(findings) == 2
    bl = Baseline.from_findings(findings)
    assert len(bl.entries) == 1  # same (rule, path, code) key


def test_stale_entries_reported_after_fix():
    bl = Baseline.from_findings(
        [f for f in active(lint(HS01_BAD)) if f.rule == "HS01"])
    fixed = HS01_BAD.replace("total += float(loss)", "total = loss")
    findings = lint(fixed, baseline=bl)
    stale = bl.stale_entries(findings)
    assert len(stale) == len(bl.entries) == 1


def test_baseline_load_missing_file_is_empty(tmp_path):
    bl = Baseline.load(str(tmp_path / "nope.json"))
    assert bl.entries == []


def test_baseline_load_rejects_foreign_json(tmp_path):
    p = tmp_path / "other.json"
    p.write_text(json.dumps([1, 2, 3]))
    with pytest.raises(ValueError):
        Baseline.load(str(p))


# --------------------------------------------------------------------------- metrics

def test_emit_metrics_publishes_per_rule_gauges():
    from deeplearning4j_tpu import observability as obs

    obs.enable()
    obs.METRICS.reset()
    findings = lint(HS01_BAD)
    emit_metrics(findings, registry=obs.METRICS)

    snap = obs.METRICS.snapshot()
    assert snap["counters"]["graftlint.runs"] == 1
    assert snap["gauges"]["graftlint.violations.HS01"] == 1
    # rules with no hits still publish an explicit zero (scrapable absence)
    assert snap["gauges"]["graftlint.violations.DON01"] == 0
    assert snap["gauges"]["graftlint.violations.total"] == len(
        active(findings))


def test_emit_metrics_counts_only_active_findings():
    from deeplearning4j_tpu import observability as obs

    obs.enable()
    obs.METRICS.reset()
    suppressed = HS01_BAD.replace(
        "total += float(loss)",
        "total += float(loss)  # graftlint: disable=HS01")
    emit_metrics(lint(suppressed), registry=obs.METRICS)
    assert obs.METRICS.snapshot()["gauges"]["graftlint.violations.HS01"] == 0


# --------------------------------------------------------------------------- CLI

def _write(tmp_path, name, source):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return str(p)


def test_cli_check_passes_on_clean_file(tmp_path):
    from tools.graftlint import main

    path = _write(tmp_path, "ok.py", "x = 1\n")
    assert main([path, "--check", "--no-metrics",
                 "--baseline", str(tmp_path / "b.json")]) == 0


def test_cli_check_fails_on_new_violation(tmp_path, capsys):
    from tools.graftlint import main

    path = _write(tmp_path, "bad.py", HS01_BAD)
    assert main([path, "--check", "--no-metrics",
                 "--baseline", str(tmp_path / "b.json")]) == 1
    out = capsys.readouterr().out
    assert "HS01" in out and "bad.py" in out


def test_cli_check_fails_on_parse_error(tmp_path, capsys):
    from tools.graftlint import main

    path = _write(tmp_path, "broken.py", "def f(:\n")
    assert main([path, "--check", "--no-metrics",
                 "--baseline", str(tmp_path / "b.json")]) == 1
    assert "parse error" in capsys.readouterr().err


def test_cli_write_baseline_then_check_is_clean(tmp_path, capsys):
    from tools.graftlint import main

    path = _write(tmp_path, "bad.py", HS01_BAD)
    bfile = str(tmp_path / "b.json")
    assert main([path, "--write-baseline", "--no-metrics",
                 "--baseline", bfile]) == 0
    assert main([path, "--check", "--no-metrics", "--baseline", bfile]) == 0
    capsys.readouterr()
    # the accepted finding shows up as baselined in the JSON report
    assert main([path, "--json", "--no-metrics", "--baseline", bfile]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["tool"] == "graftlint"
    assert payload["summary"]["baselined"] >= 1
    assert payload["summary"]["active"] == 0


def test_cli_rules_filter_and_unknown_rule(tmp_path, capsys):
    from tools.graftlint import main

    path = _write(tmp_path, "bad.py", HS01_BAD)
    bfile = str(tmp_path / "b.json")
    # HS01 filtered out: only HOT02 can fire on this fixture
    assert main([path, "--check", "--no-metrics", "--baseline", bfile,
                 "--rules", "RC01,TB01"]) == 0
    capsys.readouterr()
    assert main([path, "--no-metrics", "--rules", "NOPE"]) == 2
    assert "unknown rule" in capsys.readouterr().err


# --------------------------------------------------------------------------- OB01

OB01_BAD = """
    import time
    import jax

    step = jax.jit(lambda p, x: p * x)

    def decode(p, x):
        t0 = time.perf_counter()
        out = step(p, x)
        return out, time.perf_counter() - t0
"""

OB01_GOOD = """
    import time
    import jax
    from deeplearning4j_tpu.observability import METRICS

    step = jax.jit(lambda p, x: p * x)

    def decode(p, x):
        t0 = time.perf_counter()
        out = step(p, x)
        METRICS.observe_time("serving.decode_step", time.perf_counter() - t0)
        return out
"""

OB01_GOOD_RECORD_SPAN = """
    import time
    import jax
    from deeplearning4j_tpu.observability import trace

    step = jax.jit(lambda p, x: p * x)

    def decode(p, x):
        t0 = time.perf_counter()
        out = step(p, x)
        trace.record_span("serving.decode", t0, time.perf_counter() - t0)
        return out
"""


def test_ob01_fires_on_raw_timing_of_dispatch_in_serving():
    findings = lint(OB01_BAD, only="OB01",
                    path="deeplearning4j_tpu/serving/snippet.py")
    assert rules_hit(findings) == {"OB01"}


def test_ob01_fires_in_parallel_tree_too():
    findings = lint(OB01_BAD, only="OB01",
                    path="deeplearning4j_tpu/parallel/snippet.py")
    assert rules_hit(findings) == {"OB01"}


def test_ob01_quiet_outside_serving_and_parallel():
    assert not lint(OB01_BAD, only="OB01",
                    path="deeplearning4j_tpu/models/snippet.py")


def test_ob01_quiet_when_measurement_reaches_registry():
    assert not lint(OB01_GOOD, only="OB01",
                    path="deeplearning4j_tpu/serving/snippet.py")


def test_ob01_quiet_when_measurement_reaches_tracer():
    assert not lint(OB01_GOOD_RECORD_SPAN, only="OB01",
                    path="deeplearning4j_tpu/serving/snippet.py")


def test_ob01_quiet_on_clock_without_dispatch():
    src = """
        import time

        def backoff(attempt):
            t0 = time.monotonic()
            return t0 + 2.0 ** attempt
    """
    assert not lint(src, only="OB01",
                    path="deeplearning4j_tpu/serving/snippet.py")


# --------------------------------------------------------------------------- QT01

QT01_BAD = """
    import jax.numpy as jnp

    def pack(kv):
        return kv.astype(jnp.int8)
"""

QT01_BAD_FP8 = """
    import jax.numpy as jnp

    def pack(kv):
        return kv.astype(jnp.float8_e4m3fn)
"""

QT01_BAD_KWARG = """
    import jax.numpy as jnp

    def pack(kv):
        return kv.astype(dtype=jnp.int8)
"""

QT01_GOOD = """
    import jax.numpy as jnp
    from deeplearning4j_tpu.ops.pallas.kv_quant import cast_to

    def pack(kv, scale):
        return cast_to(kv / scale, jnp.int8)
"""


def test_qt01_fires_on_raw_int8_cast_in_serving():
    findings = lint(QT01_BAD, only="QT01",
                    path="deeplearning4j_tpu/serving/snippet.py")
    assert rules_hit(findings) == {"QT01"}


def test_qt01_fires_on_fp8_and_dtype_kwarg_in_models():
    for src in (QT01_BAD_FP8, QT01_BAD_KWARG):
        findings = lint(src, only="QT01",
                        path="deeplearning4j_tpu/models/snippet.py")
        assert rules_hit(findings) == {"QT01"}


def test_qt01_quiet_outside_serving_and_models():
    """The quant helpers themselves (ops/pallas) hold the one allowed
    raw cast — the rule scopes to the consumer trees."""
    assert not lint(QT01_BAD, only="QT01",
                    path="deeplearning4j_tpu/ops/pallas/kv_quant.py")


def test_qt01_quiet_on_helper_and_float_casts():
    assert not lint(QT01_GOOD, only="QT01",
                    path="deeplearning4j_tpu/serving/snippet.py")
    src = """
        import jax.numpy as jnp

        def widen(x):
            return x.astype(jnp.float32)
    """
    assert not lint(src, only="QT01",
                    path="deeplearning4j_tpu/serving/snippet.py")


# --------------------------------------------------------------------------- EL01

EL01_BAD = """
    import jax
    from jax.sharding import Mesh

    def build():
        m = Mesh(jax.devices(), ("dp",))
        first_eight = jax.devices()[:8]
        chip = jax.local_devices()[0]
        return m, first_eight, chip
"""

EL01_GOOD = """
    import jax
    from deeplearning4j_tpu.parallel.mesh import elastic_mesh

    def build(n):
        return elastic_mesh(jax.devices()[:n])
"""


def test_el01_fires_on_raw_mesh_and_literal_device_slice():
    findings = lint(EL01_BAD, only="EL01",
                    path="deeplearning4j_tpu/parallel/snippet.py")
    assert rules_hit(findings) == {"EL01"}
    assert len(findings) == 3           # Mesh(...) + [:8] + [0]
    findings = lint(EL01_BAD, only="EL01",
                    path="deeplearning4j_tpu/resilience/snippet.py")
    assert len(findings) == 3           # resilience/ is in scope too


def test_el01_quiet_on_helpers_and_variable_slices():
    """Variable-bounded slices are the sanctioned idiom: the width is a
    parameter the caller re-derives after a resize (driver.py/dryrun.py)."""
    assert not lint(EL01_GOOD, only="EL01",
                    path="deeplearning4j_tpu/parallel/snippet.py")


def test_el01_scoped_to_parallel_and_resilience():
    """mesh.py is the one sanctioned construction site; trees outside
    parallel/+resilience/ (tools, tests, serving) are out of scope."""
    assert not lint(EL01_BAD, only="EL01",
                    path="deeplearning4j_tpu/parallel/mesh.py")
    assert not lint(EL01_BAD, only="EL01",
                    path="deeplearning4j_tpu/serving/snippet.py")
    assert not lint(EL01_BAD, only="EL01", path="tools/snippet.py")


# --------------------------------------------------------------------------- OB02

OB02_BAD = """
    from deeplearning4j_tpu.observability import METRICS
    def work(registry):
        METRICS.increment("serving.bogus_counter")
        registry.gauge("made.up.gauge", 1.0)
        with METRICS.time("undocumented.timer"):
            pass
"""

OB02_GOOD = """
    from deeplearning4j_tpu.observability import METRICS
    def work(site, registry):
        METRICS.increment("serving.requests")
        METRICS.increment(f"faults.injected.{site}")
        METRICS.gauge("goodput.seconds." + "stall", 1.0)
        registry.gauge("goodput.fraction", 0.5)
        name = compute_name()
        METRICS.increment(name)          # runtime-composed: out of scope
        other.increment("not.a.registry.recv")
"""


def _ob02(source, documented):
    from deeplearning4j_tpu.analysis.rules import UndocumentedMetricNameRule
    UndocumentedMetricNameRule.set_documented(documented)
    try:
        return lint(source, only="OB02",
                    path="deeplearning4j_tpu/serving/snippet.py")
    finally:
        UndocumentedMetricNameRule.set_documented(None)


def test_ob02_fires_on_undocumented_names():
    findings = _ob02(OB02_BAD, ["serving.requests"])
    assert rules_hit(findings) == {"OB02"}
    assert len(findings) == 3            # increment + gauge + time
    assert any("serving.bogus_counter" in f.message for f in findings)


def test_ob02_quiet_on_documented_and_wildcard_names():
    documented = ["serving.requests", "faults.injected.<site>",
                  "goodput.seconds.<state>", "goodput.fraction"]
    assert not _ob02(OB02_GOOD, documented)


def test_ob02_fstring_prefix_checked_against_wildcards():
    """An f-string's leading literal must overlap a wildcard row; a
    fully documented exact row also covers names built under it."""
    src = """
        from deeplearning4j_tpu.observability import METRICS
        def work(rule):
            METRICS.gauge(f"graftlint.violations.{rule}", 1.0)
    """
    assert not _ob02(src, ["graftlint.violations.<rule>"])
    findings = _ob02(src, ["serving.requests"])
    assert len(findings) == 1
    assert "graftlint.violations." in findings[0].message


def test_ob02_package_tables_cover_the_tree():
    """The committed README/DESIGN tables must cover every name the
    package emits — the zero-baseline contract for this rule."""
    from deeplearning4j_tpu.analysis import Analyzer, active
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    analyzer = Analyzer(rules=[all_rules()["OB02"]], root=repo)
    findings = analyzer.analyze_paths(
        [os.path.join(repo, "deeplearning4j_tpu")])
    assert [f for f in active(findings)] == []
