"""Pipeline-parallelism parity: the GPipe fill/drain schedule over pp must
be arithmetically the SAME training step as the unsharded model.

Mirrors the reference's distributed-without-a-cluster test pattern
(``BaseTestDistributed``): the pp/dp/tp mesh runs on the virtual 8-device
CPU pool, compared leaf-by-leaf against a single-device step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.pipeline import (
    PipelinedTransformerLM, pipeline_param_specs, stack_layers, unstack_layers)
from deeplearning4j_tpu.models.transformer import (
    TransformerConfig, TransformerLM)
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh


def _cfg(n_heads=4, n_layers=4, seq=16):
    return TransformerConfig(
        vocab_size=64, d_model=8 * n_heads, n_heads=n_heads,
        n_layers=n_layers, d_ff=64, max_len=seq, causal=True,
        dtype=jnp.float32, remat=False)


def _data(cfg, batch, seq, seed=0):
    k = jax.random.key(seed)
    tokens = jax.random.randint(k, (batch, seq), 0, cfg.vocab_size)
    return tokens, jnp.roll(tokens, -1, axis=1)


def _single_step(cfg, tokens, targets, tx):
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    new_params, _, loss = step(params, opt, tokens, targets)
    return new_params, float(loss)


def _pipelined_step(cfg, tokens, targets, tx, mesh_spec, n_micro):
    n = mesh_spec.dp * mesh_spec.pp * mesh_spec.sp * mesh_spec.tp
    mesh = make_mesh(mesh_spec, devices=jax.devices()[:n])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=n_micro)
    params = model.place(model.init(jax.random.key(0)))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    new_params, _, loss = step(params, opt, tokens, targets)
    return unstack_layers(jax.device_get(new_params), cfg.n_layers), float(loss)


def _assert_tree_close(a, b, atol):
    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol,
                                   rtol=1e-4)


def test_pp2_parity_with_single_device():
    """pp=2 alone: fill/drain over 2 stages == unsharded step."""
    cfg = _cfg(n_layers=4)
    tokens, targets = _data(cfg, batch=8, seq=16)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))
    ref_params, ref_loss = _single_step(cfg, tokens, targets, tx)
    pp_params, pp_loss = _pipelined_step(
        cfg, tokens, targets, tx, MeshSpec(dp=1, pp=2, sp=1, tp=1), n_micro=4)
    assert abs(ref_loss - pp_loss) < 1e-5
    _assert_tree_close(ref_params, pp_params, atol=1e-5)


def test_pp2_dp2_tp2_parity_with_single_device():
    """The full composed mesh (dp2·pp2·tp2 on 8 devices) == unsharded step."""
    cfg = _cfg(n_heads=4, n_layers=2)
    tokens, targets = _data(cfg, batch=8, seq=16)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))
    ref_params, ref_loss = _single_step(cfg, tokens, targets, tx)
    pp_params, pp_loss = _pipelined_step(
        cfg, tokens, targets, tx, MeshSpec(dp=2, pp=2, sp=1, tp=2), n_micro=2)
    assert abs(ref_loss - pp_loss) < 1e-5
    _assert_tree_close(ref_params, pp_params, atol=1e-5)


def test_pp2_sp2_parity_with_single_device():
    """pp composed with ring-attention sequence parallelism."""
    cfg = _cfg(n_layers=2)
    tokens, targets = _data(cfg, batch=4, seq=16)
    tx = T.sgd_lr(1e-2)
    ref_params, ref_loss = _single_step(cfg, tokens, targets, tx)
    pp_params, pp_loss = _pipelined_step(
        cfg, tokens, targets, tx, MeshSpec(dp=2, pp=2, sp=2, tp=1), n_micro=2)
    assert abs(ref_loss - pp_loss) < 1e-5
    _assert_tree_close(ref_params, pp_params, atol=1e-5)


def test_pipeline_training_reduces_loss():
    """A few pipelined steps actually learn (loss decreases)."""
    cfg = _cfg(n_layers=2)
    tokens, targets = _data(cfg, batch=8, seq=16)
    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=2),
                     devices=jax.devices()[:8])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=4)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(5e-2))
    params = model.place(model.init(jax.random.key(0)))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)
    losses = []
    for _ in range(8):
        params, opt, loss = step(params, opt, tokens, targets)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, losses


def test_pp2_finetune_parity_with_single_device():
    """Classifier fine-tune through the pipeline (VERDICT r3 #5): pp=2
    fine-tune step == unsharded fine-tune step, loss and all grads/params
    (backbone AND head)."""
    cfg = _cfg(n_layers=4, seq=16)
    tokens, _ = _data(cfg, batch=8, seq=16)
    labels = jax.random.randint(jax.random.key(7), (8,), 0, 3)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(1e-2))

    ref_model = TransformerLM(cfg)
    ref_tree = ref_model.init_finetune(jax.random.key(0), n_classes=3)
    ref_opt = ref_model.init_opt(ref_tree, tx)
    ref_step = ref_model.build_finetune_step(tx)
    ref_tree, _, ref_loss = ref_step(ref_tree, ref_opt, tokens, labels)

    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=2),
                     devices=jax.devices()[:8])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    tree = model.init_finetune(jax.random.key(0), n_classes=3)
    opt = model.init_opt(tree, tx)
    step = model.build_finetune_step(tx)
    tree, _, loss = step(tree, opt, tokens, labels)

    assert abs(float(ref_loss) - float(loss)) < 1e-5
    got = jax.device_get(tree)
    got["backbone"] = unstack_layers(got["backbone"], cfg.n_layers)
    _assert_tree_close(ref_tree, got, atol=1e-5)


def test_pp_forward_matches_single_device():
    """Stacked-layout forward (logits) through the pipeline == TransformerLM
    forward, replicated to every pp rank."""
    cfg = _cfg(n_layers=2)
    tokens, _ = _data(cfg, batch=4, seq=16)
    ref = TransformerLM(cfg)
    params = ref.init(jax.random.key(0))
    want = ref.forward(params, tokens)

    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=1),
                     devices=jax.devices()[:4])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    pp_params = model.place(stack_layers(params))
    got = model.forward(pp_params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=1e-3)


def test_pp2_finetune_reduces_loss_via_fit():
    """The inherited fit() convenience loop works with the pp layout."""
    cfg = _cfg(n_layers=2)
    tokens, _ = _data(cfg, batch=8, seq=16)
    labels = jax.random.randint(jax.random.key(3), (8,), 0, 2)
    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=1),
                     devices=jax.devices()[:4])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    tree = model.init_finetune(jax.random.key(0), n_classes=2)
    tx = T.chain(T.momentum(0.9), T.sgd_lr(5e-2))
    opt = model.init_opt(tree, tx)
    tree, opt, losses = model.fit(tree, opt, [(tokens, labels)], tx=tx,
                                  epochs=8, finetune=True)
    assert losses[-1] < losses[0], losses


def test_stack_unstack_roundtrip():
    cfg = _cfg()
    params = TransformerLM(cfg).init(jax.random.key(0))
    rt = unstack_layers(stack_layers(params), cfg.n_layers)
    _assert_tree_close(params, rt, atol=0)


def test_layers_not_divisible_by_pp_rejected():
    cfg = _cfg(n_layers=3)
    mesh = make_mesh(MeshSpec(dp=4, pp=2, sp=1, tp=1),
                     devices=jax.devices()[:8])
    with pytest.raises(AssertionError):
        PipelinedTransformerLM(cfg, mesh)


def test_pp2_dp2_zero1_matches_replicated_pipelined_step():
    """ZeRO-1 composed with pp: dp-sharded optimizer state with a pp row
    dimension on stage-sharded leaves computes the SAME training math as
    the replicated pipelined step — and really is 1/n_dp per (pp, dp)
    rank."""
    cfg = _cfg(n_heads=4, n_layers=2)
    tokens, targets = _data(cfg, batch=8, seq=16)
    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=2),
                     devices=jax.devices()[:8])
    copy = lambda t: jax.tree_util.tree_map(jnp.array, t)
    p_init = PipelinedTransformerLM(cfg, mesh, n_micro=2).init(
        jax.random.key(0))

    def tx():
        return T.adamw(0.01)

    # replicated-state pipelined baseline
    model0 = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    p0 = model0.place(copy(p_init))
    o0 = model0.init_opt(p0, tx())
    step0 = model0.build_train_step(tx())
    for _ in range(2):
        p0, o0, loss0 = step0(p0, o0, tokens, targets)

    # zero1 pipelined
    model1 = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    p1 = model1.place(copy(p_init))
    o1 = model1.init_opt_zero1(p1, tx())
    step1 = model1.build_train_step(tx(), zero1=True)
    for _ in range(2):
        p1, o1, loss1 = step1(p1, o1, tokens, targets)

    np.testing.assert_allclose(float(loss1), float(loss0), rtol=2e-4)
    for a, b in zip(jax.tree.leaves(p1), jax.tree.leaves(p0)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

    # every adam moment leaf's addressable shard covers 1/dp of its last
    # dim, and stage-sharded leaves carry the pp row dimension
    stacked_rows = {2}  # n_pp
    mu_leaves = jax.tree.leaves(o1[1])
    assert any(x.shape[0] in stacked_rows or x.shape[0] == 4  # pp, pp*tp
               for x in mu_leaves if x.ndim == 2)
    for x in mu_leaves:
        if x.ndim != 2:
            continue
        shard = next(iter(x.addressable_shards))
        assert shard.data.shape[1] * 2 == x.shape[1]  # dp=2 sharding


def test_pp_zero1_checkpoint_resume_parity(tmp_path):
    """The pp-row ZeRO-1 optimizer state survives a host checkpoint
    roundtrip: save mid-training, 'restart' into a fresh model, place with
    opt_specs_zero1, and the resumed trajectory matches the uninterrupted
    one leaf for leaf."""
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager

    cfg = _cfg(n_heads=4, n_layers=2)
    tokens, targets = _data(cfg, batch=8, seq=16)
    mesh = make_mesh(MeshSpec(dp=2, pp=2, sp=1, tp=1),
                     devices=jax.devices()[:4])

    def tx():
        return T.adamw(0.01)

    model = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    params = model.place(model.init(jax.random.key(0)))
    opt = model.init_opt_zero1(params, tx())
    step = model.build_train_step(tx(), zero1=True)
    params, opt, _ = step(params, opt, tokens, targets)

    mgr = CheckpointManager(tmp_path)
    mgr.save(1, jax.device_get(params), jax.device_get(opt))

    # uninterrupted reference: two more steps
    ref_params = params
    ref_opt = opt
    for _ in range(2):
        ref_params, ref_opt, _ = step(ref_params, ref_opt, tokens, targets)

    # "restart": fresh model instance, restore from host arrays (restore
    # only needs tree structure + leaf shapes, so host-side zero templates
    # suffice — no device placement before restore)
    model2 = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    tmpl_p = jax.device_get(model2.init(jax.random.key(0)))
    z1_tmpl, _ = model2._z1_template_and_specs(tmpl_p, model2._specs())
    tmpl_o = jax.device_get((jnp.zeros((), jnp.int32), tx().init(z1_tmpl)))
    restored = mgr.restore(tmpl_p, tmpl_o)
    assert restored["step"] == 1
    p2 = model2.place(restored["params"])
    o2 = model2.place(restored["tstate"], model2.opt_specs_zero1(tx()))
    step2 = model2.build_train_step(tx(), zero1=True)
    for _ in range(2):
        p2, o2, _ = step2(p2, o2, tokens, targets)

    for a, b in zip(jax.tree.leaves(p2), jax.tree.leaves(ref_params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_pipelined_decode_guards_point_to_unstack():
    """sample/beam_search/score on the stacked layout fail with a clear
    pointer to the unstack interchange instead of a shape error deep in
    forward_local — and the suggested path actually works."""
    cfg = _cfg(n_layers=2)
    mesh = make_mesh(MeshSpec(dp=1, pp=2, sp=1, tp=1), devices=jax.devices()[:2])
    model = PipelinedTransformerLM(cfg, mesh, n_micro=2)
    params = model.place(model.init(jax.random.key(0)))
    for fn in (model.sample, model.beam_search, model.score):
        with pytest.raises(NotImplementedError, match="unstack"):
            fn(params, [1, 2], 4)

    solo = TransformerLM(cfg)
    flat = unstack_layers(jax.device_get(params), cfg.n_layers)
    out = solo.sample(flat, [1, 2], 4, temperature=0.0)
    assert len(out) == 6
