#!/bin/bash
# One-shot TPU measurement battery (round 5). Run when the relay is up (check:
# `python -c "import socket;s=socket.socket();print(s.connect_ex(('127.0.0.1',8080)))"`
# prints 0). Writes TUNE_r05.jsonl + trace/BENCH artifacts; serialize TPU
# access — never run two TPU processes at once.
set -u
cd "$(dirname "$0")/.."
exec > >(tee BATTERY_r05.log) 2>&1     # the battery writes its own log

echo "== flash validation + post-change sweep =="
timeout 1500 python tools/tune_tpu.py post 2>/dev/null | tee TUNE_r05.jsonl

echo "== BERT step-time ablation =="
timeout 900 python tools/tune_tpu.py ablate 2>/dev/null | tee -a TUNE_r05.jsonl

echo "== ResNet step ablation (bn_fold variant) =="
timeout 900 python tools/tune_tpu.py resnet_ablate 2>/dev/null | tee -a TUNE_r05.jsonl

echo "== ResNet XPlane trace (top-op table) =="
timeout 900 python tools/tune_tpu.py resnet_trace 2>/dev/null | tee -a TUNE_r05.jsonl

echo "== full benchmark =="
timeout 1800 python bench.py 2>bench_stderr.log
rc=$?
echo "bench rc=$rc (stderr tail below)"
tail -3 bench_stderr.log
rm -f bench_stderr.log

echo
echo "Next: python tools/summarize_tune.py  (markdown table + the flash/"
echo "bn_fold verdicts). bench.py ADOPTS winners from TUNE_r05.jsonl"
echo "automatically (_pick_attention/_pick_bn_fold) — no manual flip needed;"
echo "commit TUNE_r05.jsonl + BATTERY_r05.log + LAST_VALID_TPU_BENCH.json"
echo "and paste the summary into BASELINE.md's measured table."
