"""Filesystem-backed scaleout state plane — the cross-PROCESS analog of
``scaleout.StateTracker``.

Capability parity targets in the reference:

- ``statetracker/updatesaver/LocalFileUpdateSaver.java:20`` — per-worker
  param updates spilled to local files so the data grid stays small and
  updates survive restarts.  Here: :class:`FileUpdateSaver` (and the
  tracker's ``add_update`` routes through it — updates live on disk, never
  in a master-process dict).
- ``statetracker/workretriever/LocalWorkRetriever.java:19`` — per-worker job
  persistence for re-retrieval after a restart: :class:`FileWorkRetriever`.
- ``BaseHazelCastStateTracker.java:31,61-76`` — the shared blackboard
  (workers/heartbeats/jobs/updates/current model) reachable from every
  process.  Hazelcast's role (an in-memory grid shared by JVMs) maps to a
  shared directory of atomically-replaced pickle files: each worker process
  writes only its own files, the master is the only writer of the shared
  model, so no cross-process locking is needed beyond atomic rename.

Used by :class:`~.procrunner.ProcessDistributedRunner`, whose workers are
real OS processes (SIGKILL-able) rather than threads.
"""

from __future__ import annotations

import os
import pickle
import time
from pathlib import Path
from typing import Any, Callable

__all__ = ["FileUpdateSaver", "FileWorkRetriever", "FileStateTracker"]


def _atomic_pickle(path: Path, value: Any) -> None:
    tmp = path.with_name(path.name + f".tmp{os.getpid()}")
    with open(tmp, "wb") as f:
        pickle.dump(value, f)
    tmp.replace(path)


def _load_pickle(path: Path, default: Any = None) -> Any:
    try:
        with open(path, "rb") as f:
            return pickle.load(f)
    except (FileNotFoundError, EOFError, pickle.UnpicklingError):
        # mid-replace or already removed — treat as absent
        return default


class FileUpdateSaver:
    """Per-worker update spill (``LocalFileUpdateSaver.java:20``): one
    pickle per worker id, atomically replaced."""

    def __init__(self, directory: Path | str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save(self, worker_id: str, update: Any) -> None:
        _atomic_pickle(self.dir / worker_id, update)

    def load(self, worker_id: str) -> Any:
        return _load_pickle(self.dir / worker_id)

    def ids(self) -> list[str]:
        return sorted(p.name for p in self.dir.iterdir()
                      if ".tmp" not in p.name)

    def clear(self, worker_id: str | None = None) -> None:
        for p in list(self.dir.iterdir()):
            if ".tmp" in p.name:
                continue
            if worker_id is None or p.name == worker_id:
                p.unlink(missing_ok=True)


class FileWorkRetriever:
    """Per-worker job persistence (``LocalWorkRetriever.java:19``): the job
    most recently assigned to a worker, re-retrievable after restart."""

    def __init__(self, directory: Path | str):
        self.dir = Path(directory)
        self.dir.mkdir(parents=True, exist_ok=True)

    def save(self, worker_id: str, job: Any) -> None:
        _atomic_pickle(self.dir / worker_id, job)

    def load(self, worker_id: str) -> Any:
        return _load_pickle(self.dir / worker_id)


class FileStateTracker:
    """Cross-process StateTracker: same surface as
    ``scaleout.StateTracker``, state under one shared directory.

    Write discipline (lock-free by construction): workers write only
    ``heartbeats/<self>``, ``updates/<self>``, and remove ``jobs/<self>``;
    the master writes ``jobs/*``, ``current``, ``DONE``, and worker
    registration.  Every write is tmp-file + atomic rename.
    """

    def __init__(self, directory: Path | str):
        self.dir = Path(directory)
        for sub in ("workers", "heartbeats", "jobs", "updates", "saved",
                    "replicate", "disabled", "counters", "boot",
                    "failed", "quarantined"):
            (self.dir / sub).mkdir(parents=True, exist_ok=True)
        self.update_saver = FileUpdateSaver(self.dir / "updates")
        self.work_retriever = FileWorkRetriever(self.dir / "saved")
        # master-process-local listeners (parity seam; fires on local adds)
        self.update_listeners: list[Callable[[Any], None]] = []

    # -- workers --------------------------------------------------------
    def add_worker(self, worker_id: str) -> None:
        (self.dir / "workers" / worker_id).touch()
        self.heartbeat(worker_id)

    def remove_worker(self, worker_id: str) -> None:
        for sub in ("workers", "heartbeats", "jobs", "disabled"):
            (self.dir / sub / worker_id).unlink(missing_ok=True)

    def workers(self) -> list[str]:
        return sorted(p.name for p in (self.dir / "workers").iterdir())

    def enable_worker(self, worker_id: str) -> None:
        (self.dir / "disabled" / worker_id).unlink(missing_ok=True)

    def disable_worker(self, worker_id: str) -> None:
        (self.dir / "disabled" / worker_id).touch()

    def is_enabled(self, worker_id: str) -> bool:
        return ((self.dir / "workers" / worker_id).exists()
                and not (self.dir / "disabled" / worker_id).exists())

    # -- heartbeats / failure detection ---------------------------------
    def heartbeat(self, worker_id: str) -> None:
        p = self.dir / "heartbeats" / worker_id
        p.touch()
        os.utime(p)

    def last_heartbeat(self, worker_id: str) -> float:
        try:
            return (self.dir / "heartbeats" / worker_id).stat().st_mtime
        except FileNotFoundError:
            return 0.0

    def evict_stale(self, timeout_s: float = 120.0):
        """(evicted ids, orphaned jobs) — ``MasterActor.java:123-153``."""
        now = time.time()
        evicted, orphans = [], []
        for w in self.workers():
            if now - self.last_heartbeat(w) > timeout_s:
                evicted.append(w)
                job = self.job_for(w)
                if job is not None:
                    orphans.append(job)
                self.remove_worker(w)
        return evicted, orphans

    # -- jobs -----------------------------------------------------------
    def add_job(self, job) -> None:
        _atomic_pickle(self.dir / "jobs" / job.worker_id, job)
        self.work_retriever.save(job.worker_id, job)

    def job_for(self, worker_id: str):
        return _load_pickle(self.dir / "jobs" / worker_id)

    def clear_job(self, worker_id: str) -> None:
        (self.dir / "jobs" / worker_id).unlink(missing_ok=True)

    def current_jobs(self) -> list:
        out = []
        for p in (self.dir / "jobs").iterdir():
            if ".tmp" in p.name:
                continue
            job = _load_pickle(p)
            if job is not None:
                out.append(job)
        return out

    def load_for_worker(self, worker_id: str):
        return self.work_retriever.load(worker_id)

    # -- failures / quarantine ------------------------------------------
    def record_failure(self, worker_id: str, job, error: str = "") -> None:
        """Prompt failure report (``scaleout.StateTracker`` parity).

        Write order matters for the master's finish check: the failed
        record must exist BEFORE the in-flight job file disappears, so
        the master can never observe 'no jobs, no failures' mid-report.
        """
        job.last_error = error
        name = f"{worker_id}.{os.getpid()}.{time.monotonic_ns()}"
        _atomic_pickle(self.dir / "failed" / name, (worker_id, job, error))
        self.clear_job(worker_id)

    def take_failed(self) -> list:
        out = []
        for p in sorted((self.dir / "failed").iterdir()):
            if ".tmp" in p.name:
                continue
            rec = _load_pickle(p)
            if rec is not None:
                out.append(rec)
            p.unlink(missing_ok=True)
        return out

    def has_failures(self) -> bool:
        return any(".tmp" not in p.name
                   for p in (self.dir / "failed").iterdir())

    def quarantine(self, job) -> None:
        name = f"{os.getpid()}.{time.monotonic_ns()}"
        _atomic_pickle(self.dir / "quarantined" / name, job)

    def quarantined(self) -> list:
        out = []
        for p in sorted((self.dir / "quarantined").iterdir()):
            if ".tmp" in p.name:
                continue
            job = _load_pickle(p)
            if job is not None:
                out.append(job)
        return out

    # -- updates (file-backed spill) ------------------------------------
    def add_update(self, worker_id: str, update: Any) -> None:
        self.update_saver.save(worker_id, update)
        for listener in list(self.update_listeners):
            listener(update)

    def updates(self) -> dict[str, Any]:
        return {w: self.update_saver.load(w) for w in self.update_saver.ids()}

    def clear_updates(self) -> None:
        self.update_saver.clear()

    # -- counters -------------------------------------------------------
    def increment(self, key: str, by: float = 1.0) -> None:
        # single-writer per key is the expected pattern (master-side);
        # read-modify-write through atomic replace
        self.counter_set(key, self.count(key) + by)

    def counter_set(self, key: str, value: float) -> None:
        _atomic_pickle(self.dir / "counters" / key, float(value))

    def count(self, key: str) -> float:
        return float(_load_pickle(self.dir / "counters" / key, 0.0))

    # -- current model / replication ------------------------------------
    def set_current(self, value: Any) -> None:
        _atomic_pickle(self.dir / "current", value)
        for w in self.workers():
            (self.dir / "replicate" / w).touch()

    def get_current(self) -> Any:
        return _load_pickle(self.dir / "current")

    def add_replicate(self, worker_id: str) -> None:
        (self.dir / "replicate" / worker_id).touch()

    def needs_replicate(self, worker_id: str) -> bool:
        return (self.dir / "replicate" / worker_id).exists()

    def done_replicating(self, worker_id: str) -> None:
        (self.dir / "replicate" / worker_id).unlink(missing_ok=True)

    # -- lifecycle ------------------------------------------------------
    def finish(self) -> None:
        (self.dir / "DONE").touch()

    def reset_done(self) -> None:
        (self.dir / "DONE").unlink(missing_ok=True)

    def is_done(self) -> bool:
        return (self.dir / "DONE").exists()
