"""Train a 2-layer MLP on Iris and evaluate it — the minimum vertical slice.

Mirrors the reference workflow of ``nn/multilayer/MultiLayerTest.java:33-70``
(configure -> init -> fit -> evaluate with F1), re-expressed through this
framework's functional config/builder API.

Run:  python examples/01_iris_mlp.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")   # examples run anywhere; drop for TPU

from deeplearning4j_tpu.datasets import IrisDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import (NeuralNetConfiguration,
                                        OptimizationAlgorithm, list_builder)


def main():
    ds = (IrisDataSetIterator(batch=150).next()
          .normalize_zero_mean_unit_variance().shuffle(seed=42))

    base = NeuralNetConfiguration(
        n_in=4, n_out=3, lr=0.1, momentum=0.9, use_adagrad=True,
        num_iterations=200, activation="tanh",
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT)
    conf = (list_builder(base, 2)
            .hidden_layer_sizes(10)
            .override(1, kind="output", activation="softmax", loss="mcxent")
            .pretrain(False)
            .build())

    net = MultiLayerNetwork(conf)
    net.init(jax.random.key(0))
    net.fit(ds)

    ev = net.evaluate(ds)
    print(ev.stats())
    print(f"F1 = {ev.f1():.3f}")


if __name__ == "__main__":
    main()
