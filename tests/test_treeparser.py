"""Treebank parser tests: CKY structure, glue robustness, grammar
induction, and the RNTN-from-raw-sentences path that VERDICT round 2
required (reference: TreeParser.java:41 getTrees -> Tree -> RNTN)."""

import numpy as np
import pytest

from deeplearning4j_tpu.models.rntn import RNTN
from deeplearning4j_tpu.text.tree import binarize
from deeplearning4j_tpu.text.treeparser import Grammar, TreebankParser


@pytest.fixture(scope="module")
def parser():
    return TreebankParser()


def test_parse_simple_sentence_structure(parser):
    tree = parser.parse_tokens(
        ["the", "quick", "brown", "fox", "jumps", "over", "the", "lazy",
         "dog"])
    assert tree.label == "S"
    np_node, vp_node = tree.children
    assert np_node.label == "NP"
    assert np_node.words() == ["the", "quick", "brown", "fox"]
    assert vp_node.label == "VP"
    assert vp_node.words() == ["jumps", "over", "the", "lazy", "dog"]
    # PP attachment inside the VP
    labels = {t.label for t in vp_node.subtrees()}
    assert "PP" in labels


def test_pp_spans(parser):
    tree = parser.parse_tokens(["the", "dog", "sleeps", "on", "the", "mat"])
    pp = [t for t in tree.subtrees() if t.label == "PP"]
    assert pp and pp[0].span() == (3, 6)
    assert pp[0].words() == ["on", "the", "mat"]


def test_get_trees_segments_sentences(parser):
    trees = parser.get_trees("The cat sleeps. The dog barks loudly.")
    assert len(trees) == 2
    assert all(t.label == "S" for t in trees)


def test_glue_fallback_always_parses(parser):
    # word salad the grammar cannot derive still yields one spanning tree
    tree = parser.parse_tokens(["over", "over", "the", "the", "and"])
    assert sorted(tree.words()) == ["and", "over", "over", "the", "the"]
    assert tree.span() == (0, 5)


def test_single_token(parser):
    tree = parser.parse_tokens(["dog"])
    assert tree.words() == ["dog"]


def test_grammar_induction_roundtrip(parser):
    """Induce a PCFG from parsed trees; the induced grammar parses the
    same sentences into spanning trees with the same yields."""
    texts = ["the quick fox jumps", "she reads a long book",
             "the dog sleeps on the mat"]
    trees = [parser.parse_tokens(t.split()) for t in texts]
    g2 = Grammar.from_trees(trees)
    p2 = TreebankParser(grammar=g2, tagger=parser.tagger)
    for text in texts:
        t2 = p2.parse_tokens(text.split())
        assert t2.words() == text.split()
        assert t2.label == "S"


def test_rntn_trains_from_raw_sentences(parser):
    """The round-2 verdict's done-criterion: RNTN sentiment from RAW
    sentences via the real parser (no right-branching fallback)."""
    pos = ["the happy children play in the warm park",
           "she sings a happy song", "the kind teacher helps the children",
           "we eat sweet honey", "the gentle breeze cools the beach"]
    neg = ["the angry dog barks at the stranger",
           "dark clouds gather above the field", "the sad man walks alone",
           "rain falls on the cold town", "the broken clock stops"]
    trees = []
    for label, sents in ((1, pos), (0, neg)):
        for s in sents:
            t = binarize(parser.parse_tokens(s.split()))
            t.gold_label = label
            trees.append(t)
    model = RNTN(layer_size=8, n_classes=2, max_nodes=64, lr=0.1, seed=0)
    losses = model.fit(trees, epochs=25)
    assert losses[-1] < losses[0], losses
    # root predictions on training sentences: should beat chance clearly
    right = 0
    for t in trees:
        pred = model.predict_tree(t)
        right += int(pred[-1]) == t.gold_label
    assert right / len(trees) >= 0.8, f"{right}/{len(trees)}"
