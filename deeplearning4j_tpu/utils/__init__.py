"""Shared host-side utilities (reference: ``util/*``, ``berkeley/*``)."""

from . import tree_math

__all__ = ["tree_math"]
