"""deeplearning4j_tpu — a TPU-native deep learning framework.

A ground-up JAX/XLA/Pallas re-design with the capability set of early
DeepLearning4J (reference: /root/reference, deeplearning4j-parent
0.0.3.4-SNAPSHOT).  The compute path is functional JAX compiled by XLA onto
the MXU; parallelism is SPMD over `jax.sharding.Mesh` axes with XLA
collectives riding ICI/DCN; host-side runtime pieces (data decode, vocab
builds, prefetch) have native C++ implementations with pure-Python fallbacks.

Top-level namespaces (mirroring the reference's layer map, SURVEY.md §1):

- ``ops``       — L0 tensor/math substrate (the ND4J/JBLAS contract, TPU-native)
- ``nn``        — L1 core NN runtime: configs, layers, MultiLayerNetwork
- ``optimize``  — L2 optimization engine: transforms, solvers, listeners
- ``datasets``  — L3 data layer: DataSet, iterators, fetchers
- ``eval``      — L4 evaluation: confusion-matrix metrics
- ``plot``      — L4 visualization: t-SNE, renderers
- ``clustering``— L4 clustering: k-means, kd/vp/quad trees
- ``parallel``  — L5-7 distributed: mesh, collectives, routers, checkpointing
- ``text``      — L8 NLP: tokenization, vocab, embeddings models
- ``models``    — flagship model zoo (MLP/DBN, LeNet, LSTM, transformer)
- ``utils``     — shared host-side utilities
"""

__version__ = "0.1.0"
