"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh (SURVEY.md §4 implication (c)): the
collectives layer is exercised on one host with
``--xla_force_host_platform_device_count=8``, mirroring the reference's
"distributed-without-a-cluster" pattern (``BaseTestDistributed``).  These env
vars MUST be set before jax initializes, hence this module-level block.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng_np():
    return np.random.default_rng(42)
