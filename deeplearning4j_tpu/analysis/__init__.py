"""graftlint — static JAX/TPU hazard analysis + runtime transfer guards.

Two halves of one contract (DESIGN.md §11):

- **static**: an AST rule engine (``engine.Analyzer``) with six rules for
  the hazards PR 2 removed by hand — host syncs in hot paths (HS01),
  recompile storms (RC01), PRNG key reuse (RNG01), use-after-donate
  (DON01), traced-value branching (TB01), and uninstrumented hot loops
  (HOT02) — plus per-line suppressions and a committed baseline so
  ``python -m tools.graftlint --check`` can gate every PR on *new*
  violations only.
- **runtime**: ``runtime.hot_loop_guard()`` wraps the trainer/bench hot
  loops in ``jax.transfer_guard("disallow")`` so implicit transfers fail
  loudly at the call site (opt out: ``DL4J_TPU_TRANSFER_GUARD=0``),
  ``lockguard.LOCKGUARD`` instruments ``threading`` locks to detect
  lock-order inversions and Eraser-style unguarded shared writes at
  test time (``@pytest.mark.lockguard`` / ``DL4J_TPU_LOCKGUARD=1``), and
  ``shardguard.SHARDGUARD`` diffs the shardings crossing wrapped step
  dispatches against the placed ``NamedSharding``s to catch implicit
  resharding (``@pytest.mark.shardguard`` / ``DL4J_TPU_SHARDGUARD=1``).

The static sharding tier (SH01-SH04, NM01) resolves mesh-axis bindings
interprocedurally in ``sharding.ShardingInfo``; its canonical axis
registry is parsed from ``parallel/mesh.py``.

Results flow through the PR 1 observability layer as
``graftlint.violations.<RULE>`` and ``shardguard.violations.<kind>``
gauges (``report.emit_metrics`` / ``ShardGuard.emit_metrics``).
"""

from .baseline import Baseline
from .core import ACTIVE, BASELINED, SUPPRESSED, Finding, Rule, all_rules
from .engine import Analyzer, active
from .jitinfo import JitInfo, ModuleInfo
from .lockguard import (ENV_LOCKGUARD, LOCKGUARD, LockGuard, Violation,
                        enabled_from_env, lockguard_active)
from .report import emit_metrics, summarize, to_json, to_text
from .runtime import ENV_FLAG, allow_transfers, guard_mode, hot_loop_guard
from .sharding import ShardingInfo, axis_registry, sharding_info
from .shardguard import (ENV_SHARDGUARD, SHARDGUARD, ShardGuard,
                         shardguard_active)

__all__ = [
    "ACTIVE", "Analyzer", "BASELINED", "Baseline", "ENV_FLAG",
    "ENV_LOCKGUARD", "ENV_SHARDGUARD", "Finding", "JitInfo", "LOCKGUARD",
    "LockGuard", "ModuleInfo", "Rule", "SHARDGUARD", "SUPPRESSED",
    "ShardGuard", "ShardingInfo", "Violation", "active", "all_rules",
    "allow_transfers", "axis_registry", "emit_metrics", "enabled_from_env",
    "guard_mode", "hot_loop_guard", "lockguard_active", "sharding_info",
    "shardguard_active", "summarize", "to_json", "to_text",
]
