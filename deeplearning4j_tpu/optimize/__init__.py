"""L2 — optimization engine (reference: ``deeplearning4j-core/.../optimize``).

Gradient transforms (optax-style chain mirroring ``BaseOptimizer``'s
post-processing), solvers (GD/CG/LBFGS/line-search/Hessian-free), and the
listener/termination/early-stopping SPI.
"""

from . import api, solvers, transforms
from .api import (
    ComposableIterationListener,
    DefaultStepFunction,
    EpsTermination,
    GradientStepFunction,
    NegativeDefaultStepFunction,
    Norm2Termination,
    OutputLayerTrainingEvaluator,
    ScoreIterationListener,
    TimingListener,
    ZeroDirection,
)
from .solvers import (
    BackTrackLineSearch,
    BaseOptimizer,
    ConjugateGradient,
    GradientAscent,
    IterationGradientDescent,
    LBFGS,
    OptimizeResult,
    Solver,
    StochasticHessianFree,
)
from .transforms import GradientTransform, apply_updates, chain, from_conf

__all__ = [
    "api", "solvers", "transforms",
    "ComposableIterationListener", "DefaultStepFunction", "EpsTermination",
    "GradientStepFunction", "NegativeDefaultStepFunction", "Norm2Termination",
    "OutputLayerTrainingEvaluator", "ScoreIterationListener", "TimingListener",
    "ZeroDirection",
    "BackTrackLineSearch", "BaseOptimizer", "ConjugateGradient",
    "GradientAscent", "IterationGradientDescent", "LBFGS", "OptimizeResult",
    "Solver", "StochasticHessianFree",
    "GradientTransform", "apply_updates", "chain", "from_conf",
]
