"""Elastic tier (ISSUE 13): resharding restore + live topology resize.

The contract under test, per DESIGN.md §21:

- a checkpoint written at dp-width N restores onto width M for EVERY
  zero stage — natural-layout leaves pass through width-agnostic, flat
  padded ``P('dp')`` leaves are re-split host-side EXACTLY (bitwise:
  slice + reshape, no renormalization);
- a cross-width restore without ``reshard=True`` raises
  :class:`MeshMismatchError` naming both widths — never a raw shape
  error deep in ``zero.py`` (the silent-failure regression, satellite 1);
- the supervisor survives losing chips mid-run (``mesh.shrink`` ->
  ``DeviceLossError`` -> rebuild from survivors -> reshard-resume) and
  gaining them back (``mesh.grow`` -> drain -> rebuild larger), emitting
  ``mesh_resize`` flight bundles and ``elastic.*`` gauges;
- the scaleout wave shrinks when a worker stays dead past its respawn
  budget and grows on :meth:`DistributedRunner.register_worker`.

Loss parity across widths is a WINDOW (|Δ| <= 1e-5), not bitwise: psum
association order changes with dp width.  Same-width comparisons stay
bitwise.
"""

import pathlib
import threading

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.observability import FLIGHTREC, METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel import (CheckpointManager,
                                         DataParallelTrainer, elastic_mesh)
from deeplearning4j_tpu.parallel.mesh import (MeshMismatchError, grow_mesh,
                                              shrink_mesh)
from deeplearning4j_tpu.parallel.scaleout import (CollectionJobIterator,
                                                  DistributedRunner,
                                                  StateTracker)
from deeplearning4j_tpu.parallel.zero import (flat_padded_size,
                                              host_flat_to_natural,
                                              host_natural_to_flat)
from deeplearning4j_tpu.resilience import (FAULTS, DeviceLossError, FaultSpec,
                                           RetryPolicy, TrainingSupervisor,
                                           inject_faults)

D = 16
BATCH = 32          # divisible by every dp width in the matrix


@pytest.fixture(autouse=True)
def _disarm_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


def _data(n_batches=6, seed=0):
    rng = np.random.default_rng(seed)
    w_true = rng.normal(size=(D, 1)).astype(np.float32)
    xs = rng.normal(size=(n_batches * BATCH, D)).astype(np.float32)
    ys = (xs @ w_true).astype(np.float32)
    return [(xs[i * BATCH:(i + 1) * BATCH], ys[i * BATCH:(i + 1) * BATCH])
            for i in range(n_batches)]


def _loss_fn(p, xb, yb, key=None):
    return ((xb @ p["w"] - yb) ** 2).mean()


def _trainer(width, stage):
    return DataParallelTrainer(_loss_fn, T.adam(1e-3),
                               mesh=elastic_mesh(jax.devices()[:width]),
                               zero_stage=stage)


def _params():
    return {"w": np.zeros((D, 1), np.float32)}


def _host_params(mgr, step=None):
    """The checkpoint's params gathered to host-natural numpy (the
    width-agnostic on-disk view both sides of a matrix cell share)."""
    out = mgr.restore(_params(), step=step)
    return np.asarray(out["params"]["w"])


# --------------------------------------------------------------- mesh helpers

def test_elastic_mesh_shrink_grow_roundtrip():
    devs = jax.devices()[:8]
    mesh = elastic_mesh(devs)
    assert mesh.devices.size == 8
    smaller = shrink_mesh(mesh, devs[-2:])
    assert smaller.devices.size == 6
    assert all(d.id not in {x.id for x in devs[-2:]}
               for d in smaller.devices.flat)
    back = grow_mesh(smaller, devs[-2:])
    assert back.devices.size == 8
    # idempotent: re-admitting a device already present is a no-op
    assert grow_mesh(back, devs[-2:]).devices.size == 8
    with pytest.raises(ValueError):
        elastic_mesh([])


def test_flat_pad_roundtrip_is_exact():
    rng = np.random.default_rng(1)
    for shape in ((5,), (3, 7), (2, 3, 4), (1,)):
        nat = rng.normal(size=shape).astype(np.float32)
        for dp in (1, 2, 3, 4, 8):
            flat = host_natural_to_flat(nat, dp)
            assert flat.shape == (flat_padded_size(nat.size, dp),)
            np.testing.assert_array_equal(
                host_flat_to_natural(flat, shape, dp), nat)
    with pytest.raises(ValueError):
        host_flat_to_natural(np.zeros(5, np.float32), (3,), 2)


# ------------------------------------------------- MeshMismatchError contract

@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_cross_width_restore_without_flag_raises_per_stage(tmp_path, stage):
    """Satellite 1 regression: reshard=False across widths must raise
    MeshMismatchError naming both widths — for every stage, including the
    flat layout whose padded leaves would otherwise shape-error (or, on a
    padding coincidence, silently corrupt)."""
    src = _trainer(4, stage)
    state = src.init_state(_params())
    for x, y in _data(2):
        state, _ = src.step(state, x, y)
    mgr = CheckpointManager(tmp_path, keep=5)
    src.checkpoint(state, mgr)
    with pytest.raises(MeshMismatchError) as ei:
        mgr.restore(_params(), step=None, reshard=False, dp_width=2)
    msg = str(ei.value)
    assert "dp=4" in msg and "dp=2" in msg and "reshard=True" in msg
    # trainer-level: a width-2 trainer refuses too when told not to reshard
    dst = _trainer(2, stage)
    with pytest.raises(MeshMismatchError):
        dst.restore(dst.init_state(_params()), mgr, reshard=False)


def test_flat_layout_mismatch_is_typed_not_shape_error(tmp_path):
    """The historical silent failure: a flat padded leaf saved at dp=4
    fed to a dp=2 template used to reach jnp.asarray unchecked.  It must
    now surface as MeshMismatchError, whatever the leaf sizes."""
    src = _trainer(4, 2)
    state = src.init_state(_params())
    x, y = _data(1)[0]
    state, _ = src.step(state, x, y)
    mgr = CheckpointManager(tmp_path, keep=5)
    src.checkpoint(state, mgr, layout="flat")
    dst = _trainer(2, 2)
    with pytest.raises(MeshMismatchError):
        dst.restore(dst.init_state(_params()), mgr, reshard=False)


def test_same_width_flat_restore_is_layout_normalization(tmp_path):
    """flat -> natural at the SAME width is not a reshard: it must be
    allowed with reshard=False (the flag gates topology changes only)."""
    src = _trainer(4, 2)
    state = src.init_state(_params())
    x, y = _data(1)[0]
    state, _ = src.step(state, x, y)
    nat_dir, flat_dir = tmp_path / "nat", tmp_path / "flat"
    src.checkpoint(state, CheckpointManager(nat_dir, keep=5))
    src.checkpoint(state, CheckpointManager(flat_dir, keep=5),
                   layout="flat")
    dst = _trainer(4, 2)
    restored = dst.restore(dst.init_state(_params()),
                           CheckpointManager(flat_dir), reshard=False)
    out_dir = tmp_path / "out"
    dst.checkpoint(restored, CheckpointManager(out_dir, keep=5))
    np.testing.assert_array_equal(_host_params(CheckpointManager(out_dir)),
                                  _host_params(CheckpointManager(nat_dir)))


def test_pre_topology_checkpoint_still_restores(tmp_path):
    """Back-compat: a checkpoint saved before the topology stamp existed
    (no dp_width/zero_stage meta) restores without the flag — there is no
    stamped width to mismatch against."""
    mgr = CheckpointManager(tmp_path, keep=5)
    mgr.save(1, {"w": np.full((D, 1), 2.0, np.float32)})
    out = mgr.restore(_params(), dp_width=2)
    assert out["saved_dp"] is None and not out["resharded"]
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.full((D, 1), 2.0, np.float32))


# --------------------------------------------------------------- interop matrix

def test_interop_matrix_restore_is_bitwise_and_step_parity(tmp_path):
    """Satellite 3: every (save_dp, save_stage) cell restores at a
    DIFFERENT width and stage with bitwise-identical gathered params, and
    the first post-restore step matches the fixed-seed uninterrupted run
    — bitwise at the same width, inside the 1e-5 window across widths."""
    data = _data(3)
    next_x, next_y = _data(1, seed=9)[0]
    other_width = {1: 2, 2: 4, 4: 1}
    max_cross = 0.0
    for save_stage in (0, 1, 2, 3):
        for save_dp in (1, 2, 4):
            restore_dp = other_width[save_dp]
            restore_stage = (save_stage + 2) % 4
            mgr = CheckpointManager(tmp_path / f"{save_stage}-{save_dp}",
                                    keep=5)
            src = _trainer(save_dp, save_stage)
            state = src.init_state(_params())
            for x, y in data:
                state, _ = src.step(state, x, y)
            src.checkpoint(state, mgr)
            _, ref_lazy = src.step(state, next_x, next_y)  # uninterrupted
            ref_loss = float(ref_lazy)

            dst = _trainer(restore_dp, restore_stage)
            restored = dst.restore(dst.init_state(_params()), mgr,
                                   reshard=True)
            assert int(restored.step) == int(state.step)
            out_mgr = CheckpointManager(
                tmp_path / f"{save_stage}-{save_dp}-out", keep=5)
            dst.checkpoint(restored, out_mgr)
            np.testing.assert_array_equal(
                _host_params(out_mgr), _host_params(mgr),
                err_msg=(f"save (dp={save_dp}, stage={save_stage}) -> "
                         f"restore (dp={restore_dp}, stage={restore_stage})"))
            _, lazy = dst.step(restored, next_x, next_y)
            max_cross = max(max_cross, abs(float(lazy) - ref_loss))
    assert max_cross <= 1e-5, f"cross-width window {max_cross:.2e}"


def test_flat_layout_cross_width_restore_is_exact(tmp_path):
    """Flat padded P('dp') leaves saved as-is at dp=4 re-split onto dp=2
    bitwise — the host-side slice+reshape path, per sharded stage."""
    x, y = _data(1)[0]
    for stage in (1, 2, 3):
        src = _trainer(4, stage)
        state = src.init_state(_params())
        state, _ = src.step(state, x, y)
        nat_dir = tmp_path / f"nat{stage}"
        flat_dir = tmp_path / f"flat{stage}"
        src.checkpoint(state, CheckpointManager(nat_dir, keep=5))
        src.checkpoint(state, CheckpointManager(flat_dir, keep=5),
                       layout="flat")
        dst = _trainer(2, stage)
        restored = dst.restore(dst.init_state(_params()),
                               CheckpointManager(flat_dir), reshard=True)
        out_dir = tmp_path / f"out{stage}"
        dst.checkpoint(restored, CheckpointManager(out_dir, keep=5))
        np.testing.assert_array_equal(
            _host_params(CheckpointManager(out_dir)),
            _host_params(CheckpointManager(nat_dir)),
            err_msg=f"stage {stage}")


def test_reshard_restore_is_counted_and_gauged(tmp_path):
    src = _trainer(4, 1)
    state = src.init_state(_params())
    x, y = _data(1)[0]
    state, _ = src.step(state, x, y)
    mgr = CheckpointManager(tmp_path, keep=5)
    src.checkpoint(state, mgr)
    METRICS.reset()
    dst = _trainer(2, 1)
    dst.restore(dst.init_state(_params()), mgr, reshard=True)
    snap = METRICS.snapshot()
    assert snap["counters"].get("checkpoint.reshards", 0) == 1
    assert snap["gauges"].get("elastic.reshard_seconds", 0.0) > 0.0


# --------------------------------------------------------- live supervisor

class _Batch:
    def __init__(self, x, y):
        self.features, self.labels = x, y


def _sup_fixture(tmp_path):
    data = [_Batch(x, y) for x, y in _data(8)]
    stage = 1

    def factory(devices):
        devs = devices if devices is not None else jax.devices()[:8]
        return DataParallelTrainer(_loss_fn, T.adam(1e-3),
                                   mesh=elastic_mesh(devs),
                                   zero_stage=stage)

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    sup = TrainingSupervisor(mgr, RetryPolicy(max_attempts=6,
                                              backoff_base_s=0.01),
                             install_signal_handlers=False)
    return factory, mgr, sup, data


def test_supervisor_survives_device_loss(tmp_path):
    """Tentpole: mesh.shrink kills 2 chips mid-run; the supervisor
    rebuilds from the 6 survivors, reshard-restores, and completes every
    step inside the documented window — with the mesh_resize bundle and
    elastic gauges on record."""
    factory, _, sup, data = _sup_fixture(tmp_path)
    ref_trainer = factory(None)
    _, ref_losses = ref_trainer.fit(ref_trainer.init_state(_params()), data,
                                    epochs=1)
    old_dump = FLIGHTREC.dump_dir
    FLIGHTREC.dump_dir = pathlib.Path(tmp_path / "rec")
    try:
        METRICS.reset()
        with inject_faults(FaultSpec("mesh.shrink", at_step=4, kind="2"),
                           seed=0):
            state, _ = sup.fit(factory, _params(), data, epochs=1,
                               checkpoint_every=2)
        bundles = list(FLIGHTREC.dump_dir.glob("flightrec-mesh_resize-*"))
    finally:
        FLIGHTREC.dump_dir = old_dump
    assert int(state.step) == len(data)
    assert sup.report.resizes == 1 and sup.report.mesh_sizes == [6]
    assert int(sup.trainer.mesh.devices.size) == 6
    assert bundles, "device loss emitted no mesh_resize flight bundle"
    window = max(abs(v - ref_losses[s - 1])
                 for s, v in sup.report.losses_by_step.items())
    assert window <= 1e-5, f"elastic window {window:.2e}"
    snap = METRICS.snapshot()
    assert snap["counters"]["resilience.device_losses"] == 1
    assert snap["counters"]["elastic.mesh_resizes"] == 1
    assert snap["gauges"]["elastic.mesh_size"] == 6
    assert snap["gauges"]["elastic.resizes_total"] == 1


def test_supervisor_shrinks_then_grows_back(tmp_path):
    factory, _, sup, data = _sup_fixture(tmp_path)
    with inject_faults(FaultSpec("mesh.shrink", at_step=3, kind="2"),
                       FaultSpec("mesh.grow", at_step=5), seed=0):
        state, _ = sup.fit(factory, _params(), data, epochs=1,
                           checkpoint_every=2)
    assert int(state.step) == len(data)
    assert sup.report.mesh_sizes == [6, 8]
    assert int(sup.trainer.mesh.devices.size) == 8


def test_device_loss_without_factory_propagates(tmp_path):
    """A plain trainer (no factory) cannot rebuild its mesh — the loss
    must propagate instead of retrying onto dead hardware."""
    factory, _, sup, data = _sup_fixture(tmp_path)
    trainer = factory(None)
    with inject_faults(FaultSpec("mesh.shrink", at_step=2), seed=0):
        with pytest.raises(DeviceLossError):
            sup.fit(trainer, _params(), data, epochs=1, checkpoint_every=2)


# --------------------------------------------------------- scaleout wave

class _SumPerformer:
    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job):
        current = self.tracker.get_current()
        base = np.zeros(4) if current is None else np.asarray(current)
        job.result = base + np.full(4, float(job.work))

    def update(self, *args):
        pass


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_wave_shrinks_when_respawn_budget_exhausted():
    """A worker dies with max_respawns=0: the wave shrinks to the live
    count (counter + gauge + bundle) and the run still completes."""

    class DieOnce(_SumPerformer):
        died = []
        _lock = threading.Lock()

        def perform(self, job):
            with DieOnce._lock:
                if not DieOnce.died:
                    DieOnce.died.append(job.worker_id)
                    raise SystemExit  # thread exits, heartbeats stop
            super().perform(job)

    DieOnce.died = []
    tracker = StateTracker()
    tracker.set_current(np.zeros(4))
    runner = DistributedRunner(
        CollectionJobIterator([1.0, 2.0, 3.0, 4.0]), DieOnce,
        n_workers=2, tracker=tracker, eviction_timeout_s=0.3,
        max_respawns=0)
    METRICS.reset()
    result = runner.run(max_wall_s=60.0)
    assert result is not None and tracker.is_done()
    assert runner.n_workers == 1
    snap = METRICS.snapshot()
    assert snap["counters"]["scaleout.wave_shrinks"] >= 1
    assert snap["gauges"]["elastic.wave_size"] == 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_register_worker_grows_live_wave():
    gate = threading.Event()

    class Gated(_SumPerformer):
        def perform(self, job):
            gate.wait(timeout=30.0)
            super().perform(job)

    tracker = StateTracker()
    tracker.set_current(np.zeros(4))
    runner = DistributedRunner(
        CollectionJobIterator([1.0, 2.0, 3.0]), Gated,
        n_workers=1, tracker=tracker)
    METRICS.reset()
    out = {}

    def run():
        out["result"] = runner.run(max_wall_s=60.0)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    wid = runner.register_worker()          # grow while jobs are gated
    gate.set()
    t.join(timeout=60.0)
    assert not t.is_alive() and out["result"] is not None
    assert runner.n_workers == 2
    assert wid in tracker.workers()
    snap = METRICS.snapshot()
    assert snap["counters"]["scaleout.wave_grows"] == 1


# --------------------------------------------------------- rendering + chaos

def test_render_elasticity_table():
    from tools.metrics_dump import render_elasticity
    snap = {"gauges": {"elastic.mesh_size": 6.0,
                       "elastic.wave_size": 3.0,
                       "elastic.resizes_total": 2.0,
                       "elastic.reshard_seconds": 0.0123},
            "counters": {"checkpoint.reshards": 2,
                         "resilience.device_losses": 1,
                         "scaleout.wave_shrinks": 1}}
    table = render_elasticity(snap)
    assert "elasticity" in table
    for frag in ("6 chips", "3 workers", "reshard_restores", "device_losses"):
        assert frag in table, frag
    assert render_elasticity({"gauges": {}, "counters": {}}) is None


def test_chaos_smoke_elastic_leg():
    """The chaos plan's elastic leg holds on a fixed seed: run completes
    on the resized mesh, losses inside the window, bundle emitted."""
    from tools.chaos_smoke import run_elastic
    result = run_elastic(13)
    assert result["final_step"] == result["ref_step"]
    assert result["loss_window"] <= 1e-5
    assert result["mesh_resize_bundles"]
