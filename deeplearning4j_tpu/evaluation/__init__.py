"""L4 — evaluation (reference: ``deeplearning4j-core/.../eval``)."""

from .evaluation import ConfusionMatrix, Evaluation

__all__ = ["ConfusionMatrix", "Evaluation"]
