"""The Driver: single-controller training with checkpoint/auto-resume.

The role the reference splits across the Spark driver program and the YARN
superstep master: one object owns the device mesh, the jitted data-parallel
step, checkpointing (params + optimizer state + data cursor), and the REST
status endpoint. This example trains a linear model over a dp=8 mesh of
virtual devices, kills the run midway, and resumes from the checkpoint.

Run:  python examples/07_driver_checkpoint.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.driver import Driver
from deeplearning4j_tpu.parallel.mesh import MeshSpec


def make_problem():
    w_true = jnp.asarray([1.0, -2.0, 0.5])
    x = jax.random.normal(jax.random.key(0), (64, 3))
    y = x @ w_true

    def loss_fn(p, xb, yb, key=None):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    batches = [DataSet(np.asarray(x[i * 8:(i + 1) * 8]),
                       np.asarray(y[i * 8:(i + 1) * 8])) for i in range(8)]
    return {"w": jnp.zeros(3)}, loss_fn, batches, w_true


def main():
    params, loss_fn, batches, w_true = make_problem()
    tx = T.chain(T.momentum(0.9), T.sgd_lr(5e-2))
    with tempfile.TemporaryDirectory() as ckpt:
        # phase 1: train 3 epochs, checkpointing every 4 steps
        d1 = Driver(loss_fn, tx, mesh_spec=MeshSpec(dp=8),
                    checkpoint_dir=ckpt, checkpoint_every=4)
        _, losses1 = d1.run(params, batches, epochs=3)
        d1.close()
        print(f"phase 1: {len(losses1)} steps, loss {losses1[0]:.4f} -> "
              f"{losses1[-1]:.4f}, checkpoint at step "
              f"{d1.checkpoint_manager.latest_step()}")

        # phase 2: a NEW driver auto-resumes from the checkpoint cursor
        d2 = Driver(loss_fn, tx, mesh_spec=MeshSpec(dp=8),
                    checkpoint_dir=ckpt, checkpoint_every=4)
        state, losses2 = d2.run(params, batches, epochs=10)
        d2.close()
        w = np.asarray(d2.final_params(state)["w"])
        print(f"phase 2 resumed: {len(losses2)} more steps "
              f"(not {10 * len(batches)} — the cursor survived)")
        print(f"w = {np.round(w, 3)}  (true {np.asarray(w_true)})")
        assert len(losses2) < 10 * len(batches)
        np.testing.assert_allclose(w, np.asarray(w_true), atol=0.2)


if __name__ == "__main__":
    main()
