"""Replica abstraction + the health-probing, breaker-tripping pool.

A :class:`Replica` is one serving backend with the ``ModelServer``
surface reduced to what the router needs: ``generate`` / ``healthz`` /
``metrics_prom`` / ``reload``.  Two implementations:

- :class:`EngineReplica` — an in-process :class:`~..engine.InferenceEngine`
  (the N-engines-one-process shape; cheapest, shares the jit cache's host).
- :class:`ProcessReplica` — a ``procrunner``-style spawned child running
  ``python -m deeplearning4j_tpu.serving.router.procserver`` (a real
  ``ModelServer`` process) reached through :class:`~..client.ServingClient`.
  The factory travels as the same ``"module:callable"`` spec string the
  scaleout workers use, the bound port comes back through a port file
  (boot barrier: interpreter startup takes seconds), and a SIGKILL'd
  child surfaces as :class:`ReplicaUnavailable` within the client
  timeout — never a hang.

:class:`ReplicaPool` owns per-replica breaker state (DESIGN.md §19
quarantine state machine): ``fail_threshold`` consecutive failures —
probe or dispatch, they share one counter — trip ACTIVE → QUARANTINED
(flight-recorder bundle naming the replica and its last probe);
``recover_threshold`` consecutive probe successes re-admit.  The prober
thread also aggregates replica stats into the ``router.*`` gauges — the
pool-weighted prefix hit rate (Σhits/Σlookups across replicas) is the
number the multi-replica smoke compares against a single-replica run.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

from ...observability import METRICS
from ...resilience.faults import FAULTS
from ..batcher import ServingRejected
from ..client import ServingClient, ServingError


class ReplicaUnavailable(ServingRejected):
    """The replica could not be reached (connection refused/reset, probe
    timeout, injected ``router.replica_down``).  503: the request was
    never admitted anywhere, so the caller may safely retry."""

    status = 503


class AllReplicasUnavailable(ServingRejected):
    """Every ring node was quarantined, unreachable, or shedding."""

    status = 503


def replica_down(name: str) -> bool:
    """Chaos seam: does ``router.replica_down`` target this replica now?
    ``FaultSpec.kind`` names the target; the default payload (and "any")
    match every replica."""
    spec = FAULTS.check("router.replica_down")
    return spec is not None and spec.kind in ("any", "bitflip", name)


class Replica:
    """One serving backend; methods raise :class:`ServingRejected`
    subclasses (``.status`` is the HTTP answer) or
    :class:`ReplicaUnavailable` for transport-level death."""

    def __init__(self, name: str):
        self.name = name

    def generate(self, payload: dict, timeout_s: float) -> dict:
        raise NotImplementedError

    def healthz(self, timeout_s: float) -> dict:
        raise NotImplementedError

    def metrics_prom(self, timeout_s: float) -> str:
        raise NotImplementedError

    def reload(self, step: int | None = None) -> int:
        raise NotImplementedError

    def close(self) -> None:
        pass


class EngineReplica(Replica):
    """In-process replica over an :class:`~..engine.InferenceEngine`.

    ``own_engine=True`` (the pool built it) means ``close()`` stops it.
    """

    def __init__(self, name: str, engine, own_engine: bool = False):
        super().__init__(name)
        self.engine = engine
        self._own = own_engine

    def generate(self, payload: dict, timeout_s: float) -> dict:
        if replica_down(self.name):
            raise ReplicaUnavailable(f"replica {self.name} down (injected)")
        eos = payload.get("eos_id")
        dl = payload.get("deadline_ms")
        comp = self.engine.generate(
            payload["prompt"], int(payload.get("max_new_tokens", 16)),
            temperature=float(payload.get("temperature", 0.0)),
            seed=int(payload.get("seed", 0)),
            eos_id=int(eos) if eos is not None else None,
            deadline_ms=float(dl) if dl is not None else None,
            tenant=str(payload.get("tenant") or ""),
            priority=int(payload.get("priority", 0)),
            timeout=timeout_s)
        return {"tokens": comp.tokens, "finish_reason": comp.finish_reason,
                "latency_s": comp.latency_s, "ttft_s": comp.ttft_s}

    def healthz(self, timeout_s: float) -> dict:
        if replica_down(self.name):
            raise ReplicaUnavailable(f"replica {self.name} down (injected)")
        return {"ok": True, "engine": self.engine.stats()}

    def metrics_prom(self, timeout_s: float) -> str:
        return ""  # in-process replicas share the router's own registry

    def reload(self, step: int | None = None) -> int:
        return self.engine.reload(step=step)

    def close(self) -> None:
        if self._own:
            self.engine.stop()


class ProcessReplica(Replica):
    """A spawned ``ModelServer`` child behind a :class:`ServingClient`.

    ``factory_spec`` is a ``"module:callable"`` string resolved in the
    child (procrunner idiom); the callable gets ``factory_kwargs`` and
    returns an (unstarted) ``InferenceEngine``.
    """

    def __init__(self, name: str, factory_spec: str, workdir: str | Path,
                 factory_kwargs: dict | None = None,
                 env: dict[str, str] | None = None,
                 boot_timeout_s: float = 120.0,
                 client_timeout_s: float = 60.0,
                 trace_out: str | Path | None = None):
        super().__init__(name)
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        port_file = self.workdir / f"{name}.port"
        self._stop_file = self.workdir / f"{name}.stop"
        child_env = dict(os.environ)
        if env:
            child_env.update(env)
        # make the package importable in the child regardless of parent cwd
        pkg_root = str(Path(__file__).resolve().parents[3])
        child_env["PYTHONPATH"] = (pkg_root + os.pathsep
                                   + child_env.get("PYTHONPATH", ""))
        argv = [sys.executable, "-m",
                "deeplearning4j_tpu.serving.router.procserver",
                "--name", name, "--port-file", str(port_file),
                "--stop-file", str(self._stop_file),
                "--factory", factory_spec,
                "--factory-json", json.dumps(factory_kwargs or {})]
        if trace_out is not None:
            argv += ["--trace-out", str(trace_out)]
        log = open(self.workdir / f"{name}.log", "wb")
        try:
            self.proc = subprocess.Popen(argv, env=child_env, stdout=log,
                                         stderr=subprocess.STDOUT)
        finally:
            log.close()
        self.port = self._await_port(port_file, boot_timeout_s)
        self.client = ServingClient(port=self.port,
                                    timeout_s=client_timeout_s)
        # dedicated no-retry transport for metric scrapes: the default
        # client retries idempotent GETs once with backoff, so a child
        # SIGKILL'd mid-scrape would cost TWO socket timeouts plus the
        # backoff — past the fleet scraper's per-replica budget.  One
        # attempt bounds a dead scrape to exactly one ``timeout_s``.
        self._scrape_client = ServingClient(port=self.port,
                                            timeout_s=client_timeout_s,
                                            retries=0)

    def _await_port(self, port_file: Path, timeout_s: float) -> int:
        """Boot barrier: the child writes its bound port atomically once
        the engine + server are actually up."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name} exited rc={self.proc.returncode} "
                    f"before binding (see {self.workdir / (self.name + '.log')})")
            if port_file.exists():
                text = port_file.read_text().strip()
                if text:
                    return int(text)
            time.sleep(0.05)
        self.proc.kill()
        raise TimeoutError(f"replica {self.name} did not boot "
                           f"within {timeout_s}s")

    def generate(self, payload: dict, timeout_s: float) -> dict:
        if replica_down(self.name):
            raise ReplicaUnavailable(f"replica {self.name} down (injected)")
        try:
            return self.client.generate(
                payload["prompt"],
                int(payload.get("max_new_tokens", 16)),
                temperature=float(payload.get("temperature", 0.0)),
                seed=int(payload.get("seed", 0)),
                eos_id=payload.get("eos_id"),
                deadline_ms=payload.get("deadline_ms"),
                tenant=payload.get("tenant"),
                priority=int(payload.get("priority", 0) or 0),
                timeout_s=timeout_s)
        except OSError as e:
            # connection refused/reset or socket timeout: the child is
            # dead or wedged — fail fast, the router decides what's next.
            # (ServingError is NOT an OSError: an answered error keeps
            # its HTTP status and is re-raised untouched.)
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable: {e}") from e

    def healthz(self, timeout_s: float) -> dict:
        if replica_down(self.name):
            raise ReplicaUnavailable(f"replica {self.name} down (injected)")
        try:
            return self.client.healthz(timeout_s=timeout_s)
        except OSError as e:
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable: {e}") from e

    def metrics_prom(self, timeout_s: float) -> str:
        # a child that already exited can never answer: short-circuit
        # before paying any socket timeout (SIGKILL leaves no listener,
        # but a half-closed accept queue can still absorb a connect)
        if self.proc.poll() is not None:
            raise ReplicaUnavailable(
                f"replica {self.name} dead (rc={self.proc.returncode})")
        try:
            return self._scrape_client.metrics_prom(timeout_s=timeout_s)
        except OSError as e:
            raise ReplicaUnavailable(
                f"replica {self.name} unreachable: {e}") from e

    def reload(self, step: int | None = None) -> int:
        return self.client.reload(step)

    def kill(self) -> None:
        """SIGKILL the child (chaos tests): no goodbye, probes just fail."""
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10.0)

    def close(self) -> None:
        try:
            self._stop_file.touch()
        except OSError:
            pass
        try:
            self.proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait(timeout=10.0)


ACTIVE = "active"
QUARANTINED = "quarantined"
DRAINING = "draining"   # administrative quarantine: scale-in in progress


class _ReplicaState:
    """Breaker bookkeeping for one replica (all fields guarded by the
    pool lock)."""

    __slots__ = ("state", "consecutive_failures", "consecutive_successes",
                 "inflight", "last_probe", "quarantines")

    def __init__(self):
        self.state = ACTIVE
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.inflight = 0
        self.last_probe: dict = {}
        self.quarantines = 0


class ReplicaPool:
    """N replicas + breaker state + a background health prober.

    Lock discipline: ``self._lock`` guards only the state table and is a
    leaf — probes and dispatches (blocking HTTP / engine calls) always
    happen OUTSIDE it.
    """

    def __init__(self, replicas: list[Replica],
                 probe_interval_s: float = 0.5,
                 probe_timeout_s: float = 2.0,
                 fail_threshold: int = 2,
                 recover_threshold: int = 2):
        if not replicas:
            raise ValueError("need at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.fail_threshold = fail_threshold
        self.recover_threshold = recover_threshold
        self._replicas: dict[str, Replica] = {r.name: r for r in replicas}
        self._lock = threading.Lock()
        self._state = {r.name: _ReplicaState() for r in replicas}  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        for name in self._replicas:
            METRICS.gauge(f"router.replica_state.{name}", 1.0)

    # ------------------------------------------------------------ membership
    def names(self) -> list[str]:
        return list(self._replicas)

    def replica(self, name: str) -> Replica:
        return self._replicas[name]

    def is_active(self, name: str) -> bool:
        with self._lock:
            st = self._state.get(name)
            return st is not None and st.state == ACTIVE

    def active_names(self) -> list[str]:
        with self._lock:
            return [n for n, st in self._state.items() if st.state == ACTIVE]

    def last_probe(self, name: str) -> dict:
        with self._lock:
            return dict(self._state[name].last_probe)

    def inflight(self, name: str) -> int:
        with self._lock:
            st = self._state.get(name)
            return st.inflight if st is not None else 0

    # --------------------------------------------------- elastic membership
    #
    # The scale seams (DESIGN.md §26).  ``_replicas`` is mutated copy-on-
    # write under ``_lock`` — dispatch and the prober read it LOCKLESS, so
    # they must always see a complete dict, never a half-mutated one.
    # Scale-in reuses the quarantine state machine: ``drain_replica`` parks
    # the replica in DRAINING (``is_active`` false — routing drains its
    # keys to the clockwise ring successors exactly as a breaker trip
    # would, and probes can never re-admit it), and ``remove_replica``
    # refuses until the drain finished (zero in flight) — a half-drained
    # replica is unrepresentable.

    def add_replica(self, replica: Replica) -> None:
        """Admit a NEW replica into the pool (ACTIVE).  The caller is
        responsible for warming it first — see ``PrefixRouter.scale_up``,
        which gates ring admission on the replica's warmed health flag."""
        with self._lock:
            if replica.name in self._replicas:
                raise ValueError(f"replica {replica.name!r} already pooled")
            self._replicas = {**self._replicas, replica.name: replica}
            self._state[replica.name] = _ReplicaState()
        METRICS.increment("router.replicas_added")
        METRICS.gauge(f"router.replica_state.{replica.name}", 1.0)

    def drain_replica(self, name: str) -> None:
        """Begin scale-in: stop routing to ``name`` (quarantine-path
        semantics — its ring segment drains to clockwise successors) while
        in-flight requests finish.  Idempotent."""
        with self._lock:
            st = self._state[name]
            already = st.state == DRAINING
            st.state = DRAINING
            inflight = st.inflight
        if already:
            return
        METRICS.increment("router.drains")
        METRICS.gauge(f"router.replica_state.{name}", 0.0)
        from ...observability import FLIGHTREC
        FLIGHTREC.dump("router_replica_drain",
                       extra={"replica": name, "inflight": inflight})

    def reactivate_replica(self, name: str) -> None:
        """Abort a drain (scale-in timed out or was cancelled): the
        replica returns to ACTIVE and its ring segment snaps back to the
        original assignment — fail safe is *more* capacity, never a
        half-drained replica."""
        with self._lock:
            st = self._state[name]
            if st.state != DRAINING:
                return
            st.state = ACTIVE
            st.consecutive_failures = 0
        METRICS.increment("router.drain_aborts")
        METRICS.gauge(f"router.replica_state.{name}", 1.0)

    def remove_replica(self, name: str) -> Replica:
        """Complete scale-in: detach a fully drained replica and return
        it (the caller owns ``close()``).  Refuses while the replica is
        still ACTIVE or has requests in flight."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                raise KeyError(name)
            if st.state == ACTIVE:
                raise RuntimeError(
                    f"replica {name!r} is ACTIVE — drain_replica() first")
            if st.inflight:
                raise RuntimeError(
                    f"replica {name!r} still has {st.inflight} request(s) "
                    "in flight — drain must finish before removal")
            replicas = dict(self._replicas)
            rep = replicas.pop(name)
            self._replicas = replicas
            del self._state[name]
        METRICS.increment("router.replicas_removed")
        METRICS.gauge(f"router.replica_state.{name}", 0.0)
        return rep

    # ------------------------------------------------------------ breaker
    def record_failure(self, name: str, reason: str) -> bool:
        """One failed probe or dispatch; returns True when this failure
        tripped the breaker (ACTIVE -> QUARANTINED)."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return False   # removed (scale-in) while a probe ran
            st.consecutive_successes = 0
            st.consecutive_failures += 1
            tripped = (st.state == ACTIVE
                       and st.consecutive_failures >= self.fail_threshold)
            if tripped:
                st.state = QUARANTINED
                st.quarantines += 1
                last_probe = dict(st.last_probe)
                failures = st.consecutive_failures
        if tripped:
            METRICS.increment("router.quarantines")
            METRICS.gauge(f"router.replica_state.{name}", 0.0)
            # a dead replica must leave evidence: bundle names the replica
            # and the last health probe it ever answered
            from ...observability import FLIGHTREC
            FLIGHTREC.dump("router_replica_quarantine",
                           extra={"replica": name, "reason": reason,
                                  "consecutive_failures": failures,
                                  "last_probe": last_probe})
        return tripped

    def record_success(self, name: str, probe: dict | None = None) -> bool:
        """One successful probe or dispatch; returns True when it
        re-admitted a quarantined replica."""
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return False   # removed (scale-in) while a probe ran
            st.consecutive_failures = 0
            st.consecutive_successes += 1
            if probe is not None:
                st.last_probe = probe
            readmitted = (st.state == QUARANTINED
                          and st.consecutive_successes
                          >= self.recover_threshold)
            if readmitted:
                st.state = ACTIVE
        if readmitted:
            METRICS.increment("router.readmissions")
            METRICS.gauge(f"router.replica_state.{name}", 1.0)
        return readmitted

    # ------------------------------------------------------------ load
    def begin_request(self, name: str) -> None:
        with self._lock:
            self._state[name].inflight += 1
            load = self._state[name].inflight
        METRICS.gauge(f"router.replica_load.{name}", float(load))

    def end_request(self, name: str) -> None:
        with self._lock:
            st = self._state.get(name)
            if st is None:
                return
            st.inflight -= 1
            load = st.inflight
        METRICS.gauge(f"router.replica_load.{name}", float(load))

    # ------------------------------------------------------------ probing
    def probe_once(self) -> None:
        """One health sweep: every replica probed (outside the lock),
        breaker state advanced, aggregate gauges published."""
        total_hits = total_lookups = 0
        have_prefix = False
        # membership is copy-on-write: this grabs one consistent snapshot,
        # so a concurrent scale-up/scale-in can never break the sweep
        for name, rep in list(self._replicas.items()):
            try:
                if replica_down(name):
                    raise ReplicaUnavailable(
                        f"replica {name} down (injected)")
                health = rep.healthz(self.probe_timeout_s)
            except (ServingRejected, ServingError, OSError) as e:
                self.record_failure(name, f"probe: {e}")
                continue
            stats = health.get("engine") or {}
            if stats.get("role") == "prefill":
                # a prefill-role replica can never decode — routing it
                # decode traffic would fail every request.  Role is in
                # the health JSON precisely so this is verifiable over
                # HTTP; treat it as a hard probe failure and let the
                # breaker keep it out of the ring (DESIGN.md §27)
                self.record_failure(
                    name, "probe: prefill-role replica cannot serve "
                          "decode traffic")
                continue
            probe = {"time": time.time(), "health": health}
            self.record_success(name, probe=probe)
            qd = stats.get("queue_depth")
            if qd is not None:
                METRICS.gauge(f"router.replica_queue_depth.{name}",
                              float(qd))
            if "prefix_lookups" in stats:
                have_prefix = True
                total_hits += int(stats.get("prefix_hits", 0))
                total_lookups += int(stats.get("prefix_lookups", 0))
        if have_prefix:
            # pool-weighted aggregate: each in-process engine publishes
            # serving.prefix_hit_rate to the SAME global gauge, so only
            # this Σhits/Σlookups view is meaningful across replicas
            METRICS.gauge("router.prefix_hit_rate",
                          total_hits / total_lookups if total_lookups
                          else 0.0)

    def _probe_loop(self) -> None:
        while not self._stop.is_set():
            self.probe_once()
            self._stop.wait(self.probe_interval_s)

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ReplicaPool":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._probe_loop,
                                            daemon=True,
                                            name="router-prober")
            self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        for rep in self._replicas.values():
            rep.close()
