"""DataSet iterators.

Capability match of ``datasets/iterator/*`` in the reference:
``DataSetIterator`` protocol (``DataSetIterator.java:10-31``),
fetcher-backed ``BaseDatasetIterator``, list-backed ``ListDataSetIterator``,
the test helper ``TestDataSetIterator`` (main-tree in the reference too),
and the wrappers ``MultipleEpochsIterator``, ``SamplingDataSetIterator``,
``ReconstructionDataSetIterator``, ``MovingWindowBaseDataSetIterator``;
plus the ``DataSetPreProcessor`` hook.
"""

from __future__ import annotations

from typing import Callable, Iterator as PyIterator, Protocol, Sequence

import numpy as np

from .dataset import DataSet
from .fetchers import (
    BaseDataFetcher,
    CSVDataFetcher,
    CurvesDataFetcher,
    DigitsDataFetcher,
    IrisDataFetcher,
    MnistDataFetcher,
)

DataSetPreProcessor = Callable[[DataSet], DataSet]


class DataSetIterator(Protocol):
    """``DataSetIterator.java:10-31`` contract."""

    def next(self, num: int | None = None) -> DataSet: ...
    def has_next(self) -> bool: ...
    def total_examples(self) -> int: ...
    def input_columns(self) -> int: ...
    def total_outcomes(self) -> int: ...
    def reset(self) -> None: ...
    def batch(self) -> int: ...
    def cursor(self) -> int: ...
    def set_pre_processor(self, pre: DataSetPreProcessor) -> None: ...


class _IterBase:
    """Python-iteration sugar shared by all iterators."""

    def __iter__(self) -> PyIterator[DataSet]:
        self.reset()
        while self.has_next():
            yield self.next()


class BaseDatasetIterator(_IterBase):
    """Fetcher-backed iterator (``BaseDatasetIterator.java``)."""

    def __init__(self, batch_size: int, num_examples: int, fetcher: BaseDataFetcher):
        self._batch = batch_size
        self._num_examples = num_examples if num_examples > 0 else fetcher.total_examples()
        self.fetcher = fetcher
        self.pre_processor: DataSetPreProcessor | None = None

    def has_next(self) -> bool:
        return self.fetcher.has_more() and self.fetcher.cursor < self._num_examples

    def next(self, num: int | None = None) -> DataSet:
        n = num or self._batch
        # honor the num_examples cap (fetch clamps only to the full corpus)
        n = min(n, self._num_examples - self.fetcher.cursor)
        self.fetcher.fetch(n)
        ds = self.fetcher.next()
        return self.pre_processor(ds) if self.pre_processor else ds

    def total_examples(self) -> int:
        return self._num_examples

    def input_columns(self) -> int:
        self.fetcher._ensure_loaded()
        return self.fetcher.input_columns

    def total_outcomes(self) -> int:
        self.fetcher._ensure_loaded()
        return self.fetcher.num_outcomes

    def reset(self) -> None:
        self.fetcher.reset()

    def batch(self) -> int:
        return self._batch

    def cursor(self) -> int:
        return self.fetcher.cursor

    def set_pre_processor(self, pre: DataSetPreProcessor) -> None:
        self.pre_processor = pre


class IrisDataSetIterator(BaseDatasetIterator):
    """``IrisDataSetIterator``."""

    def __init__(self, batch: int = 150, num_examples: int = 150):
        super().__init__(batch, num_examples, IrisDataFetcher())


class DigitsDataSetIterator(BaseDatasetIterator):
    """Offline 8x8-digits iterator (fast MNIST-class corpus for tests)."""

    def __init__(self, batch: int = 100, num_examples: int = 0, **kw):
        super().__init__(batch, num_examples, DigitsDataFetcher(**kw))


class MnistDataSetIterator(BaseDatasetIterator):
    """``MnistDataSetIterator`` (IDX-file MNIST w/ offline fallback)."""

    def __init__(self, batch: int = 100, num_examples: int = 0, **kw):
        super().__init__(batch, num_examples, MnistDataFetcher(**kw))


class CurvesDataSetIterator(BaseDatasetIterator):
    """``CurvesDataSetIterator`` (synthesized curves; see the fetcher)."""

    def __init__(self, batch: int = 100, num_examples: int = 0, **kw):
        super().__init__(batch, num_examples, CurvesDataFetcher(**kw))


class CSVDataSetIterator(BaseDatasetIterator):
    """``CSVDataSetIterator``."""

    def __init__(self, batch: int, num_examples: int, path, label_col: int = -1, **kw):
        super().__init__(batch, num_examples, CSVDataFetcher(path, label_col, **kw))


class ListDataSetIterator(_IterBase):
    """``ListDataSetIterator`` — iterate over an in-memory list of examples."""

    def __init__(self, data: DataSet | Sequence[DataSet], batch: int = 10):
        ds = data if isinstance(data, DataSet) else DataSet.merge(list(data))
        self.data = ds
        self._batch = batch
        self._cursor = 0
        self.pre_processor: DataSetPreProcessor | None = None

    def has_next(self) -> bool:
        return self._cursor < self.data.num_examples()

    def next(self, num: int | None = None) -> DataSet:
        n = num or self._batch
        end = min(self._cursor + n, self.data.num_examples())
        ds = DataSet(self.data.features[self._cursor:end], self.data.labels[self._cursor:end])
        self._cursor = end
        return self.pre_processor(ds) if self.pre_processor else ds

    def total_examples(self) -> int:
        return self.data.num_examples()

    def input_columns(self) -> int:
        return self.data.num_inputs()

    def total_outcomes(self) -> int:
        return self.data.num_outcomes()

    def reset(self) -> None:
        self._cursor = 0

    def batch(self) -> int:
        return self._batch

    def cursor(self) -> int:
        return self._cursor

    def set_pre_processor(self, pre: DataSetPreProcessor) -> None:
        self.pre_processor = pre


class TestDataSetIterator(ListDataSetIterator):
    """``datasets/test/TestDataSetIterator.java`` — wrap any DataSet for
    tests (main-tree fixture in the reference as well)."""

    __test__ = False  # not a pytest class despite the name


# --------------------------------------------------------------------------- wrappers

class MultipleEpochsIterator(_IterBase):
    """``MultipleEpochsIterator.java`` — replay an iterator N epochs."""

    def __init__(self, num_epochs: int, inner):
        self.num_epochs = num_epochs
        self.inner = inner
        self.epoch = 0

    def has_next(self) -> bool:
        return self.epoch < self.num_epochs - 1 or self.inner.has_next()

    def next(self, num: int | None = None) -> DataSet:
        if not self.inner.has_next():
            self.inner.reset()
            self.epoch += 1
        return self.inner.next(num)

    def reset(self) -> None:
        self.epoch = 0
        self.inner.reset()

    def total_examples(self) -> int:
        return self.inner.total_examples() * self.num_epochs

    def input_columns(self) -> int:
        return self.inner.input_columns()

    def total_outcomes(self) -> int:
        return self.inner.total_outcomes()

    def batch(self) -> int:
        return self.inner.batch()

    def cursor(self) -> int:
        return self.inner.cursor()

    def set_pre_processor(self, pre) -> None:
        self.inner.set_pre_processor(pre)


class SamplingDataSetIterator(_IterBase):
    """``SamplingDataSetIterator`` — draw with-replacement samples from a
    base DataSet for a fixed number of batches."""

    def __init__(self, data: DataSet, batch: int, total_batches: int, seed: int = 0):
        self.data = data
        self._batch = batch
        self.total_batches = total_batches
        self._count = 0
        self._rng = np.random.default_rng(seed)
        self.pre_processor: DataSetPreProcessor | None = None

    def has_next(self) -> bool:
        return self._count < self.total_batches

    def next(self, num: int | None = None) -> DataSet:
        n = num or self._batch
        idx = self._rng.choice(self.data.num_examples(), size=n, replace=True)
        self._count += 1
        ds = DataSet(self.data.features[idx], self.data.labels[idx])
        return self.pre_processor(ds) if self.pre_processor else ds

    def reset(self) -> None:
        self._count = 0

    def total_examples(self) -> int:
        return self._batch * self.total_batches

    def input_columns(self) -> int:
        return self.data.num_inputs()

    def total_outcomes(self) -> int:
        return self.data.num_outcomes()

    def batch(self) -> int:
        return self._batch

    def cursor(self) -> int:
        return self._count * self._batch

    def set_pre_processor(self, pre) -> None:
        self.pre_processor = pre


class ReconstructionDataSetIterator(_IterBase):
    """``ReconstructionDataSetIterator`` — labels become the features
    (unsupervised pretraining view)."""

    def __init__(self, inner):
        self.inner = inner

    def has_next(self) -> bool:
        return self.inner.has_next()

    def next(self, num: int | None = None) -> DataSet:
        return self.inner.next(num).as_reconstruction()

    def reset(self) -> None:
        self.inner.reset()

    def total_examples(self) -> int:
        return self.inner.total_examples()

    def input_columns(self) -> int:
        return self.inner.input_columns()

    def total_outcomes(self) -> int:
        return self.inner.input_columns()

    def batch(self) -> int:
        return self.inner.batch()

    def cursor(self) -> int:
        return self.inner.cursor()

    def set_pre_processor(self, pre) -> None:
        self.inner.set_pre_processor(pre)


class MovingWindowDataSetIterator(_IterBase):
    """``MovingWindowBaseDataSetIterator.java:12`` — slide a (rows, cols)
    window over each example image and emit the flattened windows as
    examples (same labels)."""

    def __init__(self, batch: int, data: DataSet, window_rows: int, window_cols: int):
        feats = data.features
        if feats.ndim == 2:
            side = int(np.sqrt(feats.shape[1]))
            feats = feats.reshape(-1, side, side)
        elif feats.ndim == 4:
            feats = feats[..., 0]
        windows, labels = [], []
        for i in range(feats.shape[0]):
            img = feats[i]
            for r in range(0, img.shape[0] - window_rows + 1, window_rows):
                for c in range(0, img.shape[1] - window_cols + 1, window_cols):
                    windows.append(img[r:r + window_rows, c:c + window_cols].reshape(-1))
                    labels.append(data.labels[i])
        self._list = ListDataSetIterator(
            DataSet(np.stack(windows), np.stack(labels)), batch)

    def __getattr__(self, name):
        return getattr(self._list, name)

    def __iter__(self):
        return iter(self._list)


def _device_put_tree(batch, sharding):
    """``jax.device_put`` every array leaf of ``batch`` (non-array leaves —
    e.g. the python-int sample counts the trainer threads alongside padded
    batches — pass through untouched)."""
    import jax

    leaves, treedef = jax.tree.flatten(batch)
    leaves = [jax.device_put(x, sharding) if hasattr(x, "shape") else x
              for x in leaves]
    return jax.tree.unflatten(treedef, leaves)


class _ThreadedPrefetch:
    """Background-thread variant of :func:`prefetch_to_device`.

    A daemon worker pulls from the source iterable, stages each batch on
    device, and parks it in a bounded queue; the consumer thread never
    blocks on *host-side* batch production (augmentation, parsing, a
    generator doing real work).  The device transfers themselves are still
    async jax transfers.

    Lifecycle contract (what the tests pin down): the worker exits on
    source exhaustion, on worker error (re-raised in the consumer), and on
    ``close()`` — it must never outlive the iterator, even when the
    consumer abandons iteration mid-stream with a full queue.
    """

    _DONE = object()  # sentinel: source exhausted

    def __init__(self, iterable, size: int, sharding):
        import queue as _queue
        import threading

        self._q: "_queue.Queue" = _queue.Queue(maxsize=max(1, size))
        self._stop = threading.Event()
        self._err_lock = threading.Lock()
        self._error: BaseException | None = None  # guarded-by: self._err_lock
        self._source = iterable
        self._sharding = sharding
        self.thread = threading.Thread(
            target=self._work, name="prefetch_to_device", daemon=True)
        self.thread.start()

    def _work(self):
        try:
            for batch in self._source:
                if self._stop.is_set():
                    return
                staged = _device_put_tree(batch, self._sharding)
                # stop-aware put: a plain blocking put on a full queue
                # would deadlock close() when the consumer walked away
                while not self._stop.is_set():
                    try:
                        self._q.put(staged, timeout=0.05)
                        break
                    except Exception:  # queue.Full
                        continue
        except BaseException as e:  # noqa: BLE001 - re-raised at consumer
            with self._err_lock:
                self._error = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(self._DONE, timeout=0.05)
                    break
                except Exception:  # queue.Full
                    continue

    def __iter__(self):
        return self

    def _take_error(self):
        """Claim the worker error (swap-out, at most one claimant wins)."""
        with self._err_lock:
            err, self._error = self._error, None
        return err

    def _peek_error(self) -> bool:
        with self._err_lock:
            return self._error is not None

    def __next__(self):
        while True:
            err = self._take_error()
            if err is not None:
                self.close()
                raise err
            try:
                item = self._q.get(timeout=0.05)
            except Exception:  # queue.Empty — re-check error/stop, wait on
                if not self.thread.is_alive() and self._q.empty() \
                        and not self._peek_error():
                    raise StopIteration from None
                continue
            if item is self._DONE:
                if self._peek_error():
                    continue  # surface the error on the next spin
                self.close()
                raise StopIteration
            return item

    def close(self):
        """Stop the worker and join it (idempotent)."""
        self._stop.set()
        # drain so a worker blocked in put() sees the stop flag promptly
        try:
            while True:
                self._q.get_nowait()
        except Exception:  # queue.Empty
            pass
        if self.thread.is_alive():
            self.thread.join(timeout=5.0)

    def __del__(self):  # best effort — close() is the contract
        try:
            self._stop.set()
        except Exception:
            pass


def prefetch_to_device(iterable, size: int = 2, sharding=None,
                       host_thread: bool = False):
    """Double-buffered host->device staging (SURVEY §7 L3: "double-buffered
    host->device transfer"; the role the reference fills with its fetcher
    cursor + Akka batch actor hand-off).

    Issues ``jax.device_put`` for up to ``size`` batches ahead of the
    consumer: JAX transfers are asynchronous, so the copy of batch k+1
    overlaps the device compute of batch k without any helper thread.
    Works on (features, labels) tuples, DataSets, or any pytree of host
    arrays; ``sharding`` (e.g. a NamedSharding) places each leaf when given.

    With ``host_thread=True`` a daemon worker additionally overlaps
    *producing* the batches (generator work: parsing, augmentation,
    padding) with device compute — use when the source iterable itself is
    expensive.  Returns a :class:`_ThreadedPrefetch` (iterable, plus
    ``close()`` for deterministic shutdown); the default stays threadless.
    """
    if host_thread:
        return _ThreadedPrefetch(iterable, size, sharding)

    def _threadless():
        import collections

        queue = collections.deque()
        it = iter(iterable)
        try:
            while len(queue) < max(1, size):
                queue.append(_device_put_tree(next(it), sharding))
        except StopIteration:
            pass
        while queue:
            out = queue.popleft()
            try:
                queue.append(_device_put_tree(next(it), sharding))
            except StopIteration:
                pass
            yield out

    return _threadless()
