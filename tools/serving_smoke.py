"""Serving load generator: drive the full HTTP stack, report latency + fill.

Spins up a tiny random transformer, an :class:`InferenceEngine`, a
:class:`BatchScorer` and a :class:`ModelServer` on a free port, then fires
``--requests`` generations from ``--threads`` concurrent clients (random
prompt lengths/temperatures/budgets from ``--seed``).  Everything observable
flows through the PR-1 metrics registry — the JSON result line reports
p50/p99 request latency and queue wait, time-to-first-token, batch fill
ratio and tokens/sec exactly as a Prometheus scrape of ``/metrics.prom``
would see them, so this doubles as an end-to-end check that the serving
histograms land.

    python tools/serving_smoke.py [--requests 32] [--threads 4] [--seed 0]
                                  [--lockguard] [--prefix-workload]
                                  [--trace-out trace.json] [--slo] [--online]
                                  [--autoscale] [--disagg]

``--disagg`` switches to the disaggregated-tier leg (DESIGN.md §27): a
bimodal workload where decode-heavy requests stream through the
prefill tier + KV-page migration while prefill-heavy background load
runs at 1x and then 2x.  FAILS unless every migrated decode matches
the offline reference token-for-token and the decode stream's p99
inter-token latency at 2x prefill load stays within 1.15x of baseline.

``--autoscale`` switches to the control-plane leg (DESIGN.md §26): an
``Autoscaler`` scales a live router pool 1 -> 2 -> 1 through the real
warm-before-admission / drain-before-remove seams with a greedy probe
held token-identical across every membership change, then the same
controller runs a deterministic diurnal-plus-spike day and must hold
the TTFT objective (>= 95% of simulated time) with measurably fewer
replica-hours than a static peak-provisioned fleet.  The JSON line
carries ``{"autoscale": {"saved_frac": ...}}`` for ``perf_gate.py``.

``--online`` switches to the online-learning leg (DESIGN.md §23): waves
of greedy traffic are served through a ``ModelServer`` whose capture
hook feeds a ``CaptureStore``; between waves an ``OnlineLoop`` round
replays the captures, fine-tunes, publishes a checkpoint and hot-reloads
it into the live engine.  The run FAILS unless every response's tokens
match offline sampling under the checkpoint named by its own
``loaded_step`` stamp and at least one reload applied.

``--slo`` switches to the SLO-watchdog leg: the Zipf workload is served
while a ``TimeSeriesStore`` samples the registry and an ``SLOEvaluator``
computes multi-window burn rates for ``default_serving_objectives``
(smoke-sized windows via ``--window``, default 2 s).  The run FAILS
unless at least one objective accrues a full window with a computed
burn rate; the JSON line carries every ``slo.burn_rate.*`` gauge.

``--lockguard`` runs the whole smoke with instrumented threading locks
(analysis/lockguard.py): lock-order inversions and Eraser-style unguarded
shared writes observed anywhere in the engine/queue/HTTP path fail the
run, and the violation count lands in the JSON result.

``--trace-out PATH`` saves a merged Chrome trace of the run (each client
call opens a ``client.generate`` span whose trace id rides the W3C
``traceparent`` header, so server-side ``serving.*`` spans join it) and
FAILS unless every completed request's trace carries the full
queue_wait -> prefill -> decode -> emit chain under one trace id.  Feed
the file to ``tools/trace_report.py`` for the per-request TTFT breakdown.

``--fleet`` switches to the fleet-observability leg (DESIGN.md §24): a
Zipf multi-tenant workload over ``--replicas N`` (default 3) REAL
process replicas, federated through a ``FleetScraper``.  The run FAILS
unless the federated token counters equal the sum of every replica's
own counters equal the client-observed totals EXACTLY (overall and per
tenant), a mid-run SIGKILL of one replica degrades to
``fleet.scrape_errors`` + a stale mark for that replica only, and — on
a synthetic ramp — the ``forecast_breach`` flight bundle lands strictly
before the ``SLOEvaluator`` records the breach.  The JSON line carries
``{"fleet": {"scrape_ms": ...}}`` for ``perf_gate.py --record``.

``--replicas N`` switches to the multi-replica router smoke: the SAME
Zipf multi-tenant workload is run twice through a ``RouterServer`` —
once over a single replica, once over N — with the aggregate
pool-weighted prefix hit rate scraped from the router's
``/metrics.prom`` exactly as a Prometheus poller would see it.  The run
FAILS unless the N-replica aggregate hit rate is at least the
single-replica run's (prefix affinity must not shred locality across
the ring), the affinity rate (requests landing on their ring owner) is
high, throughput does not collapse versus one replica, and every
temperature-0 completion — including any that spilled — matches
``Transformer.sample`` offline token-for-token.  ``--strict-scaling``
additionally asserts near-linear throughput (>= 0.6*N); the default
floor is lenient because a tiny CPU model is GIL/dispatch-bound — the
near-linear claim is owed to the real-hardware battery (ROADMAP item 2),
and the JSON line always reports the measured ratio.

``--prefix-workload`` switches to the paged/prefix-cache smoke: a
Zipf-skewed population of shared system prompts (the multi-tenant
chatbot shape) is served by a ``paged=True, prefix_cache=True`` engine
while a background thread scrapes ``/metrics.prom`` exactly as a
Prometheus poller would.  The JSON line reports p50/p99 latency, TTFT,
the scraped prefix hit rate and peak KV pages in use, and the scraped
peak device-KV bytes per occupied slot next to the dense
``max_len``-per-slot baseline; the run FAILS unless the hit rate is
positive and the paged footprint stays under the dense baseline.
``--kv-quant int8`` runs the workload twice (float leg, then quantized
leg) and additionally FAILS unless bytes/slot drops >= 1.9x, the hit
rate does not regress, and greedy served tokens agree top-1 >= 0.999
across the legs.

Exits nonzero if any request fails, the registry is missing a serving
histogram, or lockguard saw a violation.
"""

from __future__ import annotations

import json
import random
import sys
import threading


def run(requests: int = 32, threads: int = 4, seed: int = 0,
        lockguard: bool = False, trace_out: str | None = None) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS, TRACER, trace
    from deeplearning4j_tpu.serving import (BatchScorer, InferenceEngine,
                                            ModelServer, ServingClient,
                                            ServingConfig, ServingError)

    observability.enable()
    METRICS.reset()
    if trace_out is not None:
        TRACER.clear()

    guard = None
    if lockguard:
        from deeplearning4j_tpu.analysis.lockguard import LockGuard

        guard = LockGuard().install()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))

    def score_fn(x):
        # any row-wise fn serves; use the LM's own forward as the scorer
        return model.forward(params, jnp.asarray(x, jnp.int32))[:, -1, :]

    rng = random.Random(seed)
    failures: list[str] = []
    statuses: list[int] = []
    lock = threading.Lock()

    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=4, resolve_every=4))
    scorer = BatchScorer(score_fn, max_batch=16)
    with engine, scorer, ModelServer(engine=engine, scorer=scorer) as server:
        client = ServingClient(port=server.port)
        plans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                              for _ in range(rng.randint(1, 12))],
                      max_new_tokens=rng.randint(1, 10),
                      temperature=rng.choice([0.0, 0.7, 1.0]),
                      seed=rng.randrange(1 << 20))
                 for _ in range(requests)]

        completed_traces: list[str] = []

        def worker(mine):
            for plan in mine:
                try:
                    # a client-side span per call: its trace id rides the
                    # traceparent header, so the server JOINS this trace
                    # instead of minting its own
                    with trace.span("client.generate") as sp:
                        out = client.generate(**plan)
                    with lock:
                        statuses.append(200)
                        if getattr(sp, "trace_id", ""):
                            completed_traces.append(sp.trace_id)
                    if len(out["tokens"]) > plan["max_new_tokens"]:
                        with lock:
                            failures.append(f"overlong answer for {plan}")
                except ServingError as e:
                    with lock:
                        statuses.append(e.status)
                        failures.append(str(e))

        ts = [threading.Thread(target=worker, args=(plans[i::threads],))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # one scorer round-trip through HTTP as well
        rows = [[rng.randrange(cfg.vocab_size) for _ in range(4)]
                for _ in range(6)]
        outputs = client.score(rows)
        if len(outputs) != len(rows):
            failures.append("score row count mismatch")
        health = client.healthz()
        prom = client.metrics_prom()

    if guard is not None:
        guard.uninstall()
        guard.emit_metrics()
        for v in guard.violations():
            failures.append(str(v))

    trace_summary = None
    if trace_out is not None:
        # engine + server + client all live in this process, so the
        # tracer already holds every side's spans; write, then round-trip
        # through the merger so the output is the same shape a multi-
        # process merge would produce
        from tools.trace_report import merge
        TRACER.save_chrome_trace(trace_out)
        merged = merge([trace_out])
        with open(trace_out, "w") as f:
            json.dump(merged, f)
        events = merged["traceEvents"]
        by_trace: dict[str, set] = {}
        tokens_by_trace: dict[str, int] = {}
        for ev in events:
            tid = (ev.get("args") or {}).get("trace_id")
            if not tid:
                continue
            by_trace.setdefault(tid, set()).add(ev["name"])
            if ev["name"] == "serving.request":
                tokens_by_trace[tid] = int((ev.get("args") or {}).get("tokens") or 0)
        need = {"serving.request", "serving.queue_wait",
                "serving.prefill", "serving.emit"}
        for tid in completed_traces:
            names = by_trace.get(tid, set())
            missing_spans = need - names
            # a 1-token answer legitimately finishes inside prefill —
            # decode segments are only required when decode actually ran
            if tokens_by_trace.get(tid, 0) > 1 and \
                    "serving.decode.segment" not in names:
                missing_spans.add("serving.decode.segment")
            if missing_spans:
                failures.append(
                    f"trace {tid[:12]} missing spans {sorted(missing_spans)}")
        trace_summary = {"path": trace_out, "events": len(events),
                         "requests_traced": len(completed_traces),
                         "dropped": merged["metadata"]["dropped"]}

    snap = METRICS.snapshot()
    timers, gauges = snap["timers"], snap["gauges"]

    def pct(name):
        t = timers.get(name)
        return {"p50": t["p50_s"], "p99": t["p99_s"], "count": t["count"],
                "mean": t["mean_s"]} if t else None

    required = ["serving.request_latency", "serving.queue_wait",
                "serving.ttft", "serving.batch_fill_ratio",
                "serving.decode_step"]
    missing = [n for n in required
               if n not in timers
               or n.replace(".", "_") + "_seconds" not in prom]
    result = {
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "completed": statuses.count(200),
        "rejected": len(statuses) - statuses.count(200),
        "request_latency_s": pct("serving.request_latency"),
        "queue_wait_s": pct("serving.queue_wait"),
        "ttft_s": pct("serving.ttft"),
        "batch_fill_ratio": pct("serving.batch_fill_ratio"),
        "tokens_per_sec": gauges.get("serving.tokens_per_sec"),
        "tokens_total": snap["counters"].get("serving.tokens"),
        "prefill_buckets": health["engine"]["prefill_buckets"],
        "missing_histograms": missing,
        "failures": failures[:5],
    }
    if trace_summary is not None:
        result["trace"] = trace_summary
    if guard is not None:
        result["lockguard_violations"] = len(guard.violations())
    assert not failures, failures[:5]
    assert not missing, f"registry missing serving histograms: {missing}"
    assert result["completed"] == requests
    return result


def _sharpen(model, params, cfg, steps: int = 80):
    """A few SGD steps on a cyclic token stream so greedy decoding has
    decisive top-2 logit margins.  A randomly-initialized model's logits
    are near-flat — its argmax is a coin toss that ANY perturbation
    (including int8 KV quantization, ~0.2% of activation absmax) can
    flip, which would make token-agreement floors measure init noise
    instead of the quantizer.  Trained margins (~10x the quantization
    error) make the >= 0.999 agreement assertion test the quantizer."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.models.transformer import lm_loss_local

    toks = jnp.tile(jnp.arange(cfg.vocab_size, dtype=jnp.int32), 2)
    toks = jnp.broadcast_to(toks[None, :cfg.max_len], (4, cfg.max_len))
    tgts = (toks + 1) % cfg.vocab_size
    vg = jax.jit(jax.value_and_grad(
        lambda p: lm_loss_local(p, toks, tgts, cfg)))
    for _ in range(steps):
        _, g = vg(params)
        params = jax.tree_util.tree_map(lambda p, gg: p - 0.5 * gg,
                                        params, g)
    return params


def _scrape_gauges(prom_text: str, names: tuple[str, ...]) -> dict:
    """Parse plain ``name value`` gauge samples out of a Prometheus
    exposition page (comments and histogram series skipped)."""
    out: dict[str, float] = {}
    for line in prom_text.splitlines():
        if line.startswith("#"):
            continue
        parts = line.split()
        if len(parts) == 2 and parts[0] in names:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                pass
    return out


def run_prefix(requests: int = 32, threads: int = 4, seed: int = 0,
               page_size: int = 6, lockguard: bool = False,
               kv_quant: str | None = None) -> dict:
    """The ``--prefix-workload`` leg: Zipf-shared system prompts against
    a paged + prefix-cache engine, observed through real scrapes.

    With ``kv_quant`` set (``--kv-quant int8``) the SAME workload runs
    twice — float leg then quantized leg — and the run FAILS unless the
    scraped peak ``serving.kv_bytes_per_slot`` drops >= 1.9x, the prefix
    hit rate does not regress, and temperature-0 served tokens agree
    top-1 >= 0.999 between the legs."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.serving import (InferenceEngine, ModelServer,
                                            ServingClient, ServingConfig,
                                            ServingError)

    observability.enable()

    guard = None
    if lockguard:
        from deeplearning4j_tpu.analysis.lockguard import LockGuard

        guard = LockGuard().install()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))
    if kv_quant is not None:
        params = _sharpen(model, params, cfg)
    dense_bytes_per_slot = (cfg.max_len * cfg.n_heads * cfg.head_dim * 2
                            * cfg.n_layers * jnp.dtype(cfg.dtype).itemsize)

    rng = random.Random(seed)
    # Zipf-skewed tenant population: a handful of shared system prompts
    # (4 full pages each), rank-1 dominating — the shape prefix sharing
    # exists for
    n_tenants = 6
    sys_prompts = [[rng.randrange(cfg.vocab_size)
                    for _ in range(4 * page_size)] for _ in range(n_tenants)]
    zipf_w = [1.0 / (r + 1) ** 1.5 for r in range(n_tenants)]
    plans = []
    for _ in range(requests):
        tenant = rng.choices(range(n_tenants), weights=zipf_w)[0]
        user = [rng.randrange(cfg.vocab_size)
                for _ in range(rng.randint(1, 5))]
        plans.append(dict(prompt=sys_prompts[tenant] + user,
                          max_new_tokens=rng.randint(1, 8),
                          temperature=rng.choice([0.0, 0.7]),
                          seed=rng.randrange(1 << 20)))

    scrape_names = ("serving_prefix_hit_rate", "serving_kv_pages_in_use",
                    "serving_kv_bytes_per_slot", "serving_kv_bytes")

    def leg(kvq: str | None) -> dict:
        """One full pass of the workload against a fresh engine; scraped
        peaks + per-plan completions for cross-leg agreement."""
        METRICS.reset()
        failures: list[str] = []
        statuses: list[int] = []
        tokens_by_plan: dict[int, list[int]] = {}
        lock = threading.Lock()
        scraped: dict[str, float] = {}   # name -> peak value seen
        done = threading.Event()

        engine = InferenceEngine(
            model, params=params,
            cfg=ServingConfig(slots=4, resolve_every=4, paged=True,
                              page_size=page_size, prefix_cache=True,
                              kv_quant=kvq))
        with engine, ModelServer(engine=engine) as server:
            client = ServingClient(port=server.port)

            def scraper():
                # a real Prometheus poller: GET /metrics.prom on an
                # interval, keep the peaks (footprint claims come from
                # scrapes, not from reaching into the engine)
                while not done.is_set():
                    try:
                        vals = _scrape_gauges(client.metrics_prom(),
                                              scrape_names)
                        with lock:
                            for k, v in vals.items():
                                scraped[k] = max(scraped.get(k, 0.0), v)
                    except ServingError:
                        pass
                    done.wait(0.05)

            def worker(mine):
                for idx, plan in mine:
                    try:
                        out = client.generate(**plan)
                        with lock:
                            statuses.append(200)
                            tokens_by_plan[idx] = out["tokens"]
                        if len(out["tokens"]) > plan["max_new_tokens"]:
                            with lock:
                                failures.append(
                                    f"overlong answer for {plan}")
                    except ServingError as e:
                        with lock:
                            statuses.append(e.status)
                            failures.append(str(e))

            scrape_t = threading.Thread(target=scraper, daemon=True)
            scrape_t.start()
            numbered = list(enumerate(plans))
            ts = [threading.Thread(target=worker,
                                   args=(numbered[i::threads],))
                  for i in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            _time.sleep(0.1)             # let eviction-fence gauges land
            final = _scrape_gauges(client.metrics_prom(), scrape_names)
            done.set()
            scrape_t.join()
            with lock:
                for k, v in final.items():
                    scraped[k] = max(scraped.get(k, 0.0), v)
        return {"failures": failures, "completed": statuses.count(200),
                "rejected": len(statuses) - statuses.count(200),
                "scraped": scraped, "tokens": tokens_by_plan}

    float_leg = leg(None)
    quant_leg = leg(kv_quant) if kv_quant is not None else None
    primary = quant_leg if quant_leg is not None else float_leg
    failures = list(float_leg["failures"])
    if quant_leg is not None:
        failures += quant_leg["failures"]

    if guard is not None:
        guard.uninstall()
        guard.emit_metrics()
        for v in guard.violations():
            failures.append(str(v))

    snap = METRICS.snapshot()
    timers = snap["timers"]

    def pct(name):
        t = timers.get(name)
        return {"p50": t["p50_s"], "p99": t["p99_s"], "count": t["count"],
                "mean": t["mean_s"]} if t else None

    hit_rate = primary["scraped"].get("serving_prefix_hit_rate", 0.0)
    peak_bytes_per_slot = primary["scraped"].get(
        "serving_kv_bytes_per_slot", 0.0)
    float_bytes_per_slot = float_leg["scraped"].get(
        "serving_kv_bytes_per_slot", 0.0)
    result = {
        "workload": "prefix",
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "page_size": page_size,
        "kv_quant": kv_quant,
        "completed": primary["completed"],
        "rejected": primary["rejected"],
        "request_latency_s": pct("serving.request_latency"),
        "ttft_s": pct("serving.ttft"),
        "prefix_hit_rate": hit_rate,
        "kv_pages_in_use_peak": primary["scraped"].get(
            "serving_kv_pages_in_use"),
        "kv_bytes_per_slot_peak": peak_bytes_per_slot,
        "dense_kv_bytes_per_slot": dense_bytes_per_slot,
        "failures": failures[:5],
    }
    if guard is not None:
        result["lockguard_violations"] = len(guard.violations())
    assert not failures, failures[:5]
    assert float_leg["completed"] == requests
    assert primary["completed"] == requests
    assert hit_rate > 0.0, "prefix cache never hit under a Zipf workload"
    assert 0.0 < peak_bytes_per_slot < dense_bytes_per_slot, (
        f"paged KV bytes/slot {peak_bytes_per_slot} not below dense "
        f"baseline {dense_bytes_per_slot}")

    if quant_leg is not None:
        # the ISSUE-12 capacity claim, observed through real scrapes:
        # quantized bytes/slot must drop >= 1.9x, locality must hold,
        # and greedy served tokens must agree top-1 across the legs
        shrink = (float_bytes_per_slot / peak_bytes_per_slot
                  if peak_bytes_per_slot else 0.0)
        float_hit = float_leg["scraped"].get("serving_prefix_hit_rate", 0.0)
        agree, compared = 0, 0
        for idx, plan in enumerate(plans):
            if plan["temperature"] != 0.0:
                continue
            a = float_leg["tokens"].get(idx)
            b = quant_leg["tokens"].get(idx)
            if a is None or b is None:
                continue
            compared += len(a)
            agree += sum(1 for x, y in zip(a, b) if x == y)
        agreement = agree / compared if compared else 0.0
        result["kv_bytes_per_slot_float"] = float_bytes_per_slot
        result["kv_bytes_per_slot_shrink"] = shrink
        result["prefix_hit_rate_float"] = float_hit
        result["greedy_token_agreement"] = agreement
        result["greedy_tokens_compared"] = compared
        assert shrink >= 1.9, (
            f"kv_quant={kv_quant} bytes/slot shrink {shrink:.2f}x under "
            "the 1.9x floor")
        assert hit_rate >= float_hit - 0.05, (
            f"prefix hit rate regressed under kv_quant: {hit_rate:.3f} vs "
            f"float {float_hit:.3f}")
        assert compared > 0, "no greedy completions to compare across legs"
        assert agreement >= 0.999, (
            f"served-token top-1 agreement {agreement:.4f} under the "
            "0.999 floor")
    return result


def run_disagg(requests: int = 24, threads: int = 3, seed: int = 0,
               lockguard: bool = False) -> dict:
    """The ``--disagg`` leg (DESIGN.md §27): a bimodal workload against
    the disaggregated prefill/decode tier.

    Decode-heavy requests (short prompts, 16-token budgets) stream
    while prefill-heavy background traffic (page-spanning prompts,
    1-token budgets) runs at 1x and then at DOUBLE the load.  The run
    FAILS unless (a) every decode answer matches ``Transformer.sample``
    token-for-token — migration parity under load — and (b) the decode
    stream's p99 inter-token latency at 2x prefill load stays within
    1.15x of its 1x baseline: prefill pressure lands on the prefill
    tier, not on the decode cadence.  The shared background prompts
    also exercise the content-addressed dedup path; the emitted
    ``{"disagg": {"dedup_frac": ...}}`` feeds ``perf_gate.py
    --record``."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.serving import (DisaggScheduler, InferenceEngine,
                                            ServingConfig)

    observability.enable()
    METRICS.reset()

    guard = None
    if lockguard:
        from deeplearning4j_tpu.analysis.lockguard import LockGuard

        guard = LockGuard().install()

    page_size = 8
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))

    def mk(role):
        return InferenceEngine(
            model, params=params,
            cfg=ServingConfig(slots=4, resolve_every=4, max_queue=64,
                              paged=True, page_size=page_size,
                              prefix_cache=True, role=role))

    rng = random.Random(seed)
    # decode-heavy stream: short prompts, long budgets, greedy so every
    # answer is checkable against the offline reference
    dplans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                           for _ in range(rng.randint(4, 9))],
                   max_new_tokens=16, temperature=0.0, seed=0)
              for _ in range(requests)]
    expected = [list(model.sample(params, p["prompt"], 16, temperature=0.0,
                                  key=jax.random.key(0),
                                  kv_cache=True))[len(p["prompt"]):]
                for p in dplans]
    # prefill-heavy background: a few shared page-spanning prompts
    # (5 full pages), 1-token budgets — nearly all their cost is prefill
    bg_prompts = [[rng.randrange(cfg.vocab_size)
                   for _ in range(5 * page_size)] for _ in range(3)]

    failures: list[str] = []
    lock = threading.Lock()

    pf = mk("prefill")
    dec = mk("decode")
    sched = DisaggScheduler([pf], dec).start()
    try:
        def phase(bg_threads: int, measure: bool) -> dict:
            """Drive the decode stream while ``bg_threads`` background
            loops hammer the prefill tier; per-request mean inter-token
            seconds for the decode stream come back for the p99."""
            stop = threading.Event()
            itls: list[float] = []
            bg_done = [0]

            def bg_loop(k):
                i = k
                while not stop.is_set():
                    try:
                        sched.generate(bg_prompts[i % len(bg_prompts)], 1,
                                       temperature=0.0, seed=0, timeout=120)
                        with lock:
                            bg_done[0] += 1
                    except Exception as e:  # noqa: BLE001 - tallied
                        with lock:
                            failures.append(f"bg: {e}")
                        return
                    i += 1

            def worker(mine):
                for idx, plan in mine:
                    try:
                        c = sched.generate(**plan, timeout=120)
                    except Exception as e:  # noqa: BLE001 - tallied
                        with lock:
                            failures.append(f"decode: {e}")
                        continue
                    if c.tokens != expected[idx]:
                        with lock:
                            failures.append(
                                f"parity: plan {idx} {c.tokens} != "
                                f"{expected[idx]}")
                    if measure and len(c.tokens) > 1:
                        with lock:
                            itls.append((c.latency_s - c.ttft_s)
                                        / (len(c.tokens) - 1))

            bgs = [threading.Thread(target=bg_loop, args=(k,))
                   for k in range(bg_threads)]
            for t in bgs:
                t.start()
            numbered = list(enumerate(dplans))
            ts = [threading.Thread(target=worker,
                                   args=(numbered[i::threads],))
                  for i in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            stop.set()
            for t in bgs:
                t.join()
            itls.sort()
            p99 = (itls[min(len(itls) - 1, int(0.99 * len(itls)))]
                   if itls else None)
            return {"itl_p99_s": p99, "bg_completed": bg_done[0]}

        # warmup: touch every prompt bucket once so neither measured
        # phase pays jit compilation inside its latency samples
        phase(1, measure=False)
        base = phase(1, measure=True)
        doubled = phase(2, measure=True)
    finally:
        sched.stop()

    if guard is not None:
        guard.uninstall()
        guard.emit_metrics()
        for v in guard.violations():
            failures.append(str(v))

    snap = METRICS.snapshot()["counters"]
    moved = snap.get("disagg.pages_moved", 0.0)
    deduped = snap.get("disagg.pages_deduped", 0.0)
    dedup_frac = deduped / max(1.0, moved + deduped)
    ratio = (doubled["itl_p99_s"] / base["itl_p99_s"]
             if base["itl_p99_s"] else None)
    result = {
        "workload": "disagg",
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "page_size": page_size,
        "itl_p99_base_s": base["itl_p99_s"],
        "itl_p99_doubled_s": doubled["itl_p99_s"],
        "itl_p99_ratio": round(ratio, 4) if ratio is not None else None,
        "bg_completed": (base["bg_completed"], doubled["bg_completed"]),
        "migrations": snap.get("disagg.migrations", 0.0),
        "requeues": snap.get("disagg.requeues", 0.0),
        "disagg": {"dedup_frac": round(dedup_frac, 4),
                   "pages_moved": moved, "pages_deduped": deduped},
        "failures": failures[:5],
    }
    if guard is not None:
        result["lockguard_violations"] = len(guard.violations())
    assert not failures, failures[:5]
    assert doubled["bg_completed"] >= 2 * base["bg_completed"] * 0.5, (
        "doubled phase did not actually raise prefill load", result)
    assert deduped > 0, "shared background prompts never deduped a page"
    assert ratio is not None and ratio <= 1.15, (
        f"decode p99 inter-token degraded {ratio:.2f}x when prefill load "
        f"doubled — the tiers are not isolated ({result})")
    return result


def run_replicas(requests: int = 48, threads: int = 8, seed: int = 0,
                 replicas: int = 4, page_size: int = 6,
                 lockguard: bool = False, trace_out: str | None = None,
                 strict_scaling: bool = False) -> dict:
    """The ``--replicas N`` leg: one Zipf multi-tenant workload, run
    against a single-replica router and then an N-replica router, with
    affinity / aggregate-hit-rate / throughput / parity assertions."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS, TRACER, trace
    from deeplearning4j_tpu.serving import (EngineReplica, InferenceEngine,
                                            PrefixRouter, RouterConfig,
                                            RouterServer, ServingClient,
                                            ServingConfig, ServingError)

    observability.enable()
    METRICS.reset()
    if trace_out is not None:
        TRACER.clear()

    guard = None
    if lockguard:
        from deeplearning4j_tpu.analysis.lockguard import LockGuard

        guard = LockGuard().install()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))

    rng = random.Random(seed)
    n_tenants = 6
    sys_prompts = [[rng.randrange(cfg.vocab_size)
                    for _ in range(4 * page_size)] for _ in range(n_tenants)]
    zipf_w = [1.0 / (r + 1) ** 1.5 for r in range(n_tenants)]
    plans = []
    for _ in range(requests):
        tenant = rng.choices(range(n_tenants), weights=zipf_w)[0]
        user = [rng.randrange(cfg.vocab_size)
                for _ in range(rng.randint(1, 5))]
        plans.append(dict(prompt=sys_prompts[tenant] + user,
                          max_new_tokens=rng.randint(1, 8),
                          temperature=rng.choice([0.0, 0.7]),
                          seed=rng.randrange(1 << 20)))

    rcfg = RouterConfig(page_size=page_size, affinity_pages=4,
                        probe_interval_s=0.1, fail_threshold=2,
                        recover_threshold=2)

    def one_leg(n: int, want_traces: bool) -> dict:
        """Drive the full workload through a RouterServer over n fresh
        in-process replicas; returns scraped + client-side measurements."""
        METRICS.reset()
        failures: list[str] = []
        results: list[tuple[dict, dict]] = []     # (plan, completion)
        traces: list[str] = []
        lock = threading.Lock()
        engines = [InferenceEngine(
            model, params=params,
            cfg=ServingConfig(slots=2, resolve_every=4, paged=True,
                              page_size=page_size, prefix_cache=True))
            for _ in range(n)]
        reps = [EngineReplica(f"r{i}", e, own_engine=True)
                for i, e in enumerate(engines)]
        for e in engines:
            e.start()
        router = PrefixRouter(reps, rcfg)
        with RouterServer(router) as server:
            client = ServingClient(port=server.port)

            def worker(mine):
                for plan in mine:
                    try:
                        with trace.span("client.generate") as sp:
                            out = client.generate(**plan)
                        with lock:
                            results.append((plan, out))
                            if want_traces and getattr(sp, "trace_id", ""):
                                traces.append(sp.trace_id)
                    except ServingError as e:
                        with lock:
                            failures.append(str(e))

            t0 = _time.perf_counter()
            ts = [threading.Thread(target=worker, args=(plans[i::threads],))
                  for i in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            wall_s = _time.perf_counter() - t0
            _time.sleep(3 * rcfg.probe_interval_s)  # let the prober publish
            prom = client.metrics_prom()
            health = client.healthz()
        scraped = _scrape_gauges(prom, ("router_prefix_hit_rate",))
        counters = _scrape_counters(
            prom, ("router_requests_total", "router_prefix_affinity_hit_total",
                   "router_spillover_total", "router_quarantines_total"))
        tokens = sum(len(o["tokens"]) for _, o in results)
        return {"replicas": n, "wall_s": wall_s, "tokens": tokens,
                "tokens_per_sec": tokens / wall_s if wall_s else 0.0,
                "completed": len(results), "failures": failures,
                "hit_rate": scraped.get("router_prefix_hit_rate", 0.0),
                "counters": counters, "results": results,
                "traces": traces, "health": health}

    single = one_leg(1, want_traces=False)
    multi = one_leg(replicas, want_traces=trace_out is not None)

    failures = single["failures"] + multi["failures"]

    # token parity, including spilled requests: every temperature-0
    # completion must equal the offline sample for its seed
    parity_checked = 0
    for plan, out in multi["results"]:
        if plan["temperature"] != 0.0 or parity_checked >= 8:
            continue
        exp = model.sample(params, plan["prompt"], plan["max_new_tokens"],
                           temperature=0.0, key=jax.random.key(plan["seed"]),
                           kv_cache=True)[len(plan["prompt"]):]
        if out["tokens"] != [int(t) for t in exp]:
            failures.append(f"parity mismatch on replica {out['replica']} "
                            f"(spills={out['spills']})")
        parity_checked += 1

    trace_summary = None
    if trace_out is not None:
        from tools.trace_report import merge, request_breakdowns
        TRACER.save_chrome_trace(trace_out)
        merged = merge([trace_out])
        with open(trace_out, "w") as f:
            json.dump(merged, f)
        by_trace: dict[str, set] = {}
        for ev in merged["traceEvents"]:
            tid = (ev.get("args") or {}).get("trace_id")
            if tid:
                by_trace.setdefault(tid, set()).add(ev["name"])
        need = {"router.request", "router.route", "serving.request",
                "serving.queue_wait", "serving.prefill", "serving.emit"}
        for tid in multi["traces"]:
            missing = need - by_trace.get(tid, set())
            if missing:
                failures.append(
                    f"trace {tid[:12]} missing spans {sorted(missing)}")
        routed_rows = [r for r in request_breakdowns(merged["traceEvents"])
                       if r["route_hops"]]
        if not routed_rows:
            failures.append("trace_report shows no router hop on any request")
        trace_summary = {"path": trace_out,
                         "events": len(merged["traceEvents"]),
                         "requests_traced": len(multi["traces"]),
                         "routed_breakdown_rows": len(routed_rows)}

    if guard is not None:
        guard.uninstall()
        guard.emit_metrics()
        for v in guard.violations():
            failures.append(str(v))

    reqs = multi["counters"].get("router_requests_total", 0.0)
    affinity = (multi["counters"].get("router_prefix_affinity_hit_total", 0.0)
                / reqs if reqs else 0.0)
    scaling = (multi["tokens_per_sec"] / single["tokens_per_sec"]
               if single["tokens_per_sec"] else 0.0)
    result = {
        "workload": "replicas",
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "replicas": replicas,
        "page_size": page_size,
        "completed": multi["completed"],
        "single_hit_rate": single["hit_rate"],
        "aggregate_hit_rate": multi["hit_rate"],
        "prefix_affinity_rate": affinity,
        "spillover": multi["counters"].get("router_spillover_total", 0.0),
        "quarantines": multi["counters"].get("router_quarantines_total", 0.0),
        "single_tokens_per_sec": single["tokens_per_sec"],
        "tokens_per_sec": multi["tokens_per_sec"],
        "throughput_scaling": scaling,
        "parity_checked": parity_checked,
        "failures": failures[:5],
    }
    if trace_summary is not None:
        result["trace"] = trace_summary
    if guard is not None:
        result["lockguard_violations"] = len(guard.violations())
    assert not failures, failures[:5]
    assert single["completed"] == requests and multi["completed"] == requests
    assert parity_checked > 0, "no temperature-0 plans to parity-check"
    assert multi["hit_rate"] >= single["hit_rate"] - 0.05, (
        f"aggregate prefix hit rate {multi['hit_rate']:.3f} fell below the "
        f"single-replica run {single['hit_rate']:.3f} — affinity routing is "
        "shredding locality")
    assert affinity >= 0.9 - (result["spillover"] / max(reqs, 1.0)), (
        f"prefix affinity rate {affinity:.3f} too low for a healthy ring")
    floor = 0.6 * replicas if strict_scaling else 0.8
    assert scaling >= floor, (
        f"throughput scaling {scaling:.2f}x under the {floor:.2f}x floor "
        f"({replicas} replicas)")
    return result


def run_slo(requests: int = 48, threads: int = 4, seed: int = 0,
            window_s: float = 2.0, ts_interval_s: float = 0.1) -> dict:
    """The ``--slo`` leg: the Zipf multi-tenant workload served while a
    :class:`TimeSeriesStore` samples the registry and an
    :class:`SLOEvaluator` watches ``default_serving_objectives`` over
    smoke-sized windows (``window_s`` and ``2*window_s`` instead of
    30/120 s).  The run holds the sampler alive until the short window
    is fully covered and FAILS unless at least one objective reaches a
    full window with a computed burn rate — the live end-to-end proof
    that sampling, windowing, and burn math connect.  Burn rates land
    in the JSON line; with ``DL4J_TPU_TS_DIR`` set the samples also
    land as JSONL for ``metrics_dump.py --timeline``."""
    import time as _time

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import (METRICS, SLOEvaluator,
                                                  TimeSeriesStore,
                                                  default_serving_objectives)
    from deeplearning4j_tpu.serving import (InferenceEngine, ModelServer,
                                            ServingClient, ServingConfig,
                                            ServingError)

    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))

    rng = random.Random(seed)
    n_tenants = 6
    sys_prompts = [[rng.randrange(cfg.vocab_size)
                    for _ in range(8)] for _ in range(n_tenants)]
    zipf_w = [1.0 / (r + 1) ** 1.5 for r in range(n_tenants)]
    plans = []
    for _ in range(requests):
        tenant = rng.choices(range(n_tenants), weights=zipf_w)[0]
        user = [rng.randrange(cfg.vocab_size)
                for _ in range(rng.randint(1, 5))]
        plans.append(dict(prompt=sys_prompts[tenant] + user,
                          max_new_tokens=rng.randint(1, 8),
                          temperature=rng.choice([0.0, 0.7]),
                          seed=rng.randrange(1 << 20)))

    windows = (window_s, 2.0 * window_s)
    store = TimeSeriesStore(interval_s=ts_interval_s)
    evaluator = SLOEvaluator(default_serving_objectives(windows=windows),
                             store, breach_cooldown_s=windows[-1])

    failures: list[str] = []
    statuses: list[int] = []
    lock = threading.Lock()
    t0 = _time.time()
    store.start()
    try:
        engine = InferenceEngine(model, params=params,
                                 cfg=ServingConfig(slots=4, resolve_every=4))
        with engine, ModelServer(engine=engine) as server:
            client = ServingClient(port=server.port)

            def worker(mine):
                for plan in mine:
                    try:
                        client.generate(**plan)
                        with lock:
                            statuses.append(200)
                    except ServingError as e:
                        with lock:
                            statuses.append(e.status)
                            failures.append(str(e))

            ts = [threading.Thread(target=worker, args=(plans[i::threads],))
                  for i in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            # hold the sampler until the short window is fully covered —
            # a series only exists once its first request lands (after
            # jit compile), so anchor the hold to the workload's end, and
            # the registry keeps serving the last percentiles meanwhile
            deadline = _time.time() + windows[0] * 1.1
            while _time.time() < deadline:
                _time.sleep(ts_interval_s)
    finally:
        store.stop()

    status = evaluator.status()
    full_computed = sorted(
        name for name, burns in status["objectives"].items()
        if any(b["full"] and b["burn"] is not None for b in burns))
    gauges = METRICS.snapshot()["gauges"]
    burn_rates = {k[len("slo.burn_rate."):]: v for k, v in gauges.items()
                  if k.startswith("slo.burn_rate.")}
    timers = METRICS.snapshot()["timers"]
    ttft = timers.get("serving.ttft")

    result = {
        "workload": "slo",
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "windows_s": list(windows),
        "completed": statuses.count(200),
        "rejected": len(statuses) - statuses.count(200),
        "samples": store.stats()["samples"],
        "evaluations": status["evaluations"],
        "burn_rates": burn_rates,
        "full_window_objectives": full_computed,
        "breaches": status["breaches"],
        "ttft_s": ({"p50": ttft["p50_s"], "p99": ttft["p99_s"],
                    "count": ttft["count"]} if ttft else None),
        "failures": failures[:5],
    }
    assert not failures, failures[:5]
    assert statuses.count(200) == requests
    assert status["evaluations"] > 0, "SLO evaluator never ran"
    assert full_computed, (
        "no objective reached a full window with a computed burn rate "
        f"(windows {windows}, {store.stats()['samples']} samples)")
    assert burn_rates, "no slo.burn_rate.* gauges published"
    return result


def _scrape_counters(prom_text: str, names: tuple[str, ...]) -> dict:
    """Counter samples (``name_total value``) from a Prometheus page."""
    return _scrape_gauges(prom_text, names)


def run_online(requests: int = 24, threads: int = 3, seed: int = 0,
               rounds: int = 2) -> dict:
    """The ``--online`` leg: the full serve → capture → fine-tune →
    hot-reload dataflow (DESIGN.md §23) over the HTTP surface.  Each
    round serves a wave of greedy requests through a ``ModelServer``
    whose capture hook feeds a ``CaptureStore``, then runs one
    ``OnlineLoop`` round — replay, supervised fine-tune, checkpoint
    publish, canaried hot reload into the live engine.  The run FAILS
    unless every completed response's tokens match offline
    ``Transformer.sample`` under the checkpoint named by its OWN
    ``loaded_step`` stamp (the generation-consistency invariant: no
    response ever decodes under a torn or mixed model) and at least one
    reload applied.  The JSON line carries the online metric tier
    (``online.generation``/``online.reloads``/``capture.bytes``/…)."""
    import tempfile
    import time as _time

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.online import CaptureStore, OnlineConfig, OnlineLoop
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
    from deeplearning4j_tpu.serving import (InferenceEngine, ModelServer,
                                            ServingClient, ServingConfig,
                                            ServingError)

    observability.enable()
    METRICS.reset()

    cfg = TransformerConfig(vocab_size=32, d_model=16, n_heads=2, n_layers=1,
                            d_ff=32, max_len=32, dtype=jnp.float32,
                            remat=False)
    model = TransformerLM(cfg)
    params0 = model.init(jax.random.key(7))

    rng = random.Random(seed)
    root = tempfile.mkdtemp(prefix="online-smoke-")
    store = CaptureStore(f"{root}/capture", segment_bytes=1 << 14)
    mgr = CheckpointManager(f"{root}/ckpt", keep=32)
    failures: list[str] = []
    served: list[dict] = []
    lock = threading.Lock()
    round_reports: list[dict] = []
    t0 = _time.time()

    engine = InferenceEngine(model, params=params0, checkpoint=mgr,
                             cfg=ServingConfig(slots=2, idle_wait_s=0.01))
    loop = OnlineLoop(store, mgr, model, params0=params0, engine=engine,
                      cfg=OnlineConfig(batch=2, seq=8))
    with engine, ModelServer(engine=engine, capture=store) as server:
        client = ServingClient(port=server.port)

        def worker(mine):
            for plan in mine:
                try:
                    out = client.generate(**plan)
                    with lock:
                        served.append({"plan": plan, "out": out})
                except ServingError as e:
                    with lock:
                        failures.append(f"request failed: {e}")

        per_round = max(1, requests // max(1, rounds))
        for _ in range(rounds):
            plans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                                  for _ in range(rng.randint(2, 6))],
                          max_new_tokens=rng.randint(2, 8),
                          temperature=0.0, seed=rng.randrange(1 << 20))
                     for _ in range(per_round)]
            ts = [threading.Thread(target=worker,
                                   args=(plans[i::threads],))
                  for i in range(threads)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            round_reports.append(loop.run_once().to_dict())

    store.close()
    # generation-consistency audit: every completed response must match
    # offline sampling under the checkpoint its OWN stamp names
    restored_cache: dict = {None: params0}

    def params_at(step):
        if step not in restored_cache:
            restored_cache[step] = mgr.restore(params0, step=step)["params"]
        return restored_cache[step]

    for rec in served:
        plan, out = rec["plan"], rec["out"]
        exp = model.sample(params_at(out.get("loaded_step")), plan["prompt"],
                           len(out["tokens"]), temperature=0.0,
                           key=jax.random.key(plan["seed"]),
                           kv_cache=True)[len(plan["prompt"]):]
        if out["tokens"] != exp:
            failures.append(
                f"generation-stamp parity: step {out.get('loaded_step')} "
                f"gen {out.get('generation')}: {out['tokens']} != {exp}")
    if not any(r["status"] == "ok" for r in round_reports):
        failures.append(f"no round applied a reload: {round_reports}")

    snap = METRICS.snapshot()
    gauges, counters = snap.get("gauges", {}), snap.get("counters", {})
    return {
        "ok": not failures,
        "failures": failures,
        "requests": len(served),
        "rounds": [r["status"] for r in round_reports],
        "generations": sorted({r["out"].get("generation") for r in served}),
        "online.generation": gauges.get("online.generation"),
        "online.reloads": counters.get("online.reloads", 0),
        "online.rollbacks": counters.get("online.rollbacks", 0),
        "online.captured_records": counters.get("online.captured_records", 0),
        "capture.bytes": gauges.get("capture.bytes"),
        "online.reload_seconds": gauges.get("online.reload_seconds"),
        "wall_s": _time.time() - t0,
    }


def run_fleet(requests: int = 36, threads: int = 6, seed: int = 0,
              replicas: int = 3) -> dict:
    """The ``--fleet`` leg (DESIGN.md §24): a Zipf multi-tenant workload
    over N REAL process replicas (each with its own registry), federated
    by a :class:`FleetScraper` over the router's pool.

    Three contracts are asserted live:

    - **Exact federation**: the ``fleet.tokens_total`` rollup equals the
      sum of every replica's own ``serving.tokens`` counter equals the
      client-observed token total — token-for-token, no sampling slack.
    - **Exact tenancy**: every tenant's ``tenant.<t>.generated_tokens``,
      summed across replicas, equals the tokens the client watched that
      tenant receive.
    - **Graceful degradation**: SIGKILLing one replica mid-run costs
      ``fleet.scrape_errors`` plus a stale mark for THAT replica only —
      scrapes never hang, other replicas' rollups stay exact, and the
      killed replica's already-generated tokens stay in the counter
      rollup (stale counters are history, not noise).

    A synthetic-ramp forecast phase then proves the §24 ordering claim:
    ``forecast.time_to_breach.serving_ttft`` dumps its
    ``forecast_breach`` bundle strictly before the ``SLOEvaluator``
    records the actual breach.  The JSON line carries
    ``{"fleet": {"scrape_ms": ...}}`` for ``perf_gate.py``.
    """
    import tempfile
    import time as _time

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.observability import (METRICS, FleetScraper,
                                                  ForecastEvaluator,
                                                  MetricsRegistry,
                                                  SLOEvaluator, SLObjective,
                                                  TENANTS, TimeSeriesStore)
    from deeplearning4j_tpu.serving import (PrefixRouter, ProcessReplica,
                                            RouterConfig, RouterServer,
                                            ServingClient, ServingError)

    observability.enable()
    METRICS.reset()
    TENANTS.reset()

    rng = random.Random(seed)
    vocab, page_size = 64, 4
    tenants = ["acme", "globex", "initech", "umbrella"]
    zipf_w = [1.0 / (r + 1) ** 1.5 for r in range(len(tenants))]

    def make_plans(n: int) -> list[dict]:
        out = []
        for _ in range(n):
            t = rng.choices(tenants, weights=zipf_w)[0]
            out.append(dict(prompt=[rng.randrange(vocab)
                                    for _ in range(rng.randint(2, 10))],
                            max_new_tokens=rng.randint(1, 8),
                            temperature=rng.choice([0.0, 0.7]),
                            seed=rng.randrange(1 << 20), tenant=t))
        return out

    failures: list[str] = []
    observed: list[tuple[str, int]] = []      # (tenant, tokens delivered)
    lock = threading.Lock()
    workdir = tempfile.mkdtemp(prefix="fleet-smoke-")
    reps = [ProcessReplica(
        f"p{i}", "deeplearning4j_tpu.serving.router.procserver"
                 ":tiny_lm_factory", workdir,
        factory_kwargs={"max_len": 32, "slots": 2, "paged": True,
                        "page_size": page_size, "prefix_cache": True},
        env={"JAX_PLATFORMS": "cpu"}, client_timeout_s=30.0)
        for i in range(replicas)]
    router = PrefixRouter(reps, RouterConfig(
        page_size=page_size, affinity_pages=2, probe_interval_s=0.2,
        fail_threshold=2, recover_threshold=2))
    scraper = FleetScraper(router.pool, interval_s=0.25, timeout_s=5.0)

    def drive(plans):
        def worker(mine):
            for plan in mine:
                try:
                    out = client.generate(**plan)
                    with lock:
                        observed.append((plan["tenant"],
                                         len(out["tokens"])))
                except ServingError as e:
                    with lock:
                        failures.append(str(e))

        ts = [threading.Thread(target=worker, args=(plans[i::threads],))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    with RouterServer(router) as server:
        client = ServingClient(port=server.port)
        scraper.start()
        drive(make_plans(requests // 2))
        _time.sleep(0.2)                   # let final evictions account
        scraper.scrape_once()              # all replicas alive + scraped
        live_before = dict(
            scraper.fed.values("serving.tokens", include_stale=True))
        if len(live_before) != replicas:
            failures.append(
                f"expected {replicas} federated replicas before the kill, "
                f"got {sorted(live_before)}")

        # chaos: SIGKILL one replica; scrapes must fail fast (bounded by
        # one timeout, here a poll() short-circuit), mark ONLY it stale,
        # and keep its already-generated tokens in the counter rollup
        killed = reps[-1].name
        reps[-1].kill()
        t_kill = _time.perf_counter()
        scraper.scrape_once()
        kill_scrape_s = _time.perf_counter() - t_kill

        drive(make_plans(requests - requests // 2))
        _time.sleep(0.2)
        scraper.scrape_once()
        scraper.stop()
        snap = METRICS.snapshot()

        # per-replica ground truth: scrape the LIVE replicas directly
        # (the killed one's truth is its last federated value)
        per_replica: dict[str, float] = {}
        for rep in reps:
            if rep.name == killed:
                per_replica[rep.name] = live_before.get(killed, 0.0)
                continue
            body = rep.metrics_prom(timeout_s=5.0)
            per_replica[rep.name] = _scrape_counters(
                body, ("serving_tokens_total",)).get(
                    "serving_tokens_total", 0.0)

    client_tokens = sum(n for _, n in observed)
    fed_tokens = scraper.fed.values("serving.tokens", include_stale=True)
    fed_total = sum(fed_tokens.values())
    fleet_gauge = snap["gauges"].get("fleet.tokens_total")
    scrape_errors = snap["counters"].get("fleet.scrape_errors", 0.0)
    stale = scraper.fed.stale_replicas()
    client_by_tenant: dict[str, int] = {}
    for t, n in observed:
        client_by_tenant[t] = client_by_tenant.get(t, 0) + n
    fed_by_tenant = {
        t: sum(scraper.fed.values(f"tenant.{t}.generated_tokens",
                                  include_stale=True).values())
        for t in tenants}
    scrape_timer = snap["timers"].get("fleet.scrape")

    # ---- synthetic-ramp forecast phase: warning strictly before breach
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    obj = SLObjective("serving_ttft", "upper", "serving.ttft.p99", 0.5,
                      budget=0.05, windows=(8.0, 16.0))
    slo = SLOEvaluator([obj], store, registry=reg,
                       breach_cooldown_s=1e9)
    fore = ForecastEvaluator([obj], store, registry=reg, horizon_s=30.0,
                             window_s=8.0, min_samples=4,
                             breach_cooldown_s=1e9)
    t = 0.0
    while t <= 40.0:
        reg.gauge("serving.ttft.p99", 0.1 + 0.02 * t)   # crosses 0.5 @ t=20
        store.sample_once(t=t)
        t += 0.5
    warn_t = fore._last_warn_t.get("serving_ttft")
    breach_t = slo.breach_times.get("serving_ttft")
    forecast_led = (warn_t is not None and breach_t is not None
                    and warn_t < breach_t)

    result = {
        "workload": "fleet",
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "replicas": replicas,
        "completed": len(observed),
        "client_tokens": client_tokens,
        "federated_tokens": fed_total,
        "fleet_tokens_total_gauge": fleet_gauge,
        "per_replica_tokens": per_replica,
        "killed_replica": killed,
        "kill_scrape_s": kill_scrape_s,
        "scrape_errors": scrape_errors,
        "stale_replicas": stale,
        "tenants_client": client_by_tenant,
        "tenants_federated": fed_by_tenant,
        "forecast_warn_t": warn_t,
        "slo_breach_t": breach_t,
        "forecast_breach_bundles": len(fore.warnings),
        "fleet": {"scrape_ms": (scrape_timer["mean_s"] * 1e3
                                if scrape_timer else None),
                  "scrapes": snap["counters"].get("fleet.scrapes", 0.0)},
        "failures": failures[:5],
    }
    assert not failures, failures[:5]
    assert len(observed) == requests, (
        f"only {len(observed)}/{requests} requests completed")
    assert fed_total == client_tokens, (
        f"federated token sum {fed_total} != client-observed "
        f"{client_tokens} — federation must be exact")
    assert fleet_gauge == sum(per_replica.values()) == client_tokens, (
        f"fleet.tokens_total {fleet_gauge} != per-replica sum "
        f"{sum(per_replica.values())} != client {client_tokens}")
    assert scrape_errors >= 1.0, "killed replica never counted as a scrape error"
    assert stale == [killed], (
        f"stale set {stale} != [{killed}] — only the killed replica may "
        "be marked stale")
    assert kill_scrape_s < 2 * scraper.timeout_s, (
        f"scrape after SIGKILL took {kill_scrape_s:.1f}s — must be "
        "bounded, never a hang")
    for t_name, n in client_by_tenant.items():
        assert fed_by_tenant.get(t_name) == n, (
            f"tenant {t_name}: federated {fed_by_tenant.get(t_name)} != "
            f"client-observed {n}")
    assert forecast_led, (
        f"forecast (warn_t={warn_t}) did not lead the SLO breach "
        f"(breach_t={breach_t})")
    assert fore.warnings, "no forecast_breach bundle was dumped"
    return result


def run_autoscale(seed: int = 0, requests: int = 24, threads: int = 4,
                  day_s: float = 86400.0) -> dict:
    """The ``--autoscale`` leg (DESIGN.md §26), in two phases.

    **Real seams**: a scripted-signal :class:`Autoscaler` wired through
    ``router_actuators`` scales a live ``RouterServer`` pool 1 -> 2 -> 1.
    The scale-up replica warms BEFORE ring admission, the scale-down
    rides the quarantine drain path, and a fixed greedy probe must stay
    token-identical to offline ``Transformer.sample`` across every
    membership change — elasticity must never cost correctness.

    **Diurnal-plus-spike**: the same controller (real ``evaluate``/
    ``step`` logic, injected clock) runs over a deterministic fluid
    model of one simulated day — a diurnal sine plus an afternoon
    spike, fixed per-replica service rate, queue carried between
    windows.  The run FAILS unless the TTFT objective holds for >= 95%
    of simulated time (the SLO budget) while the autoscaler burns
    measurably fewer replica-hours than a static fleet provisioned for
    the peak.  The JSON line carries
    ``{"autoscale": {"saved_frac": ...}}`` for ``perf_gate.py``.
    """
    import math

    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.control import (Autoscaler, AutoscalerConfig,
                                            ControlSignals)
    from deeplearning4j_tpu.control.autoscaler import router_actuators
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.serving import (EngineReplica, InferenceEngine,
                                            PrefixRouter, RouterConfig,
                                            RouterServer, ServingClient,
                                            ServingConfig, ServingError)

    observability.enable()
    METRICS.reset()
    rng = random.Random(seed)

    # ---- phase 1: the controller over the real router seams -------------
    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=32, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))
    scfg = ServingConfig(slots=2, resolve_every=2)

    def replica(name: str) -> EngineReplica:
        eng = InferenceEngine(model, params=params, cfg=scfg).start()
        return EngineReplica(name, eng, own_engine=True)

    probe = dict(prompt=[3, 1, 4, 1, 5], max_new_tokens=8, temperature=0.0,
                 seed=0)
    expected = model.sample(params, probe["prompt"], probe["max_new_tokens"],
                            temperature=0.0, key=jax.random.key(0),
                            kv_cache=True)[len(probe["prompt"]):]

    acfg = AutoscalerConfig(min_replicas=1, max_replicas=2, cooldown_s=10.0,
                            down_consecutive=2, warm_timeout_s=60.0,
                            drain_timeout_s=30.0)
    feed: list[ControlSignals] = []
    sim_t = [0.0]
    serial = [0]

    def factory() -> EngineReplica:
        serial[0] += 1
        return replica(f"a{serial[0]}")

    router = PrefixRouter([replica("a0")], RouterConfig(
        page_size=4, probe_interval_s=0.5, fail_threshold=2,
        recover_threshold=2))
    up, down, size = router_actuators(router, factory, acfg)
    scaler = Autoscaler(lambda: feed.pop(0), up, down, size, acfg,
                        clock=lambda: sim_t[0])

    failures: list[str] = []
    probes: list[list[int]] = []
    pool_sizes: list[int] = []
    lock = threading.Lock()

    def drive(client, n: int) -> None:
        plans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                              for _ in range(rng.randint(2, 8))],
                      max_new_tokens=rng.randint(1, 6),
                      temperature=rng.choice([0.0, 0.7]),
                      seed=rng.randrange(1 << 20))
                 for _ in range(n)]
        def worker(mine):
            for plan in mine:
                try:
                    client.generate(**plan)
                except ServingError as e:
                    with lock:
                        failures.append(str(e))
        ts = [threading.Thread(target=worker, args=(plans[i::threads],))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    def play(sig: ControlSignals) -> str | None:
        sim_t[0] += acfg.cooldown_s + 1.0
        feed.append(sig)
        return scaler.step()

    with RouterServer(router) as server:
        client = ServingClient(port=server.port)
        probes.append(client.generate(**probe)["tokens"])
        drive(client, requests // 3)
        pool_sizes.append(len(router.pool.names()))

        took_up = play(ControlSignals(burn=2.0, queue_depth=40))
        pool_sizes.append(len(router.pool.names()))
        probes.append(client.generate(**probe)["tokens"])
        drive(client, requests // 3)

        took_down = None
        for _ in range(acfg.down_consecutive + 1):
            took_down = play(ControlSignals(burn=0.0, queue_depth=0)) \
                or took_down
        pool_sizes.append(len(router.pool.names()))
        probes.append(client.generate(**probe)["tokens"])
        drive(client, requests - 2 * (requests // 3))

    snap = METRICS.snapshot()
    router.close()

    # ---- phase 2: diurnal + spike fluid model over one simulated day ----
    dt, cap, ttft_target = 60.0, 10.0, 1.0

    def lam(t: float) -> float:
        diurnal = 8.0 + 52.0 * (0.5 - 0.5 * math.cos(2 * math.pi * t / day_s))
        spike = 30.0 if 0.55 * day_s <= t < 0.62 * day_s else 0.0
        return diurnal + spike

    peak = max(lam(i * dt) for i in range(int(day_s / dt)))
    n_static = math.ceil(peak / cap)

    def simulate(elastic: bool) -> dict:
        state = {"n": n_static if not elastic else 2, "t": 0.0}
        fcfg = AutoscalerConfig(interval_s=dt, min_replicas=1,
                                max_replicas=n_static + 2, cooldown_s=2 * dt,
                                burn_up=1.0, burn_down=0.55, queue_high=50,
                                queue_low=5, down_consecutive=5)
        sig_box: list[ControlSignals] = [ControlSignals()]

        def bump(delta):
            def act():
                state["n"] += delta
            return act

        ctl = Autoscaler(lambda: sig_box[0], bump(+1), bump(-1),
                         lambda: state["n"], fcfg, clock=lambda: state["t"])
        q = replica_s = ok_s = 0.0
        actions = {"up": 0, "down": 0}
        for i in range(int(day_s / dt)):
            t = i * dt
            state["t"] = t
            n = state["n"]
            served = min(q + lam(t) * dt, n * cap * dt)
            q = max(0.0, q + lam(t) * dt - served)
            ttft = 0.05 + q / (n * cap)
            replica_s += n * dt
            ok_s += dt if ttft <= ttft_target else 0.0
            if elastic:
                # burn against an 80%-utilisation budget: queue growth is
                # the breach, sustained high utilisation is the warning
                sig_box[0] = ControlSignals(
                    burn=lam(t) / (n * cap) / 0.8, queue_depth=int(q))
                took = ctl.step()
                if took:
                    actions[took] += 1
        return {"replica_hours": replica_s / 3600.0,
                "ttft_ok_frac": ok_s / day_s,
                "scale_ups": actions["up"], "scale_downs": actions["down"],
                "final_n": state["n"]}

    elastic = simulate(elastic=True)
    static = simulate(elastic=False)
    saved = 1.0 - elastic["replica_hours"] / static["replica_hours"]

    result = {
        "workload": "autoscale",
        "seed": seed,
        "probe_parity": all(p == expected for p in probes),
        "pool_sizes": pool_sizes,
        "actions_real": [took_up, took_down],
        "router_scale_up": snap["counters"].get("router.scale_up", 0.0),
        "router_scale_down": snap["counters"].get("router.scale_down", 0.0),
        "control_scale_up": snap["counters"].get("control.scale_up", 0.0),
        "control_scale_down": snap["counters"].get("control.scale_down", 0.0),
        "failures": failures[:5],
        "static_peak_replicas": n_static,
        "elastic": elastic,
        "static": {k: static[k] for k in ("replica_hours", "ttft_ok_frac")},
        "autoscale": {"saved_frac": round(saved, 4),
                      "replica_hours": round(elastic["replica_hours"], 3),
                      "static_replica_hours": round(static["replica_hours"],
                                                    3)},
    }
    assert not failures, failures[:5]
    assert result["probe_parity"], (
        f"greedy probe diverged across scale events: {probes} != {expected}")
    assert pool_sizes == [1, 2, 1], (
        f"pool did not scale 1 -> 2 -> 1 through the real seams: "
        f"{pool_sizes} (actions {took_up!r}/{took_down!r})")
    assert took_up == "up" and took_down == "down", (took_up, took_down)
    assert result["router_scale_up"] >= 1.0 \
        and result["router_scale_down"] >= 1.0, snap["counters"]
    assert static["ttft_ok_frac"] == 1.0, (
        f"static-peak baseline itself breached TTFT: {static}")
    assert elastic["ttft_ok_frac"] >= 0.95, (
        f"autoscaler failed to hold the TTFT objective: {elastic}")
    assert elastic["scale_ups"] >= 2 and elastic["scale_downs"] >= 2, elastic
    assert saved >= 0.2, (
        f"autoscaling saved only {saved:.1%} replica-hours vs static peak "
        f"({elastic['replica_hours']:.1f}h vs {static['replica_hours']:.1f}h)")
    return result


def main(argv: list[str]) -> int:
    def arg(flag, default, cast=int):
        return cast(argv[argv.index(flag) + 1]) if flag in argv else default

    if "--online" in argv:
        out = run_online(requests=arg("--requests", 24),
                         threads=arg("--threads", 3),
                         seed=arg("--seed", 0),
                         rounds=arg("--rounds", 2))
        print(json.dumps(out))
        return 0 if out["ok"] else 1
    if "--autoscale" in argv:
        out = run_autoscale(seed=arg("--seed", 0),
                            requests=arg("--requests", 24),
                            threads=arg("--threads", 4))
        print(json.dumps(out))
        return 0
    if "--disagg" in argv:
        out = run_disagg(requests=arg("--requests", 24),
                         threads=arg("--threads", 3),
                         seed=arg("--seed", 0),
                         lockguard="--lockguard" in argv)
        print(json.dumps(out))
        return 0
    if "--fleet" in argv:
        out = run_fleet(requests=arg("--requests", 36),
                        threads=arg("--threads", 6),
                        seed=arg("--seed", 0),
                        replicas=arg("--replicas", 3))
    elif "--replicas" in argv:
        out = run_replicas(requests=arg("--requests", 48),
                           threads=arg("--threads", 8),
                           seed=arg("--seed", 0),
                           replicas=arg("--replicas", 4),
                           page_size=arg("--page-size", 6),
                           lockguard="--lockguard" in argv,
                           trace_out=arg("--trace-out", None, str),
                           strict_scaling="--strict-scaling" in argv)
    elif "--slo" in argv:
        out = run_slo(requests=arg("--requests", 48),
                      threads=arg("--threads", 4),
                      seed=arg("--seed", 0),
                      window_s=arg("--window", 2.0, float))
    elif "--prefix-workload" in argv:
        out = run_prefix(requests=arg("--requests", 32),
                         threads=arg("--threads", 4),
                         seed=arg("--seed", 0),
                         page_size=arg("--page-size", 6),
                         lockguard="--lockguard" in argv,
                         kv_quant=arg("--kv-quant", None, str))
    else:
        out = run(requests=arg("--requests", 32),
                  threads=arg("--threads", 4),
                  seed=arg("--seed", 0),
                  lockguard="--lockguard" in argv,
                  trace_out=arg("--trace-out", None, str))
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    import os
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main(sys.argv[1:]))
