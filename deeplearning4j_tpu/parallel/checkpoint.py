"""Checkpoint / resume.

Exceeds the reference (SURVEY.md §5.4: java-serialized params only, no
optimizer state or data cursor — ``DefaultModelSaver``,
``ModelSavingActor.java:75-79``): checkpoints carry params + optimizer
(transform) state + step counter + RNG key + data cursor, with keep-last-N
rotation and atomic writes.  Storage is a directory of npz payloads + JSON
metadata — host-side, mesh-agnostic (arrays are gathered to host before
write; on restore the trainer re-places them onto its mesh).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import METRICS, trace


def _flatten_with_paths(tree) -> dict[str, np.ndarray]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        out[key] = np.asarray(leaf)
    return out


def _restore_like(template, arrays: dict[str, np.ndarray]):
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        arr = arrays[key]
        leaves.append(jnp.asarray(arr) if isinstance(leaf, (jnp.ndarray, np.ndarray))
                      else type(leaf)(arr.item()))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Keep-last-N rotating checkpoints under a directory."""

    def __init__(self, directory: str | Path, keep: int = 3):
        self.directory = Path(directory)
        self.directory.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    # ------------------------------------------------------------------ save
    def save(self, step: int, params, tstate=None, key=None,
             data_cursor: int = 0, extra: dict | None = None) -> Path:
        with trace.span("checkpoint.save", step=step), \
                METRICS.time("checkpoint.save"):
            # Fence before reading: under async dispatch the caller's latest
            # step may still be executing — np.asarray on an in-flight array
            # would block leaf-by-leaf mid-flatten; one explicit barrier up
            # front snapshots a consistent state.  (The trainer additionally
            # resolves its pending-loss ring before calling save.)
            jax.block_until_ready((params, tstate))
            path = self._save(step, params, tstate, key, data_cursor, extra)
        METRICS.increment("checkpoint.saves")
        return path

    def _save(self, step: int, params, tstate=None, key=None,
              data_cursor: int = 0, extra: dict | None = None) -> Path:
        ckpt_dir = self.directory / f"ckpt_{step:010d}"
        tmp = Path(tempfile.mkdtemp(dir=self.directory))
        try:
            np.savez(tmp / "params.npz", **_flatten_with_paths(params))
            if tstate is not None:
                np.savez(tmp / "tstate.npz", **_flatten_with_paths(tstate))
            meta = {
                "step": step,
                "data_cursor": data_cursor,
                "has_tstate": tstate is not None,
                "has_key": key is not None,
                "extra": extra or {},
            }
            if key is not None:
                np.save(tmp / "key.npy", np.asarray(jax.random.key_data(key)))
            (tmp / "meta.json").write_text(json.dumps(meta, indent=2))
            if ckpt_dir.exists():
                shutil.rmtree(ckpt_dir)
            os.replace(tmp, ckpt_dir)  # atomic publish
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._rotate()
        return ckpt_dir

    def _rotate(self):
        ckpts = self.all_steps()
        for step in ckpts[:-self.keep] if self.keep > 0 else []:
            shutil.rmtree(self.directory / f"ckpt_{step:010d}", ignore_errors=True)

    # ------------------------------------------------------------------ load
    def all_steps(self) -> list[int]:
        steps = []
        for p in self.directory.glob("ckpt_*"):
            try:
                steps.append(int(p.name.split("_")[1]))
            except (IndexError, ValueError):
                continue
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def restore(self, params_template, tstate_template=None,
                step: int | None = None) -> dict:
        """Returns dict(step, params, tstate, key, data_cursor, extra)."""
        with trace.span("checkpoint.restore"), \
                METRICS.time("checkpoint.restore"):
            out = self._restore(params_template, tstate_template, step)
        METRICS.increment("checkpoint.restores")
        return out

    def _restore(self, params_template, tstate_template=None,
                 step: int | None = None) -> dict:
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        ckpt_dir = self.directory / f"ckpt_{step:010d}"
        meta = json.loads((ckpt_dir / "meta.json").read_text())
        params_npz = np.load(ckpt_dir / "params.npz")
        params = _restore_like(params_template, dict(params_npz))
        tstate = None
        if meta["has_tstate"] and tstate_template is not None:
            tstate = _restore_like(tstate_template, dict(np.load(ckpt_dir / "tstate.npz")))
        key = None
        if meta["has_key"]:
            key = jax.random.wrap_key_data(jnp.asarray(np.load(ckpt_dir / "key.npy")))
        return {
            "step": meta["step"],
            "params": params,
            "tstate": tstate,
            "key": key,
            "data_cursor": meta["data_cursor"],
            "extra": meta["extra"],
        }
