"""Treebank constituency parser: raw text -> labeled trees for RNTN.

Capability parity with the reference's ``text/corpora/treeparser/
TreeParser.java:41`` (``getTrees(text)``: sentence-segment, tokenize, run a
constituency parser, build ``Tree``s) — there the parsing itself is an
external OpenNLP/ClearTK analysis engine; here it is self-contained:

- preterminals come from the :class:`~.annotator.AveragedPerceptronTagger`
  (emission distributions, not hard tags — ambiguity survives into the
  chart),
- structure comes from probabilistic CKY over a binary PCFG with unary
  closure: either the vendored default grammar (covers the tagger's
  universal-ish tagset) or one induced from any s-expression treebank via
  :meth:`Grammar.from_trees`,
- a low-probability glue rule guarantees a parse for any input, replacing
  the old right-branching fallback with "worst case glue, not always glue".

Output trees are :class:`~.tree.Tree`; ``binarize()`` makes them RNTN-ready.
"""

from __future__ import annotations

import math
from collections import defaultdict
from dataclasses import dataclass, field

from .annotator import AveragedPerceptronTagger, SentenceAnnotator
from .tokenization import DefaultTokenizerFactory
from .tree import Tree, binarize

GLUE = "X"                       # universal fallback nonterminal
_GLUE_LOGP = math.log(1e-4)


@dataclass
class Grammar:
    """Binary PCFG + unary rules, log-prob weighted.

    ``binary[(B, C)] -> list[(A, logp)]``; ``unary[B] -> list[(A, logp)]``.
    Terminals are POS tags (the tagger provides tag distributions per word).
    """

    binary: dict = field(default_factory=lambda: defaultdict(list))
    unary: dict = field(default_factory=lambda: defaultdict(list))
    start: str = "S"

    def add_binary(self, a: str, b: str, c: str, p: float) -> None:
        self.binary[(b, c)].append((a, math.log(p)))

    def add_unary(self, a: str, b: str, p: float) -> None:
        self.unary[b].append((a, math.log(p)))

    # ------------------------------------------------------------- vendored
    @classmethod
    def default(cls) -> "Grammar":
        """Hand-written grammar over the vendored tagger's tagset
        (DET/ADJ/NOUN/VERB/ADV/ADP/PRON/CONJ/NUM/.) — small-English
        declarative coverage; induce from a treebank for more."""
        g = cls()
        # NP
        g.add_unary("NBAR", "NOUN", 0.7)
        g.add_binary("NBAR", "ADJ", "NBAR", 0.2)
        g.add_binary("NBAR", "NOUN", "NBAR", 0.1)
        g.add_binary("NP", "DET", "NBAR", 0.5)
        g.add_binary("NP", "NUM", "NBAR", 0.1)
        g.add_unary("NP", "NBAR", 0.2)
        g.add_unary("NP", "PRON", 0.2)
        # PP
        g.add_binary("PP", "ADP", "NP", 1.0)
        # VP
        g.add_unary("VP", "VERB", 0.3)
        g.add_binary("VP", "VERB", "NP", 0.3)
        g.add_binary("VP", "VP", "PP", 0.15)
        g.add_binary("VP", "VP", "ADV", 0.1)
        g.add_binary("VP", "ADV", "VP", 0.05)
        g.add_binary("VP", "VERB", "ADJ", 0.05)
        g.add_binary("VP", "VP", "NP", 0.05)
        # NP conj / PP attachment to NP
        g.add_binary("NP", "NP", "CONJP", 0.05)
        g.add_binary("CONJP", "CONJ", "NP", 1.0)
        g.add_binary("NP", "NP", "PP", 0.05)
        # S
        g.add_binary("S", "NP", "VP", 0.8)
        g.add_binary("S", "S", ".", 0.15)
        g.add_binary("S", "S", "CONJS", 0.05)
        g.add_binary("CONJS", "CONJ", "S", 1.0)
        return g

    # ------------------------------------------------------------- induced
    @classmethod
    def from_trees(cls, trees, start: str = "S") -> "Grammar":
        """Maximum-likelihood PCFG from binarized treebank trees whose
        preterminals are POS tags (the interchange role of the reference's
        ``TreeFactory``/``TreeVectorization`` corpus path)."""
        bin_counts = defaultdict(lambda: defaultdict(int))
        un_counts = defaultdict(lambda: defaultdict(int))
        for t in trees:
            for node in binarize(t).subtrees():
                if node.is_leaf() or node.is_pre_terminal():
                    continue
                kids = [c.label for c in node.children]
                if len(kids) == 2:
                    bin_counts[node.label][tuple(kids)] += 1
                elif len(kids) == 1:
                    un_counts[node.label][kids[0]] += 1
        g = cls(start=start)
        for a, prods in bin_counts.items():
            total = sum(prods.values()) + sum(un_counts.get(a, {}).values())
            for (b, c), n in prods.items():
                g.add_binary(a, b, c, n / total)
        for a, prods in un_counts.items():
            total = sum(prods.values()) + sum(bin_counts.get(a, {}).values())
            for b, n in prods.items():
                g.add_unary(a, b, n / total)
        return g


class TreebankParser:
    """``getTrees(text)`` parity: sentence-segment, tokenize, CKY-parse.

    Always returns a tree: spans the grammar cannot derive are joined by
    the glue rule at negligible probability, so well-covered substructure
    is preserved even for out-of-grammar sentences."""

    def __init__(self, grammar: Grammar | None = None,
                 tagger: AveragedPerceptronTagger | None = None,
                 tokenizer_factory=None,
                 sentence_annotator: SentenceAnnotator | None = None):
        self.grammar = grammar or Grammar.default()
        self.tagger = tagger or AveragedPerceptronTagger.default()
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.sentences = sentence_annotator or SentenceAnnotator()

    # ------------------------------------------------------------------ api
    def get_trees(self, text: str) -> list[Tree]:
        """Sentences -> trees (mirror of ``TreeParser.getTrees``)."""
        out = []
        for sent in self.sentences.annotate(text):
            tokens = self.tf.create(sent).get_tokens()
            if tokens:
                out.append(self.parse_tokens(tokens))
        return out

    def parse_tokens(self, tokens: list[str]) -> Tree:
        """Probabilistic CKY with unary closure + glue fallback."""
        if not tokens:
            raise ValueError("parse_tokens needs at least one token")
        n = len(tokens)
        emissions = self.tagger.emissions(tokens)        # (n, n_tags)
        classes = self.tagger.classes

        # chart[i][j]: dict sym -> (logp, backpointer)
        # backpointer: ("tag", tag) | ("un", child_sym) | ("bin", k, B, C)
        chart = [[dict() for _ in range(n + 1)] for _ in range(n + 1)]

        for i in range(n):
            cell = chart[i][i + 1]
            for j, tag in enumerate(classes):
                p = float(emissions[i, j])
                if p > 1e-6:
                    cell[tag] = (math.log(p), ("tag", tag))
            self._unary_closure(cell)

        for span in range(2, n + 1):
            for i in range(n - span + 1):
                j = i + span
                cell = chart[i][j]
                for k in range(i + 1, j):
                    left, right = chart[i][k], chart[k][j]
                    for b, (lp_b, _) in left.items():
                        for c, (lp_c, _) in right.items():
                            for a, lp_rule in self.grammar.binary.get(
                                    (b, c), ()):
                                lp = lp_b + lp_c + lp_rule
                                if a not in cell or lp > cell[a][0]:
                                    cell[a] = (lp, ("bin", k, b, c))
                self._unary_closure(cell)
                if not cell:
                    # glue: best-scoring split joined under X
                    best = None
                    for k in range(i + 1, j):
                        for b, (lp_b, _) in chart[i][k].items():
                            for c, (lp_c, _) in chart[k][j].items():
                                lp = lp_b + lp_c + _GLUE_LOGP
                                if best is None or lp > best[0]:
                                    best = (lp, ("bin", k, b, c))
                    if best is not None:
                        cell[GLUE] = best
                        self._unary_closure(cell)

        root_cell = chart[0][n]
        root = (self.grammar.start if self.grammar.start in root_cell
                else max(root_cell, key=lambda s: root_cell[s][0]))
        tree = self._build(chart, tokens, 0, n, root)
        tree.assign_spans()
        return tree

    # ------------------------------------------------------------------ internals
    def _unary_closure(self, cell):
        # iterate to fixpoint: updates strictly increase a cell entry's
        # log-prob and rule log-probs are <= 0, so termination is guaranteed
        # (a capped loop would silently truncate unary chains longer than
        # the cap in induced grammars)
        while True:
            changed = False
            for b, (lp_b, _) in list(cell.items()):
                for a, lp_rule in self.grammar.unary.get(b, ()):
                    lp = lp_b + lp_rule
                    if a not in cell or lp > cell[a][0]:
                        cell[a] = (lp, ("un", b))
                        changed = True
            if not changed:
                break

    def _build(self, chart, tokens, i, j, sym) -> Tree:
        _, back = chart[i][j][sym]
        if back[0] == "tag":
            # preterminal: tag node over the word leaf
            return Tree(label=sym, children=[Tree(word=tokens[i], label=sym)])
        if back[0] == "un":
            return Tree(label=sym, children=[self._build(chart, tokens, i, j,
                                                         back[1])])
        _, k, b, c = back
        return Tree(label=sym, children=[self._build(chart, tokens, i, k, b),
                                         self._build(chart, tokens, k, j, c)])
