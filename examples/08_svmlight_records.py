"""Sparse-text records end to end: write an SVMLight file, shard it by
byte ranges (the multi-host loader contract), and train an MLP from it.

Mirrors the reference's YARN record path (``SVMLightRecordFactory`` /
``SVMLightDataFetcher`` / ``TextRecordParser`` HDFS splits), redesigned for
the TPU input pipeline: lines parse to dense batched arrays, byte-range
splits replace HDFS input splits.

Run:  python examples/08_svmlight_records.py
"""
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")   # examples run anywhere; drop for TPU

import numpy as np

from deeplearning4j_tpu.datasets import SVMLightDataSetIterator, save_svmlight
from deeplearning4j_tpu.datasets.svmlight import load_svmlight
from deeplearning4j_tpu.models.zoo import mlp


def main():
    # synthesize a sparse 2-class corpus and write it as svmlight text
    rng = np.random.default_rng(0)
    n, d = 400, 12
    labels = rng.integers(0, 2, n)
    feats = rng.standard_normal((n, d)).astype(np.float32)
    feats = np.where(rng.random((n, d)) < 0.5, 0.0, feats)   # sparsify
    feats += 2.0 * labels[:, None] * np.eye(d, dtype=np.float32)[0]
    path = os.path.join(tempfile.mkdtemp(), "corpus.svmlight")
    save_svmlight(path, feats, labels)
    size = os.path.getsize(path)
    print(f"wrote {n} records, {size} bytes")

    # byte-range splits partition records exactly — each "host" loads only
    # its slice (seek-based read, O(split) IO)
    cuts = [0, size // 2, size]
    counts = [load_svmlight(path, d, 2, start=s, end=e)[0].shape[0]
              for s, e in zip(cuts, cuts[1:])]
    print(f"split record counts: {counts} (sum {sum(counts)})")
    assert sum(counts) == n

    # fetch -> train, the reference's SVMLightDataFetcher loop
    it = SVMLightDataSetIterator(path, batch=100, num_features=d, num_classes=2)
    net = mlp(d, 2, hidden=(16,), num_iterations=60)
    while it.has_next():
        net.fit(it.next())

    f, l = load_svmlight(path, d, 2)
    acc = float((net.predict(f) == l.argmax(-1)).mean())
    print(f"accuracy = {acc:.3f}")


if __name__ == "__main__":
    main()
