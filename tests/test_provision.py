"""Provisioning analog (reference ``deeplearning4j-aws``: Ec2BoxCreator /
ClusterSetup / HostProvisioner) + the YARN Kill CLI analog."""

import os
import subprocess
import sys
import time
from pathlib import Path

from deeplearning4j_tpu.parallel.procstate import FileStateTracker
from deeplearning4j_tpu.parallel.provision import (
    PodSliceProvisioner, PodSliceSpec)

REPO = Path(__file__).resolve().parents[1]


def test_pod_slice_spec_geometry():
    s = PodSliceSpec(accelerator_type="v5litepod-64")
    assert s.n_chips == 64 and s.n_hosts == 16       # v5e: 4-chip hosts
    assert PodSliceSpec(accelerator_type="v5litepod-8").n_hosts == 2


def test_create_and_launch_commands():
    spec = PodSliceSpec(name="slice1", accelerator_type="v5litepod-16",
                        zone="us-west4-a", spot=True)
    prov = PodSliceProvisioner(spec)
    create = prov.create_command()
    assert create[:5] == ["gcloud", "compute", "tpus", "tpu-vm", "create"]
    assert "--accelerator-type=v5litepod-16" in create
    assert "--spot" in create

    env = prov.launch_env(3, "10.0.0.2")
    assert env == {"JAX_COORDINATOR_ADDRESS": "10.0.0.2:8476",
                   "JAX_NUM_PROCESSES": "4", "JAX_PROCESS_ID": "3"}

    launch = prov.launch_command("-m deeplearning4j_tpu train", "$COORD")
    assert "JAX_COORDINATOR_ADDRESS=$COORD:8476" in launch
    assert "JAX_NUM_PROCESSES=4" in launch
    assert "agent-worker-number" in launch           # per-host process id


def test_render_script_is_wellformed(tmp_path):
    prov = PodSliceProvisioner(PodSliceSpec(accelerator_type="v5litepod-8"))
    path = prov.write_script(tmp_path / "up.sh", "https://example.com/r.git",
                             "-m deeplearning4j_tpu train")
    text = path.read_text()
    assert text.startswith("#!/usr/bin/env bash")
    assert "set -euo pipefail" in text
    assert "tpu-vm create" in text and "--worker=all" in text
    # remote worker-index lookup must be escaped for the outer shell
    assert "\\$(curl" in text
    assert os.access(path, os.X_OK)
    # the script parses as shell
    subprocess.run(["bash", "-n", str(path)], check=True)


def test_cli_scaleout_kill(tmp_path):
    state = tmp_path / "state"
    FileStateTracker(state)          # create the layout
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    p = subprocess.run(
        [sys.executable, "-m", "deeplearning4j_tpu", "scaleout", "-t", "kill",
         "--state-dir", str(state)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-800:]
    assert FileStateTracker(state).is_done()


def test_kill_stops_running_master(tmp_path):
    """A kill issued while a master waits on an empty-but-unfinished job
    stream makes the whole run wind down (Kill.java behavior)."""
    from deeplearning4j_tpu.parallel.procrunner import ProcessDistributedRunner
    from deeplearning4j_tpu.parallel.scaleout import CollectionJobIterator

    state = tmp_path / "state"

    class NeverDone:
        """Iterator that claims more work is coming (streaming master)."""

        def next(self, worker_id=""):
            raise AssertionError("never dispenses")

        def has_next(self):
            return False

        def reset(self):
            pass

    runner = ProcessDistributedRunner(
        CollectionJobIterator(["a b", "c"]),
        "deeplearning4j_tpu.parallel.performers:WordCountPerformer",
        state_dir=state, n_workers=1,
        worker_env={"JAX_PLATFORMS": "cpu"})

    import threading
    killer = threading.Thread(
        target=lambda: (time.sleep(1.5), FileStateTracker(state).finish()),
        daemon=True)
    killer.start()
    t0 = time.time()
    runner.run(max_wall_s=60.0)
    # jobs drain quickly; kill (or natural finish) must not hang to the wall
    assert time.time() - t0 < 50.0
    assert FileStateTracker(state).is_done()


def test_core_counted_generations():
    """v4/v5p accelerator-type suffixes count TensorCores (2/chip), not
    chips; v5litepod suffixes count chips."""
    assert PodSliceSpec(accelerator_type="v4-8").n_chips == 4
    assert PodSliceSpec(accelerator_type="v4-8").n_hosts == 1
    assert PodSliceSpec(accelerator_type="v3-8").n_hosts == 1
    assert PodSliceSpec(accelerator_type="v5p-128").n_chips == 64
    assert PodSliceSpec(accelerator_type="v5litepod-64").n_chips == 64


def test_driver_wildcard_mesh_uses_all_devices():
    import jax

    from deeplearning4j_tpu.optimize import transforms as T
    from deeplearning4j_tpu.parallel.driver import Driver
    from deeplearning4j_tpu.parallel.mesh import MeshSpec

    import jax.numpy as jnp

    def loss_fn(p, xb, yb, key=None):
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    d = Driver(loss_fn, T.sgd_lr(1e-2), mesh_spec=MeshSpec(tp=2))
    assert d.mesh.devices.size == len(jax.devices())   # wildcard dp fills
