"""Pretrain a small causal LM on raw text and generate continuations.

The GPT-shaped loop end to end: tokenize a corpus once (`LMCorpus`), pack
it into dense (B, T) blocks with shifted targets (`LMTokenBatchIterator`),
train the flagship `TransformerLM` with AdamW, then sample continuations
with the one-compiled-program decode loop.

Run:  python examples/09_lm_pretrain_generate.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")   # examples run anywhere; drop for TPU

import jax.numpy as jnp

from deeplearning4j_tpu.models.transformer import TransformerConfig, TransformerLM
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.text import LMCorpus, LMTokenBatchIterator

TEXT = [
    "the quick brown fox jumps over the lazy dog",
    "the lazy dog sleeps under the old oak tree",
    "a quick fox runs through the green field",
    "the old tree stands over the green field",
] * 12


def main():
    corpus = LMCorpus(TEXT)
    it = LMTokenBatchIterator(corpus, batch=4, seq=16, seed=0)
    print(f"corpus: {len(corpus.ids)} tokens, vocab {corpus.vocab_size}, "
          f"{it.batches_per_epoch} batches/epoch")

    cfg = TransformerConfig(
        vocab_size=corpus.vocab_size, d_model=64, n_heads=4, n_layers=2,
        d_ff=128, max_len=16, causal=True, dtype=jnp.float32, remat=False)
    model = TransformerLM(cfg)
    tx = T.adamw(T.warmup_cosine(5e-3, 20, 400), weight_decay=0.01)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, tx)
    step = model.build_train_step(tx)

    first = last = None
    for epoch in range(8):
        for tokens, targets in it.epoch_batches():
            params, opt, loss = step(params, opt, jnp.asarray(tokens),
                                     jnp.asarray(targets))
            first = first if first is not None else float(loss)
            last = float(loss)
    print(f"loss: {first:.3f} -> {last:.3f}")

    prime_words = ["the", "quick"]
    prime = [corpus.vocab.index_of(w) for w in prime_words]
    out = model.sample(params, prime, length=6, temperature=0.0)
    print("greedy:", " ".join(corpus.decode(out)))
    out = model.sample(params, prime, length=6, temperature=0.8,
                       key=jax.random.key(7), kv_cache=True)
    print("sampled (kv-cached):", " ".join(corpus.decode(out)))


if __name__ == "__main__":
    main()
