"""Loss functions.

TPU-native equivalent of ND4J ``LossFunctions`` as consumed by the reference's
``nn/layers/OutputLayer.java:70-73,125-150`` and the pretrain score in
``nn/layers/BasePretrainNetwork.java``.  All losses take ``(labels, output)``
with ``output`` already activated (e.g. softmax probabilities for MCXENT) and
return the *mean over examples* as a scalar.  Each loss is a pure jnp
composition so it fuses into the surrounding jitted step, and is
differentiable so `jax.grad` reproduces (and generalizes) the reference's
hand-coded loss-specific weight gradients (``OutputLayer.java:93-154``).
"""

from __future__ import annotations

import enum
from typing import Callable

import jax.numpy as jnp

_EPS = 1e-7


class LossFunction(str, enum.Enum):
    """Names mirror the reference's LossFunctions.LossFunction enum."""

    MSE = "mse"
    EXPLL = "expll"                 # exponential log likelihood (Poisson-like)
    XENT = "xent"                   # elementwise binary cross entropy
    MCXENT = "mcxent"               # multiclass cross entropy (softmax output)
    RMSE_XENT = "rmse_xent"         # sqrt of squared-error (reference quirk)
    SQUARED_LOSS = "squared_loss"   # summed squared error (no 1/2)
    NEGATIVELOGLIKELIHOOD = "negativeloglikelihood"
    RECONSTRUCTION_CROSSENTROPY = "reconstruction_crossentropy"

    # --- additions beyond the v0 reference (needed by modern heads) ---
    COSINE_PROXIMITY = "cosine_proximity"
    HINGE = "hinge"
    L1 = "l1"


def _clip(p):
    return jnp.clip(p, _EPS, 1.0 - _EPS)


def mse(labels, output):
    return jnp.mean(jnp.sum((labels - output) ** 2, axis=-1)) / 2.0


def squared_loss(labels, output):
    return jnp.mean(jnp.sum((labels - output) ** 2, axis=-1))


def rmse_xent(labels, output):
    # Reference computes sqrt(pow(labels - output, 2)) i.e. mean |error|-ish;
    # kept as root of summed squared error per row for parity of intent.
    return jnp.mean(jnp.sqrt(jnp.sum((labels - output) ** 2, axis=-1) + _EPS))


def xent(labels, output):
    p = _clip(output)
    return -jnp.mean(jnp.sum(labels * jnp.log(p) + (1 - labels) * jnp.log1p(-p), axis=-1))


def mcxent(labels, output):
    return -jnp.mean(jnp.sum(labels * jnp.log(_clip(output)), axis=-1))


def expll(labels, output):
    p = jnp.clip(output, _EPS, None)
    return jnp.mean(jnp.sum(p - labels * jnp.log(p), axis=-1))


def negativeloglikelihood(labels, output):
    return -jnp.mean(jnp.sum(labels * jnp.log(_clip(output)), axis=-1))


def reconstruction_crossentropy(labels, output):
    return xent(labels, output)


def cosine_proximity(labels, output):
    ln = labels / (jnp.linalg.norm(labels, axis=-1, keepdims=True) + _EPS)
    on = output / (jnp.linalg.norm(output, axis=-1, keepdims=True) + _EPS)
    return -jnp.mean(jnp.sum(ln * on, axis=-1))


def hinge(labels, output):
    # labels in {0,1} one-hot or {-1,1}
    y = jnp.where(labels > 0, 1.0, -1.0)
    return jnp.mean(jnp.sum(jnp.maximum(0.0, 1.0 - y * output), axis=-1))


def l1(labels, output):
    return jnp.mean(jnp.sum(jnp.abs(labels - output), axis=-1))


_FNS: dict[LossFunction, Callable] = {
    LossFunction.MSE: mse,
    LossFunction.EXPLL: expll,
    LossFunction.XENT: xent,
    LossFunction.MCXENT: mcxent,
    LossFunction.RMSE_XENT: rmse_xent,
    LossFunction.SQUARED_LOSS: squared_loss,
    LossFunction.NEGATIVELOGLIKELIHOOD: negativeloglikelihood,
    LossFunction.RECONSTRUCTION_CROSSENTROPY: reconstruction_crossentropy,
    LossFunction.COSINE_PROXIMITY: cosine_proximity,
    LossFunction.HINGE: hinge,
    LossFunction.L1: l1,
}


def get(loss: LossFunction | str) -> Callable:
    return _FNS[LossFunction(loss)]


def score(loss: LossFunction | str, labels, output) -> jnp.ndarray:
    return get(loss)(labels, output)
