"""Dtype policy for the TPU runtime.

The reference forces float32 for tests (``pom.xml:198`` ``-Ddtype=float``) and
threads a global float/double switch through ND4J's ``DataBuffer``
(``InMemoryLookupTable.java:207,257``).  On TPU the idiomatic split is between
a *parameter* dtype (float32 by default) and a *compute* dtype (bfloat16 on
the MXU when enabled), so the policy carries both.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class DtypePolicy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.float32

    def cast_compute(self, x):
        return x.astype(self.compute_dtype) if hasattr(x, "astype") else x

    def cast_param(self, x):
        return x.astype(self.param_dtype) if hasattr(x, "astype") else x


_POLICY = DtypePolicy()


def get_policy() -> DtypePolicy:
    return _POLICY


def set_policy(param_dtype=None, compute_dtype=None) -> DtypePolicy:
    """Set the global dtype policy (mirrors the reference's -Ddtype switch)."""
    global _POLICY
    _POLICY = DtypePolicy(
        param_dtype=jnp.dtype(param_dtype) if param_dtype is not None else _POLICY.param_dtype,
        compute_dtype=jnp.dtype(compute_dtype) if compute_dtype is not None else _POLICY.compute_dtype,
    )
    return _POLICY


def bf16_compute() -> DtypePolicy:
    """Enable bfloat16 MXU compute with float32 params (mixed precision)."""
    return set_policy(param_dtype=jnp.float32, compute_dtype=jnp.bfloat16)
