"""Vendor a real MNIST IDX subset into the repo as a test fixture.

The build container has ZERO egress (and ships no local MNIST copy — the
reference's own test resources carry only ``mnist2500_labels.txt``, labels
without pixels), so the fixture cannot be materialized from inside it.  Run
this script once from any machine WITH egress; it downloads the canonical
IDX files, takes a stratified subset, and writes gzipped IDX fixtures that
``MnistDataFetcher`` and ``tests/test_mnist_real.py`` pick up automatically:

    python tools/vendor_mnist.py            # 6000 train / 1000 test
    python -m pytest tests/test_mnist_real.py -q   # now runs on real pixels

Mirrors the reference's download+binarize path
(``datasets/fetchers/MnistDataFetcher.java:21-80``, ``base/MnistFetcher.java:30``).
"""

from __future__ import annotations

import argparse
import gzip
import shutil
import sys
import tempfile
import urllib.request
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from deeplearning4j_tpu.datasets.fetchers import MnistDataFetcher  # noqa: E402
from deeplearning4j_tpu.datasets.mnist_idx import (  # noqa: E402
    read_idx_images, read_idx_labels, write_idx_images, write_idx_labels)

FIXTURE_DIR = (Path(__file__).resolve().parents[1]
               / "deeplearning4j_tpu" / "datasets" / "fixtures" / "mnist")


def _stratified_subset(images, labels, per_class, seed=0):
    rng = np.random.default_rng(seed)
    keep = []
    for c in range(10):
        idx = np.flatnonzero(labels == c)
        keep.append(rng.choice(idx, size=min(per_class, idx.size), replace=False))
    keep = np.sort(np.concatenate(keep))
    return images[keep], labels[keep]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--train", type=int, default=6000, help="train subset size")
    ap.add_argument("--test", type=int, default=1000, help="test subset size")
    args = ap.parse_args()

    FIXTURE_DIR.mkdir(parents=True, exist_ok=True)
    with tempfile.TemporaryDirectory() as td:
        td = Path(td)
        for name, url in MnistDataFetcher.URLS.items():
            print(f"downloading {url}")
            urllib.request.urlretrieve(url, td / name)  # noqa: S310
        for split, n in (("train", args.train), ("t10k", args.test)):
            images = read_idx_images(td / f"{split}-images-idx3-ubyte.gz")
            labels = read_idx_labels(td / f"{split}-labels-idx1-ubyte.gz")
            images, labels = _stratified_subset(images, labels, n // 10)
            for stem, writer, data in (
                    (f"{split}-images-idx3-ubyte", write_idx_images, images),
                    (f"{split}-labels-idx1-ubyte", write_idx_labels, labels)):
                raw = FIXTURE_DIR / stem
                writer(raw, data)
                with open(raw, "rb") as fin, gzip.open(
                        FIXTURE_DIR / (stem + ".gz"), "wb", compresslevel=9) as fout:
                    shutil.copyfileobj(fin, fout)
                raw.unlink()
            print(f"{split}: wrote {labels.shape[0]} examples to {FIXTURE_DIR}")


if __name__ == "__main__":
    main()
