"""Tokenization SPI.

Capability match of the reference's ``text/tokenization`` package:
``Tokenizer``/``TokenizerFactory``/``TokenPreProcess`` interfaces with
default implementations (the reference's ``DefaultTokenizer`` wraps Java's
StringTokenizer; UIMA/PoS-tagging annotators are out-of-scope external
services there — here the default is a regex word tokenizer and the SPI
admits any callable).
"""

from __future__ import annotations

import re
from typing import Callable, Iterator, Protocol

TokenPreProcess = Callable[[str], str]


class LowerCasePreProcessor:
    def __call__(self, token: str) -> str:
        return token.lower()


class StripPunctuationPreProcess:
    _PUNCT = re.compile(r"[^\w\s]", re.UNICODE)

    def __call__(self, token: str) -> str:
        return self._PUNCT.sub("", token)


class CommonPreprocessor:
    """lowercase + strip punctuation (the reference's common default)."""

    def __init__(self):
        self._strip = StripPunctuationPreProcess()

    def __call__(self, token: str) -> str:
        return self._strip(token.lower())


class Tokenizer(Protocol):
    def get_tokens(self) -> list[str]: ...
    def count_tokens(self) -> int: ...


class DefaultTokenizer:
    """Whitespace/word-boundary tokenizer with optional preprocessor."""

    _WORD = re.compile(r"\S+")

    def __init__(self, text: str, pre: TokenPreProcess | None = None):
        self.text = text
        self.pre = pre
        self._tokens: list[str] | None = None

    def get_tokens(self) -> list[str]:
        if self._tokens is None:
            toks = self._WORD.findall(self.text)
            if self.pre is not None:
                toks = [self.pre(t) for t in toks]
            self._tokens = [t for t in toks if t]
        return self._tokens

    def count_tokens(self) -> int:
        return len(self.get_tokens())

    def __iter__(self) -> Iterator[str]:
        return iter(self.get_tokens())


class NGramTokenizer:
    """N-gram wrapper (reference ``NGramTokenizerFactory``)."""

    def __init__(self, text: str, n: int = 2, pre: TokenPreProcess | None = None):
        self.base = DefaultTokenizer(text, pre)
        self.n = n

    def get_tokens(self) -> list[str]:
        toks = self.base.get_tokens()
        out = list(toks)
        for n in range(2, self.n + 1):
            out.extend(" ".join(toks[i:i + n]) for i in range(len(toks) - n + 1))
        return out

    def count_tokens(self) -> int:
        return len(self.get_tokens())


class TokenizerFactory(Protocol):
    def create(self, text: str) -> Tokenizer: ...


class DefaultTokenizerFactory:
    def __init__(self, pre: TokenPreProcess | None = None):
        self.pre = pre

    def create(self, text: str) -> DefaultTokenizer:
        return DefaultTokenizer(text, self.pre)


class NGramTokenizerFactory:
    def __init__(self, n: int = 2, pre: TokenPreProcess | None = None):
        self.n = n
        self.pre = pre

    def create(self, text: str) -> NGramTokenizer:
        return NGramTokenizer(text, self.n, self.pre)
