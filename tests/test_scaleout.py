"""Scaleout control-plane tests — mirror of the reference's
``BaseTestDistributed`` pattern: boot the REAL orchestration stack
(tracker + router + master loop + worker threads) in one process with a
pluggable performer; ``NoOpPerformer`` tests orchestration alone
(``TestPerformer.java``), then a real parameter-averaging run."""

import threading
import time

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.scaleout import (
    ArrayAggregator,
    CollectionJobIterator,
    DistributedRunner,
    FileModelSaver,
    HogWildWorkRouter,
    IterativeReduceWorkRouter,
    Job,
    StateTracker,
)


class NoOpPerformer:
    """``TestPerformer.java`` — records jobs, produces no updates."""

    performed = []

    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job):
        NoOpPerformer.performed.append(job.work)

    def update(self, *args):
        pass


class AveragingPerformer:
    """Produce the work array as the 'trained params' update; master
    averages them (parameter-averaging superstep in miniature)."""

    def __init__(self, tracker):
        self.tracker = tracker
        self.received_model = None

    def perform(self, job):
        job.result = np.asarray(job.work, dtype=np.float64)

    def update(self, current):
        self.received_model = current


def test_state_tracker_basics():
    t = StateTracker()
    t.add_worker("w0")
    t.add_worker("w1")
    assert t.workers() == ["w0", "w1"]
    t.disable_worker("w1")
    assert t.is_enabled("w0") and not t.is_enabled("w1")
    t.add_job(Job(work=1, worker_id="w0"))
    assert t.job_for("w0").work == 1
    assert t.load_for_worker("w0").work == 1  # persisted for re-retrieval
    t.clear_job("w0")
    assert t.job_for("w0") is None
    t.increment("words", 10)
    t.increment("words", 5)
    assert t.count("words") == 15
    t.add_update("w0", np.ones(3))
    assert "w0" in t.updates()


def test_heartbeat_eviction():
    t = StateTracker()
    t.add_worker("alive")
    t.add_worker("dead")
    t._heartbeats["dead"] = time.time() - 1000
    t.add_job(Job(work="orphan-work", worker_id="dead"))
    evicted, orphans = t.evict_stale(timeout_s=120)
    assert evicted == ["dead"]
    assert [j.work for j in orphans] == ["orphan-work"]
    assert t.workers() == ["alive"]


def test_update_listener_fires():
    t = StateTracker()
    seen = []
    t.update_listeners.append(seen.append)
    t.add_update("w0", 42)
    assert seen == [42]


def test_array_aggregator_running_average():
    agg = ArrayAggregator()
    agg.accumulate(Job(work=None, result=np.array([2.0, 4.0])))
    agg.accumulate(Job(work=None, result=np.array([4.0, 8.0])))
    np.testing.assert_allclose(agg.aggregate(), [3.0, 6.0])


def test_routers_policy():
    t = StateTracker()
    t.add_worker("w0")
    t.add_worker("w1")
    ir = IterativeReduceWorkRouter(t)
    hw = HogWildWorkRouter(t)
    assert hw.send_work()
    assert not ir.send_work()          # no updates yet
    t.add_update("w0", np.ones(2))
    assert not ir.send_work()          # 1 of 2
    t.add_update("w1", np.ones(2))
    assert ir.send_work()              # all reported


def test_runner_orchestration_noop():
    NoOpPerformer.performed = []
    runner = DistributedRunner(
        CollectionJobIterator(list(range(20))), NoOpPerformer, n_workers=3)
    runner.run(max_wall_s=30)
    assert sorted(NoOpPerformer.performed) == list(range(20))
    assert runner.tracker.is_done()


def test_runner_parameter_averaging(tmp_path):
    """End-to-end superstep: workers 'train' (echo arrays), master averages
    via IterativeReduce policy and persists via ModelSaver."""
    items = [np.full(4, float(i)) for i in range(8)]
    saver = FileModelSaver(tmp_path / "model.bin")
    runner = DistributedRunner(
        CollectionJobIterator(items), AveragingPerformer, n_workers=2,
        router_cls=IterativeReduceWorkRouter, model_saver=saver)
    result = runner.run(max_wall_s=30)
    assert result is not None and result.shape == (4,)
    # final current model is an average of (subsets of) the items
    assert 0.0 <= float(result[0]) <= 7.0
    loaded = saver.load()
    np.testing.assert_allclose(loaded, result)


def test_runner_hogwild_always_dispatches():
    items = [np.ones(2) * i for i in range(6)]
    runner = DistributedRunner(
        CollectionJobIterator(items), AveragingPerformer, n_workers=2,
        router_cls=HogWildWorkRouter)
    result = runner.run(max_wall_s=30)
    assert runner.tracker.is_done()
    assert result is not None
