"""Confusion-matrix classification metrics.

Capability match of ``eval/Evaluation.java:16,33-64,127-222`` and the generic
``eval/ConfusionMatrix.java:32`` (Guava-multiset-backed in the reference; a
dict of Counters here).  ``eval()`` takes one-hot (or probability) matrices
and argmaxes rows, exactly like the reference; metric formulas (accuracy,
per-class precision/recall, F1) match.
"""

from __future__ import annotations

from collections import Counter, defaultdict
from typing import Hashable, Iterable

import numpy as np


class ConfusionMatrix:
    """Generic actual→(predicted→count) table (``ConfusionMatrix.java:32``)."""

    def __init__(self, classes: Iterable[Hashable] = ()):
        self.matrix: dict[Hashable, Counter] = defaultdict(Counter)
        self.classes: set[Hashable] = set(classes)

    def add(self, actual: Hashable, predicted: Hashable, count: int = 1) -> None:
        self.matrix[actual][predicted] += count
        self.classes.add(actual)
        self.classes.add(predicted)

    def add_all(self, other: "ConfusionMatrix") -> None:
        for a, row in other.matrix.items():
            for p, c in row.items():
                self.add(a, p, c)

    def count(self, actual: Hashable, predicted: Hashable) -> int:
        return self.matrix[actual][predicted]

    def actual_total(self, actual: Hashable) -> int:
        return sum(self.matrix[actual].values())

    def predicted_total(self, predicted: Hashable) -> int:
        return sum(row[predicted] for row in self.matrix.values())

    def total(self) -> int:
        return sum(self.actual_total(a) for a in list(self.matrix))

    def __str__(self) -> str:
        cs = sorted(self.classes)
        lines = ["actual\\pred\t" + "\t".join(map(str, cs))]
        for a in cs:
            lines.append(f"{a}\t" + "\t".join(str(self.count(a, p)) for p in cs))
        return "\n".join(lines)


class Evaluation:
    """Multiclass metrics from argmax'd outcome matrices
    (``Evaluation.java``)."""

    def __init__(self):
        self.confusion = ConfusionMatrix()
        self.true_positives: Counter = Counter()
        self.false_positives: Counter = Counter()
        self.false_negatives: Counter = Counter()

    # ------------------------------------------------------------------ feed
    def eval(self, real_outcomes, guesses) -> None:
        """Rows are examples; argmax of each row is the class
        (``Evaluation.java:33-64``)."""
        real = np.asarray(real_outcomes)
        guess = np.asarray(guesses)
        if real.ndim == 1:
            actual_idx, pred_idx = real.astype(int), guess.astype(int)
        else:
            actual_idx = real.argmax(axis=1)
            pred_idx = guess.argmax(axis=1)
        for a, p in zip(actual_idx.tolist(), pred_idx.tolist()):
            self.confusion.add(a, p)
            if a == p:
                self.true_positives[a] += 1
            else:
                self.false_positives[p] += 1
                self.false_negatives[a] += 1

    def merge(self, other: "Evaluation") -> None:
        self.confusion.add_all(other.confusion)
        self.true_positives.update(other.true_positives)
        self.false_positives.update(other.false_positives)
        self.false_negatives.update(other.false_negatives)

    # ------------------------------------------------------------------ metrics
    def accuracy(self) -> float:
        total = self.confusion.total()
        if total == 0:
            return 0.0
        correct = sum(self.true_positives.values())
        return correct / total

    def precision(self, klass=None) -> float:
        if klass is not None:
            tp, fp = self.true_positives[klass], self.false_positives[klass]
            return tp / (tp + fp) if tp + fp > 0 else 0.0
        cs = sorted(self.confusion.classes)
        return sum(self.precision(c) for c in cs) / len(cs) if cs else 0.0

    def recall(self, klass=None) -> float:
        if klass is not None:
            tp, fn = self.true_positives[klass], self.false_negatives[klass]
            return tp / (tp + fn) if tp + fn > 0 else 0.0
        cs = sorted(self.confusion.classes)
        return sum(self.recall(c) for c in cs) / len(cs) if cs else 0.0

    def f1(self, klass=None) -> float:
        p, r = self.precision(klass), self.recall(klass)
        return 2 * p * r / (p + r) if p + r > 0 else 0.0

    def false_positive_rate(self, klass) -> float:
        fp = self.false_positives[klass]
        tn = self.confusion.total() - (self.true_positives[klass]
                                       + fp + self.false_negatives[klass])
        return fp / (fp + tn) if fp + tn > 0 else 0.0

    def stats(self) -> str:
        """Human-readable report (``Evaluation.java:64``)."""
        lines = ["==========================Scores=========================="]
        lines.append(f" Accuracy:  {self.accuracy():.4f}")
        lines.append(f" Precision: {self.precision():.4f}")
        lines.append(f" Recall:    {self.recall():.4f}")
        lines.append(f" F1 Score:  {self.f1():.4f}")
        lines.append("===========================================================")
        lines.append(str(self.confusion))
        return "\n".join(lines)
