"""Character-level LSTM language modeling with sampling and beam search.

The reference's LSTM is a char-rnn-style sequence model with beam-search
decoding (``models/classifiers/lstm/LSTM.java:33,241``). Here: fit a small
LSTM on a repetitive character stream, then decode with greedy sampling
and beam search.

Run:  python examples/05_lstm_textgen.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.models.lstm import LSTMSequenceModel

TEXT = "abcdefg " * 60


def main():
    chars = sorted(set(TEXT))
    idx = {c: i for i, c in enumerate(chars)}
    tokens = np.array([idx[c] for c in TEXT], dtype=np.int32)

    model = LSTMSequenceModel(vocab_size=len(chars), hidden_size=48, seed=0)
    model.init()
    losses = model.fit_sequence(tokens, epochs=150)
    print(f"loss: {losses[0]:.3f} -> {losses[-1]:.3f}")

    prime = [idx[c] for c in "abc"]
    seq, logp = model.beam_search(prime, length=12, beam_width=4)
    decoded = "".join(chars[i] for i in seq[len(prime):])
    print(f"beam search after 'abc': {decoded!r}")
    assert decoded.startswith("defg"), decoded

    sampled = model.sample(prime, length=12, temperature=0.5)
    print(f"sampled     after 'abc': {''.join(chars[i] for i in sampled)!r}")


if __name__ == "__main__":
    main()
