"""Vocabulary store + Huffman coding.

Capability match of the reference's ``models/word2vec/wordstore`` package:
``VocabWord`` (word + count + Huffman code/points,
``models/word2vec/VocabWord.java``), ``VocabCache``/``InMemoryLookupCache``
(word<->index maps, counts), vocab building with min-word-frequency pruning
(the actor-based ``VocabActor`` pipeline becomes a single host pass — the
C++ native tokenizer/counter accelerates it when built), and ``Huffman``
(``models/word2vec/Huffman.java:11`` — binary tree over counts assigning
code/point paths used by hierarchical softmax).
"""

from __future__ import annotations

import heapq
from dataclasses import dataclass, field
from typing import Iterable

import numpy as np


@dataclass
class VocabWord:
    word: str
    count: float = 0.0
    index: int = -1
    codes: list[int] = field(default_factory=list)    # Huffman code bits
    points: list[int] = field(default_factory=list)   # inner-node indices


class VocabCache:
    """word <-> index <-> VocabWord store (``VocabCache.java:15``)."""

    def __init__(self):
        self._words: dict[str, VocabWord] = {}
        self._by_index: list[VocabWord] = []
        self.total_word_count = 0.0

    def add(self, word: str, by: float = 1.0) -> VocabWord:
        vw = self._words.get(word)
        if vw is None:
            vw = VocabWord(word=word)
            self._words[word] = vw
        vw.count += by
        self.total_word_count += by
        return vw

    def finalize_indices(self) -> None:
        """Assign indices by descending count (word2vec convention)."""
        self._by_index = sorted(self._words.values(), key=lambda w: -w.count)
        for i, vw in enumerate(self._by_index):
            vw.index = i

    def prune(self, min_word_frequency: float) -> None:
        kept = {w: vw for w, vw in self._words.items()
                if vw.count >= min_word_frequency}
        removed = sum(vw.count for w, vw in self._words.items() if w not in kept)
        self._words = kept
        self.total_word_count -= removed
        self.finalize_indices()

    # -- lookups ---------------------------------------------------------
    def __contains__(self, word: str) -> bool:
        return word in self._words

    def __len__(self) -> int:
        return len(self._words)

    def word_for(self, word: str) -> VocabWord | None:
        return self._words.get(word)

    def index_of(self, word: str) -> int:
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_at(self, index: int) -> str:
        return self._by_index[index].word

    def words(self) -> list[str]:
        return [vw.word for vw in self._by_index]

    def count_of(self, word: str) -> float:
        vw = self._words.get(word)
        return vw.count if vw else 0.0

    def counts_array(self) -> np.ndarray:
        return np.array([vw.count for vw in self._by_index], dtype=np.float64)


def build_vocab(sentences: Iterable[str], tokenizer_factory, min_word_frequency: float = 1.0,
                use_native: bool = True) -> VocabCache:
    """One-pass vocab build (replaces the reference's VocabActor pipeline)."""
    # materialize once: the native attempt may consume and then reject the
    # corpus (e.g. non-ASCII), and the fallback must see the same sentences
    sentences = list(sentences)
    cache = VocabCache()
    if use_native:
        try:
            from ..native import runtime as native_rt
            counts = native_rt.count_tokens(sentences, tokenizer_factory)
            if counts is not None:
                for w, c in counts.items():
                    cache.add(w, c)
                cache.prune(min_word_frequency)
                return cache
        except ImportError:
            pass
    for sentence in sentences:
        for tok in tokenizer_factory.create(sentence).get_tokens():
            cache.add(tok)
    cache.prune(min_word_frequency)
    return cache


class Huffman:
    """Huffman tree over vocab counts (``Huffman.java:11``): assigns each
    word its code (bit path) and points (inner-node ids along the path),
    consumed by hierarchical softmax."""

    def __init__(self, cache: VocabCache):
        self.cache = cache
        self.max_code_length = 0

    def build(self) -> None:
        words = [self.cache.word_for(w) for w in self.cache.words()]
        n = len(words)
        if n == 0:
            return
        if n == 1:
            words[0].codes, words[0].points = [0], [0]
            self.max_code_length = 1
            return
        # heap of (count, uid, node); leaves are 0..n-1, inner nodes n..2n-2
        heap: list[tuple[float, int]] = [(w.count, i) for i, w in enumerate(words)]
        heapq.heapify(heap)
        parent = {}
        bit = {}
        next_id = n
        while len(heap) > 1:
            c1, a = heapq.heappop(heap)
            c2, b = heapq.heappop(heap)
            parent[a], bit[a] = next_id, 0
            parent[b], bit[b] = next_id, 1
            heapq.heappush(heap, (c1 + c2, next_id))
            next_id += 1
        root = heap[0][1]
        for i, vw in enumerate(words):
            codes, points = [], []
            node = i
            while node != root:
                codes.append(bit[node])
                points.append(parent[node] - n)  # inner-node index (0-based)
                node = parent[node]
            codes.reverse()
            points.reverse()
            vw.codes, vw.points = codes, points
            self.max_code_length = max(self.max_code_length, len(codes))

    def code_arrays(self, pad_to: int | None = None):
        """(codes, points, lengths) int arrays padded to max code length —
        the batched device-side layout for hierarchical softmax."""
        n = len(self.cache)
        L = pad_to or self.max_code_length
        codes = np.zeros((n, L), np.int32)
        points = np.zeros((n, L), np.int32)
        lengths = np.zeros((n,), np.int32)
        for w in self.cache.words():
            vw = self.cache.word_for(w)
            l = min(len(vw.codes), L)
            codes[vw.index, :l] = vw.codes[:l]
            points[vw.index, :l] = vw.points[:l]
            lengths[vw.index] = l
        return codes, points, lengths
