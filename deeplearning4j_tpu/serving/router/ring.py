"""Consistent-hash ring with virtual nodes (DESIGN.md §19).

Every replica owns ``vnodes`` points on a 64-bit circle (blake2b of
``"{name}#{i}"``); a key hashes to a point and walks clockwise to the
first node.  Virtual nodes smooth ownership so equal-weight replicas get
near-equal key share, and adding/removing one replica remaps only ~1/N
of the keyspace — the property that makes prefix affinity survive
elastic membership (a scale-up event must not cold-start every
replica's KV cache at once).

Quarantine is deliberately NOT a ring operation: ``walk(key)`` yields
*every* distinct node in clockwise order and the caller filters
unhealthy ones.  Keeping quarantined nodes on the ring means their
segment drains to the immediate successors (walk order) while they are
down and snaps back to the exact original assignment on re-admission —
removing/re-adding nodes instead would reshuffle ~1/N of *unrelated*
keys on every breaker transition.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Iterator


def _point(data: str) -> int:
    """64-bit position on the circle for an arbitrary string."""
    return int.from_bytes(
        hashlib.blake2b(data.encode(), digest_size=8).digest(), "big")


class HashRing:
    """Static membership + deterministic clockwise walk.

    Not thread-safe by itself: the router mutates membership only at
    construction time; a future elastic tier would swap whole rings
    atomically rather than locking per-lookup.
    """

    def __init__(self, names: list[str] | None = None, vnodes: int = 64):
        if vnodes <= 0:
            raise ValueError("vnodes must be positive")
        self.vnodes = vnodes
        self._names: set[str] = set()
        self._points: list[tuple[int, str]] = []  # sorted (position, name)
        for name in names or ():
            self.add(name)

    def add(self, name: str) -> None:
        if name in self._names:
            raise ValueError(f"replica {name!r} already on the ring")
        self._names.add(name)
        for i in range(self.vnodes):
            bisect.insort(self._points, (_point(f"{name}#{i}"), name))

    def remove(self, name: str) -> None:
        if name not in self._names:
            raise KeyError(name)
        self._names.discard(name)
        self._points = [p for p in self._points if p[1] != name]

    def nodes(self) -> list[str]:
        return sorted(self._names)

    def __len__(self) -> int:
        return len(self._names)

    def walk(self, key: str) -> Iterator[str]:
        """Every distinct node, clockwise from ``key``'s position.  The
        first yield is the key's owner; successors are its spillover /
        drain order.  Deterministic for a fixed membership."""
        if not self._points:
            return
        start = bisect.bisect_left(self._points, (_point(key), ""))
        seen: set[str] = set()
        n = len(self._points)
        for off in range(n):
            name = self._points[(start + off) % n][1]
            if name not in seen:
                seen.add(name)
                yield name

    def primary(self, key: str) -> str | None:
        """The key's owner (first walk entry), or None on an empty ring."""
        for name in self.walk(key):
            return name
        return None
