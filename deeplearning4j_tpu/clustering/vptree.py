"""Vantage-point tree.

Capability match of ``clustering/vptree/VpTreeNode.java:290`` +
``VpTreePointINDArray``: metric-space nearest neighbors (used by Barnes-Hut
t-SNE's input-similarity pass).
"""

from __future__ import annotations

import heapq

import numpy as np


class _VPNode:
    __slots__ = ("index", "threshold", "inside", "outside")

    def __init__(self, index):
        self.index = index
        self.threshold = 0.0
        self.inside = None
        self.outside = None


class VPTree:
    def __init__(self, points, seed: int = 0):
        self.points = np.asarray(points, np.float64)
        rng = np.random.default_rng(seed)
        self.root = self._build(list(range(self.points.shape[0])), rng)

    def _dist(self, i, q):
        return float(np.linalg.norm(self.points[i] - q))

    def _build(self, idx: list[int], rng):
        if not idx:
            return None
        vp = idx[int(rng.integers(len(idx)))]
        rest = [i for i in idx if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        dists = np.linalg.norm(self.points[rest] - self.points[vp], axis=1)
        node.threshold = float(np.median(dists))
        inside = [i for i, d in zip(rest, dists) if d <= node.threshold]
        outside = [i for i, d in zip(rest, dists) if d > node.threshold]
        node.inside = self._build(inside, rng)
        node.outside = self._build(outside, rng)
        return node

    def knn(self, query, k: int) -> list[tuple[int, float]]:
        query = np.asarray(query, np.float64)
        heap: list[tuple[float, int]] = []  # max-heap (negated)
        tau = [np.inf]

        def visit(node):
            if node is None:
                return
            d = float(np.linalg.norm(self.points[node.index] - query))
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.index))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, node.index))
                tau[0] = -heap[0][0]
            if node.inside is None and node.outside is None:
                return
            if d <= node.threshold:
                visit(node.inside)
                if d + tau[0] > node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        return sorted(((i, -nd) for nd, i in heap), key=lambda t: t[1])

    def nearest(self, query) -> tuple[int, float]:
        return self.knn(query, 1)[0]
