"""Host-side bookkeeping for the paged KV cache (DESIGN.md §17).

The device side is dumb on purpose: per-layer ``(num_pages, page_size,
Kv, Dh)`` pools (``Kv = n_kv_heads`` under GQA) plus an
``(S, pages_per_slot)`` int32 block table, both living in the engine's
donated decode state.  Under ``kv_quant`` the pools are int8/fp8 with a
``(num_pages, Kv)`` f32 scale row per page riding beside them
(``ops/pallas/kv_quant.py``) — still addressed by the SAME page ids
this pool hands out, so nothing here changes: a page is a page.
Everything stateful — free-list, per-page refcounts, the
content-addressed prefix cache — lives HERE, on the host, under one
lock, so the decode hot loop never synchronizes on allocation metadata.

Quantization does lean on two pool-adjacent invariants, recorded here
because this module's lifecycle is what makes them safe: (1) prefix
sharing stays sound because quantized rewrites of identical content are
byte-identical (monotone per-page scales — see ``kv_quant``), so an
aliased page's bytes never depend on WHICH slot wrote them; (2) the
:meth:`clear_prefix` quarantine → wipe → :meth:`requeue` reload path
must reset page SCALES along with page content (``reset_cache_pages``
does both), or a stale scale would leak a superseded occupant's
magnitude into the next tenant's precision.

Prefix cache: content addressing is a chained hash over FULL token
pages — ``h_k = H(h_{k-1} || tokens[(k-1)*ps : k*ps])`` — so a lookup
walks the chain until the first miss and aliases the longest cached
run.  Only positions the prefill actually computes are shareable: a
prompt of length ``p`` prefills K/V for positions ``[0, p-1)`` (the
last token is the first decode query), so a chain of ``k`` pages is
usable only when ``k * page_size <= p - 1``.  Cache entries PIN their
pages with a refcount; slots aliasing them add one more ref each.  A
page is freed (and wiped by the engine) only when its count reaches
zero, so an aliased page can never be reused or zeroed under a reader.

The pool never touches device arrays: acquire/release return page ids
and the ENGINE gathers/scatters/wipes at its fences — keeping this
module trivially testable and the lock discipline one-directional
(pool lock is a leaf: nothing is called while holding it).
"""

from __future__ import annotations

import hashlib
import threading

from .batcher import PagePoolExhausted


def prefix_chain_keys(tokens: list[int], usable: int,
                      page_size: int) -> list[str]:
    """Chained hashes for every full token page covering positions
    ``< usable`` — key ``i`` addresses K/V for ``tokens[: (i+1)*ps]``
    and, being chained, commits to the entire prefix, not just its own
    page.

    Module-level so the serving ROUTER can compute the same keys without
    a pool: prefix-affinity routing consistent-hashes a request by this
    chain, and any drift between the router's hash and the pool's would
    silently destroy locality.  There is exactly one implementation.
    """
    keys: list[str] = []
    h = b"kv-prefix-v1"
    for k in range(1, usable // page_size + 1):
        block = tokens[(k - 1) * page_size: k * page_size]
        h = hashlib.blake2b(
            h + (",".join(map(str, block))).encode(), digest_size=16,
        ).digest()
        keys.append(h.hex())
    return keys


class PrefixEntry:
    """One cached chain: the first ``len(pages)`` full token pages of
    some prompt, pinned (one refcount per page) until LRU-evicted."""

    __slots__ = ("pages", "tick")

    def __init__(self, pages: tuple[int, ...], tick: int):
        self.pages = pages
        self.tick = tick


class PagePool:
    """Free-list + refcounts + prefix cache over ``num_pages`` usable
    pages (the engine typically appends one extra physical trash page
    OUTSIDE this pool for inactive-slot writes)."""

    def __init__(self, num_pages: int, page_size: int):
        if num_pages <= 0 or page_size <= 0:
            raise ValueError("num_pages and page_size must be positive")
        self.num_pages = num_pages
        self.page_size = page_size
        self._lock = threading.Lock()
        self._free = list(range(num_pages))          # guarded-by: self._lock
        self._ref = [0] * num_pages                  # guarded-by: self._lock
        self._prefix: dict[str, PrefixEntry] = {}    # guarded-by: self._lock
        self._tick = 0                               # guarded-by: self._lock
        self._lookups = 0                            # guarded-by: self._lock
        self._hits = 0                               # guarded-by: self._lock

    # -- content addressing ---------------------------------------------
    def chain_keys(self, tokens: list[int], usable: int) -> list[str]:
        """See :func:`prefix_chain_keys` (shared with the router)."""
        return prefix_chain_keys(tokens, usable, self.page_size)

    # -- acquire side ---------------------------------------------------
    def lookup_prefix(self, tokens: list[int], usable: int):
        """Longest cached chain of full token pages covering at most
        ``usable`` positions.  Every matched page is increffed FOR THE
        CALLER (the slot's alias) before return, so a concurrent LRU
        eviction can free the entry but never the pages under the new
        reader.  Returns ``(pages, cached_positions)``."""
        keys = self.chain_keys(tokens, usable)
        with self._lock:
            self._lookups += 1
            best: PrefixEntry | None = None
            for key in keys:
                entry = self._prefix.get(key)
                if entry is None:
                    break
                best = entry
            if best is None:
                return [], 0
            self._hits += 1
            self._tick += 1
            best.tick = self._tick
            for p in best.pages:
                self._ref[p] += 1
            return list(best.pages), len(best.pages) * self.page_size

    def peek_prefix(self, tokens: list[int], usable: int) -> int:
        """Read-only variant of :meth:`lookup_prefix`: how many leading
        positions are cached RIGHT NOW, with no incref and no LRU touch.
        A migration PROBE uses this to plan its transfer schedule (which
        pages to ship) without pinning anything; the answer is advisory
        — the import claim re-walks the chain and may find more or fewer
        pages, which the protocol handles with a re-plan, never a leak."""
        keys = self.chain_keys(tokens, usable)
        with self._lock:
            depth = 0
            for key in keys:
                entry = self._prefix.get(key)
                if entry is None:
                    break
                depth = len(entry.pages)
            return depth * self.page_size

    def alloc(self, n: int) -> list[int]:
        """Take ``n`` fresh pages (refcount 1 each), LRU-evicting unpinned
        prefix entries as needed; raises :class:`PagePoolExhausted` when
        even a drained cache cannot cover the request."""
        with self._lock:
            while len(self._free) < n and self._evict_lru_locked():
                pass
            if len(self._free) < n:
                raise PagePoolExhausted(
                    f"KV page pool exhausted: need {n} pages, "
                    f"{len(self._free)}/{self.num_pages} free and no "
                    "evictable prefix entries — retry when slots drain")
            out = [self._free.pop() for _ in range(n)]
            for p in out:
                self._ref[p] = 1
            return out

    def insert_prefix(self, tokens: list[int], pages: list[int],
                      usable: int) -> None:
        """Publish every full-page chain of ``tokens[:usable]`` backed by
        the slot's ``pages`` (block-table order).  Each new entry pins
        its pages with one more refcount; chains already present are
        left alone (their pages already hold bitwise-identical K/V —
        prefill is position-wise deterministic)."""
        keys = self.chain_keys(tokens, usable)
        with self._lock:
            for i, key in enumerate(keys):
                if key in self._prefix:
                    self._tick += 1
                    self._prefix[key].tick = self._tick
                    continue
                chain = tuple(pages[: i + 1])
                for p in chain:
                    self._ref[p] += 1
                self._tick += 1
                self._prefix[key] = PrefixEntry(chain, self._tick)

    # -- release side ---------------------------------------------------
    def decref(self, pages: list[int]) -> list[int]:
        """Drop one reference per page; returns the pages whose count
        reached zero (now back on the free list) so the caller can wipe
        them on device.  Aliased pages (count still > 0) are NOT
        returned — they must be neither wiped nor reused."""
        with self._lock:
            return self._decref_locked(pages)

    def _decref_locked(self, pages, quarantine: bool = False) -> list[int]:
        """With ``quarantine=True`` dead pages are reported but NOT
        pushed on the free list — the caller owns getting them wiped and
        handed back through :meth:`requeue`."""
        freed: list[int] = []
        for p in pages:
            if self._ref[p] <= 0:
                raise AssertionError(f"page {p} refcount underflow")
            self._ref[p] -= 1
            if self._ref[p] == 0:
                if not quarantine:
                    self._free.append(p)
                freed.append(p)
        return freed

    def decref_quarantine(self, pages: list[int]) -> list[int]:
        """Like :meth:`decref`, but pages whose count reaches zero are
        QUARANTINED (off the books, NOT reallocatable) instead of freed
        — the caller owns wiping them on device and handing them back
        through :meth:`requeue`.  This is the migration abort/handoff
        release: the KVMigrator runs off the serve thread, so it cannot
        wipe, and a page must never become allocatable before the serve
        thread has zeroed it (wipe-before-reallocatable)."""
        with self._lock:
            return self._decref_locked(pages, quarantine=True)

    def clear_prefix(self) -> list[int]:
        """Drop EVERY prefix entry — hot-reload invalidation: cached
        chains hold K/V computed under superseded weights, and a request
        that aliased one after a param swap would serve tokens matching
        neither the old nor the new model.  Pages whose cache pin was
        the last reference are QUARANTINED (removed from the books but
        NOT reallocatable) and returned so the engine's serve thread can
        zero them before :meth:`requeue` makes them allocatable again —
        wipe-before-reallocatable, so a cleared page can never be zeroed
        under a reader that just acquired it.  Pages still aliased by
        live slots stay pinned by their readers, untouched."""
        with self._lock:
            quarantined: list[int] = []
            for key in list(self._prefix):
                entry = self._prefix.pop(key)
                quarantined.extend(
                    self._decref_locked(list(entry.pages), quarantine=True))
            return quarantined

    def requeue(self, pages: list[int]) -> None:
        """Return quarantined (now wiped) pages to the free list."""
        with self._lock:
            self._free.extend(pages)

    def _evict_lru_locked(self) -> bool:
        """Drop the least-recently-touched prefix entry (its pin only —
        slots still aliasing the pages keep them alive)."""
        if not self._prefix:
            return False
        key = min(self._prefix, key=lambda k: self._prefix[k].tick)
        entry = self._prefix.pop(key)
        self._decref_locked(list(entry.pages))
        return True

    def reset(self) -> None:
        """Forget everything (serve-loop crash recovery: the engine
        reinitializes device state, so host bookkeeping starts over)."""
        with self._lock:
            self._free = list(range(self.num_pages))
            self._ref = [0] * self.num_pages
            self._prefix.clear()

    # -- introspection --------------------------------------------------
    def in_use(self) -> int:
        with self._lock:
            return self.num_pages - len(self._free)

    def free_count(self) -> int:
        with self._lock:
            return len(self._free)

    def refcount(self, page: int) -> int:
        with self._lock:
            return self._ref[page]

    def prefix_entries(self) -> int:
        with self._lock:
            return len(self._prefix)

    def refcounts(self) -> list[int]:
        """Snapshot of every page's refcount — leak audits (a balanced
        disagg migration must return the pool to its pre-migration
        counts) without poking the private array per page."""
        with self._lock:
            return list(self._ref)

    def hit_rate(self) -> float:
        with self._lock:
            return self._hits / self._lookups if self._lookups else 0.0

    def hit_counts(self) -> tuple[int, int]:
        """(hits, lookups) — absolute counts, so a router aggregating N
        replicas can compute a pool-weighted hit rate (Σhits/Σlookups)
        instead of averaging per-replica ratios."""
        with self._lock:
            return self._hits, self._lookups
