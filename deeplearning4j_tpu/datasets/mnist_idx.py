"""IDX binary format reader (MNIST).

Capability match of the reference's ``datasets/mnist/MnistManager.java`` +
``MnistImageFile``/``MnistLabelFile``/``MnistDbFile`` binary readers.  A
vectorized numpy parse replaces the per-pixel Java stream reads; the native
C++ loader (``native/``) provides a faster path when built.
"""

from __future__ import annotations

import gzip
import struct
from pathlib import Path

import numpy as np

IMAGE_MAGIC = 2051
LABEL_MAGIC = 2049


def _open(path: Path):
    path = Path(path)
    return gzip.open(path, "rb") if path.suffix == ".gz" else open(path, "rb")


def read_idx_images(path: Path | str) -> np.ndarray:
    """(n, rows, cols) uint8."""
    with _open(Path(path)) as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != IMAGE_MAGIC:
            raise ValueError(f"bad IDX image magic {magic} in {path}")
        data = np.frombuffer(f.read(n * rows * cols), dtype=np.uint8)
    return data.reshape(n, rows, cols)


def read_idx_labels(path: Path | str) -> np.ndarray:
    """(n,) uint8."""
    with _open(Path(path)) as f:
        magic, n = struct.unpack(">II", f.read(8))
        if magic != LABEL_MAGIC:
            raise ValueError(f"bad IDX label magic {magic} in {path}")
        data = np.frombuffer(f.read(n), dtype=np.uint8)
    return data


def write_idx_images(path: Path | str, images: np.ndarray) -> None:
    images = np.asarray(images, dtype=np.uint8)
    n, rows, cols = images.shape
    with open(path, "wb") as f:
        f.write(struct.pack(">IIII", IMAGE_MAGIC, n, rows, cols))
        f.write(images.tobytes())


def write_idx_labels(path: Path | str, labels: np.ndarray) -> None:
    labels = np.asarray(labels, dtype=np.uint8)
    with open(path, "wb") as f:
        f.write(struct.pack(">II", LABEL_MAGIC, labels.shape[0]))
        f.write(labels.tobytes())
