"""Child-process entry point for :class:`~.replicas.ProcessReplica`.

``python -m deeplearning4j_tpu.serving.router.procserver --factory
pkg.module:callable --factory-json '{...}' --port-file P --stop-file S``
builds an engine from the factory spec (the procrunner ``"module:attr"``
reflection idiom), mounts it on a real :class:`~..server.ModelServer` on
a free port, writes the bound port ATOMICALLY to ``--port-file`` (the
parent's boot barrier — interpreter + jax startup takes seconds), then
parks until ``--stop-file`` appears or SIGTERM lands.

``--trace-out`` streams every completed span to a JSONL event log
(crash-safe), so a multi-process run's traces merge in
``tools/trace_report.py`` into one cross-process critical path — each
replica's ``serving.*`` spans carry the trace id the router propagated
over the ``traceparent`` header.

:func:`tiny_lm_factory` ships here so parity tests can build the SAME
fixed-seed model in parent and child and compare routed tokens against
``Transformer.sample(...)`` offline.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from pathlib import Path


def tiny_lm_factory(seed: int = 7, vocab_size: int = 64, d_model: int = 32,
                    n_heads: int = 4, n_layers: int = 2, d_ff: int = 64,
                    max_len: int = 64, slots: int = 4, resolve_every: int = 4,
                    max_queue: int = 64, paged: bool = False,
                    page_size: int = 16, prefix_cache: bool = False,
                    role: str = "unified"):
    """The test-battery engine: a fixed-seed tiny transformer, identical
    for identical kwargs in any process.  ``role="prefill"`` spawns a
    prefill-tier worker (paged forced on — the migration unit is a KV
    page; no serve thread, ``/v1/generate`` refused by probes §27)."""
    import jax
    import jax.numpy as jnp

    from ...models.transformer import TransformerConfig, TransformerLM
    from ..engine import InferenceEngine, ServingConfig

    cfg = TransformerConfig(vocab_size=vocab_size, d_model=d_model,
                            n_heads=n_heads, n_layers=n_layers, d_ff=d_ff,
                            max_len=max_len, dtype=jnp.float32, remat=False,
                            xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(seed))
    if role == "prefill":
        paged = True
    return InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=slots, resolve_every=resolve_every,
                          max_queue=max_queue, paged=paged,
                          page_size=page_size, prefix_cache=prefix_cache,
                          role=role))


def _resolve(spec: str):
    """``"pkg.module:attr"`` -> callable (procrunner idiom)."""
    import importlib

    mod, _, attr = spec.partition(":")
    return getattr(importlib.import_module(mod), attr)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--name", required=True)
    ap.add_argument("--port-file", required=True)
    ap.add_argument("--stop-file", required=True)
    ap.add_argument("--factory", required=True,
                    help='engine factory as "module:callable"')
    ap.add_argument("--factory-json", default="{}",
                    help="JSON kwargs for the factory")
    ap.add_argument("--trace-out", default=None,
                    help="stream completed spans to this JSONL file")
    args = ap.parse_args(argv)

    from ... import observability
    from ...observability import TRACER
    from ..server import ModelServer

    observability.enable()
    if args.trace_out:
        TRACER.stream_jsonl(args.trace_out)

    engine = _resolve(args.factory)(**json.loads(args.factory_json))
    engine.start()
    server = ModelServer(engine=engine)
    server.start()

    # atomic publish: the parent must never read a half-written port
    port_file = Path(args.port_file)
    tmp = port_file.with_suffix(".tmp")
    tmp.write_text(str(server.port))
    os.replace(tmp, port_file)

    stop_file = Path(args.stop_file)
    stopping = {"now": False}

    def _sigterm(_sig, _frm):
        stopping["now"] = True

    signal.signal(signal.SIGTERM, _sigterm)
    while not stopping["now"] and not stop_file.exists():
        time.sleep(0.1)

    server.stop()
    engine.stop()
    TRACER.stop_stream()
    return 0


if __name__ == "__main__":
    sys.exit(main())
