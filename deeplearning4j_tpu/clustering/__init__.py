"""L4 — clustering & spatial trees (reference: ``clustering/``)."""

from .kmeans import KMeansClustering
from .kdtree import KDTree
from .vptree import VPTree
from .quadtree import QuadTree

__all__ = ["KMeansClustering", "KDTree", "VPTree", "QuadTree"]
