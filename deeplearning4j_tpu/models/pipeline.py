"""Pipeline parallelism over the ``pp`` mesh axis — GPipe-style micro-batch
fill/drain, TPU-first.

No reference analog (the v0 reference tops out at parameter-averaging data
parallelism, ``IterativeReduceWorkRouter.java:16``); the spec is the
BASELINE.json north star (multi-axis sharding on a pod).  The design is the
idiomatic JAX/XLA one, NOT a port of torch-style stage processes:

- The transformer blocks are **stacked on a leading layer axis** and that
  axis is sharded over ``pp``: each pp rank holds ``n_layers / pp``
  contiguous blocks (a *stage*) as one pytree of ``(L_loc, ...)`` leaves.
- ONE SPMD program runs on every rank under ``shard_map``.  A ``lax.scan``
  over ``M + S - 1`` ticks implements fill/drain: at each tick a rank
  applies its stage to its current activation and hands the result to the
  next rank via ``lax.ppermute``.  Rank 0 feeds micro-batch ``t`` in; the
  last rank collects finished micro-batches from tick ``S-1`` on.
- **Backward needs no schedule of its own**: the VJP of ``ppermute`` is the
  reverse rotation, so differentiating the scan yields the drain-ordered
  backward pipeline automatically.
- Embedding/final-LN/head are replicated over ``pp`` but *used* only on the
  first/last rank; their local gradients are partial contributions (zero on
  unused ranks), so the pp gradient sync is ``psum`` — unlike dp/sp where
  replicas hold full per-shard gradients and the sync is ``pmean``.

Composes with the existing axes: dp (batch shard + grad pmean), sp (ring
attention inside each block), tp (Megatron psum boundaries inside each
block) — all in the same mesh, same shard_map.

Bubble fraction is ``(S-1)/(M+S-1)``; pick ``n_micro >= 2*S`` (GPipe's
guidance is ~4x) to keep it small.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax spells the flag check_rep
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _sm_old

    @wraps(_sm_old)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma)

from ..parallel.mesh import DP, PP, SP, TP
from .transformer import (
    TransformerConfig,
    TransformerLM,
    _block,
    _layernorm,
    embed_local,
    lm_head_loss,
    param_specs,
)


# --------------------------------------------------------------------- layout

def stack_layers(params):
    """List-of-layer-dicts -> single stacked pytree with leading layer axis
    (the axis ``pp`` shards).  Non-layer leaves pass through."""
    out = {k: v for k, v in params.items() if k != "layers"}
    out["layers"] = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *params["layers"])
    return out


def unstack_layers(params, n_layers: int):
    """Inverse of :func:`stack_layers` (checkpoint interchange with the
    list-layout ``TransformerLM``)."""
    out = {k: v for k, v in params.items() if k != "layers"}
    st = params["layers"]
    out["layers"] = [jax.tree_util.tree_map(lambda x: x[i], st)
                     for i in range(n_layers)]
    return out


def pipeline_param_specs(cfg: TransformerConfig):
    """Stacked-layout PartitionSpecs: the stacked layer axis is sharded over
    pp; inner axes keep their tp sharding; everything else replicated."""
    base = param_specs(cfg)
    specs = {k: v for k, v in base.items() if k != "layers"}
    specs["layers"] = jax.tree_util.tree_map(
        lambda s: P(PP, *s), base["layers"][0],
        is_leaf=lambda x: isinstance(x, P))
    return specs


# --------------------------------------------------------------------- schedule

def pipelined_encode_local(params, tokens, cfg: TransformerConfig, *,
                           n_pp: int, n_micro: int, n_sp: int = 1,
                           sp_axis=None, tp_axis=None):
    """Final hidden states for the local (dp/sp-sharded) token block, the
    layer stack executed as an ``n_pp``-stage, ``n_micro``-micro-batch
    pipeline.  Runs inside shard_map.  Every rank returns the same-shaped
    output; only the LAST rank's is the real sequence encoding (callers
    mask with ``lax.axis_index(PP)``)."""
    B, T = tokens.shape
    assert B % n_micro == 0, f"local batch {B} % n_micro {n_micro}"
    stage = lax.axis_index(PP)

    # Embedding on every rank (SPMD; a gather — cheap), used only by rank 0.
    x = embed_local(params, tokens, cfg, sp_axis)

    bm = B // n_micro
    micro = x.reshape(n_micro, bm, T, x.shape[-1])

    stacked = params["layers"]                    # (L_loc, ...) leaves

    def apply_stage(h):
        def body(carry, lp):
            out = _block(lp, carry, cfg, n_sp, sp_axis, tp_axis, T)
            return out, None
        body_fn = jax.checkpoint(body) if cfg.remat else body
        h, _ = lax.scan(body_fn, h, stacked)
        return h

    n_ticks = n_micro + n_pp - 1
    right = [(i, (i + 1) % n_pp) for i in range(n_pp)]

    def tick(carry, t):
        recv, outs = carry
        x0 = lax.dynamic_index_in_dim(micro, jnp.clip(t, 0, n_micro - 1), 0,
                                      keepdims=False)
        xin = jnp.where(stage == 0, x0, recv)
        # Zero the garbage lane (fill/drain bubble ticks) BEFORE the stage
        # runs: a masked-out lane that went non-finite (bf16 overflow) would
        # poison real gradients through the jnp.where backward (0 * inf =
        # nan).  Zeros stay finite through the block, so the trap can't arm.
        valid = (t >= stage) & (t < n_micro + stage)
        xin = jnp.where(valid, xin, jnp.zeros_like(xin))
        y = apply_stage(xin)
        out_idx = jnp.clip(t - (n_pp - 1), 0, n_micro - 1)
        updated = lax.dynamic_update_index_in_dim(outs, y, out_idx, 0)
        outs = jnp.where(t >= n_pp - 1, updated, outs)
        recv = lax.ppermute(y, PP, right)
        return (recv, outs), None

    outs0 = jnp.zeros_like(micro)
    recv0 = jnp.zeros_like(micro[0])
    (_, outs), _ = lax.scan(tick, (recv0, outs0), jnp.arange(n_ticks))

    h = outs.reshape(B, T, x.shape[-1])
    return _layernorm(h, params["final_ln_scale"], params["final_ln_bias"])


def pipelined_lm_loss_local(params, tokens, targets, cfg: TransformerConfig,
                            *, n_pp: int, n_micro: int, **axes):
    """Local masked LM loss: real on the last pp rank, 0 elsewhere; callers
    ``psum`` over pp (exactly one rank contributes) then pmean over dp/sp."""
    h = pipelined_encode_local(params, tokens, cfg, n_pp=n_pp,
                               n_micro=n_micro, **axes)
    loss = lm_head_loss(params, h, targets, cfg)
    is_last = lax.axis_index(PP) == n_pp - 1
    return jnp.where(is_last, loss, 0.0)


def pipelined_cls_loss_local(backbone, head, tokens, labels,
                             cfg: TransformerConfig, *, n_pp: int,
                             n_micro: int, n_sp: int = 1, sp_axis=None,
                             tp_axis=None):
    """Classifier fine-tune loss through the pipeline (the BERT-fine-tune
    north star composed with pp): mean-pool the last rank's encoding, dense
    head, cross entropy — real on the last pp rank, 0 elsewhere (callers
    psum over pp, as with the LM loss)."""
    h = pipelined_encode_local(backbone, tokens, cfg, n_pp=n_pp,
                               n_micro=n_micro, n_sp=n_sp, sp_axis=sp_axis,
                               tp_axis=tp_axis)
    pooled = h.astype(jnp.float32).mean(axis=1)
    if sp_axis:
        pooled = lax.pmean(pooled, sp_axis)
    logits = pooled @ head["w_cls"].astype(jnp.float32) + head["b_cls"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))
    is_last = lax.axis_index(PP) == n_pp - 1
    return jnp.where(is_last, loss, 0.0)


# --------------------------------------------------------------------- facade

class PipelinedTransformerLM(TransformerLM):
    """Flagship trainer with the pp axis live: (dp, pp, sp, tp) explicit
    SPMD.  Param layout is the STACKED one (use :func:`stack_layers` /
    :func:`unstack_layers` to interchange with ``TransformerLM``)."""

    def __init__(self, cfg: TransformerConfig, mesh, n_micro: int | None = None):
        super().__init__(cfg, mesh)
        s = mesh.shape
        self.n_pp = s.get(PP, 1)
        assert self.n_pp > 1, "use TransformerLM when pp == 1"
        assert cfg.n_layers % self.n_pp == 0, (
            f"n_layers {cfg.n_layers} % pp {self.n_pp}")
        self.n_micro = n_micro if n_micro is not None else 2 * self.n_pp

    def init(self, key=None) -> dict:
        return stack_layers(super().init(key))

    def _unstacked_only(name):
        def guard(self, *a, **kw):
            raise NotImplementedError(
                f"{name} runs on the unstacked single-device layout: "
                f"TransformerLM(cfg).{name}(unstack_layers("
                "jax.device_get(params), cfg.n_layers), ...)")
        guard.__name__ = name
        return guard

    sample = _unstacked_only("sample")
    beam_search = _unstacked_only("beam_search")
    score = _unstacked_only("score")
    del _unstacked_only

    def _specs(self):
        return pipeline_param_specs(self.cfg)


    def _grad_sync(self, specs, sp_axis, tp_axis, include_dp: bool = True):
        """dp/sp replicas hold full per-shard grads -> pmean; pp holds
        PARTIAL contributions on pp-replicated leaves -> psum (stage-sharded
        leaves already have their full grad locally).  ``include_dp=False``
        is the ZeRO-1 path: dp handled by the caller's reduce-scatter."""
        base = super()._grad_sync(specs, sp_axis, tp_axis, include_dp)

        def sync(grads):
            grads = base(grads)

            def pp_fix(g, spec):
                if any(ax == PP for ax in spec if ax is not None):
                    return g
                return lax.psum(g, PP)

            return jax.tree_util.tree_map(
                pp_fix, grads, specs, is_leaf=lambda x: isinstance(x, P))

        return sync

    def _loss_reduce(self, loss, sp_axis):
        """Exactly one pp rank (the last) holds the real loss; psum over pp
        recovers it, then the usual dp/sp pmean applies."""
        return super()._loss_reduce(lax.psum(loss, PP), sp_axis)

    # -- ZeRO-1 over dp, composed with pp -------------------------------
    #
    # Stage-sharded leaves (the stacked ``layers`` subtree, spec
    # ``P(PP, ...)``) hold a DIFFERENT local chunk per pp rank, exactly as
    # tp-sharded leaves do per tp rank — so their dp-sharded optimizer
    # state grows a pp row dimension: state leaves are encoded globally as
    # ``(rows, n_dp * k)`` with ``rows = n_pp·[n_tp]`` and spec
    # ``P((PP[, TP]), DP)``.  Inside shard_map every rank still sees a
    # ``(1, k)`` local leaf, so the parent's scatter/update/gather local
    # step needs no change at all.

    def _decay_mask(self, tree):
        """Stacking grafts a leading layer axis onto every per-layer leaf,
        so the ndim >= 2 weight-class default misfires there (a (D,) LN
        scale becomes (L, D)): stacked leaves are weight-class iff their
        UNstacked form is, i.e. ndim >= 3."""
        def mask(path, w):
            stacked = any(getattr(k, "key", None) == "layers" for k in path)
            return w.ndim >= (3 if stacked else 2)
        return jax.tree_util.tree_map_with_path(mask, tree)

    def _z1_leaf_is_pp_sharded(self, spec) -> bool:
        return any(ax == PP for ax in spec if ax is not None)

    def _z1_row_layout(self, spec):
        """(row count multiplier axes, row PartitionSpec entry) for a leaf."""
        _, _, n_tp = self._axes()
        axes = []
        if self._z1_leaf_is_pp_sharded(spec):
            axes.append((PP, self.n_pp))
        if self._z1_leaf_is_tp_sharded(spec) and n_tp > 1:
            axes.append((TP, n_tp))
        names = tuple(a for a, _ in axes)
        row_spec = names if len(names) > 1 else (names[0] if names else None)
        rows = 1
        for _, n in axes:
            rows *= n
        return rows, row_spec

    def _z1_template_and_specs(self, params, specs):
        n_dp = self._axes()[0]

        def template(p, spec):
            rows, _ = self._z1_row_layout(spec)
            local_size = int(np.prod(p.shape)) // rows
            k = self._z1_chunk(local_size, n_dp)
            return jnp.zeros((rows, n_dp * k), p.dtype)

        def spec_of(p, spec):
            return P(self._z1_row_layout(spec)[1], DP)

        is_p = lambda x: isinstance(x, P)
        tmpl = jax.tree_util.tree_map(template, params, specs, is_leaf=is_p)
        tspec = jax.tree_util.tree_map(spec_of, params, specs, is_leaf=is_p)
        return tmpl, tspec

    def _z1_state_specs(self, specs):
        return jax.tree_util.tree_map(
            lambda spec: P(self._z1_row_layout(spec)[1], DP), specs,
            is_leaf=lambda x: isinstance(x, P))

    def build_train_step(self, tx=None, lr: float = 1e-3, zero1: bool = False):
        """``step(params, opt, tokens, targets) -> (params, opt, loss)``
        with the layer stack pipelined over pp (shared ``_build_step``
        wiring; only the loss fn, specs, and reductions differ).
        ``zero1=True`` shards optimizer state over dp, including the
        pp-stage-sharded leaves (pair with ``init_opt_zero1``)."""
        cfg = self.cfg
        tx = tx if tx is not None else self._default_tx(lr)
        n_pp, n_micro = self.n_pp, self.n_micro

        def loss_of(params, tokens, targets, axes):
            return pipelined_lm_loss_local(params, tokens, targets, cfg,
                                           n_pp=n_pp, n_micro=n_micro, **axes)

        return self._build_step(tx, loss_of, self._specs(),
                                (P(DP, SP), P(DP, SP)), zero1=zero1)

    def _pipeline_axes(self):
        s = self.mesh.shape
        n_sp, n_tp = s.get(SP, 1), s.get(TP, 1)
        return dict(n_sp=n_sp, sp_axis=SP if n_sp > 1 else None,
                    tp_axis=TP if n_tp > 1 else None)

    def forward(self, params, tokens):
        """Vocabulary logits through the pipeline.  The last pp rank holds
        the real logits; a pp psum of the masked value replicates them so
        every rank returns the same (global) array."""
        if self._fwd is None:
            cfg, n_pp, n_micro = self.cfg, self.n_pp, self.n_micro
            axes = self._pipeline_axes()

            def local_fwd(params, tokens):
                h = pipelined_encode_local(params, tokens, cfg, n_pp=n_pp,
                                           n_micro=n_micro, **axes)
                logits = jnp.einsum(
                    "btd,dv->btv", h.astype(cfg.dtype),
                    (params["tok_embed"].T if cfg.tie_embeddings
                     else params["lm_head"]).astype(cfg.dtype)
                ).astype(jnp.float32)
                is_last = lax.axis_index(PP) == n_pp - 1
                return lax.psum(jnp.where(is_last, logits, 0.0), PP)

            self._fwd = jax.jit(shard_map(
                local_fwd, mesh=self.mesh,
                in_specs=(self._specs(), P(DP, SP)),
                out_specs=P(DP, SP), check_vma=False))
        return self._fwd(params, tokens)

    def init_finetune(self, key, n_classes, params=None):
        """Stacked-layout ``{"backbone", "head"}`` tree (inherits the parent
        wiring: ``init`` already stacks, ``finetune_specs`` routes through
        ``_specs``)."""
        return super().init_finetune(key, n_classes, params)

    def build_finetune_step(self, tx=None, lr: float = 2e-5):
        """Classifier fine-tune step with the layer stack pipelined over pp
        (the BERT-fine-tune north star composed with pipeline parallelism)."""
        cfg = self.cfg
        tx = tx if tx is not None else self._default_tx(lr)
        n_pp, n_micro = self.n_pp, self.n_micro

        def loss_of(tree, tokens, labels, axes):
            return pipelined_cls_loss_local(
                tree["backbone"], tree["head"], tokens, labels, cfg,
                n_pp=n_pp, n_micro=n_micro, **axes)

        return self._build_step(tx, loss_of, self.finetune_specs(),
                                (P(DP, SP), P(DP)))
