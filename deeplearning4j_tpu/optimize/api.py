"""Optimization SPI: listeners, step functions, termination conditions,
training evaluators.

Mirrors the reference's ``optimize/api/*`` + ``optimize/stepfunctions/*`` +
``optimize/terminations/*`` + ``optimize/listeners/*`` +
``optimize/OutputLayerTrainingEvaluator.java`` (early stopping).
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Protocol, Sequence

log = logging.getLogger(__name__)


# --------------------------------------------------------------------------- listeners

class IterationListener(Protocol):
    """``optimize/api/IterationListener.java`` — invoked each optimizer
    iteration (``BaseOptimizer.java:176-177``)."""

    def iteration_done(self, model: Any, iteration: int) -> None: ...


class ScoreIterationListener:
    """Log score every N iterations (reference logs each iteration,
    ``BaseOptimizer.java:201``)."""

    def __init__(self, print_every: int = 10):
        self.print_every = print_every
        self.scores: list[float] = []

    def iteration_done(self, model, iteration: int) -> None:
        score = float(model.score()) if hasattr(model, "score") else float("nan")
        self.scores.append(score)
        if iteration % self.print_every == 0:
            log.info("iteration %d score %.6f", iteration, score)


class ComposableIterationListener:
    """``optimize/listeners/ComposableIterationListener.java``."""

    def __init__(self, *listeners: IterationListener):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration: int) -> None:
        for l in self.listeners:
            l.iteration_done(model, iteration)


class TimingListener:
    """Beyond-v0: per-iteration wall-clock (profiler hook, SURVEY.md §5.1)."""

    def __init__(self):
        self.times: list[float] = []
        self._last = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self.times.append(now - self._last)
        self._last = now


# --------------------------------------------------------------------------- step functions

class StepFunction(Protocol):
    """``optimize/api/StepFunction.java`` — how to move params along a
    search direction."""

    def step(self, params, direction, step_size: float): ...


class DefaultStepFunction:
    """params + step * direction (ascent orientation, reference default)."""

    def step(self, params, direction, step_size: float):
        from ..utils import tree_math as tm
        return tm.axpy(step_size, direction, params)


class NegativeDefaultStepFunction:
    """params - step * direction (descent orientation)."""

    def step(self, params, direction, step_size: float):
        from ..utils import tree_math as tm
        return tm.axpy(-step_size, direction, params)


class GradientStepFunction:
    """Step directly by the (post-processed) gradient."""

    def step(self, params, direction, step_size: float = 1.0):
        from ..utils import tree_math as tm
        return tm.axpy(step_size, direction, params)


# --------------------------------------------------------------------------- terminations

class TerminationCondition(Protocol):
    """``optimize/api/TerminationCondition.java``."""

    def terminate(self, cost: float, old_cost: float, extra: Sequence[Any]) -> bool: ...


class EpsTermination:
    """``optimize/terminations/EpsTermination.java`` — relative/absolute
    improvement below eps."""

    def __init__(self, eps: float = 1e-4, tolerance: float = 1e-10):
        self.eps, self.tolerance = eps, tolerance

    def terminate(self, cost: float, old_cost: float, extra=()) -> bool:
        if old_cost == 0:
            return abs(cost) < self.tolerance
        improvement = abs(old_cost - cost) / max(abs(old_cost), abs(cost), 1e-30)
        return improvement < self.eps


class ZeroDirection:
    """``ZeroDirection.java`` — stop when gradient direction vanishes."""

    def __init__(self, tol: float = 1e-10):
        self.tol = tol

    def terminate(self, cost: float, old_cost: float, extra=()) -> bool:
        if not extra:
            return False
        from ..utils import tree_math as tm
        return float(tm.norm2(extra[0])) < self.tol


class Norm2Termination:
    """``Norm2Termination.java`` — stop when gradient L2 below threshold."""

    def __init__(self, gradient_tolerance: float = 1e-5):
        self.gradient_tolerance = gradient_tolerance

    def terminate(self, cost: float, old_cost: float, extra=()) -> bool:
        if not extra:
            return False
        from ..utils import tree_math as tm
        return float(tm.norm2(extra[0])) < self.gradient_tolerance


# --------------------------------------------------------------------------- training evaluator

class TrainingEvaluator(Protocol):
    """``optimize/api/TrainingEvaluator.java`` — validation-driven early
    stopping."""

    def should_stop(self, iteration: int) -> bool: ...


class OutputLayerTrainingEvaluator:
    """Early stopping on validation F1.

    Capability match of ``optimize/OutputLayerTrainingEvaluator.java``: every
    ``validation_epochs`` check validation F1; stop when improvement over the
    best drops below ``improvement_threshold`` for ``patience`` consecutive
    checks.
    """

    def __init__(self, model, features, labels, validation_epochs: int = 10,
                 patience: int = 5, improvement_threshold: float = 1e-4):
        self.model = model
        self.features = features
        self.labels = labels
        self.validation_epochs = validation_epochs
        self.patience = patience
        self.improvement_threshold = improvement_threshold
        self.best_f1 = -1.0
        self.bad_checks = 0

    def should_stop(self, iteration: int) -> bool:
        if iteration == 0 or iteration % self.validation_epochs != 0:
            return False
        from ..evaluation import Evaluation
        ev = Evaluation()
        ev.eval(self.labels, self.model.output(self.features))
        f1 = ev.f1()
        if f1 > self.best_f1 + self.improvement_threshold:
            self.best_f1 = f1
            self.bad_checks = 0
        else:
            self.bad_checks += 1
        return self.bad_checks >= self.patience
