"""Example ``WorkerPerformer``s + aggregators, importable by spec string in
worker processes (``resolve_performer_factory``).

- :class:`WordCountPerformer` — the reference's distributed word-count
  "hello world" (``deeplearning4j-scaleout/deeplearning4j-nlp/src/main/java/
  org/deeplearning4j/scaleout/perform/text/`` WordCountWorkPerformer et al.):
  the natural smoke test of the scaleout SPI, counting tokens per job and
  summing counts across workers via :class:`CounterAggregator`.
- :class:`VectorDeltaPerformer` — deterministic parameter-averaging-style
  performer used by the elastic-recovery tests: each job adds a known delta
  to the current model, so the final model equals init + sum(deltas) iff
  every job ran exactly once.
- :class:`SlowVectorDeltaPerformer` — same, with a sleep inside ``perform``
  to widen the SIGKILL window for process-death tests.
"""

from __future__ import annotations

import time
from collections import Counter

import numpy as np

from .scaleout import IterativeReduceWorkRouter, Job


class CounterAggregator:
    """Sums ``collections.Counter`` results across workers (the word-count
    aggregation; contrast with ``ArrayAggregator``'s running average)."""

    def __init__(self):
        self._total = Counter()

    def accumulate(self, job: Job) -> None:
        if job.result:
            self._total.update(job.result)

    def aggregate(self) -> Counter:
        return Counter(self._total)


class WordCountPerformer:
    """Tokenize-and-count: ``job.work`` is a text line (or token list);
    ``job.result`` is a Counter of token frequencies."""

    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job: Job) -> None:
        work = job.work
        tokens = work.split() if isinstance(work, str) else list(work)
        job.result = Counter(tokens)

    def update(self, *args) -> None:
        pass


class WordCountRouter(IterativeReduceWorkRouter):
    """Synchronous router whose aggregate ACCUMULATES across waves (counts
    are a running total, unlike the parameter-averaging ArrayAggregator
    which replaces the current model each superstep)."""

    def __init__(self, tracker):
        super().__init__(tracker, aggregator_factory=CounterAggregator)

    def update(self) -> None:
        updates = self.tracker.updates()
        if not updates:
            return
        agg = CounterAggregator()
        current = self.tracker.get_current()
        if current:
            agg._total.update(current)
        for wid, upd in updates.items():
            agg.accumulate(Job(work=None, worker_id=wid, result=upd))
        self.tracker.set_current(agg.aggregate())
        self.tracker.clear_updates()


class VectorDeltaPerformer:
    """current-model + per-job delta (order-free total; see module doc)."""

    dim = 4

    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job: Job) -> None:
        current = self.tracker.get_current()
        base = np.zeros(self.dim) if current is None else np.asarray(current)
        job.result = base + np.full(self.dim, float(job.work))

    def update(self, *args) -> None:
        pass


class SlowVectorDeltaPerformer(VectorDeltaPerformer):
    """0.25 s of "work" before the delta — keeps a job in-flight long
    enough for a test to SIGKILL the worker process mid-perform."""

    def perform(self, job: Job) -> None:
        time.sleep(0.25)
        super().perform(job)


class SVMLightTrainPerformer:
    """IterativeReduce worker over svmlight byte-range splits — the YARN
    path's SVMLight worker (``hadoop-yarn/cdh4/.../IRUnitSVMLightWorkerTest``
    pattern: each worker trains on its input split, the master averages).

    ``job.work`` is ``"path::start::end::num_features::num_classes"``;
    ``local_steps`` softmax-regression gradient steps over the split,
    starting from the current averaged model (workers train locally, the
    superstep averages — the IterativeReduce shape), emitting updated flat
    params for the ``ArrayAggregator`` average."""

    lr = 0.5
    local_steps = 10

    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job: Job) -> None:
        from ..datasets.svmlight import load_svmlight
        path, s, e, nf, nc = str(job.work).split("::")
        s, e, nf, nc = int(s), int(e), int(nf), int(nc)
        x, y = load_svmlight(path, nf, nc, start=s, end=e)
        cur = self.tracker.get_current()
        w = (np.zeros((nf, nc)) if cur is None
             else np.asarray(cur).reshape(nf, nc))
        for _ in range(self.local_steps):
            logits = x @ w
            logits -= logits.max(-1, keepdims=True)
            p = np.exp(logits)
            p /= p.sum(-1, keepdims=True)
            w = w - self.lr * (x.T @ (p - y)) / max(len(x), 1)
        job.result = w.reshape(-1)

    def update(self, *args) -> None:
        pass
