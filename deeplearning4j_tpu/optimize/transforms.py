"""Gradient transforms (optax-style) mirroring the reference's gradient
post-processing chain.

``optimize/solvers/BaseOptimizer.updateGradientAccordingToParams``
(``BaseOptimizer.java:68-118``) applies, in order: AdaGrad per-param learning
rates, momentum (with per-iteration schedule), L2 weight decay, clip to unit
norm, and divide-by-batch-size.  Here each is a pure ``(init, update)``
GradientTransform; ``chain`` composes them; ``from_conf`` assembles the
reference's exact chain from a ``NeuralNetConfiguration``.

State lives in device arrays (pytrees) so the whole update is jittable —
the AdaGrad historical-gradient state is the TPU equivalent of the
reference's ``org.nd4j.linalg.learning.AdaGrad`` mutable learner.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp  # noqa: F401  (l2_penalty)

from ..nn.conf import NeuralNetConfiguration

tree_map = jax.tree_util.tree_map


class GradientTransform(NamedTuple):
    init: Callable[[Any], Any]           # params -> state
    update: Callable[[Any, Any, Any, Any], tuple[Any, Any]]
    # (grads, state, params, iteration) -> (new_grads, new_state)
    state_spec: Callable[[Any], Any] | None = None
    # param_specs -> state_specs (same structure as init's output); None
    # means the state is EMPTY (stateless transform).  Stateful custom
    # transforms used with sharded trainers must provide this so optimizer
    # state is placed with the same PartitionSpecs as the params it mirrors.


def _empty_spec(param_specs):
    return ()


# --------------------------------------------------------------------- decay mask
#
# "Which leaves get weight decay" defaults to the ndim >= 2 heuristic
# (weight matrices yes, biases/layernorms no).  That heuristic is a
# statement about the CANONICAL param layout — trainers that re-lay params
# out break it: the pipelined model stacks per-layer leaves (an (D,) LN
# scale becomes (L, D), ndim 2) and the ZeRO-1 step flattens every param
# to a 1-D chunk.  Such trainers wrap their tx.update call in
# ``decay_mask_override`` with a bool pytree (matching the params tree
# they pass) saying which leaves are weight-class.  The context is read at
# trace time, so it composes with jit/shard_map.

from contextlib import contextmanager
from contextvars import ContextVar

# A ContextVar (not a module-level list): two threads tracing steps for
# DIFFERENT models concurrently — the serving engine warming up while a
# trainer builds its step, or two trainers in one process — must not see
# each other's overrides; a shared list would leak one model's decay mask
# into the other's tx.update.  Each thread (and each contextvars.Context)
# observes only the overrides pushed on its own stack.
_DECAY_MASK_STACK: ContextVar[tuple] = ContextVar("decay_mask_stack",
                                                  default=())


@contextmanager
def decay_mask_override(mask):
    """Override the decay-leaf choice for tx.update calls traced inside
    this context.  ``mask``: bool pytree matching the params tree handed
    to update (None = keep the ndim >= 2 default)."""
    token = _DECAY_MASK_STACK.set(_DECAY_MASK_STACK.get() + (mask,))
    try:
        yield
    finally:
        _DECAY_MASK_STACK.reset(token)


def decay_leaf_mask(params):
    """Effective decay mask for ``params``: the innermost active override,
    else the ndim >= 2 heuristic."""
    stack = _DECAY_MASK_STACK.get()
    if stack and stack[-1] is not None:
        return stack[-1]
    return tree_map(lambda w: jnp.ndim(w) >= 2, params)


def state_shardings(transform: GradientTransform, params, leaf_spec, mesh):
    """``NamedSharding`` tree for ``transform.init(params)``, derived from
    ``state_spec`` with every param leaf placed as ``leaf_spec``.

    This is the ZeRO plumbing: the trainer hands the FLATTENED param tree
    (each leaf a padded 1-D chunk) with ``leaf_spec = P('dp')`` and jits
    ``init`` with the returned tree as ``out_shardings`` — so optimizer
    state is born shard-local (each chip materializes 1/ndp of every
    leaf), never replicated-then-resharded.  ``state_spec`` callbacks keep
    the structure contract ``init`` set (chain -> tuple of sub-states,
    scale_by_adam -> (mu, nu), adagrad/momentum -> params-shaped), so the
    spec tree and the state tree flatten to the same leaf sequence.
    """
    from jax.sharding import NamedSharding, PartitionSpec

    param_specs = tree_map(lambda _: leaf_spec, params)
    specs = ((transform.state_spec or _empty_spec)(param_specs))
    state_shape = jax.eval_shape(transform.init, params)
    treedef = jax.tree_util.tree_structure(state_shape)
    spec_leaves = jax.tree_util.tree_leaves(
        specs, is_leaf=lambda s: isinstance(s, PartitionSpec))
    if len(spec_leaves) != treedef.num_leaves:
        raise ValueError(
            f"state_spec produced {len(spec_leaves)} leaf specs for a "
            f"state with {treedef.num_leaves} leaves — a stateful "
            "transform in the chain is missing (or mis-declaring) its "
            "state_spec")
    return jax.tree_util.tree_unflatten(
        treedef, [NamedSharding(mesh, s) for s in spec_leaves])


def init_sharded(transform: GradientTransform, params, leaf_spec, mesh):
    """``transform.init(params)`` with every state leaf materialized
    directly into the sharding ``state_shardings`` derives — the
    shard-local construction path ZeRO trainers use (no full-size
    intermediate on any single chip)."""
    shardings = state_shardings(transform, params, leaf_spec, mesh)
    return jax.jit(transform.init, out_shardings=shardings)(params)


def chain(*transforms: GradientTransform) -> GradientTransform:
    def init(params):
        return tuple(t.init(params) for t in transforms)

    def update(grads, state, params=None, iteration=0):
        new_state = []
        for t, s in zip(transforms, state):
            grads, s2 = t.update(grads, s, params, iteration)
            new_state.append(s2)
        return grads, tuple(new_state)

    def state_spec(param_specs):
        return tuple((t.state_spec or _empty_spec)(param_specs)
                     for t in transforms)

    return GradientTransform(init, update, state_spec)


def identity() -> GradientTransform:
    return GradientTransform(lambda p: (), lambda g, s, p=None, i=0: (g, s))


def scale(factor: float) -> GradientTransform:
    return GradientTransform(lambda p: (),
                             lambda g, s, p=None, i=0: (tree_map(lambda x: factor * x, g), s))


def _f32_zeros(params):
    """Optimizer state is ALWAYS f32 (even for bf16 params): accumulators
    round away small contributions in low precision."""
    return tree_map(lambda p: jnp.zeros(jnp.shape(p), jnp.float32), params)


def adagrad(lr: float, eps: float = 1e-6) -> GradientTransform:
    """Per-parameter adaptive LR (reference: nd4j ``AdaGrad``,
    ``BaseOptimizer.java:29,68-118``): g * lr / sqrt(sum g^2 + eps)."""

    init = _f32_zeros

    def update(grads, hist, params=None, iteration=0):
        hist = tree_map(lambda h, g: h + g.astype(jnp.float32) ** 2, hist, grads)
        out = tree_map(lambda g, h: lr * g * jax.lax.rsqrt(h + eps), grads, hist)
        return out, hist

    return GradientTransform(init, update, lambda ps: ps)


def sgd_lr(lr: float) -> GradientTransform:
    return scale(lr)


# --------------------------------------------------------------------- schedules
#
# A schedule is a jit-safe callable ``step -> lr`` (step may be a traced
# int).  ``scale_by_schedule`` accepts either a float or a schedule, so
# ``adam(warmup_cosine(...))`` and ``adam(1e-3)`` both work.

def constant_schedule(lr: float) -> Callable[[Any], Any]:
    return lambda step: jnp.asarray(lr, jnp.float32)


def warmup_linear(peak: float, warmup_steps: int, total_steps: int,
                  end: float = 0.0) -> Callable[[Any], Any]:
    """Linear warmup 0→peak then linear decay peak→end (the BERT fine-tune
    schedule)."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak * step / max(warmup_steps, 1)
        frac = (step - warmup_steps) / max(total_steps - warmup_steps, 1)
        frac = jnp.clip(frac, 0.0, 1.0)
        decay = peak + (end - peak) * frac
        return jnp.where(step < warmup_steps, warm, decay)

    return sched


def warmup_cosine(peak: float, warmup_steps: int, total_steps: int,
                  end: float = 0.0) -> Callable[[Any], Any]:
    """Linear warmup then cosine decay to ``end``."""

    def sched(step):
        step = jnp.asarray(step, jnp.float32)
        warm = peak * step / max(warmup_steps, 1)
        frac = (step - warmup_steps) / max(total_steps - warmup_steps, 1)
        frac = jnp.clip(frac, 0.0, 1.0)
        decay = end + 0.5 * (peak - end) * (1.0 + jnp.cos(jnp.pi * frac))
        return jnp.where(step < warmup_steps, warm, decay)

    return sched


def scale_by_schedule(lr) -> GradientTransform:
    """Multiply updates by ``lr`` (float) or ``lr(iteration)`` (schedule)."""

    def update(grads, s, params=None, iteration=0):
        factor = lr(iteration) if callable(lr) else lr
        return tree_map(lambda g: g * factor, grads), s

    return GradientTransform(lambda p: (), update)


# --------------------------------------------------------------------- adam family

def scale_by_adam(b1: float = 0.9, b2: float = 0.999,
                  eps: float = 1e-8) -> GradientTransform:
    """Adam moment rescaling (Kingma & Ba) with bias correction driven by
    the ``iteration`` argument.  State = (mu, nu), f32 device arrays mirroring
    the param tree — the TPU-native replacement for the reference's mutable
    nd4j learner state (``BaseOptimizer.java:68-118`` seam)."""

    def init(params):
        return (_f32_zeros(params), _f32_zeros(params))

    def update(grads, state, params=None, iteration=0):
        mu, nu = state
        g32 = tree_map(lambda g: g.astype(jnp.float32), grads)
        mu = tree_map(lambda m, g: b1 * m + (1 - b1) * g, mu, g32)
        nu = tree_map(lambda v, g: b2 * v + (1 - b2) * g * g, nu, g32)
        t = jnp.asarray(iteration, jnp.float32) + 1.0
        c1 = 1.0 - jnp.power(b1, t)
        c2 = 1.0 - jnp.power(b2, t)
        out = tree_map(
            lambda m, v: (m / c1) / (jnp.sqrt(v / c2) + eps), mu, nu)
        return out, (mu, nu)

    return GradientTransform(init, update, lambda ps: (ps, ps))


def add_decayed_weights(wd: float) -> GradientTransform:
    """Decoupled weight decay (AdamW): updates += wd * w on weight-class
    leaves only (``decay_leaf_mask``: ndim >= 2 unless overridden) —
    biases/layernorms stay undecayed."""

    def update(grads, s, params=None, iteration=0):
        if params is None or wd == 0.0:
            return grads, s
        return tree_map(
            lambda g, w, m: g + wd * w.astype(g.dtype) if m else g,
            grads, params, decay_leaf_mask(params)), s

    return GradientTransform(lambda p: (), update)


def adam(lr=1e-3, b1: float = 0.9, b2: float = 0.999,
         eps: float = 1e-8) -> GradientTransform:
    """Adam: moment rescaling then LR (float or schedule)."""
    return chain(scale_by_adam(b1, b2, eps), scale_by_schedule(lr))


def adamw(lr=1e-3, weight_decay: float = 0.01, b1: float = 0.9,
          b2: float = 0.999, eps: float = 1e-8) -> GradientTransform:
    """AdamW: decoupled weight decay added after moment rescaling, both
    scaled by the schedule (Loshchilov & Hutter)."""
    return chain(scale_by_adam(b1, b2, eps), add_decayed_weights(weight_decay),
                 scale_by_schedule(lr))


def momentum(base: float, schedule: dict[int, float] | None = None) -> GradientTransform:
    """Heavy-ball momentum with optional iteration schedule
    (``BaseOptimizer.java:75-84``): v = m*v + g; emit v."""
    schedule = dict(schedule or {})

    def momentum_at(iteration):
        if not schedule:
            return base
        its = jnp.array(sorted(schedule.keys()))
        vals = jnp.array([schedule[int(i)] for i in sorted(schedule.keys())])
        # piecewise-constant lookup, jit-safe
        idx = jnp.sum(its <= iteration) - 1
        return jnp.where(idx >= 0, vals[jnp.maximum(idx, 0)], base)

    init = _f32_zeros

    def update(grads, vel, params=None, iteration=0):
        m = momentum_at(iteration)
        vel = tree_map(lambda v, g: m * v + g.astype(jnp.float32), vel, grads)
        return vel, vel

    return GradientTransform(init, update, lambda ps: ps)


def weight_decay(l2: float) -> GradientTransform:
    """L2 regularization g += l2 * w (``BaseOptimizer.java``), applied to
    weight matrices only (ndim >= 2) — biases stay unregularized, matching
    the reference, which decays only the "W"-class params."""

    def update(grads, s, params=None, iteration=0):
        if params is None:
            return grads, s
        return l2_grad(l2, grads, params), s

    return GradientTransform(lambda p: (), update)


def l2_grad(l2: float, grads, params):
    """g + l2*w over the same leaves weight_decay touches
    (``decay_leaf_mask``) — the single source of truth for 'which leaves
    get decayed'."""
    return tree_map(lambda g, w, m: g + l2 * w if m else g,
                    grads, params, decay_leaf_mask(params))


def l2_penalty(l2: float, params) -> jnp.ndarray:
    """0.5*l2*||W||^2 over the same leaves weight_decay touches
    (``decay_leaf_mask``) — use when an objective VALUE must stay
    consistent with the decayed direction (line-search probes)."""
    leaves = [0.5 * l2 * jnp.sum(w * w)
              for w, m in zip(jax.tree_util.tree_leaves(params),
                              jax.tree_util.tree_leaves(decay_leaf_mask(params)))
              if m]
    return sum(leaves) if leaves else jnp.zeros(())


def clip_unit_norm() -> GradientTransform:
    """``constrainGradientToUnitNorm``: scale full gradient to unit L2."""
    from ..utils import tree_math as tm

    def update(grads, s, params=None, iteration=0):
        return tm.unit_norm(grads), s

    return GradientTransform(lambda p: (), update)


def clip_by_global_norm(max_norm: float) -> GradientTransform:
    from ..utils import tree_math as tm

    def update(grads, s, params=None, iteration=0):
        return tm.clip_by_global_norm(grads, max_norm), s

    return GradientTransform(lambda p: (), update)


def divide_by_batch(batch_size_fn: Callable[[], float] | float) -> GradientTransform:
    def update(grads, s, params=None, iteration=0):
        bs = batch_size_fn() if callable(batch_size_fn) else batch_size_fn
        return tree_map(lambda g: g / bs, grads), s

    return GradientTransform(lambda p: (), update)


def from_conf(conf: NeuralNetConfiguration) -> GradientTransform:
    """Assemble the reference's exact post-processing chain from a conf
    (order per ``BaseOptimizer.java:68-118``): AdaGrad (or plain LR) first,
    then momentum, then L2 — the reference subtracts ``l2*params`` AFTER the
    adaptive-LR scaling, so the decay term is NOT rescaled by the per-param
    learning rate — then the unit-norm clip."""
    parts: list[GradientTransform] = []
    if conf.use_adagrad:
        parts.append(adagrad(conf.lr))
    else:
        parts.append(sgd_lr(conf.lr))
    if conf.momentum > 0 or conf.momentum_schedule:
        parts.append(momentum(conf.momentum, conf.momentum_schedule))
    if conf.use_regularization and conf.l2 > 0:
        parts.append(weight_decay(conf.l2))
    if conf.constrain_gradient_to_unit_norm:
        parts.append(clip_unit_norm())
    return chain(*parts) if parts else identity()


def apply_updates(params, updates):
    """Gradient-descent application: params - updates."""
    return tree_map(lambda p, u: p - u.astype(p.dtype), params, updates)
