"""Artifact storage + cluster config registry.

Capability match of the reference's storage/config planes:

- S3/HDFS model & dataset IO (``aws/s3/reader/S3Downloader``, ``S3Uploader``,
  ``S3ModelSaver``, ``HdfsModelSaver``) → an ``ArtifactStore`` interface with
  a local-filesystem backend and a GCS backend gated on the google-cloud
  client (GCS plays the S3/HDFS role on TPU infrastructure).
- ZooKeeper config registration/retrieval (``ZooKeeperConfigurationRegister``
  /``ZookeeperConfigurationRetriever``) → ``ConfigRegistry``: namespaced
  key/value JSON documents in the artifact store, registered per host/job —
  on TPU pods the coordination service + shared storage replace the
  ZooKeeper ensemble.
- EC2 provisioning (``Ec2BoxCreator``/``ClusterSetup``) is intentionally out
  of scope as code: TPU capacity is provisioned by the platform (GKE/queued
  resources), not by the framework; documented deviation.
"""

from __future__ import annotations

import json
import pickle
import shutil
from pathlib import Path
from typing import Any, Protocol


class ArtifactStore(Protocol):
    def put_bytes(self, key: str, data: bytes) -> None: ...
    def get_bytes(self, key: str) -> bytes: ...
    def exists(self, key: str) -> bool: ...
    def delete(self, key: str) -> None: ...
    def list(self, prefix: str = "") -> list[str]: ...


class LocalArtifactStore:
    """Directory-backed store (the reference's LocalFileUpdateSaver/
    DefaultModelSaver role)."""

    def __init__(self, root: str | Path):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)

    def _path(self, key: str) -> Path:
        p = (self.root / key).resolve()
        if self.root.resolve() not in p.parents and p != self.root.resolve():
            raise ValueError(f"key escapes store root: {key}")
        return p

    def put_bytes(self, key: str, data: bytes) -> None:
        p = self._path(key)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(p.suffix + ".tmp")
        tmp.write_bytes(data)
        tmp.replace(p)

    def get_bytes(self, key: str) -> bytes:
        return self._path(key).read_bytes()

    def exists(self, key: str) -> bool:
        return self._path(key).exists()

    def delete(self, key: str) -> None:
        p = self._path(key)
        if p.is_dir():
            shutil.rmtree(p)
        else:
            p.unlink(missing_ok=True)

    def list(self, prefix: str = "") -> list[str]:
        base = self.root
        return sorted(str(p.relative_to(base)) for p in base.rglob("*")
                      if p.is_file() and str(p.relative_to(base)).startswith(prefix)
                      and not p.name.endswith(".tmp"))


class GCSArtifactStore:
    """GCS backend (plays the reference's S3 role on TPU infra).  Gated on
    the google-cloud-storage client being importable AND credentialed."""

    def __init__(self, bucket: str, prefix: str = ""):
        try:
            from google.cloud import storage  # type: ignore
        except ImportError as e:
            raise RuntimeError(
                "google-cloud-storage is not available in this environment; "
                "use LocalArtifactStore") from e
        self._bucket = storage.Client().bucket(bucket)
        self.prefix = prefix.rstrip("/")

    def _name(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put_bytes(self, key: str, data: bytes) -> None:
        self._bucket.blob(self._name(key)).upload_from_string(data)

    def get_bytes(self, key: str) -> bytes:
        return self._bucket.blob(self._name(key)).download_as_bytes()

    def exists(self, key: str) -> bool:
        return self._bucket.blob(self._name(key)).exists()

    def delete(self, key: str) -> None:
        self._bucket.blob(self._name(key)).delete()

    def list(self, prefix: str = "") -> list[str]:
        full = self._name(prefix)
        skip = len(self.prefix) + 1 if self.prefix else 0
        return sorted(b.name[skip:] for b in self._bucket.list_blobs(prefix=full))


# --------------------------------------------------------------------------- typed helpers

def save_model(store: ArtifactStore, key: str, model: Any) -> None:
    store.put_bytes(key, pickle.dumps(model))


def load_model(store: ArtifactStore, key: str) -> Any:
    return pickle.loads(store.get_bytes(key))


class StoreModelSaver:
    """ModelSaver SPI over any ArtifactStore (S3ModelSaver/HdfsModelSaver
    parity)."""

    def __init__(self, store: ArtifactStore, key: str = "model.bin"):
        self.store = store
        self.key = key

    def save(self, model: Any) -> None:
        save_model(self.store, self.key, model)

    def load(self) -> Any:
        return load_model(self.store, self.key)


class ConfigRegistry:
    """Namespaced JSON config documents (ZooKeeper-role config plane)."""

    def __init__(self, store: ArtifactStore, namespace: str = "conf"):
        self.store = store
        self.namespace = namespace.strip("/")

    def _key(self, host: str, name: str) -> str:
        return f"{self.namespace}/{host}/{name}.json"

    def register(self, host: str, name: str, config: dict) -> None:
        self.store.put_bytes(self._key(host, name),
                             json.dumps(config, sort_keys=True).encode())

    def retrieve(self, host: str, name: str) -> dict:
        return json.loads(self.store.get_bytes(self._key(host, name)))

    def exists(self, host: str, name: str) -> bool:
        return self.store.exists(self._key(host, name))

    def unregister(self, host: str, name: str) -> None:
        self.store.delete(self._key(host, name))

    def hosts(self) -> list[str]:
        seen = set()
        for k in self.store.list(self.namespace + "/"):
            parts = k.split("/")
            if len(parts) >= 3:
                seen.add(parts[1])
        return sorted(seen)
