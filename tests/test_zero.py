"""ZeRO sharded weight update (DESIGN.md §15): parity, layout, portability.

Pins the PR's acceptance criteria:
- stage parity: zero_stage 1/2/3 produce BITWISE-equal losses and params
  to the replicated stage-0 step on the CPU mesh, same data/seed — the
  sharded update is a layout change, not a numerics change,
- memory: optimizer-state bytes/device shrink ~1/ndp vs replicated
  (within flatten-padding tolerance), visible through the
  ``train.opt_state_bytes`` gauges,
- sharded layout: state leaves are 1-D chunks placed with a dp
  ``NamedSharding``; stage 3 additionally keeps params sharded between
  steps,
- portable checkpoints: a zero-2 checkpoint saved on dp=2 restores onto
  dp=1 (and vice versa) and continues bitwise-equal to an unsharded
  fixed-seed reference; stages interoperate through the same natural
  on-disk layout,
- the transfer-guard contract (PR 3) holds through the sharded step.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel import DataParallelTrainer
from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager
from deeplearning4j_tpu.parallel.mesh import DP, MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.zero import ZeroLayout

D = 6
SIZES = [32, 31, 17, 9, 23, 13, 32, 5, 29, 11]


def _loss(params, x, y, key=None):
    return ((x @ params["w"] + params["b"] - y) ** 2).mean()


def _params(d=D):
    rng = np.random.default_rng(42)
    return {"w": rng.normal(size=(d, 1)).astype(np.float32),
            "b": np.zeros((1,), np.float32)}


def _data(n=10, seed=0, d=D):
    rng = np.random.default_rng(seed)
    return [(rng.normal(size=(s, d)).astype(np.float32),
             rng.normal(size=(s, 1)).astype(np.float32))
            for s in SIZES[:n]]


def _adam():
    return T.adam(1e-2)


def _momentum():
    return T.chain(T.momentum(0.9), T.sgd_lr(5e-2))


def _run(stage, transform, steps=8, mesh=None, d=D):
    tr = DataParallelTrainer(_loss, transform, mesh=mesh, zero_stage=stage)
    state = tr.init_state(_params(d))
    losses = []
    for x, y in _data(steps, d=d):
        state, lazy = tr.step(state, x, y)
        losses.append(float(lazy))
    return np.array(losses), jax.device_get(tr.final_params(state)), tr, state


# --------------------------------------------------------------- parity
@pytest.mark.no_implicit_transfers
@pytest.mark.parametrize("stage", [1, 2, 3])
def test_zero_stage_matches_replicated_bitwise(stage):
    """Acceptance: sharded update == replicated update, bit for bit."""
    l0, p0, _, _ = _run(0, _momentum())
    ls, ps, _, _ = _run(stage, _momentum())
    np.testing.assert_array_equal(ls, l0)
    for k in p0:
        np.testing.assert_array_equal(ps[k], p0[k])


def test_zero2_adam_tuple_state_bitwise():
    """Tuple-valued optimizer state (adam's (mu, nu)) shards per leaf."""
    l0, p0, _, _ = _run(0, _adam())
    l2, p2, _, _ = _run(2, _adam())
    np.testing.assert_array_equal(l2, l0)
    np.testing.assert_array_equal(p2["w"], p0["w"])


@pytest.mark.no_implicit_transfers
def test_zero2_fit_matches_sync_fit():
    """The async fit loop (prefetch, buckets, lazy ring) rides the sharded
    step unchanged — and stays inside the hot-loop transfer guard."""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    data = [DataSet(x, y) for x, y in _data(6)]
    ta = DataParallelTrainer(_loss, _momentum(), zero_stage=2)
    _, la = ta.fit(ta.init_state(_params()), data,
                   async_dispatch=True, resolve_every=3)
    ts = DataParallelTrainer(_loss, _momentum(), zero_stage=0)
    _, lsync = ts.fit(ts.init_state(_params()), data,
                      async_dispatch=False)
    np.testing.assert_array_equal(np.array(la), np.array(lsync))


# --------------------------------------------------------------- layout
def test_zero2_state_leaves_are_dp_sharded_chunks():
    tr = DataParallelTrainer(_loss, _adam(), zero_stage=2)
    state = tr.init_state(_params())
    z = tr._zero
    n_dp = tr.n_dp
    for leaf in jax.tree.leaves(state.tstate):
        assert leaf.ndim == 1
        assert leaf.shape[0] % n_dp == 0
        assert leaf.sharding.spec == P(DP)
    # params stay replicated + natural below stage 3
    for leaf in jax.tree.leaves(state.params):
        assert leaf.sharding.spec == P()
    # padded sizes match the layout's arithmetic
    flat = jax.eval_shape(z.flatten_tree, z.natural_params)
    for nat, fl in zip(jax.tree.leaves(z.natural_params),
                       jax.tree.leaves(flat)):
        assert fl.shape == (z.padded_size(int(np.prod(nat.shape))),)


def test_zero3_params_sharded_between_steps_and_final_params_natural():
    _, p3, tr, state = _run(3, _momentum(), steps=4)
    for leaf in jax.tree.leaves(state.params):
        assert leaf.ndim == 1 and leaf.sharding.spec == P(DP)
    assert p3["w"].shape == (D, 1) and p3["b"].shape == (1,)
    l0, p0, _, _ = _run(0, _momentum(), steps=4)
    np.testing.assert_array_equal(p3["w"], p0["w"])


def test_zero_rejects_hogwild_and_bad_stage():
    with pytest.raises(ValueError, match="hogwild"):
        DataParallelTrainer(_loss, _momentum(), router="hogwild",
                            zero_stage=2)
    with pytest.raises(ValueError, match="zero_stage"):
        DataParallelTrainer(_loss, _momentum(), zero_stage=5)


def test_layout_padding_arithmetic():
    mesh = make_mesh(MeshSpec(dp=8))
    z = ZeroLayout(mesh, _momentum(), _params())
    assert z.padded_size(1) == 8          # never empty
    assert z.padded_size(8) == 8          # already divisible
    assert z.padded_size(9) == 16         # round up
    assert z.chunk_size(9) == 2
    # flatten -> unflatten roundtrips the natural tree exactly
    p = _params()
    flat = z.flatten_tree(p)
    back = z.unflatten_like(flat, z.natural_params)
    for k in p:
        np.testing.assert_array_equal(np.asarray(back[k]), p[k])


# --------------------------------------------------------------- memory
def test_zero2_opt_state_bytes_shrink_per_device():
    """Acceptance: opt-state bytes/device ~ replicated/ndp (+ padding)."""
    d = 64  # big enough that per-leaf padding is small vs the total

    def opt_bytes():
        g = METRICS.snapshot()["gauges"]
        vals = [v for k, v in g.items()
                if k.startswith("train.opt_state_bytes.device.")]
        assert vals, "state gauges missing"
        return vals

    tr0 = DataParallelTrainer(_loss, _adam(), zero_stage=0)
    tr0.init_state(_params(d))
    rep = max(opt_bytes())
    METRICS.reset()
    tr2 = DataParallelTrainer(_loss, _adam(), zero_stage=2)
    tr2.init_state(_params(d))
    shard = max(opt_bytes())
    n_dp, itemsize = tr2.n_dp, 4
    n_leaves = len(jax.tree.leaves(tr2._zero.natural_tstate))
    pad_slack = n_leaves * itemsize * n_dp  # <= one dp-row of pad per leaf
    assert shard <= rep / n_dp + pad_slack
    assert shard >= rep / n_dp  # padding only ever adds
    # params are replicated below stage 3: full bytes on every device
    g = METRICS.snapshot()["gauges"]
    pb = [v for k, v in g.items()
          if k.startswith("train.params_bytes.device.")]
    assert max(pb) == (d + 1) * itemsize


# --------------------------------------------------------------- checkpoints
def _reference_losses(steps=6, split=3):
    """Unsharded fixed-seed reference: dp=1, stage 0, straight through."""
    mesh = make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    tr = DataParallelTrainer(_loss, _adam(), mesh=mesh, zero_stage=0)
    s = tr.init_state(_params())
    out = []
    for x, y in _data(steps):
        s, lz = tr.step(s, x, y)
        out.append(float(lz))
    return np.array(out[split:])


def _ckpt_roundtrip(tmp_path, save_dp, load_dp, save_stage=2, load_stage=2,
                    steps=6, split=3):
    data = _data(steps)
    mgr = CheckpointManager(tmp_path / f"dp{save_dp}to{load_dp}", keep=2)
    mesh_a = make_mesh(MeshSpec(dp=save_dp), devices=jax.devices()[:save_dp])
    tra = DataParallelTrainer(_loss, _adam(), mesh=mesh_a,
                              zero_stage=save_stage)
    sa = tra.init_state(_params())
    for x, y in data[:split]:
        sa, _ = tra.step(sa, x, y)
    tra.checkpoint(sa, mgr)

    mesh_b = make_mesh(MeshSpec(dp=load_dp), devices=jax.devices()[:load_dp])
    trb = DataParallelTrainer(_loss, _adam(), mesh=mesh_b,
                              zero_stage=load_stage)
    sb = trb.init_state(_params())
    sb = trb.restore(sb, mgr)
    assert sb.step == split
    losses = []
    for x, y in data[split:]:
        sb, lz = trb.step(sb, x, y)
        losses.append(float(lz))
    return np.array(losses), mgr


@pytest.mark.parametrize("save_dp,load_dp", [(2, 1), (1, 2)])
def test_zero2_checkpoint_resharding_across_dp_widths(tmp_path,
                                                      save_dp, load_dp):
    """Acceptance: a zero-2 checkpoint written at one dp width restores
    onto another and continues BITWISE-equal to an unsharded reference."""
    got, mgr = _ckpt_roundtrip(tmp_path, save_dp, load_dp)
    np.testing.assert_array_equal(got, _reference_losses())
    # the manifest records provenance for tooling/debugging
    r = mgr.restore(jax.eval_shape(lambda t: t, _params()))
    assert r["extra"] == {"zero_stage": 2, "saved_dp": save_dp}


@pytest.mark.parametrize("save_stage,load_stage", [(0, 2), (2, 0), (3, 0)])
def test_zero_checkpoints_interoperate_across_stages(tmp_path, save_stage,
                                                     load_stage):
    """Natural on-disk layout: stage-0 checkpoints load under zero and
    vice versa — sharding is a runtime property, not a disk format."""
    got, _ = _ckpt_roundtrip(tmp_path, 2, 2, save_stage=save_stage,
                             load_stage=load_stage)
    np.testing.assert_array_equal(got, _reference_losses())


def test_zero2_fit_resume_matches_stage0_resume(tmp_path):
    """Supervisor-style resume parity: interrupt a fit at step 4, restart
    with resume=True — the zero-2 continuation is bitwise-equal to a
    stage-0 run interrupted and resumed the same way.  (Both are compared
    post-resume: a fresh trainer re-anchors its bucket ladder on the first
    batch it sees, so interrupted-vs-straight-through runs can differ by
    reduction order within a padded bucket — a ladder property, not a
    zero property.)"""
    from deeplearning4j_tpu.datasets.dataset import DataSet

    data = [DataSet(x, y) for x, y in _data(8)]

    def interrupted(stage, root):
        mgr = CheckpointManager(root, keep=2)
        tr1 = DataParallelTrainer(_loss, _momentum(), zero_stage=stage)
        stopped = tr1.fit(tr1.init_state(_params()), data,
                          checkpoint_manager=mgr, resume=True,
                          async_dispatch=False,
                          should_stop=lambda step: step >= 4)
        assert stopped[0].step == 4
        tr2 = DataParallelTrainer(_loss, _momentum(), zero_stage=stage)
        s2, l2 = tr2.fit(tr2.init_state(_params()), data,
                         checkpoint_manager=mgr, resume=True,
                         async_dispatch=False)
        assert s2.step == len(data)
        return np.array(l2), jax.device_get(tr2.final_params(s2))

    l_zero, p_zero = interrupted(2, tmp_path / "zero2")
    l_rep, p_rep = interrupted(0, tmp_path / "stage0")
    np.testing.assert_array_equal(l_zero, l_rep)
    for k in p_rep:
        np.testing.assert_array_equal(p_zero[k], p_rep[k])
