"""ResNet — the second north-star model family (BASELINE.json: ResNet-50
ImageNet).

TPU-first: NHWC layout, bf16 MXU compute with f32 params, batch-norm with
batch statistics (training) folded next to convs for XLA fusion, and the
data-parallel path through ``parallel.trainer`` (batch sharded on dp,
XLA-inserted gradient all-reduce).  Functional init/apply like ``nn.layers``.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    stage_sizes: tuple[int, ...] = (3, 4, 6, 3)   # ResNet-50
    width: int = 64
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_fold: bool = False  # apply BN as a folded per-channel affine in the
    #                        compute dtype (stats still f32): elementwise
    #                        reads/writes drop to bf16 and the f32 cast fuses
    #                        into the reductions — candidate for the r4
    #                        ResNet MFU gap; default off until measured
    stem_space_to_depth: bool = True  # rewrite the 7x7/2 stem conv as an
    #                                   exactly-equivalent 4x4/1 conv on a
    #                                   2x2 space-to-depth input: C_in=3 is
    #                                   MXU-hostile (contraction 7*7*3=147,
    #                                   channels padded to the 128 lane);
    #                                   the s2d form contracts over 192 with
    #                                   12 input channels (standard TPU
    #                                   ResNet optimization)

    @classmethod
    def resnet18(cls, num_classes=1000, **kw):
        return cls(num_classes=num_classes, stage_sizes=(2, 2, 2, 2), **kw)

    @classmethod
    def resnet50(cls, num_classes=1000, **kw):
        return cls(num_classes=num_classes, stage_sizes=(3, 4, 6, 3), **kw)

    def flops_per_image(self, image_size: int = 224) -> float:
        """Analytic training FLOPs per image (2*MACs forward, ×3 for
        fwd+bwd), counting convs + the classifier matmul.  Used for MFU
        accounting in bench.py (same 2*MACs convention the transformer leg
        validates against XLA ``cost_analysis()`` there)."""
        def conv_flops(hw, k, cin, cout, stride):
            out_hw = hw // stride
            return 2.0 * out_hw * out_hw * k * k * cin * cout, out_hw

        total, hw = 0.0, image_size
        f, hw = conv_flops(hw, 7, 3, self.width, 2)          # stem
        total += f
        hw //= 2                                             # 3x3/2 max pool
        c_in = self.width
        for s, blocks in enumerate(self.stage_sizes):
            c_mid = self.width * (2 ** s)
            c_out = c_mid * 4
            for b in range(blocks):
                stride = 2 if (s > 0 and b == 0) else 1
                f1, _ = conv_flops(hw, 1, c_in, c_mid, 1)
                f2, hw2 = conv_flops(hw, 3, c_mid, c_mid, stride)
                f3, _ = conv_flops(hw2, 1, c_mid, c_out, 1)
                total += f1 + f2 + f3
                if c_in != c_out or stride != 1:
                    fp, _ = conv_flops(hw, 1, c_in, c_out, stride)
                    total += fp
                hw = hw2
                c_in = c_out
        total += 2.0 * c_in * self.num_classes               # head matmul
        return 3.0 * total                                   # fwd + bwd


def _conv_init(key, shape, pd):
    fan_in = shape[0] * shape[1] * shape[2]
    return (jax.random.normal(key, shape) * np.sqrt(2.0 / fan_in)).astype(pd)


def _bn_params(c, pd):
    return {"scale": jnp.ones((c,), pd), "bias": jnp.zeros((c,), pd)}


def init_params(key, cfg: ResNetConfig) -> dict:
    pd = cfg.param_dtype
    keys = iter(jax.random.split(key, 2048))
    params: dict = {
        "stem": {"conv": _conv_init(next(keys), (7, 7, 3, cfg.width), pd),
                 "bn": _bn_params(cfg.width, pd)},
        "stages": [],
    }
    c_in = cfg.width
    for s, blocks in enumerate(cfg.stage_sizes):
        c_mid = cfg.width * (2 ** s)
        c_out = c_mid * 4
        stage = []
        for b in range(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = {
                "conv1": _conv_init(next(keys), (1, 1, c_in, c_mid), pd),
                "bn1": _bn_params(c_mid, pd),
                "conv2": _conv_init(next(keys), (3, 3, c_mid, c_mid), pd),
                "bn2": _bn_params(c_mid, pd),
                "conv3": _conv_init(next(keys), (1, 1, c_mid, c_out), pd),
                "bn3": _bn_params(c_out, pd),
            }
            if c_in != c_out or stride != 1:
                blk["proj"] = _conv_init(next(keys), (1, 1, c_in, c_out), pd)
                blk["proj_bn"] = _bn_params(c_out, pd)
            stage.append(blk)
            c_in = c_out
        params["stages"].append(stage)
    params["head"] = {
        "w": (jax.random.normal(next(keys), (c_in, cfg.num_classes)) *
              np.sqrt(1.0 / c_in)).astype(pd),
        "b": jnp.zeros((cfg.num_classes,), pd),
    }
    return params


def _conv(x, w, stride=1, dtype=jnp.bfloat16):
    return lax.conv_general_dilated(
        x.astype(dtype), w.astype(dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _bn(x, p, rs=None, train=True, momentum=0.9, eps=1e-5, fold=False):
    """BatchNorm, statistics in f32.  ``rs`` = running stats
    ``{"mean", "var"}``: train mode normalizes with batch statistics (and,
    when ``rs`` is given, returns EMA-updated running stats under
    stop_gradient); eval mode normalizes with ``rs`` so inference is
    batch-independent.  ``fold=False`` does the elementwise normalize in
    f32 (the r4 path, byte-identical); ``fold=True`` folds (mean, var,
    scale, bias) into one per-channel affine applied in the input dtype —
    same math, bf16 elementwise traffic.  Returns ``(y, new_rs)`` —
    ``new_rs`` is None when stats aren't threaded."""
    x32 = x.astype(jnp.float32)
    if train:
        mean = x32.mean(axis=(0, 1, 2))
        var = x32.var(axis=(0, 1, 2))
        new_rs = None
        if rs is not None:
            sg = lax.stop_gradient
            new_rs = {"mean": momentum * rs["mean"] + (1 - momentum) * sg(mean),
                      "var": momentum * rs["var"] + (1 - momentum) * sg(var)}
    else:
        if rs is None:
            raise ValueError("eval-mode BN needs running stats "
                             "(init_batch_stats + a training pass)")
        mean, var, new_rs = rs["mean"], rs["var"], rs
    inv = lax.rsqrt(var + eps)
    if fold:
        sc = (p["scale"] * inv).astype(x.dtype)
        bi = (p["bias"] - p["scale"] * mean * inv).astype(x.dtype)
        return x * sc + bi, new_rs
    y = (x32 - mean) * inv
    return (y * p["scale"] + p["bias"]).astype(x.dtype), new_rs


def init_batch_stats(cfg: ResNetConfig) -> dict:
    """Running-stats pytree mirroring the BN nodes of ``init_params``
    (flax-style separate collection: params stay a pure gradient target;
    stats thread through train steps as data)."""
    def node(c):
        return {"mean": jnp.zeros((c,), jnp.float32),
                "var": jnp.ones((c,), jnp.float32)}

    stats: dict = {"stem": {"bn": node(cfg.width)}, "stages": []}
    c_in = cfg.width
    for s, blocks in enumerate(cfg.stage_sizes):
        c_mid = cfg.width * (2 ** s)
        c_out = c_mid * 4
        stage = []
        for b in range(blocks):
            stride = 2 if (s > 0 and b == 0) else 1
            blk = {"bn1": node(c_mid), "bn2": node(c_mid), "bn3": node(c_out)}
            if c_in != c_out or stride != 1:
                blk["proj_bn"] = node(c_out)
            stage.append(blk)
            c_in = c_out
        stats["stages"].append(stage)
    return stats


def _bottleneck(x, blk, stride, dtype, rs=None, train=True, momentum=0.9,
                fold=False):
    g = lambda name: None if rs is None else rs[name]
    new_rs = {} if rs is not None else None

    def bn(name, h):
        y, n = _bn(h, blk[name], g(name), train, momentum, fold=fold)
        if new_rs is not None:
            new_rs[name] = n
        return y

    h = jax.nn.relu(bn("bn1", _conv(x, blk["conv1"], 1, dtype)))
    h = jax.nn.relu(bn("bn2", _conv(h, blk["conv2"], stride, dtype)))
    h = bn("bn3", _conv(h, blk["conv3"], 1, dtype))
    if "proj" in blk:
        x = bn("proj_bn", _conv(x, blk["proj"], stride, dtype))
    return jax.nn.relu(x + h), new_rs


def _space_to_depth(x):
    """(N, H, W, C) -> (N, H/2, W/2, 4C), channel-minor order (a, b, c)."""
    N, H, W, C = x.shape
    x = x.reshape(N, H // 2, 2, W // 2, 2, C)
    return x.transpose(0, 1, 3, 2, 4, 5).reshape(N, H // 2, W // 2, 4 * C)


def _stem_s2d_kernel(w):
    """Rearrange the (7, 7, C, O) stride-2 stem kernel into the (4, 4, 4C, O)
    stride-1 kernel that computes the identical map on a space-to-depth
    input: pad to 8x8 (the extra taps are zero), then space-to-depth the
    kernel itself with the same (a, b, c) channel order as the input."""
    _, _, C, O = w.shape
    wp = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
    wp = wp.reshape(4, 2, 4, 2, C, O)
    return wp.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * C, O)


def forward(params, images, cfg: ResNetConfig, batch_stats=None,
            train: bool = True, momentum: float = 0.9):
    """images: (N, H, W, 3) -> logits (N, num_classes).

    Without ``batch_stats`` (the default, the benched training path) BN
    uses batch statistics and only logits return.  With ``batch_stats``
    (from :func:`init_batch_stats`) the call returns ``(logits,
    new_stats)``: train mode still normalizes by batch but EMA-updates the
    running stats; ``train=False`` normalizes by the running stats, making
    eval-mode inference batch-independent (the reference has no BN to
    match — VERDICT r4 'missing' #4, implied by the ResNet north star)."""
    dt = cfg.dtype
    N, H, W, _ = images.shape
    if cfg.stem_space_to_depth and H % 2 == 0 and W % 2 == 0:
        # SAME on the s2d conv reproduces SAME on the original exactly:
        # k=7 s=2 pads (2, 3) on 2H -> k=4 s=1 pads (1, 2) on H
        w = _stem_s2d_kernel(params["stem"]["conv"]).astype(dt)
        x = lax.conv_general_dilated(
            _space_to_depth(images).astype(dt), w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    else:
        x = _conv(images, params["stem"]["conv"], 2, dt)
    rs = batch_stats
    new_stats = None if rs is None else {"stem": {}, "stages": []}
    x, n = _bn(x, params["stem"]["bn"],
               None if rs is None else rs["stem"]["bn"], train, momentum,
               fold=cfg.bn_fold)
    if rs is not None:
        new_stats["stem"]["bn"] = n
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1), "SAME")
    for s, stage in enumerate(params["stages"]):
        if rs is not None:
            new_stats["stages"].append([])
        for b, blk in enumerate(stage):
            stride = 2 if (s > 0 and b == 0) else 1
            x, n = _bottleneck(
                x, blk, stride, dt,
                None if rs is None else rs["stages"][s][b], train, momentum,
                fold=cfg.bn_fold)
            if rs is not None:
                new_stats["stages"][s].append(n)
    x = x.mean(axis=(1, 2)).astype(jnp.float32)       # global average pool
    logits = x @ params["head"]["w"].astype(jnp.float32) + params["head"]["b"]
    return logits if rs is None else (logits, new_stats)


def cross_entropy(params, images, labels, cfg: ResNetConfig) -> jnp.ndarray:
    logits = forward(params, images, cfg)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def cross_entropy_with_stats(params, batch_stats, images, labels,
                             cfg: ResNetConfig, momentum: float = 0.9):
    """(loss, new_batch_stats) for train loops that maintain running BN
    statistics — use with ``jax.value_and_grad(..., has_aux=True)``."""
    logits, new_stats = forward(params, images, cfg, batch_stats,
                                train=True, momentum=momentum)
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1)), new_stats


class ResNet:
    def __init__(self, cfg: ResNetConfig):
        self.cfg = cfg
        self.params = None
        self.batch_stats = None
        self._fwd = None
        self._fwd_eval = None

    def init(self, key=None):
        self.params = init_params(key if key is not None else jax.random.key(0),
                                  self.cfg)
        self.batch_stats = init_batch_stats(self.cfg)
        return self.params

    def predict_logits(self, images, use_running_stats: bool = False):
        """``use_running_stats=True`` gives batch-independent eval-mode
        inference (meaningful once training has populated
        ``self.batch_stats`` via ``train_step``)."""
        if use_running_stats:
            if self._fwd_eval is None:
                self._fwd_eval = jax.jit(partial(
                    forward, cfg=self.cfg, train=False))
            logits, _ = self._fwd_eval(self.params, jnp.asarray(images),
                                       batch_stats=self.batch_stats)
            return logits
        if self._fwd is None:
            self._fwd = jax.jit(partial(forward, cfg=self.cfg))
        return self._fwd(self.params, jnp.asarray(images))

    def loss_fn(self):
        """(params, x, y, key) -> scalar, pluggable into parallel.trainer.
        Note: this path trains with batch statistics only; loops that need
        eval-mode inference maintain running stats via
        ``cross_entropy_with_stats`` (see ``train_step``)."""
        cfg = self.cfg
        return lambda p, x, y, k=None: cross_entropy(p, x, y, cfg)

    def train_step(self, tx):
        """Jitted ``(params, stats, opt, x, y) -> (params, stats, opt,
        loss)`` that maintains running BN statistics alongside training."""
        from ..optimize.transforms import apply_updates
        cfg = self.cfg

        def step(params, stats, opt, x, y):
            count, st = opt
            (loss, new_stats), g = jax.value_and_grad(
                cross_entropy_with_stats, has_aux=True)(
                    params, stats, x, y, cfg)
            updates, st = tx.update(g, st, params, count)
            return (apply_updates(params, updates), new_stats,
                    (count + 1, st), loss)

        return jax.jit(step, donate_argnums=(0, 1, 2))
