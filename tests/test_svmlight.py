"""SVMLight record IO (reference: hadoop-yarn cdh4 runtime/io —
``SVMLightRecordFactory.java``, ``SVMLightDataFetcher.java``,
``TextRecordParser.java``; tests mirror ``TestSVMLightDataFetcher`` /
``TestSVMLightRecordFactory``)."""

import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet
from deeplearning4j_tpu.datasets.svmlight import (
    SVMLightDataFetcher,
    SVMLightDataSetIterator,
    SVMLightVectorNoLabelError,
    load_svmlight,
    parse_svmlight_line,
    save_svmlight,
)


def test_parse_line_matches_reference_semantics():
    # reference example line: "-1 1:0.43 3:0.12 9284:0.2 # abcdef"
    vec, label = parse_svmlight_line("1 1:0.43 3:0.12 5:0.2 # abcdef", 6)
    assert label == 1.0
    np.testing.assert_allclose(vec, [0.43, 0.0, 0.12, 0.0, 0.2, 0.0])

    # 1-based indexing: index 0 raises (SVMLightRecordFactory.java:96-99)
    with pytest.raises(ValueError, match="0-based"):
        parse_svmlight_line("1 0:0.5", 6)

    # out-of-range feature -> skipped with a warning, not an error
    with pytest.warns(UserWarning, match="beyond"):
        vec, _ = parse_svmlight_line("0 2:1.0 99:3.0", 4)
    np.testing.assert_allclose(vec, [0.0, 1.0, 0.0, 0.0])

    with pytest.raises(SVMLightVectorNoLabelError):
        parse_svmlight_line("   # only a comment", 4)


def test_round_trip(tmp_path):
    rng = np.random.default_rng(0)
    feats = np.where(rng.random((20, 8)) < 0.4,
                     rng.random((20, 8)).astype(np.float32), 0.0)
    idx = rng.integers(0, 3, 20)
    onehot = np.eye(3, dtype=np.float32)[idx]
    p = tmp_path / "t.svmlight"
    save_svmlight(p, feats, onehot)
    f2, l2 = load_svmlight(p, 8, 3)
    np.testing.assert_allclose(f2, feats, atol=1e-6)
    np.testing.assert_array_equal(l2, onehot)


def test_byte_range_splits_partition_records(tmp_path):
    """Disjoint byte ranges over one file must partition its lines exactly
    (the TextRecordParser/HDFSLineParser split contract)."""
    feats = np.arange(30, dtype=np.float32).reshape(10, 3)
    labels = np.arange(10) % 2
    p = tmp_path / "s.svmlight"
    save_svmlight(p, feats, labels)
    size = p.stat().st_size
    cuts = [0, size // 3, (2 * size) // 3, size]
    rows = []
    for s, e in zip(cuts, cuts[1:]):
        f, _ = load_svmlight(p, 3, 2, start=s, end=e)
        rows.extend(f.tolist())
    np.testing.assert_allclose(np.asarray(rows), feats)


def test_fetcher_and_iterator(tmp_path):
    feats = np.eye(6, dtype=np.float32)
    labels = np.arange(6) % 3
    p = tmp_path / "f.svmlight"
    save_svmlight(p, feats, labels)

    fetcher = SVMLightDataFetcher(p, 6, 3)
    fetcher.fetch(4)
    ds = fetcher.next()
    assert isinstance(ds, DataSet)
    assert ds.num_examples() == 4
    assert fetcher.has_more()
    fetcher.fetch(4)                       # clamps to the 2 remaining
    assert fetcher.next().num_examples() == 2
    assert not fetcher.has_more()
    fetcher.reset()
    assert fetcher.has_more()

    it = SVMLightDataSetIterator(p, batch=4, num_features=6, num_classes=3)
    batches = [it.next() for _ in range(2) if it.has_next()]
    assert [b.num_examples() for b in batches] == [4, 2]


def test_train_zoo_mlp_from_svmlight_file(tmp_path):
    """fetch -> train closes the reference loop (SVMLightDataFetcher feeding
    a network): an MLP learns a linearly-separable svmlight corpus."""
    from deeplearning4j_tpu.models.zoo import mlp

    rng = np.random.default_rng(1)
    n, d = 120, 6
    idx = rng.integers(0, 2, n)
    feats = (rng.standard_normal((n, d)).astype(np.float32)
             + 2.5 * idx[:, None] * np.eye(d, dtype=np.float32)[0])
    feats = np.where(np.abs(feats) < 0.1, 0.0, feats)   # some true zeros
    p = tmp_path / "train.svmlight"
    save_svmlight(p, feats, idx)

    it = SVMLightDataSetIterator(p, batch=40, num_features=d, num_classes=2)
    net = mlp(d, 2, hidden=(16,), num_iterations=60)
    while it.has_next():
        net.fit(it.next())
    f2, l2 = load_svmlight(p, d, 2)
    acc = (net.predict(f2) == l2.argmax(-1)).mean()
    assert acc > 0.85, f"svmlight-trained MLP accuracy {acc}"


def test_native_parser_matches_python_parser(tmp_path, monkeypatch):
    """The C fast path (host_runtime.cpp drt_parse_svmlight) and the Python
    parser produce identical arrays; malformed input falls back to Python's
    exact errors."""
    from deeplearning4j_tpu.native import runtime as native_rt

    if native_rt.lib() is None:
        pytest.skip("native lib unavailable")

    rng = np.random.default_rng(5)
    feats = np.where(rng.random((50, 9)) < 0.35,
                     rng.random((50, 9)).astype(np.float32), 0.0)
    labels = rng.integers(0, 4, 50)
    p = tmp_path / "n.svmlight"
    save_svmlight(p, feats, labels)
    with open(p, "a") as f:
        f.write("# trailing comment line\n\n2 3:0.5 # inline\n")

    f_native, l_native = load_svmlight(p, 9, 4)

    monkeypatch.setattr(native_rt, "parse_svmlight", lambda *a: None)
    f_py, l_py = load_svmlight(p, 9, 4)
    np.testing.assert_array_equal(f_native, f_py)
    np.testing.assert_array_equal(l_native, l_py)
    assert f_native.shape == (51, 9)

    # 0-based indexing must still raise (via the Python fallback inside the
    # native attempt: the C parser returns -1 and Python reports)
    monkeypatch.undo()
    bad = tmp_path / "bad.svmlight"
    bad.write_text("1 0:0.5\n")
    with pytest.raises(ValueError, match="0-based"):
        load_svmlight(bad, 4, 2)

    # out-of-range features warn on the native path too
    warn = tmp_path / "warn.svmlight"
    warn.write_text("1 2:1.0 99:3.0\n")
    with pytest.warns(UserWarning, match="beyond"):
        f, _ = load_svmlight(warn, 4, 2)
    np.testing.assert_allclose(f, [[0.0, 1.0, 0.0, 0.0]])

    # an empty value ("2:" at end of line / before whitespace) must raise
    # like Python's float(""), not let strtof read across the boundary
    for text in ("1 2:\n3 1:1\n", "1 2: 0.5\n"):
        mal = tmp_path / "mal.svmlight"
        mal.write_text(text)
        with pytest.raises(ValueError):
            load_svmlight(mal, 4, 2)

    # non-finite labels must hit the informative label error
    inf = tmp_path / "inf.svmlight"
    inf.write_text("inf 1:0.5\n")
    with pytest.raises(ValueError, match="non-negative integer"):
        load_svmlight(inf, 4, 2)
