"""Sentence / document iteration SPI.

Capability match of ``text/sentenceiterator`` + ``text/documentiterator`` in
the reference: ``SentenceIterator`` (next/hasNext/reset + preprocessor),
collection/file/line-based implementations, and the label-aware variants
used by ParagraphVectors.
"""

from __future__ import annotations

from pathlib import Path
from typing import Callable, Iterable, Iterator, Protocol

SentencePreProcessor = Callable[[str], str]


class SentenceIterator(Protocol):
    def next_sentence(self) -> str: ...
    def has_next(self) -> bool: ...
    def reset(self) -> None: ...


class _Base:
    pre_processor: SentencePreProcessor | None = None

    def _prep(self, s: str) -> str:
        return self.pre_processor(s) if self.pre_processor else s

    def __iter__(self) -> Iterator[str]:
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(_Base):
    """``CollectionSentenceIterator`` — iterate an in-memory collection."""

    def __init__(self, sentences: Iterable[str]):
        self.sentences = list(sentences)
        self._i = 0

    def next_sentence(self) -> str:
        s = self.sentences[self._i]
        self._i += 1
        return self._prep(s)

    def has_next(self) -> bool:
        return self._i < len(self.sentences)

    def reset(self) -> None:
        self._i = 0


class LineSentenceIterator(CollectionSentenceIterator):
    """``LineSentenceIterator`` — one sentence per line of a file."""

    def __init__(self, path: str | Path):
        lines = [l for l in Path(path).read_text().splitlines() if l.strip()]
        super().__init__(lines)


class FileSentenceIterator(CollectionSentenceIterator):
    """``FileSentenceIterator`` — every file under a directory, one sentence
    per line."""

    def __init__(self, root: str | Path):
        root = Path(root)
        files = sorted(p for p in root.rglob("*") if p.is_file()) if root.is_dir() else [root]
        lines: list[str] = []
        for f in files:
            lines.extend(l for l in f.read_text(errors="ignore").splitlines() if l.strip())
        super().__init__(lines)


class LabelAwareListSentenceIterator(_Base):
    """``text/sentenceiterator/labelaware`` — sentences with labels (for
    ParagraphVectors / supervised windowing)."""

    def __init__(self, sentences: Iterable[str], labels: Iterable[str]):
        self.sentences = list(sentences)
        self.labels = list(labels)
        assert len(self.sentences) == len(self.labels)
        self._i = 0

    def next_sentence(self) -> str:
        s = self.sentences[self._i]
        self._i += 1
        return self._prep(s)

    def current_label(self) -> str:
        return self.labels[self._i - 1 if self._i > 0 else 0]

    def has_next(self) -> bool:
        return self._i < len(self.sentences)

    def reset(self) -> None:
        self._i = 0
