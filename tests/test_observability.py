"""Observability layer: spans/tracing, histogram metrics, Prometheus
exposition, status server, and end-to-end instrumentation of the training
stack (ISSUE 1 acceptance: Perfetto-valid Chrome trace + parseable
/metrics.prom + train_step percentiles from a tiny fit, and a disabled
mode that records nothing)."""

import json
import re
import threading
import urllib.request

import numpy as np
import pytest

import jax.numpy as jnp

from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.observability import (
    METRICS,
    Histogram,
    MetricsRegistry,
    StatusServer,
    StepTimer,
    Tracer,
    trace,
)
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.trainer import DataParallelTrainer


# --------------------------------------------------------------------------- spans

def test_span_nesting_and_attrs():
    tracer = Tracer()
    with tracer.span("outer", phase="fit") as s:
        s.set(batch=3)
        with tracer.span("inner", idx=1):
            pass
    events = tracer.to_chrome_trace()["traceEvents"]
    assert [e["name"] for e in events] == ["inner", "outer"]  # close order
    inner, outer = events
    assert inner["args"]["parent"] == "outer"
    assert inner["args"]["depth"] == 1
    assert outer["args"]["parent"] is None
    assert outer["args"]["phase"] == "fit" and outer["args"]["batch"] == 3
    # inner is contained within outer on the timeline
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3


def test_span_nesting_propagates_to_threads():
    tracer = Tracer()
    done = threading.Event()

    def worker():
        # fresh thread -> fresh context: no parent inherited
        with tracer.span("thread_span"):
            pass
        done.set()

    with tracer.span("main_span"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert done.wait(1)
    by_name = {e["name"]: e for e in tracer.to_chrome_trace()["traceEvents"]}
    assert by_name["thread_span"]["args"]["parent"] is None
    assert by_name["thread_span"]["tid"] != by_name["main_span"]["tid"]


def test_span_records_error_attr():
    tracer = Tracer()
    with pytest.raises(ValueError):
        with tracer.span("boom"):
            raise ValueError("x")
    (ev,) = tracer.to_chrome_trace()["traceEvents"]
    assert ev["args"]["error"] == "ValueError"


def test_chrome_trace_roundtrip(tmp_path):
    tracer = Tracer()
    with tracer.span("a", k="v"):
        with tracer.span("b"):
            pass
    path = tracer.save_chrome_trace(tmp_path / "trace.json")
    doc = json.loads(path.read_text())
    assert isinstance(doc["traceEvents"], list) and len(doc["traceEvents"]) == 2
    for ev in doc["traceEvents"]:
        # the Chrome trace-event schema fields Perfetto requires
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0
        assert isinstance(ev["pid"], int)
        assert isinstance(ev["tid"], int)
        assert isinstance(ev["name"], str)


def test_jsonl_export_and_stream(tmp_path):
    tracer = Tracer()
    tracer.stream_jsonl(tmp_path / "stream.jsonl")
    with tracer.span("s1"):
        pass
    with tracer.span("s2"):
        pass
    tracer.stop_stream()
    streamed = [json.loads(l) for l in
                (tmp_path / "stream.jsonl").read_text().splitlines()]
    assert [e["name"] for e in streamed] == ["s1", "s2"]
    tracer.export_jsonl(tmp_path / "dump.jsonl")
    dumped = [json.loads(l) for l in
              (tmp_path / "dump.jsonl").read_text().splitlines()]
    assert dumped == streamed


def test_tracer_buffer_is_bounded():
    tracer = Tracer(max_events=16)
    for i in range(64):
        with tracer.span("s"):
            pass
    assert len(tracer.to_chrome_trace()["traceEvents"]) == 16


# --------------------------------------------------------------------------- metrics

def test_histogram_percentiles():
    h = Histogram()
    for v in [i / 1000 for i in range(1, 101)]:  # 1ms..100ms
        h.observe(v)
    s = h.summary()
    assert s["count"] == 100
    assert s["p50_s"] == pytest.approx(0.050, abs=0.002)
    assert s["p95_s"] == pytest.approx(0.095, abs=0.002)
    assert s["p99_s"] == pytest.approx(0.099, abs=0.002)
    assert s["max_s"] == pytest.approx(0.100)
    assert s["mean_s"] == pytest.approx(sum(range(1, 101)) / 100 / 1000)


def test_observe_time_is_the_locked_path():
    reg = MetricsRegistry()
    reg.observe_time("op", 0.25)
    snap = reg.snapshot()
    assert snap["timers"]["op"]["count"] == 1
    assert snap["timers"]["op"]["total_s"] == pytest.approx(0.25)
    # seed regression: StepTimer must route through observe_time, never
    # append to registry.timers[...] bare lists
    timer = StepTimer(reg, "step")
    timer.iteration_done(object(), 1)
    timer.iteration_done(object(), 2)
    assert reg.snapshot()["timers"]["step"]["count"] == 1
    assert isinstance(reg.timers["step"], Histogram)


def test_registry_reset():
    reg = MetricsRegistry()
    reg.increment("c")
    reg.gauge("g", 1.0)
    reg.observe_time("t", 0.1)
    reg.reset()
    snap = reg.snapshot()
    assert snap == {"counters": {}, "gauges": {}, "timers": {}}


def test_concurrent_increments_from_threads():
    reg = MetricsRegistry()
    n_threads, n_iter = 8, 500

    def worker():
        for _ in range(n_iter):
            reg.increment("hits")
            reg.observe_time("lat", 0.001)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    snap = reg.snapshot()
    assert snap["counters"]["hits"] == n_threads * n_iter
    assert snap["timers"]["lat"]["count"] == n_threads * n_iter


PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"]*"(,[a-zA-Z_]'
    r'[a-zA-Z0-9_]*="[^"]*")*\})? (?:[0-9.eE+-]+|NaN|\+Inf)$')


def _check_prometheus(text: str) -> dict[str, str]:
    """Validate Prometheus text exposition; return {metric_name: type}."""
    types = {}
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                         r"(counter|gauge|histogram|summary|untyped)$", line)
            assert m, f"bad comment line: {line!r}"
            types[m.group(1)] = m.group(2)
        else:
            assert PROM_SAMPLE.match(line), f"bad sample line: {line!r}"
    return types


def test_prometheus_exposition_format():
    reg = MetricsRegistry()
    reg.increment("train.steps", 3)
    reg.gauge("loss", 0.5)
    reg.observe_time("step_time", 0.003)
    reg.observe_time("step_time", 0.3)
    text = reg.to_prometheus()
    types = _check_prometheus(text)
    assert types["train_steps_total"] == "counter"
    assert types["loss"] == "gauge"
    assert types["step_time_seconds"] == "histogram"
    # bucket counts are cumulative & monotone, +Inf == _count
    buckets = [int(m.group(1)) for m in
               re.finditer(r'step_time_seconds_bucket\{le="[^+]*"\} (\d+)', text)]
    assert buckets == sorted(buckets)
    inf = re.search(r'step_time_seconds_bucket\{le="\+Inf"\} (\d+)', text)
    count = re.search(r"^step_time_seconds_count (\d+)$", text, re.M)
    assert int(inf.group(1)) == int(count.group(1)) == 2


# --------------------------------------------------------------------------- server

class _VanishingTracker:
    """Tracker whose worker evaporates between workers() and the per-worker
    lookups — the eviction race the /status endpoint must survive."""

    def workers(self):
        return ["w0", "ghost"]

    def is_enabled(self, w):
        if w == "ghost":
            raise KeyError(w)
        return True

    def last_heartbeat(self, w):
        if w == "ghost":
            raise KeyError(w)
        return 0.0

    def current_jobs(self):
        return []

    def updates(self):
        return {}

    def is_done(self):
        return False


def test_status_server_partial_on_vanished_worker():
    srv = StatusServer(_VanishingTracker(), MetricsRegistry()).start()
    try:
        r = urllib.request.urlopen(f"http://127.0.0.1:{srv.port}/status")
        assert r.status == 200
        status = json.loads(r.read())
        assert status["workers"] == ["w0", "ghost"]
        assert status["enabled"] == {"w0": True}      # ghost skipped
        assert "w0" in status["heartbeats_age_s"]
        assert any("ghost" in e for e in status["errors"])
    finally:
        srv.stop()


def test_status_server_metrics_prom_endpoint():
    reg = MetricsRegistry()
    reg.increment("served", 2)
    reg.observe_time("lat", 0.01)
    srv = StatusServer(None, reg).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        r = urllib.request.urlopen(base + "/metrics.prom")
        assert r.status == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        types = _check_prometheus(r.read().decode())
        assert types["served_total"] == "counter"
        assert types["lat_seconds"] == "histogram"
        # JSON twin still serves
        snap = json.loads(urllib.request.urlopen(base + "/metrics").read())
        assert snap["counters"]["served"] == 2
    finally:
        srv.stop()


# --------------------------------------------------------------------------- e2e

def _loss_fn(params, x, y, key):
    return jnp.mean((x @ params["w"] - y) ** 2)


def _tiny_fit(n_batches=3, epochs=2):
    rng = np.random.default_rng(0)
    params = {"w": jnp.asarray(rng.standard_normal((4, 2), dtype=np.float32))}
    tr = DataParallelTrainer(_loss_fn, T.sgd_lr(1e-2))
    state = tr.init_state(params)
    batches = [DataSet(rng.standard_normal((16, 4), dtype=np.float32),
                       rng.standard_normal((16, 2), dtype=np.float32))
               for _ in range(n_batches)]
    return tr.fit(state, batches, epochs=epochs)


def test_end_to_end_training_instrumentation(tmp_path):
    state, losses = _tiny_fit()
    snap = METRICS.snapshot()
    n_steps = len(losses)
    assert snap["counters"]["train_step.iterations"] == n_steps
    assert snap["gauges"]["train_step.loss"] == pytest.approx(losses[-1])
    assert snap["gauges"]["train_step.samples_per_sec"] > 0
    # compile-vs-execute split: first call in .compile, rest in train_step
    assert snap["timers"]["train_step.compile"]["count"] == 1
    st = snap["timers"]["train_step"]
    assert st["count"] == n_steps - 1
    for q in ("p50_s", "p95_s", "p99_s"):
        assert st[q] > 0
    assert st["p50_s"] <= st["p95_s"] <= st["p99_s"] <= st["max_s"]
    # steady-state steps must not carry the compile cost
    assert st["max_s"] <= snap["timers"]["train_step.compile"]["max_s"]

    # the same run produced a Perfetto-loadable chrome trace
    doc = json.loads(obs.TRACER.save_chrome_trace(
        tmp_path / "trace.json").read_text())
    names = [e["name"] for e in doc["traceEvents"]]
    assert "trainer.fit" in names and "train_step.compile" in names
    assert names.count("train_step") == n_steps - 1
    for ev in doc["traceEvents"]:
        assert ev["ph"] == "X" and ev["ts"] >= 0 and ev["dur"] >= 0
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
    steps = [e for e in doc["traceEvents"] if e["name"] == "train_step"]
    assert all(e["args"]["parent"] == "trainer.fit" for e in steps)

    # and a parseable Prometheus exposition with the histogram in it
    types = _check_prometheus(METRICS.to_prometheus())
    assert types["train_step_seconds"] == "histogram"
    assert types["train_step_iterations_total"] == "counter"
    assert types["train_step_loss"] == "gauge"


def test_pad_batch_counter():
    rng = np.random.default_rng(1)
    params = {"w": jnp.asarray(rng.standard_normal((4, 2), dtype=np.float32))}
    tr = DataParallelTrainer(_loss_fn, T.sgd_lr(1e-2))
    state = tr.init_state(params)
    # 15 % 8 != 0 -> every step pads
    b = DataSet(rng.standard_normal((15, 4), dtype=np.float32),
                rng.standard_normal((15, 2), dtype=np.float32))
    tr.fit(state, [b], epochs=2)
    snap = METRICS.snapshot()
    assert snap["counters"]["train_step.pad_batch"] == 2
    assert snap["counters"]["train_step.padded_samples"] == 2 * (8 - 15 % 8)


def test_disabled_mode_records_nothing():
    obs.disable()
    try:
        state, losses = _tiny_fit(n_batches=2, epochs=1)
        assert len(losses) == 2          # training itself still works
        snap = METRICS.snapshot()
        assert snap["counters"] == {} and snap["timers"] == {}
        assert snap["gauges"] == {}
        assert obs.TRACER.to_chrome_trace()["traceEvents"] == []
        # and span() hands back the shared no-op (no per-step allocation)
        assert trace.span("x") is obs.NOOP_SPAN
        assert METRICS.time("x") is obs.NOOP_SPAN
    finally:
        obs.enable()


def test_checkpoint_instrumentation(tmp_path):
    from deeplearning4j_tpu.parallel.checkpoint import CheckpointManager

    mgr = CheckpointManager(tmp_path)
    params = {"w": np.ones((2, 2), np.float32)}
    mgr.save(3, params)
    mgr.restore(params)
    snap = METRICS.snapshot()
    assert snap["counters"]["checkpoint.saves"] == 1
    assert snap["counters"]["checkpoint.restores"] == 1
    assert snap["timers"]["checkpoint.save"]["count"] == 1
    assert snap["timers"]["checkpoint.restore"]["count"] == 1


def test_scaleout_job_lifecycle_metrics():
    from deeplearning4j_tpu.parallel.scaleout import (
        CollectionJobIterator, DistributedRunner)

    class Performer:
        def __init__(self, tracker):
            pass

        def perform(self, job):
            job.result = np.asarray([float(job.work)])

        def update(self, *a):
            pass

    runner = DistributedRunner(CollectionJobIterator([1, 2, 3, 4]),
                               Performer, n_workers=2)
    out = runner.run(max_wall_s=30.0)
    assert out is not None
    snap = METRICS.snapshot()
    assert snap["counters"]["scaleout.runs"] == 1
    assert snap["counters"]["scaleout.jobs_dispatched"] == 4
    assert snap["counters"]["scaleout.jobs_completed"] == 4
    assert snap["counters"]["scaleout.updates"] == 4
    assert snap["timers"]["scaleout.job"]["count"] == 4


def test_multilayer_fit_instrumentation():
    from deeplearning4j_tpu.nn.conf import (
        NeuralNetConfiguration, OptimizationAlgorithm, list_builder)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    base = NeuralNetConfiguration(
        n_in=4, n_out=3, lr=0.1, num_iterations=2,
        optimization_algo=OptimizationAlgorithm.ITERATION_GRADIENT_DESCENT,
        activation="tanh")
    conf = (list_builder(base, 2)
            .hidden_layer_sizes(8)
            .override(1, kind="output", activation="softmax", loss="mcxent")
            .pretrain(False)
            .build())
    net = MultiLayerNetwork(conf)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((12, 4)).astype(np.float32)
    labels = rng.integers(0, 3, 12)
    net.fit_arrays(x, labels)
    snap = METRICS.snapshot()
    assert snap["counters"]["multilayer.iterations"] >= 2
    assert snap["timers"]["multilayer.fit_iteration"]["count"] >= 2
    assert "multilayer.loss" in snap["gauges"]
    names = [e["name"] for e in obs.TRACER.to_chrome_trace()["traceEvents"]]
    assert "multilayer.fit" in names


def test_device_memory_sampler_is_safe_on_cpu():
    # CPU backend has no memory_stats — must be a clean no-op
    from deeplearning4j_tpu.observability import sample_device_memory
    assert sample_device_memory(METRICS) >= 0


def test_metrics_dump_rendering():
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "metrics_dump",
        Path(__file__).resolve().parent.parent / "tools" / "metrics_dump.py")
    md = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(md)

    reg = MetricsRegistry()
    reg.increment("steps", 5)
    reg.gauge("loss", 0.25)
    reg.observe_time("step", 0.01)
    srv = StatusServer(None, reg).start()
    try:
        rc = md.main(["--port", str(srv.port)])
        assert rc == 0
        rc = md.main(["--url", f"http://127.0.0.1:{srv.port}", "--prom"])
        assert rc == 0
    finally:
        srv.stop()
    out = md.render_metrics(reg.snapshot())
    assert "steps" in out and "p95" in out
    # no state gauges published -> no state-memory section
    assert md.render_state_memory(reg.snapshot()) is None
    reg.gauge("train.params_bytes.device.0", 2048.0)
    reg.gauge("train.opt_state_bytes.device.0", 256.0)
    section = md.render_state_memory(reg.snapshot())
    assert "state memory" in section and "2.00KiB" in section \
        and "256B" in section
    assert "state memory" in md.render_metrics(reg.snapshot())


def test_sample_state_bytes_gauges_sharded_trees():
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from deeplearning4j_tpu.observability import sample_state_bytes
    from deeplearning4j_tpu.parallel.mesh import DP, local_mesh

    mesh = local_mesh()
    n_dp = mesh.shape[DP]
    rep = jax.device_put(jnp.zeros((n_dp * 4,), jnp.float32),
                         NamedSharding(mesh, P()))
    shd = jax.device_put(jnp.zeros((n_dp * 4,), jnp.float32),
                         NamedSharding(mesh, P(DP)))
    assert sample_state_bytes({"w": rep}, {"m": shd}, METRICS) == n_dp
    g = METRICS.snapshot()["gauges"]
    # replicated: every device holds the whole leaf; sharded: 1/ndp each
    assert g["train.params_bytes.device.0"] == n_dp * 4 * 4
    assert g["train.opt_state_bytes.device.0"] == 4 * 4
    # non-array leaves pass through silently
    assert sample_state_bytes({"k": 3}, (), METRICS) == 0


def test_observe_shim_still_exports_legacy_names():
    from deeplearning4j_tpu.parallel import observe

    assert observe.METRICS is METRICS
    assert observe.MetricsRegistry is MetricsRegistry
    assert observe.StatusServer is StatusServer
    assert observe.StepTimer is StepTimer


# --------------------------------------------------------------------------- trace identity (PR 10)

def test_span_ids_mint_and_inherit():
    tracer = Tracer()
    with tracer.span("root") as r:
        with tracer.span("child") as c:
            assert c.trace_id == r.trace_id
            assert c.parent_id == r.span_id
            assert c.span_id != r.span_id
    assert re.fullmatch(r"[0-9a-f]{32}", r.trace_id)
    assert re.fullmatch(r"[0-9a-f]{16}", r.span_id)
    events = {e["name"]: e["args"] for e in tracer.to_chrome_trace()["traceEvents"]}
    assert events["child"]["trace_id"] == events["root"]["trace_id"]
    assert events["child"]["parent_span_id"] == events["root"]["span_id"]
    assert events["root"]["parent_span_id"] is None


def test_traceparent_roundtrip_and_rejection():
    tid, sid = trace.new_trace_id(), trace.new_span_id()
    header = f"00-{tid}-{sid}-01"
    assert trace.parse_traceparent(header) == (tid, sid)
    assert trace.parse_traceparent(header.upper()) == (tid, sid)
    for bad in (None, "", "garbage", "00-short-ids-01",
                f"00-{'0' * 32}-{sid}-01",        # all-zero trace id
                f"00-{tid}-{'0' * 16}-01",        # all-zero span id
                f"zz-{tid}-{sid}-01",             # non-hex version
                f"00-{tid}-{sid}"):               # missing flags
        assert trace.parse_traceparent(bad) is None, bad


def test_bind_adopts_remote_context():
    """A span opened inside ``bind`` joins the bound trace — the server
    side of traceparent propagation."""
    tid, parent = trace.new_trace_id(), trace.new_span_id()
    with trace.bind(tid, parent):
        assert trace.current_traceparent() == f"00-{tid}-{parent}-01"
        with trace.span("handler") as sp:
            assert sp.trace_id == tid
            assert sp.parent_id == parent
    assert trace.current_trace_context() is None


def test_current_traceparent_reflects_open_span():
    with trace.span("outer") as sp:
        tp = trace.current_traceparent()
        assert tp == f"00-{sp.trace_id}-{sp.span_id}-01"
    assert trace.current_traceparent() is None


def test_record_span_explicit_times():
    import time as _time

    tracer = Tracer()
    tid = trace.new_trace_id()
    t0 = _time.perf_counter()
    sid = tracer.record_span("explicit", t0, 0.25, trace_id=tid,
                             parent_id="a" * 16, request=7)
    (ev,) = tracer.to_chrome_trace()["traceEvents"]
    assert ev["args"]["trace_id"] == tid
    assert ev["args"]["span_id"] == sid
    assert ev["args"]["parent_span_id"] == "a" * 16
    assert ev["args"]["request"] == 7
    assert abs(ev["dur"] - 0.25e6) < 1.0      # 250ms in µs
    assert ev["ts"] >= 0


def test_dropped_events_counted_and_stamped():
    """Satellite 1: overrunning the bounded ring is observable — a
    counter increments and the export carries the drop count."""
    tracer = Tracer(max_events=16)
    before = METRICS.snapshot()["counters"].get("trace.dropped_events", 0)
    for _ in range(64):
        with tracer.span("s"):
            pass
    doc = tracer.to_chrome_trace()
    assert len(doc["traceEvents"]) == 16
    assert doc["metadata"]["dropped"] == 48
    after = METRICS.snapshot()["counters"].get("trace.dropped_events", 0)
    assert after - before == 48
    tracer.clear()
    assert tracer.to_chrome_trace()["metadata"]["dropped"] == 0


def test_chrome_trace_validity_and_nesting(tmp_path):
    """Satellite 3: exported traces parse, every ts/dur is non-negative,
    and expanding complete events to B/E pairs yields a properly nested
    per-thread stack (no partial overlap from the ``with`` API)."""
    tracer = Tracer()
    with tracer.span("a"):
        with tracer.span("b"):
            with tracer.span("c"):
                pass
        with tracer.span("d"):
            pass
    doc = json.loads(tracer.save_chrome_trace(tmp_path / "t.json").read_text())
    events = doc["traceEvents"]
    assert len(events) == 4
    be = []
    for ev in events:
        assert ev["ph"] == "X"
        assert ev["ts"] >= 0 and ev["dur"] >= 0
        be.append((ev["ts"], "B", ev["name"]))
        be.append((ev["ts"] + ev["dur"], "E", ev["name"]))
    # sort by time; at equal timestamps E comes before B (adjacent spans)
    be.sort(key=lambda t: (t[0], t[1] == "B"))
    stack = []
    for _, ph, name in be:
        if ph == "B":
            stack.append(name)
        else:
            assert stack and stack[-1] == name, \
                f"unbalanced B/E pairs: closing {name} with stack {stack}"
            stack.pop()
    assert stack == []


@pytest.mark.lockguard
def test_registry_and_tracer_survive_serving_style_contention():
    """Satellite 3: hammer observe_time/increment/to_prometheus (and the
    listener fan-out to the flight recorder) from concurrent threads the
    way the serving engine + HTTP scrape threads do, under instrumented
    locks — no deadlock, no lost-update assertion, no exception."""
    from deeplearning4j_tpu.observability import FLIGHTREC

    reg = METRICS           # the real singleton: listener fan-out included
    errors = []
    n_threads, n_iter = 6, 300

    def worker(i):
        try:
            for k in range(n_iter):
                reg.increment("hammer.count")
                reg.observe_time("hammer.lat", 0.001 * (k % 7 + 1))
                reg.gauge("hammer.gauge", float(k))
                if k % 50 == 0:
                    reg.to_prometheus()
                    reg.snapshot()
        except Exception as e:          # pragma: no cover - failure path
            errors.append(repr(e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30)
    assert not errors
    snap = METRICS.snapshot()
    assert snap["counters"]["hammer.count"] == n_threads * n_iter
    assert snap["timers"]["hammer.lat"]["count"] == n_threads * n_iter
    # the passive listener saw the traffic too (bounded ring, no growth)
    assert len(FLIGHTREC.metric_events) <= FLIGHTREC.metric_events.maxlen
