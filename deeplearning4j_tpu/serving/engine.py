"""Continuous-batching inference engine (DESIGN.md §13).

Two workloads over one discipline — keep the device batch full, keep the
host off the per-token path:

- :class:`InferenceEngine`: slot-based continuous batching for the
  flagship transformer.  The KV cache is a POOL of ``slots`` rows
  (``(S, max_len, H, Dh)`` per layer); every decode step advances ALL
  occupied slots one token through :func:`decode_step` with per-slot
  positions, new sequences are admitted into free rows between steps
  (prefill on a batch-of-1 cache, then one scatter into the pool), and a
  finished sequence (EOS / length budget) frees its row for the next
  arrival.  Sequences at different depths share every device batch —
  ragged traffic cannot drain the batch the way static batching does.

- :class:`BatchScorer`: batched forward/score for ``MultiLayerNetwork``
  and zoo models — concurrent callers coalesce into one padded
  (power-of-two bucket) device batch through any row-wise ``fn``.

Hot-path rules (PR-2/PR-3 heritage): the decode loop dispatches
``resolve_every`` steps back-to-back under ``hot_loop_guard()`` — zero
host syncs per token — and resolves the emitted-token stack at ONE
``allow_transfers()`` fence per segment, where EOS/length bookkeeping,
admissions, and metrics publication happen.  Every jitted entry donates
the engine state, so the cache pool is updated in place.

RNG parity contract: slot ``s`` runs the exact draw sequence of
``Transformer.sample(..., key=jax.random.key(seed), kv_cache=True)`` —
split once per generated token, sample from the second half — so a
served continuation is token-identical to the offline sampler under the
same seed (the tier-1 acceptance test).

PR-9 memory/latency tier, all OFF by default (DESIGN.md §17):

- ``paged=True``: the dense ``(S, max_len)`` KV rows become fixed-size
  pages in a shared device pool, addressed through per-slot block
  tables (host free-list + refcounts in :class:`~.paging.PagePool`).
  Decode gathers each row's logical K/V into exactly the dense shape
  before the dense attention ops run, so logits stay bitwise.
- ``prefix_cache=True``: a content-addressed cache (chained hash of
  full token pages → pinned pages) admits shared prompt prefixes by
  block-table aliasing — the system-prompt prefill runs once.
- ``speculative=True``: a small draft model proposes ``spec_k`` greedy
  tokens; ONE windowed verify dispatch on the target scores all of
  them, and every emitted token is drawn from TARGET logits with the
  request's exact offline key stream — the draft only decides how MANY
  tokens emit per dispatch, never which, so token parity is preserved
  under greedy and temperature sampling alike.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..analysis.runtime import allow_transfers, hot_loop_guard
from ..analysis.shardguard import SHARDGUARD
from ..models.transformer import (decode_step, decode_step_paged,
                                  decode_window, decode_window_paged,
                                  gather_paged_layer, init_decode_cache,
                                  init_paged_cache, paged_flat_index,
                                  reset_cache_pages, reset_cache_slots,
                                  scatter_paged_layer)
from ..observability import COSTS, FLIGHTREC, METRICS, TENANTS, trace
from ..observability.core import enabled as _obs_enabled
from ..parallel.checkpoint import CheckpointManager
from ..parallel.compile_cache import setup_compile_cache
from ..resilience.faults import FAULTS
from .batcher import (Completion, GenerateRequest, PagePoolExhausted,
                      PendingResult, RequestQueue, ScoreRequest)
from .paging import PagePool

#: unit-interval buckets for fill-ratio histograms (observe_time is the
#: registry's generic histogram feed; these are ratios, not seconds)
FILL_BUCKETS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Engine knobs (the model's own shape lives in TransformerConfig)."""

    slots: int = 4                  # concurrent sequences in the device batch
    resolve_every: int = 4          # decode steps dispatched per host fence
    max_queue: int = 64             # RequestQueue bound (429 beyond)
    max_batch_delay_ms: float = 2.0  # idle coalescing window
    min_prefill_bucket: int = 8     # floor of the prompt bucket ladder
    idle_wait_s: float = 0.05       # queue poll period while no slot is live
    default_eos_id: int | None = None
    int8_decode: bool = False       # serve int8 weight-quantized FFN/head
    #                                 (opt-in; adoption gated on token-level
    #                                 top-1 agreement with f32 decode)
    # ---- PR-9 paged/prefix/speculative tier (all default to the dense
    # ---- behavior above; every combination keeps exact token parity)
    paged: bool = False             # page-pool KV instead of dense slot rows
    page_size: int = 16             # tokens per KV page (any size >= 1 works)
    num_pages: int | None = None    # pool capacity; None -> slots*ceil(T/ps)
    prefix_cache: bool = False      # content-addressed prefix sharing (paged)
    speculative: bool = False       # draft-proposes / target-verifies decode
    spec_k: int = 3                 # draft tokens proposed per verify window
    paged_attention_impl: str = "gather"  # "gather" (jnp, bitwise) or a
    #                                 registry candidate name — only adopt a
    #                                 kernel through the bench autopick gate
    kv_quant: str | None = None     # KV-page storage precision (DESIGN.md
    #                                 §20): None = model dtype (bitwise),
    #                                 "int8" = per-page per-head absmax int8
    #                                 (~4x pool capacity, ≥0.999 token top-1
    #                                 agreement), "fp8" = float8 storage on
    #                                 jax builds that have it (gated off by
    #                                 default like every quant tier).
    #                                 Requires paged=True.
    # ---- disaggregated prefill/decode tier (DESIGN.md §27)
    role: str = "unified"           # "unified" (classic colocated engine),
    #                                 "prefill" (prompt prefill only: no
    #                                 serve thread, work arrives through
    #                                 prefill() and leaves as KV pages), or
    #                                 "decode" (a unified engine that also
    #                                 publishes the decode-tier queue gauge
    #                                 and is the admit_from_pages target).
    #                                 "prefill" requires paged=True — the
    #                                 migration unit is a KV page.


def kv_page_bytes(mcfg, page_size: int, kv_quant: str | None = None) -> int:
    """Device bytes one KV page costs under the given storage mode — the
    accounting behind ``serving.kv_bytes*`` and the capacity planning in
    ``tools/metrics_dump.py``.  Counts K+V data across all layers at the
    storage itemsize (1 for int8/fp8) plus, when quantized, the per-page
    per-kv-head f32 absmax scales stored beside the pool."""
    from ..ops.pallas import kv_quant as kvq
    kvh = mcfg.kv_heads
    item = kvq.kv_itemsize(kv_quant, mcfg.dtype)
    per_layer = page_size * kvh * mcfg.head_dim * 2 * item
    if kv_quant is not None:
        per_layer += 2 * kvh * 4   # k_scale + v_scale rows, f32
    return per_layer * mcfg.n_layers


class MigrationRejected(RuntimeError):
    """A migrated request could not be admitted into the decode batch
    (weight generation moved between claim and admission, engine
    stopping).  Nothing was corrupted — the decode-side refcounts were
    released and the request should simply be requeued and re-migrated
    (the :class:`~.disagg.DisaggScheduler` does exactly that)."""


@dataclasses.dataclass
class PrefillRecord:
    """The atomic migration handoff unit :meth:`InferenceEngine.prefill`
    returns: the request's filled KV pages (block-table order, ONE
    refcount per page owned by this record) plus everything the decode
    side needs to continue the request token-identically.  Ownership is
    linear — exactly one of :meth:`InferenceEngine.release_prefill` or
    the KVMigrator's export seam consumes it."""

    prompt: list[int]
    max_new_tokens: int
    temperature: float
    seed: int
    eos_id: int | None
    pages: list[int]        # block-table order; record owns one ref each
    cached_len: int         # positions aliased from the prefill-side cache
    generation: int         # prefill-engine weight generation of the pages


class MigrationTicket:
    """Accept/reject signal for one :meth:`admit_from_pages` handoff.

    The serve thread resolves it at the drain fence — accepted means the
    engine now owns the pages and the request WILL decode (its
    completion arrives through the pending handle); rejected means the
    refcounts were already released and the caller should requeue."""

    def __init__(self):
        self._ev = threading.Event()
        self._accepted = False          # write-once before _ev.set()
        self._reason: str | None = None

    def _resolve(self, accepted: bool, reason: str | None = None) -> None:
        self._accepted = accepted
        self._reason = reason
        self._ev.set()

    def wait(self, timeout: float | None = None) -> bool:
        """True = admitted, False = rejected (see :attr:`reason`)."""
        if not self._ev.wait(timeout):
            raise TimeoutError("migration ticket unresolved — is the "
                               "decode engine's serve loop running?")
        return self._accepted

    @property
    def reason(self) -> str | None:
        return self._reason


@dataclasses.dataclass
class _MigratedIn:
    """One migrated request parked for the serve thread's drain fence.
    ``pages`` arrive already increfed on THIS engine's pool (claim +
    alloc happened in the KVMigrator); ownership passes to the engine
    the moment the record enters ``_migrated_in``."""

    pending: PendingResult
    pages: list[int]                  # block-table order, decode-side ids
    uploads: list                     # [(page_id, [{name: ndarray}, ...])]
    generation: int | None            # decode generation the claim assumed
    ticket: MigrationTicket


@dataclasses.dataclass
class _Slot:
    """Host-side record of one occupied cache row."""

    pending: PendingResult
    delivered: list = dataclasses.field(default_factory=list)
    admitted_s: float = 0.0
    first_token_s: float | None = None
    # weight generation this request was admitted (and will fully decode)
    # under — swaps apply only at fences with every slot free, so the
    # stamp is exact, not advisory
    generation: int = 0
    loaded_step: int | None = None


class InferenceEngine:
    """Continuous-batching decode over a trained ``TransformerLM``.

    ``params`` may be passed directly, or loaded from ``checkpoint`` (a
    directory path or a :class:`CheckpointManager`) — the engine opens
    checkpoint directories READ-ONLY and restores ``latest_valid_step()``.
    ``model.init`` shapes the restore template, so the checkpoint must
    match ``model.cfg``.
    """

    def __init__(self, model, params=None, checkpoint=None,
                 cfg: ServingConfig = ServingConfig(),
                 compile_cache_dir: str | None = None,
                 draft_model=None, draft_params=None):
        # PR-2 warmup integration: with a persistent cache dir configured
        # (env or explicit), the warmup compiles below hit disk
        setup_compile_cache(compile_cache_dir)
        self.model = model
        self.cfg = cfg
        if cfg.prefix_cache and not cfg.paged:
            raise ValueError("prefix_cache requires paged=True (sharing is "
                             "block-table aliasing)")
        if cfg.role not in ("unified", "prefill", "decode"):
            raise ValueError(f"role must be unified/prefill/decode, "
                             f"got {cfg.role!r}")
        if cfg.role == "prefill" and not cfg.paged:
            raise ValueError("role='prefill' requires paged=True — the "
                             "migration unit is a KV page")
        if cfg.kv_quant is not None:
            if not cfg.paged:
                raise ValueError("kv_quant requires paged=True (scales live "
                                 "beside the page pool)")
            from ..ops.pallas import kv_quant as kvq
            kvq.storage_dtype(cfg.kv_quant)  # validates mode / fp8 support
        if cfg.speculative:
            if draft_model is None or draft_params is None:
                raise ValueError("speculative=True needs draft_model and "
                                 "draft_params (see zoo.draft_lm)")
            if (draft_model.cfg.vocab_size != model.cfg.vocab_size
                    or draft_model.cfg.max_len != model.cfg.max_len):
                raise ValueError("draft model must share the target's "
                                 "vocab_size and max_len")
            if cfg.spec_k < 1:
                raise ValueError("spec_k must be >= 1")
        self._draft_model = draft_model if cfg.speculative else None
        self._draft_params = draft_params if cfg.speculative else None
        # paged sizing: pages_per_slot covers max_len; one EXTRA physical
        # trash page (index num_pages) absorbs the masked writes of
        # inactive rows, whose stale block-table entries must never point
        # at reallocatable pages
        self._page_size = cfg.page_size
        self._pages_per_slot = -(-model.cfg.max_len // cfg.page_size)
        self._num_pages = (cfg.num_pages if cfg.num_pages is not None
                           else cfg.slots * self._pages_per_slot)
        self._pool = (PagePool(self._num_pages, cfg.page_size)
                      if cfg.paged else None)
        self._page_bytes = kv_page_bytes(model.cfg, cfg.page_size,
                                         cfg.kv_quant)
        # per-tier queue depth: the autoscaler distinguishes prefill
        # pressure (bursty, compute-bound) from decode pressure (steady,
        # memory-bound) by gauge name; unified keeps the classic name
        self._queue = RequestQueue(
            cfg.max_queue, cfg.max_batch_delay_ms,
            depth_gauge={"prefill": "serving.queue.depth.prefill",
                         "decode": "serving.queue.depth.decode"}.get(
                             cfg.role, "serving.queue.depth"))
        self._ckpt: CheckpointManager | None = None
        self._loaded_step: int | None = None
        if checkpoint is not None:
            self._ckpt = (checkpoint if isinstance(checkpoint, CheckpointManager)
                          else CheckpointManager.open_read_only(checkpoint))
        if params is None:
            if self._ckpt is None:
                raise ValueError("need params or a checkpoint to serve from")
            step = self._ckpt.latest_valid_step()
            if step is None:
                raise FileNotFoundError(
                    f"no verified checkpoint under {self._ckpt.directory}")
            template = model.init(jax.random.key(0))
            restored = self._ckpt.restore(template, step=step)
            params = restored["params"]
            self._loaded_step = restored["step"]
        # _lock guards the params swap AND the slot bookkeeping shared
        # between the serve thread and callers (stop/stats/HTTP handlers);
        # _state is deliberately OUTSIDE it — serve-thread-owned, see
        # warmup().  The guarded-by annotations are the LK01 contract:
        # every non-__init__ write must hold the lock.
        self._lock = threading.Lock()
        # _raw_params is the unquantized tree (also the reload restore
        # template — checkpoints never contain *_q leaves); _params is
        # what decode actually reads, int8-quantized when opted in
        self._raw_params = params                # guarded-by: self._lock
        self._params = self._maybe_quantize(params)  # guarded-by: self._lock
        # generation consistency (DESIGN.md §23): reload() STAGES the new
        # tree; the swap applies only at a fence with every slot free, so
        # every response decodes start-to-finish under ONE generation.
        # _generation counts applied swaps; _staged is the parked
        # (raw, quantized, step) tuple awaiting an all-slots-free fence.
        self._generation = 0                     # guarded-by: self._lock
        self._staged: tuple | None = None        # guarded-by: self._lock
        self._state = self._init_state()
        # device-resident chaos flags, built OUTSIDE the hot loop — the
        # decode segment must not upload scalars under hot_loop_guard
        self._garble = (jnp.int32(0), jnp.int32(1))
        # shardguard baseline mode: the first decode dispatch captures the
        # params/state placements; a later dispatch arriving differently
        # placed (e.g. a reload that device_puts onto the wrong sharding)
        # is counted as implicit resharding.  One flag check when off.
        self._step_fn = SHARDGUARD.wrap(
            "serving.decode_step",
            jax.jit(
                self._build_step(),
                donate_argnums=(2,) if cfg.speculative else (1,)))
        # brownout seam (DESIGN.md §26): a speculative engine also carries
        # the PLAIN step, compiled at warmup alongside the spec one, so
        # ladder level 1 (disable speculation) swaps dispatch at a fence
        # with no compile stall and no parity change — the draft only ever
        # decided how many tokens emit per dispatch, never which
        self._spec_enabled = cfg.speculative         # guarded-by: self._lock
        self._plain_step_fn = (SHARDGUARD.wrap(
            "serving.decode_step_plain",
            jax.jit(self._build_plain_step(), donate_argnums=(1,)))
            if cfg.speculative else None)
        self._max_new_cap: int | None = None         # guarded-by: self._lock
        self._admission_hook = None                  # guarded-by: self._lock
        self._step_compiled = False
        self._warmed = False   # True once warmup() finished (healthz gate)
        self._admit_fns: dict[int, Callable] = {}    # guarded-by: self._lock
        self._slots: dict[int, _Slot] = {}           # guarded-by: self._lock
        self._slot_pages: dict[int, list[int]] = {}  # guarded-by: self._lock
        self._free: list[int] = list(range(cfg.slots))  # guarded-by: self._lock
        # pages quarantined by an off-thread clear_prefix (reload): the
        # serve thread wipes them at its next fence, then requeues them
        self._pending_wipe: list[int] = []           # guarded-by: self._lock
        # ---- disagg tier (DESIGN.md §27) ----
        # serializes prefill()/release_prefill()/read_pages(): on a
        # prefill-role engine (no serve thread) _state is owned by
        # whichever worker holds this lock
        self._prefill_lock = threading.Lock()
        # migrated requests parked for the serve thread's drain fence
        self._migrated_in: list[_MigratedIn] = []    # guarded-by: self._lock
        # lazily compiled draft-only prefill per bucket (speculative
        # decode engines rebuild the migrated request's draft cache row
        # locally — draft state never crosses the wire, and it only ever
        # decides accept LENGTH, never which tokens emit)
        self._draft_prefill_fns: dict[int, Callable] = {}  # guarded-by: self._lock
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._admitted = 0                           # guarded-by: self._lock
        self._completed = 0                          # guarded-by: self._lock
        # XLA cost of one decode dispatch (captured at warmup) — feeds the
        # live serving.decode_mfu gauge at every resolve fence
        self._decode_cost = None                     # serve-thread-owned

    def _maybe_quantize(self, params):
        """The serving tree decode reads: unchanged by default; with
        ``int8_decode`` the bandwidth-heavy matrices (FFN w1/w2, LM head)
        are replaced by int8 + per-channel-scale copies, and
        ``decode_step``/``_ffn`` pick the int8 path on key presence."""
        if not self.cfg.int8_decode:
            return params
        from ..ops.pallas.matmul_int8 import quantize_params_for_decode
        with allow_transfers(), METRICS.time("serving.quantize"):
            return quantize_params_for_decode(params, self.model.cfg)

    # ------------------------------------------------------------ device state
    def _init_state(self) -> dict:
        cfg = self.model.cfg
        S = self.cfg.slots
        state = {
            "toks": jnp.zeros((S, cfg.max_len), jnp.int32),
            "pos": jnp.zeros((S,), jnp.int32),
            "limit": jnp.zeros((S,), jnp.int32),
            "temp": jnp.zeros((S,), jnp.float32),
            "keys": jax.random.split(jax.random.key(0), S),
            "active": jnp.zeros((S,), bool),
        }
        if self.cfg.paged:
            # +1 physical page: the trash page every inactive block-table
            # row points at, so masked writes never land on real pages
            if self.cfg.kv_quant is not None:
                from ..ops.pallas.kv_quant import init_quantized_paged_cache
                state["pages"] = init_quantized_paged_cache(
                    cfg, self._num_pages + 1, self._page_size,
                    self.cfg.kv_quant)
            else:
                state["pages"] = init_paged_cache(
                    cfg, self._num_pages + 1, self._page_size)
            state["bt"] = jnp.full((S, self._pages_per_slot),
                                   self._num_pages, jnp.int32)
        else:
            state["cache"] = init_decode_cache(cfg, S)
        if self.cfg.speculative:
            state["draft_cache"] = init_decode_cache(self._draft_model.cfg, S)
        return state

    def _paged_attn_fn(self):
        """The paged-attention read the step uses: None selects the
        bitwise jnp gather path; any other name resolves a registry
        candidate — which only config written by the bench autopick gate
        (TUNE evidence + tolerance + margin) should ever select."""
        impl = self.cfg.paged_attention_impl
        if impl == "gather":
            return None
        from ..ops.pallas import registry as kernel_registry
        kind = ("paged_attention_int8" if self.cfg.kv_quant is not None
                else "paged_attention")
        return kernel_registry.get(kind, impl).fn

    def _build_step(self) -> Callable:
        if self.cfg.speculative:
            return self._build_spec_step()
        return self._build_plain_step()

    def _build_plain_step(self) -> Callable:
        cfg = self.model.cfg
        paged = self.cfg.paged
        attn_fn = self._paged_attn_fn() if paged else None

        def step(params, state):
            """Advance every occupied slot one token.

            Inactive / exhausted rows still flow through the batched
            matmuls (masked no-ops — cheaper than reshaping the batch),
            but their RNG keys, positions and token buffers are frozen
            and they emit -1.
            """
            toks, pos = state["toks"], state["pos"]
            temp, active, limit = state["temp"], state["active"], state["limit"]
            row = jnp.arange(toks.shape[0])
            cur = toks[row, pos]
            if paged:
                logits, pages = decode_step_paged(
                    params, state["pages"], state["bt"], cur, pos, cfg,
                    attn_fn=attn_fn)
                kv_update = {"pages": pages}
            else:
                logits, cache = decode_step(params, state["cache"], cur, pos,
                                            cfg)
                kv_update = {"cache": cache}
            # per-slot RNG, exactly Transformer.sample's kv stream: split
            # the slot key, carry the first half, draw from the second
            pair = jax.vmap(jax.random.split)(state["keys"])    # (S, 2) keys
            carry, sub = pair[:, 0], pair[:, 1]
            safe_t = jnp.where(temp > 0, temp, 1.0)
            drawn = jax.vmap(jax.random.categorical)(
                sub, logits / safe_t[:, None])
            pick = jnp.where(temp > 0, drawn.astype(jnp.int32),
                             jnp.argmax(logits, axis=-1).astype(jnp.int32))
            can = active & (pos < limit) & (pos + 1 < cfg.max_len)
            emitted = jnp.where(can, pick, -1)
            new_pos = jnp.where(can, pos + 1, pos)
            toks = toks.at[row, new_pos].set(
                jnp.where(can, pick, toks[row, new_pos]))
            kd = jax.random.key_data(state["keys"])
            keys = jax.random.wrap_key_data(
                jnp.where(can[:, None], jax.random.key_data(carry), kd))
            new_state = dict(state, toks=toks, pos=new_pos, keys=keys,
                             **kv_update)
            return new_state, emitted

        return step

    def _build_spec_step(self) -> Callable:
        """Speculative decode dispatch: draft proposes ``spec_k`` greedy
        tokens, the target verifies the whole window at once, and up to
        ``spec_k + 1`` tokens emit.

        Parity argument (DESIGN.md §17): window logits ``L_0..L_k`` are
        the target's own next-token distributions at positions
        ``pos..pos+k`` (the windowed pass is bitwise W sequential steps).
        Token ``i`` is drawn from ``L_i`` with the request's i-th key
        split — the exact op the non-speculative step would run — and
        emits only while every earlier draft proposal matched its draw,
        i.e. while the sequence prefix equals what sequential decoding
        would have produced.  Keys advance by exactly the number of
        emitted tokens.  The draft therefore controls throughput
        (``serving.spec_accept_len``), never content."""
        cfg = self.model.cfg
        dcfg = self._draft_model.cfg
        paged = self.cfg.paged
        k_spec = self.cfg.spec_k
        W = k_spec + 1

        def step(params, dparams, state, garble):
            toks, pos = state["toks"], state["pos"]
            temp, active, limit = state["temp"], state["active"], state["limit"]
            S = toks.shape[0]
            row = jnp.arange(S)
            cur = toks[row, pos]
            # -- draft proposal chain (greedy; near max_len the clamped
            # draft-cache writes can degrade proposals — accept rate
            # drops, parity is untouched since only target draws emit)
            dcache = state["draft_cache"]
            proposals = []
            inp = cur
            for i in range(k_spec):
                d_logits, dcache = decode_step(dparams, dcache, inp, pos + i,
                                               dcfg)
                nxt = jnp.argmax(d_logits, axis=-1).astype(jnp.int32)
                # chaos serving.draft: a garbled draft must only shrink
                # accept length, never change emitted tokens
                nxt = (nxt + garble) % cfg.vocab_size
                proposals.append(nxt)
                inp = nxt
            d = jnp.stack(proposals, axis=1)                     # (S, k)
            window = jnp.concatenate([cur[:, None], d], axis=1)  # (S, W)
            # -- one windowed verify on the target
            if paged:
                logits, pages = decode_window_paged(
                    params, state["pages"], state["bt"], window, pos, cfg)
                kv_update = {"pages": pages}
            else:
                logits, cache = decode_window(params, state["cache"], window,
                                              pos, cfg)
                kv_update = {"cache": cache}
            # -- the offline key stream: split i times, draw pick_i from
            # L_i with sub_i; emitted count m selects carry_m below
            safe_t = jnp.where(temp > 0, temp, 1.0)
            key_stack = [jax.random.key_data(state["keys"])]     # carry_0
            picks = []
            kcur = state["keys"]
            for i in range(W):
                pair = jax.vmap(jax.random.split)(kcur)
                kcur, sub = pair[:, 0], pair[:, 1]
                drawn = jax.vmap(jax.random.categorical)(
                    sub, logits[:, i] / safe_t[:, None])
                pick = jnp.where(
                    temp > 0, drawn.astype(jnp.int32),
                    jnp.argmax(logits[:, i], axis=-1).astype(jnp.int32))
                picks.append(pick)
                key_stack.append(jax.random.key_data(kcur))
            picks = jnp.stack(picks, axis=1)                     # (S, W)
            off = jnp.arange(W, dtype=jnp.int32)[None, :]
            can = (active[:, None] & (pos[:, None] + off < limit[:, None])
                   & (pos[:, None] + off + 1 < cfg.max_len))     # (S, W)
            match = jnp.concatenate(
                [jnp.ones((S, 1), bool), d == picks[:, :k_spec]], axis=1)
            emit = jnp.cumprod((can & match).astype(jnp.int32),
                               axis=1).astype(bool)              # (S, W)
            m = emit.sum(axis=1).astype(jnp.int32)               # (S,)
            emitted = jnp.where(emit, picks, -1)
            tpos = pos[:, None] + 1 + off                        # (S, W)
            flat = jnp.where(emit, row[:, None] * cfg.max_len + tpos,
                             S * cfg.max_len)
            toks = toks.reshape(-1).at[flat.reshape(-1)].set(
                picks.reshape(-1), mode="drop").reshape(S, cfg.max_len)
            kstack = jnp.stack(key_stack, axis=0)                # (W+1, S, ..)
            keys = jax.random.wrap_key_data(kstack[m, row])      # carry_m

            new_state = dict(state, toks=toks, pos=pos + m, keys=keys,
                             draft_cache=dcache, **kv_update)
            return new_state, emitted

        return step

    # ------------------------------------------------------------ prefill
    def _prompt_bucket(self, n: int) -> int:
        """Power-of-two prompt ladder (the PR-2 pad-batch discipline):
        one compiled prefill per bucket, so recompiles are bounded by
        ``log2(max_len)`` regardless of prompt-length diversity."""
        b = self.cfg.min_prefill_bucket
        while b < n:
            b <<= 1
        return min(b, self.model.cfg.max_len)

    def _admit_for(self, bucket: int) -> Callable:
        with self._lock:
            cached = self._admit_fns.get(bucket)
        if cached is not None:
            return cached
        cfg = self.model.cfg
        paged = self.cfg.paged
        spec = self.cfg.speculative
        dcfg = self._draft_model.cfg if spec else None
        ps = self._page_size
        n_slot_pages = self._pages_per_slot

        def admit(params, dparams, state, prompt, p_len, cached_len, slot,
                  key, temp, max_new):
            """Prefill ``prompt[:p_len]`` on a batch-of-1 cache through
            the SAME ``decode_step`` the steady loop uses (numerics cannot
            diverge from ``Transformer.sample``'s kv path), then scatter
            the row into the slot pool (dense) or the slot's pages.
            Masked iterations are no-ops: one executable per bucket.

            Paged: the batch-of-1 cache starts as a GATHER of the slot's
            block-table row, so positions ``< cached_len`` (aliased
            prefix pages) are already populated and the loop skips them;
            the scatter-back rewrites shared pages with bitwise-identical
            values (prefill is position-wise deterministic).  Speculative:
            the draft cache prefills alongside (always from 0 — the
            prefix cache holds target pages only)."""
            if paged:
                bt_row = lax.dynamic_slice(
                    state["bt"], (slot, jnp.int32(0)), (1, n_slot_pages))
                # quant-transparent: a quantized pool (kv_quant) gathers
                # DEQUANTIZED content, so the prefill loop below runs the
                # same float arithmetic either way
                cache1 = [dict(zip(("k", "v"), gather_paged_layer(
                    c, bt_row, cfg.max_len, cfg.dtype)))
                          for c in state["pages"]]
            else:
                cache1 = init_decode_cache(cfg, 1)
            dcache1 = init_decode_cache(dcfg, 1) if spec else jnp.int32(0)
            last = jnp.maximum(p_len - 2, 0)

            def body(i, carry):
                c, dc = carry
                ii = jnp.minimum(i, last)
                tok_i = lax.dynamic_slice(prompt, (ii,), (1,))
                _, c_new = decode_step(params, c, tok_i, ii, cfg)
                use = (i >= cached_len) & (i < p_len - 1)
                c = jax.tree_util.tree_map(
                    lambda a, b: jnp.where(use, a, b), c_new, c)
                if spec:
                    _, dc_new = decode_step(dparams, dc, tok_i, ii, dcfg)
                    dc = jax.tree_util.tree_map(
                        lambda a, b: jnp.where(i < p_len - 1, a, b),
                        dc_new, dc)
                return c, dc

            cache1, dcache1 = lax.fori_loop(0, bucket, body, (cache1, dcache1))
            if paged:
                t = jnp.arange(cfg.max_len, dtype=jnp.int32)[None, :]
                flat = paged_flat_index(bt_row, t, ps)[0]        # (max_len,)
                # quantize-at-write for kv_quant pools (scatter_paged_layer
                # requantizes only the row's pages; an aliased prefix page
                # rewrites with identical content → identical bytes)
                kv_update = {"pages": [
                    scatter_paged_layer(c, flat, c1["k"][0], c1["v"][0])
                    for c, c1 in zip(state["pages"], cache1)]}
            else:
                kv_update = {"cache": [
                    {"k": lax.dynamic_update_slice_in_dim(c["k"], c1["k"],
                                                          slot, axis=0),
                     "v": lax.dynamic_update_slice_in_dim(c["v"], c1["v"],
                                                          slot, axis=0)}
                    for c, c1 in zip(state["cache"], cache1)]}
            if spec:
                kv_update["draft_cache"] = [
                    {"k": lax.dynamic_update_slice_in_dim(c["k"], c1["k"],
                                                          slot, axis=0),
                     "v": lax.dynamic_update_slice_in_dim(c["v"], c1["v"],
                                                          slot, axis=0)}
                    for c, c1 in zip(state["draft_cache"], dcache1)]
            toks = lax.dynamic_update_slice(
                state["toks"], prompt[None, :], (slot, jnp.int32(0)))

            def put1(arr, v):
                return lax.dynamic_update_slice(
                    arr, jnp.reshape(v, (1,)).astype(arr.dtype), (slot,))

            kd = lax.dynamic_update_slice(
                jax.random.key_data(state["keys"]),
                jax.random.key_data(key)[None], (slot, jnp.int32(0)))
            return dict(
                state,
                toks=toks,
                # sample() prefills tokens 0..P-2; the first engine step
                # then processes token P-1 and draws the first new token
                pos=put1(state["pos"], p_len - 1),
                limit=put1(state["limit"], p_len - 1 + max_new),
                temp=put1(state["temp"], temp),
                active=put1(state["active"], True),
                keys=jax.random.wrap_key_data(kd),
                **kv_update,
            )

        prefill = jax.jit(admit, donate_argnums=(2,))
        with self._lock:
            self._admit_fns[bucket] = prefill
        METRICS.increment("serving.prefill.recompile")
        return prefill

    # ------------------------------------------------------------ submission
    def submit(self, prompt, max_new_tokens: int, temperature: float = 0.0,
               seed: int = 0, eos_id: int | None = None,
               deadline_ms: float | None = None,
               tenant: str = "", priority: int = 0) -> PendingResult:
        """Validate + enqueue; returns a handle whose ``result()`` blocks.
        Raises ``ValueError`` on malformed requests (HTTP 400) and
        :class:`~.batcher.QueueFull` under backpressure (HTTP 429).
        ``tenant`` is an opaque caller identity for per-tenant accounting;
        it is folded ONCE here through the bounded label helper and the
        folded label rides the request — downstream metric sites never
        see the raw string (graftlint OB03).  ``priority`` > 0 marks
        BACKGROUND work: claimed only when no interactive request waits
        (aging prevents starvation) and shed first under brownout."""
        cfg = self.model.cfg
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < cfg.vocab_size for t in prompt):
            raise ValueError(f"prompt token out of range [0, {cfg.vocab_size})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len ({cfg.max_len})")
        with self._lock:
            cap = self._max_new_cap
            hook = self._admission_hook
        if cap is not None and max_new_tokens > cap:
            # brownout level 2: serve a SHORTER completion instead of
            # shedding — the served tokens are exactly the offline
            # sample's prefix under the clamped budget, so token parity
            # holds for everything that is served
            max_new_tokens = cap
            METRICS.increment("serving.max_new_clamped")
        req = GenerateRequest(
            prompt=prompt, max_new_tokens=int(max_new_tokens),
            temperature=float(temperature), seed=int(seed),
            eos_id=eos_id if eos_id is not None else self.cfg.default_eos_id,
            deadline_s=(time.monotonic() + deadline_ms / 1000.0
                        if deadline_ms else None),
            tenant=TENANTS.label(str(tenant)) if tenant else "",
            priority=1 if int(priority) > 0 else 0)
        if hook is not None:
            # admission-side overload gate (control/overload.py): raises
            # a ServingRejected subclass — throttle/shed IS the API (429)
            hook(req)
        if _obs_enabled():
            # trace identity for the whole request: adopt the caller's
            # context (HTTP traceparent installed via trace.bind, or an
            # enclosing span), else mint — one trace_id spans queue wait,
            # prefill, every decode segment, and emit
            ctx = trace.current_trace_context()
            if ctx is not None:
                req.trace_id, req.parent_span_id = ctx
            else:
                req.trace_id = trace.new_trace_id()
            req.root_span_id = trace.new_span_id()
        METRICS.increment("serving.requests")
        return self._queue.submit(req)

    def generate(self, prompt, max_new_tokens: int, timeout: float = 60.0,
                 **kw) -> Completion:
        """Blocking convenience wrapper over :meth:`submit`."""
        return self.submit(prompt, max_new_tokens, **kw).result(timeout)

    # ------------------------------------------------------------ serve loop
    def start(self, warmup: bool = True) -> "InferenceEngine":
        if self._thread is not None:
            return self
        if warmup:
            self.warmup()
        if self.cfg.role == "prefill":
            # prefill tier: no decode loop to run — work arrives through
            # prefill() on the scheduler's worker threads, and _state
            # stays owned by whoever holds _prefill_lock
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._serve_loop, daemon=True,
                                        name="serving-engine")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._queue.wake()   # kick the serve loop out of its idle wait
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        with self._lock:
            dead = {s: self._slots.pop(s) for s in list(self._slots)}
            pages = {s: self._slot_pages.pop(s, [])
                     for s in list(self._slot_pages)}
            # start() after stop() is supported: restore the FULL slot
            # range — a dead slot's id must not leak out of the pool
            self._free = list(range(self.cfg.slots))
            pending, self._pending_wipe = self._pending_wipe, []
            migrated, self._migrated_in = self._migrated_in, []
        for sl in dead.values():
            sl.pending._fail(
                RuntimeError("engine stopped with request in flight"))
        for rec in migrated:
            # reject, never corrupt: the pages are decreffed below and
            # the scheduler requeues on MigrationRejected
            rec.ticket._resolve(False, "engine stopped")
            rec.pending._fail(MigrationRejected(
                "engine stopped before migrated request was admitted"))
        # the serve thread is joined, so _state is safe to touch here.
        # Reset the dead rows the way _evict would have — deactivate,
        # release K/V and (paged) park the block tables on the trash
        # page — so a restarted decode loop, which writes EVERY row's
        # K/V through its table, can never scribble on pages the pool
        # reallocates to new requests.
        if dead or pending or migrated:
            with allow_transfers():
                if self.cfg.paged:
                    freed = list(pending)
                    for pg in pages.values():
                        freed.extend(self._pool.decref(pg))
                    for rec in migrated:
                        freed.extend(self._pool.decref(rec.pages))
                    bt = self._state["bt"]
                    active = self._state["active"]
                    for s in dead:
                        bt = bt.at[s].set(self._num_pages)
                        active = active.at[s].set(False)
                    # graftlint: disable=LK01 — _state is serve-thread-
                    # owned; the join above is the happens-before edge
                    self._state = dict(self._state, bt=bt, active=active)
                    self._wipe_pages(freed)
                    if pending:
                        self._pool.requeue(pending)
                else:
                    mask = np.zeros((self.cfg.slots,), bool)
                    mask[list(dead)] = True
                    self._state = dict(
                        self._state,
                        cache=reset_cache_slots(self._state["cache"],
                                                jnp.asarray(mask)),
                        active=self._state["active"]
                        .at[jnp.asarray(list(dead), jnp.int32)].set(False))
        for p in self._queue.drain():
            p._fail(RuntimeError("engine stopped before request was admitted"))

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _bucket_ladder(self) -> list[int]:
        """Every prefill bucket traffic can ever hit: the power-of-two
        ladder from ``min_prefill_bucket`` up to (and including) the
        ``max_len`` cap bucket."""
        out = []
        b = self.cfg.min_prefill_bucket
        while b < self.model.cfg.max_len:
            out.append(b)
            b <<= 1
        out.append(self.model.cfg.max_len)
        return sorted(set(out))

    def warmup(self) -> None:
        """Compile the steady-state step and EVERY prefill bucket up to
        ``max_len`` before traffic (with the PR-2 persistent compile
        cache configured these are disk hits on restart) — first-request
        TTFT never pays a compile stall, whatever the prompt length, and
        ``serving.prefill.recompile`` stays at bucket-ladder count for
        the engine's whole lifetime."""
        with allow_transfers(), METRICS.time("serving.warmup"):
            pages: list[int] = []
            try:
                if self.cfg.paged:
                    # slot 0 needs a real block-table row for the dummy
                    # admits below; released (and re-trashed) in finally.
                    # A pool smaller than pages_per_slot is legal (sized
                    # for short requests): warm with what it has — the
                    # row's tail parks on the trash page, exactly like
                    # an admitted short request's
                    n_warm = min(self._pages_per_slot, self._num_pages)
                    pages = self._pool.alloc(n_warm)
                    row = pages + [self._num_pages] * (
                        self._pages_per_slot - n_warm)
                    # graftlint: disable=LK01 — _state is serve-thread-
                    # owned; warmup (and every other flagged site) runs
                    # either before Thread.start() or ON the serve loop,
                    # so there is a happens-before edge, never a race
                    self._state = dict(
                        self._state,
                        bt=self._state["bt"].at[0].set(
                            jnp.asarray(row, jnp.int32)))
                dparams = self._draft_params if self.cfg.speculative else {}
                # cost capture lowers with the concrete args BEFORE the
                # donating call (lowering reads avals only, never buffers)
                if self.cfg.role == "prefill":
                    # a prefill-role engine never runs the decode step —
                    # skipping its compile makes prefill-tier spin-up
                    # (and the autoscaler's scale-up path) proportionally
                    # cheaper; the bucket ladder below is the whole job
                    state = self._state
                elif self.cfg.speculative:
                    self._decode_cost = COSTS.capture(
                        "serving.decode_step", self._step_fn,
                        self._params, dparams, self._state, jnp.int32(0))
                    state, _ = self._step_fn(self._params, dparams,
                                             self._state, jnp.int32(0))
                    # the brownout fallback step compiles NOW, not at the
                    # moment the ladder disables speculation — degrading
                    # under load must never pay a compile stall
                    state, _ = self._plain_step_fn(self._params, state)
                else:
                    self._decode_cost = COSTS.capture(
                        "serving.decode_step", self._step_fn,
                        self._params, self._state)
                    state, _ = self._step_fn(self._params, self._state)
                self._step_compiled = True
                for bucket in self._bucket_ladder():
                    fn = self._admit_for(bucket)
                    state = fn(self._params, dparams, state,
                               jnp.zeros((bucket,), jnp.int32), jnp.int32(1),
                               jnp.int32(0), jnp.int32(0), jax.random.key(0),
                               jnp.float32(0.0), jnp.int32(0))
                # the warmup admits occupied slot 0 with a dummy —
                # deactivate, and park its block-table row back on the
                # trash page so the freed pages are writable by nobody.
                # graftlint: disable=LK01 — _state is serve-thread-owned
                # (every other write site runs on the serve loop); warmup
                # runs strictly before Thread.start(), which is a
                # happens-before edge, so this write can never race
                self._state = dict(
                    state, active=jnp.zeros_like(state["active"]))
            finally:
                if pages:
                    freed = self._pool.decref(pages)
                    self._wipe_pages(freed)
                    self._state = dict(
                        self._state,
                        bt=self._state["bt"].at[0].set(self._num_pages))
        # the warmed flag flips only after the step fn(s) AND the full
        # prefill bucket ladder compiled — the signal the router's
        # scale-up path gates ring admission on (a cold replica on the
        # ring is a compile-storm TTFT spike for the keys it inherits)
        self._warmed = True

    def _wipe_pages(self, freed: list[int]) -> None:
        """Zero physical pages whose refcount just hit zero (never an
        aliased page — ``PagePool.decref`` only returns dead ones)."""
        if not freed or not self.cfg.paged:
            return
        mask = np.zeros((self._num_pages + 1,), bool)
        mask[freed] = True
        self._state = dict(
            self._state,
            pages=reset_cache_pages(self._state["pages"], jnp.asarray(mask)))

    def _serve_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._serve_once()
            except Exception as e:  # defensive: a wedged loop strands callers
                METRICS.increment("serving.engine.errors")
                with self._lock:
                    dead = [self._slots.pop(s) for s in list(self._slots)]
                    self._slot_pages.clear()
                    self._free = list(range(self.cfg.slots))
                    # pool.reset() below rebuilds the free list wholesale,
                    # so quarantined page ids would go stale — drop them
                    self._pending_wipe.clear()
                    migrated, self._migrated_in = self._migrated_in, []
                for sl in dead:
                    sl.pending._fail(e)
                for rec in migrated:
                    # pool.reset() reclaims their pages wholesale below
                    rec.ticket._resolve(False, "serve loop crashed")
                    rec.pending._fail(MigrationRejected(
                        "serve loop crashed before admission"))
                if self._pool is not None:
                    self._pool.reset()
                with allow_transfers():
                    self._state = self._init_state()

    def _drain_pending_wipe(self) -> None:
        """Serve-thread half of reload's prefix invalidation: zero the
        pages :meth:`PagePool.clear_prefix` quarantined and only THEN
        hand them back to the free list.  Wipe-before-reallocatable —
        the pages are not allocatable until ``requeue``, so they can
        never be zeroed under a request that just acquired them; and
        the wipe itself runs HERE because ``_state`` is serve-thread-
        owned (reload must not touch it)."""
        with self._lock:
            pending, self._pending_wipe = self._pending_wipe, []
        if not pending:
            return
        with allow_transfers():
            self._wipe_pages(pending)
        self._pool.requeue(pending)

    def _serve_once(self) -> None:
        self._drain_pending_wipe()
        with self._lock:
            applied = self._try_apply_staged_locked()
            staged = self._staged is not None
        if applied:
            self._publish_generation_gauges()
        if not staged:
            # migrated requests enter the continuous batch HERE, between
            # decode segments — the admit_from_pages seam (DESIGN.md §27)
            self._drain_migrated()
        idle = not self._slots
        n_free = len(self._free)
        if n_free and not staged:
            # admission pauses while a swap is staged: in-flight slots
            # drain (each bounds its own decode budget), the fence
            # arrives, and queued requests then decode wholly under the
            # NEW generation — never a mid-request mix
            batch = self._queue.take(
                n_free, block_s=self.cfg.idle_wait_s if idle else 0.0)
            if batch:
                # admission is a deliberate host<->device seam (prompt
                # upload, request bookkeeping) — annotated, off the
                # per-token path
                with allow_transfers(), trace.span("serving.admit"):
                    self._admit(batch)
        if not self._slots:
            return
        METRICS.observe_time("serving.batch_fill_ratio",
                             len(self._slots) / self.cfg.slots,
                             buckets=FILL_BUCKETS)
        t0 = time.perf_counter()
        with hot_loop_guard():
            pending = self._decode_segment()
        with allow_transfers(), trace.span("serving.resolve"):
            self._resolve(pending, t0)

    def _admit(self, batch: list[PendingResult]) -> None:
        for p in batch:
            # atomic expiry-vs-admission: a deadline that passed between
            # the queue pop and this point 504s HERE, under the queue
            # lock, instead of occupying a slot to decode tokens nobody
            # is waiting for
            if not self._queue.claim(p):
                continue
            req: GenerateRequest = p.request
            if req.trace_id:
                t_claim = time.perf_counter()
                trace.record_span(
                    "serving.queue_wait", req.submitted_perf,
                    t_claim - req.submitted_perf, trace_id=req.trace_id,
                    parent_id=req.root_span_id, request=req.id)
            with self._lock:
                slot = self._free.pop()
                params = self._params
                # generation stamp is atomic with the params capture —
                # the pair can never disagree (DESIGN.md §23)
                gen, lstep = self._generation, self._loaded_step
            acquired: list[int] = []
            try:
                cached_len = 0
                if self.cfg.paged:
                    if FAULTS.check("serving.page_pool") is not None:
                        raise PagePoolExhausted(
                            "injected page-pool exhaustion (chaos site "
                            "serving.page_pool)")
                    usable = len(req.prompt) - 1
                    if self.cfg.prefix_cache:
                        # the lookup is atomic with a params re-capture:
                        # reload() swaps params AND clears the cache
                        # under this same lock, so every entry seen here
                        # holds K/V computed under exactly `params` — an
                        # aliased prefix can never mix weights with the
                        # prefill that extends it
                        with self._lock:
                            params = self._params
                            gen, lstep = self._generation, self._loaded_step
                            shared, cached_len = self._pool.lookup_prefix(
                                req.prompt, usable)
                        acquired.extend(shared)
                    # allocate for what THIS request can touch (prompt +
                    # budget, the engine writes positions [0, limit]),
                    # not max_len — the paged footprint win; the row's
                    # unneeded tail parks on the trash page, which decode
                    # may scribble on but never attends
                    need = -(-(len(req.prompt) + req.max_new_tokens)
                             // self._page_size)
                    acquired.extend(self._pool.alloc(need - len(acquired)))
                    row = acquired + [self._num_pages] * (
                        self._pages_per_slot - len(acquired))
                    self._state = dict(
                        self._state,
                        bt=self._state["bt"].at[slot].set(
                            jnp.asarray(row, jnp.int32)))
                bucket = self._prompt_bucket(len(req.prompt))
                prompt = np.zeros((bucket,), np.int32)
                prompt[:len(req.prompt)] = req.prompt
                admit_fn = self._admit_for(bucket)
                dparams = self._draft_params if self.cfg.speculative else {}
                args = (params, dparams, self._state, jnp.asarray(prompt),
                        jnp.int32(len(req.prompt)), jnp.int32(cached_len),
                        jnp.int32(slot), jax.random.key(req.seed),
                        jnp.float32(req.temperature),
                        jnp.int32(req.max_new_tokens))
                if _obs_enabled():
                    # per-bucket prefill cost (signature-cached: lowers
                    # once per bucket shape, then a dict hit per admit)
                    COSTS.capture(f"serving.prefill.b{bucket}", admit_fn,
                                  *args)
                t_pre = time.perf_counter()
                self._state = admit_fn(*args)
                if req.trace_id:
                    trace.record_span(
                        "serving.prefill", t_pre,
                        time.perf_counter() - t_pre, trace_id=req.trace_id,
                        parent_id=req.root_span_id, request=req.id,
                        bucket=bucket)
                if self.cfg.prefix_cache:
                    # publish every full-page chain of this prompt —
                    # entries pin their pages with their own refcount.
                    # Skipped when a reload swapped params mid-prefill:
                    # these pages hold OLD-weight K/V the just-cleared
                    # cache must not re-learn
                    with self._lock:
                        if self._params is params:
                            self._pool.insert_prefix(req.prompt, acquired,
                                                     usable)
                    if cached_len:
                        METRICS.increment("serving.prefix_hits")
            except Exception as e:
                # fail only THIS request — the slot (and any pages it
                # acquired) go back to the pool; the rest of the batch
                # still admits.  PagePoolExhausted lands here too: 429
                # backpressure, not an engine error
                if acquired:
                    self._wipe_pages(self._pool.decref(acquired))
                if self.cfg.paged:
                    # park the row on the trash page again — a stale
                    # table must never alias reallocatable pages
                    self._state = dict(
                        self._state,
                        bt=self._state["bt"].at[slot].set(self._num_pages))
                with self._lock:
                    self._free.append(slot)
                if isinstance(e, PagePoolExhausted):
                    METRICS.increment("serving.page_pool_exhausted")
                    FLIGHTREC.note_429()
                else:
                    METRICS.increment("serving.engine.errors")
                p._fail(e)
                continue
            with self._lock:
                self._slots[slot] = _Slot(pending=p,
                                          admitted_s=time.monotonic(),
                                          generation=gen, loaded_step=lstep)
                self._slot_pages[slot] = acquired
                self._admitted += 1
            METRICS.increment("serving.admitted")
            self._publish_kv_gauges()

    # ------------------------------------------- disagg tier (DESIGN.md §27)
    @property
    def page_pool(self) -> PagePool | None:
        """The host-side page pool (None on dense engines).  The decode
        half of a migration claims and allocates against it — but only
        through the KVMigrator's export/import seams (graftlint DG01)."""
        return self._pool

    def prefill(self, prompt, max_new_tokens: int, temperature: float = 0.0,
                seed: int = 0, eos_id: int | None = None) -> PrefillRecord:
        """Prefill-ONLY admission (the prefill tier's entire job): fill
        the request's KV pages through the SAME compiled admit path a
        colocated request uses — numerics cannot diverge — then release
        the slot without decoding a single token.  Returns a
        :class:`PrefillRecord` owning one refcount per page: the atomic
        handoff unit the KVMigrator exports to a decode engine.

        Requires a paged engine with NO serve thread running (a
        ``role='prefill'`` engine never starts one): ``_state`` is owned
        by whichever worker holds ``_prefill_lock``.
        """
        if not self.cfg.paged:
            raise ValueError("prefill-only requires paged=True — the "
                             "migration unit is a KV page")
        if self._thread is not None:
            raise RuntimeError("prefill() needs exclusive ownership of the "
                               "device state — stop the serve loop first "
                               "(role='prefill' engines never start one)")
        cfg = self.model.cfg
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if any(not 0 <= t < cfg.vocab_size for t in prompt):
            raise ValueError(f"prompt token out of range [0, {cfg.vocab_size})")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new_tokens > cfg.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds max_len ({cfg.max_len})")
        with self._prefill_lock, allow_transfers(), \
                METRICS.time("serving.prefill_only"):
            with self._lock:
                # a prefill-role engine has no serve loop to reach the
                # all-slots-free fence — prefill entry IS that fence
                applied = self._try_apply_staged_locked()
            if applied:
                self._publish_generation_gauges()
            self._drain_pending_wipe()
            with self._lock:
                if not self._free:
                    raise QueueFull("no free slot for prefill")
                slot = self._free.pop()
                params = self._params
                gen = self._generation
            acquired: list[int] = []
            try:
                cached_len = 0
                usable = len(prompt) - 1
                if self.cfg.prefix_cache:
                    # atomic with a params/generation re-capture, exactly
                    # like _admit: an aliased prefix can never mix
                    # weights with the prefill that extends it
                    with self._lock:
                        params = self._params
                        gen = self._generation
                        shared, cached_len = self._pool.lookup_prefix(
                            prompt, usable)
                    acquired.extend(shared)
                need = -(-(len(prompt) + max_new_tokens) // self._page_size)
                acquired.extend(self._pool.alloc(need - len(acquired)))
                row = acquired + [self._num_pages] * (
                    self._pages_per_slot - len(acquired))
                # graftlint: disable=LK01 — _state is prefill-lock-owned:
                # role='prefill' engines never start a serve thread, and
                # _prefill_lock serializes every prefill worker
                self._state = dict(
                    self._state,
                    bt=self._state["bt"].at[slot].set(
                        jnp.asarray(row, jnp.int32)))
                bucket = self._prompt_bucket(len(prompt))
                padded = np.zeros((bucket,), np.int32)
                padded[:len(prompt)] = prompt
                admit_fn = self._admit_for(bucket)
                dparams = self._draft_params if self.cfg.speculative else {}
                self._state = admit_fn(
                    params, dparams, self._state, jnp.asarray(padded),
                    jnp.int32(len(prompt)), jnp.int32(cached_len),
                    jnp.int32(slot), jax.random.key(int(seed)),
                    jnp.float32(temperature), jnp.int32(max_new_tokens))
                if self.cfg.prefix_cache:
                    with self._lock:
                        if self._params is params:
                            self._pool.insert_prefix(prompt, acquired,
                                                     usable)
                    if cached_len:
                        METRICS.increment("serving.prefix_hits")
            except Exception:
                if acquired:
                    self._wipe_pages(self._pool.decref(acquired))
                self._state = dict(
                    self._state,
                    bt=self._state["bt"].at[slot].set(self._num_pages))
                with self._lock:
                    self._free.append(slot)
                raise
            # release the slot WITHOUT decoding: deactivate the row and
            # park its block table back on the trash page.  The pages
            # stay pinned by the record's refcounts — that handoff (not
            # the slot) is what migrates
            self._state = dict(
                self._state,
                active=self._state["active"].at[slot].set(False),
                bt=self._state["bt"].at[slot].set(self._num_pages))
            with self._lock:
                self._free.append(slot)
            METRICS.increment("serving.prefills")
            return PrefillRecord(
                prompt=prompt, max_new_tokens=int(max_new_tokens),
                temperature=float(temperature), seed=int(seed),
                eos_id=(eos_id if eos_id is not None
                        else self.cfg.default_eos_id),
                pages=acquired, cached_len=cached_len, generation=gen)

    def release_prefill(self, record: PrefillRecord) -> None:
        """Consume a :class:`PrefillRecord` without migrating it (abort
        path, chaos-killed worker): decref its pages and wipe the ones
        that died.  Safe only where :meth:`prefill` is safe — no serve
        thread owns ``_state``."""
        if not record.pages:
            return
        with self._prefill_lock, allow_transfers():
            pages, record.pages = record.pages, []
            self._wipe_pages(self._pool.decref(pages))

    def read_pages(self, ids) -> list[dict]:
        """Host copies of the given physical pages: one dict per layer
        mapping the pool's array names (``k``/``v``, plus
        ``k_scale``/``v_scale`` under kv_quant) to an ``(n, ...)``
        ndarray — a migration export's byte payload.  int8/GQA layouts
        ride through verbatim: whatever the pool stores is what moves,
        so the decode-side scatter is byte-identical."""
        if not self.cfg.paged:
            raise ValueError("read_pages needs a paged engine")
        with self._prefill_lock, allow_transfers():
            idx = jnp.asarray(list(ids), jnp.int32)
            return [{name: np.asarray(arr[idx])
                     for name, arr in layer.items()}
                    for layer in self._state["pages"]]

    def queue_wipe(self, pages: list[int]) -> None:
        """Hand quarantined pages (refcount already zero, off the free
        list) to the serve thread for zeroing — the migration-abort
        release: the KVMigrator cannot touch device state it does not
        own, and a page must never become allocatable before the serve
        thread wipes it (wipe-before-reallocatable, DESIGN.md §17)."""
        if not pages:
            return
        with self._lock:
            self._pending_wipe.extend(pages)
        self._queue.wake()

    def admit_from_pages(self, pending: PendingResult, *, pages: list[int],
                         uploads: list,
                         generation: int | None = None) -> MigrationTicket:
        """Queue a migrated request for admission into the continuous
        batch — the serve thread installs it between decode segments
        (:meth:`_drain_migrated`), so a migration never stalls in-flight
        decode slots.

        ``pages`` (block-table order) must already hold one refcount
        each on THIS engine's pool — the KVMigrator's hash-only claims
        plus its fresh allocations.  Ownership transfers to the engine
        atomically with the queue append: whatever happens next (admit,
        generation-mismatch reject, stop, crash) the engine releases
        them exactly once.  ``uploads`` carries device bytes only for
        pages that were actually moved; deduped pages are already
        resident.  Returns a :class:`MigrationTicket` resolved at the
        drain fence."""
        if not self.cfg.paged:
            raise ValueError("admit_from_pages needs a paged engine")
        if self.cfg.role == "prefill":
            raise ValueError("a prefill-role engine cannot decode")
        req: GenerateRequest = pending.request
        need = -(-(len(req.prompt) + req.max_new_tokens) // self._page_size)
        if len(pages) != need or need > self._pages_per_slot:
            raise ValueError(
                f"page count {len(pages)} does not cover prompt+budget "
                f"(need {need}, pages_per_slot {self._pages_per_slot})")
        ticket = MigrationTicket()
        with self._lock:
            self._migrated_in.append(_MigratedIn(
                pending=pending, pages=list(pages), uploads=list(uploads),
                generation=generation, ticket=ticket))
        self._queue.wake()   # break the serve loop's idle wait
        return ticket

    def _drain_migrated(self) -> None:
        """Serve-thread drain of :meth:`admit_from_pages` records: one
        free slot per record, between decode segments.  A record whose
        claim generation no longer matches (a reload applied since the
        KVMigrator planned the transfer) is REJECTED — pages released,
        ticket failed — because its deduped pages hold old-generation
        K/V; the scheduler requeues and re-migrates under the new
        weights.  Reject, never corrupt."""
        while True:
            with self._lock:
                if not self._migrated_in or not self._free \
                        or self._staged is not None:
                    return
                rec = self._migrated_in.pop(0)
                slot = self._free.pop()
                gen, lstep = self._generation, self._loaded_step
            with allow_transfers(), trace.span("serving.admit_migrated"):
                ok = (rec.generation is None or rec.generation == gen) \
                    and not rec.pending.done()
                if not ok:
                    # REJECT, do not fail: the pending handle stays open
                    # so the migrator can re-plan under the new weights
                    # and hand the same request back — the caller only
                    # ever sees a completion or a terminal failure
                    self._wipe_pages(self._pool.decref(rec.pages))
                    with self._lock:
                        self._free.append(slot)
                    rec.ticket._resolve(
                        False, "request done" if rec.pending.done()
                        else "weight generation moved since migration plan")
                    continue
                try:
                    self._admit_migrated(rec, slot)
                except Exception as e:
                    self._wipe_pages(self._pool.decref(rec.pages))
                    self._state = dict(
                        self._state,
                        bt=self._state["bt"].at[slot].set(self._num_pages),
                        active=self._state["active"].at[slot].set(False))
                    with self._lock:
                        self._free.append(slot)
                    rec.ticket._resolve(False, str(e))
                    rec.pending._fail(e)
                    METRICS.increment("serving.engine.errors")
                    continue
                with self._lock:
                    self._slots[slot] = _Slot(
                        pending=rec.pending, admitted_s=time.monotonic(),
                        generation=gen, loaded_step=lstep)
                    self._slot_pages[slot] = rec.pages
                    self._admitted += 1
                rec.ticket._resolve(True)
                METRICS.increment("serving.admitted")
                self._publish_kv_gauges()

    def _admit_migrated(self, rec: _MigratedIn, slot: int) -> None:
        """Install a migrated request into ``slot``: upload the moved
        page bytes (deduped pages are already resident — that is the
        point), point the block-table row at the pages, and write the
        same host-side admission state the compiled admit fn would have
        produced — WITHOUT re-running prefill FLOPs (the jitted admit
        recomputes the whole prompt; skipping that is migration's win).
        The RNG key is seeded exactly as colocated admission seeds it,
        so the decode draw stream is token-identical.  Speculative
        engines additionally rebuild the slot's draft cache with a
        draft-only prefill: draft-sized cost, parity-neutral (the draft
        only ever decides accept length, never which tokens emit)."""
        cfg = self.model.cfg
        req: GenerateRequest = rec.pending.request
        p_len = len(req.prompt)
        st = self._state
        if rec.uploads:
            ids = jnp.asarray([pid for pid, _ in rec.uploads], jnp.int32)
            new_pages = []
            for li, layer in enumerate(st["pages"]):
                upd = {}
                for name, arr in layer.items():
                    vals = np.stack([u[1][li][name] for u in rec.uploads])
                    upd[name] = arr.at[ids].set(
                        jnp.asarray(vals).astype(arr.dtype))
                new_pages.append(upd)
            st = dict(st, pages=new_pages)
        row = rec.pages + [self._num_pages] * (
            self._pages_per_slot - len(rec.pages))
        padded = np.zeros((cfg.max_len,), np.int32)
        padded[:p_len] = req.prompt
        kd = jax.random.key_data(st["keys"]).at[slot].set(
            jax.random.key_data(jax.random.key(req.seed)))
        self._state = dict(
            st,
            bt=st["bt"].at[slot].set(jnp.asarray(row, jnp.int32)),
            toks=st["toks"].at[slot].set(jnp.asarray(padded)),
            # identical to compiled admission: prefill covered positions
            # [0, p_len-1); the first decode step consumes token p_len-1
            pos=st["pos"].at[slot].set(p_len - 1),
            limit=st["limit"].at[slot].set(p_len - 1 + req.max_new_tokens),
            temp=st["temp"].at[slot].set(float(req.temperature)),
            keys=jax.random.wrap_key_data(kd),
            active=st["active"].at[slot].set(True))
        if self.cfg.speculative:
            bucket = self._prompt_bucket(p_len)
            pad_b = np.zeros((bucket,), np.int32)
            pad_b[:p_len] = req.prompt
            draft_fn = self._draft_prefill_for(bucket)
            self._state = dict(
                self._state,
                draft_cache=draft_fn(self._draft_params,
                                     self._state["draft_cache"],
                                     jnp.asarray(pad_b), jnp.int32(p_len),
                                     jnp.int32(slot)))
        if self.cfg.prefix_cache:
            # publish the migrated prompt's chains on the DECODE pool —
            # the next migration of this prefix is a hash-only claim.
            # Generation already matched at the drain fence, and a swap
            # cannot apply while this slot is out of _free
            with self._lock:
                self._pool.insert_prefix(req.prompt, rec.pages, p_len - 1)

    def _draft_prefill_for(self, bucket: int) -> Callable:
        """Draft-ONLY prefill for one bucket (speculative migrated
        admission): the target pages arrived by migration, but the draft
        cache is local state — rebuild just it, at draft-model cost."""
        with self._lock:
            cached = self._draft_prefill_fns.get(bucket)
        if cached is not None:
            return cached
        dcfg = self._draft_model.cfg

        def draft_admit(dparams, dcache, prompt, p_len, slot):
            dc1 = init_decode_cache(dcfg, 1)
            last = jnp.maximum(p_len - 2, 0)

            def body(i, dc):
                ii = jnp.minimum(i, last)
                tok_i = lax.dynamic_slice(prompt, (ii,), (1,))
                _, dc_new = decode_step(dparams, dc, tok_i, ii, dcfg)
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(i < p_len - 1, a, b), dc_new, dc)

            dc1 = lax.fori_loop(0, bucket, body, dc1)
            return [
                {"k": lax.dynamic_update_slice_in_dim(c["k"], c1["k"],
                                                      slot, axis=0),
                 "v": lax.dynamic_update_slice_in_dim(c["v"], c1["v"],
                                                      slot, axis=0)}
                for c, c1 in zip(dcache, dc1)]

        draft_fn = jax.jit(draft_admit, donate_argnums=(1,))
        with self._lock:
            self._draft_prefill_fns[bucket] = draft_fn
        return draft_fn

    def _publish_kv_gauges(self) -> None:
        """Device-KV footprint gauges at admission/eviction fences: pages
        in use (shared pages count ONCE — that is the point), bytes, and
        bytes per occupied slot vs the dense ``S*max_len`` baseline."""
        from ..ops.pallas.kv_quant import kv_itemsize
        mcfg = self.model.cfg
        bits = kv_itemsize(self.cfg.kv_quant, mcfg.dtype) * 8
        METRICS.gauge("serving.kv_quant_bits", bits)
        if self._pool is None:
            dense = (mcfg.max_len * mcfg.kv_heads * mcfg.head_dim * 2
                     * mcfg.n_layers * jnp.dtype(mcfg.dtype).itemsize)
            METRICS.gauge("serving.kv_bytes", dense * self.cfg.slots)
            METRICS.gauge("serving.kv_bytes_per_slot", dense)
            return
        in_use = self._pool.in_use()
        with self._lock:
            occupied = len(self._slots)
            slot_pages: set[int] = set()
            for pages in self._slot_pages.values():
                slot_pages.update(pages)
        METRICS.gauge("serving.kv_pages_in_use", in_use)
        METRICS.gauge("serving.kv_pages_total", self._num_pages)
        METRICS.gauge("serving.kv_page_bytes", self._page_bytes)
        METRICS.gauge("serving.prefix_hit_rate", self._pool.hit_rate())
        METRICS.gauge("serving.kv_bytes", in_use * self._page_bytes)
        # per-slot cost counts pages *referenced by occupied slots* once
        # (shared prefix pages amortize — that is the point); cache pins
        # with no live reader are capacity (kv_bytes), not per-slot cost
        METRICS.gauge("serving.kv_bytes_per_slot",
                      len(slot_pages) * self._page_bytes / occupied
                      if occupied else 0.0)

    def _decode_segment(self) -> list:
        """Dispatch ``resolve_every`` decode steps with NO host syncs —
        the emitted-token arrays stay on device until ``_resolve``."""
        out = []
        with self._lock:
            params = self._params
            spec_on = self._spec_enabled
        # brownout level 1 applies HERE, at segment granularity: every
        # dispatch in a segment runs one path, and the swap happens at a
        # fence — in-flight slots keep exact token parity either way
        spec = self.cfg.speculative and spec_on
        step_fn = self._step_fn if spec or not self.cfg.speculative \
            else self._plain_step_fn
        dparams = self._draft_params if spec else None
        for _ in range(self.cfg.resolve_every):
            if FAULTS.check("serving.decode") is not None:
                # transient decode fault (chaos): this dispatch is skipped,
                # state is untouched, the next round retries — completions
                # stay token-identical under injection
                METRICS.increment("serving.decode.faults")
                continue
            if spec:
                # chaos serving.draft: garble every draft proposal this
                # dispatch — the traced flag shifts the draft argmax, so
                # accept length collapses but emitted tokens (drawn from
                # target logits) are untouched
                garbled = FAULTS.check("serving.draft") is not None
                if garbled:
                    METRICS.increment("serving.draft.faults")
                self._state, emitted = step_fn(
                    params, dparams, self._state,
                    self._garble[1 if garbled else 0])
            else:
                self._state, emitted = step_fn(params, self._state)
            out.append(emitted)
        METRICS.increment("serving.decode.dispatches", len(out))
        return out

    def _resolve(self, pending: list, t0: float) -> None:
        """The per-segment fence: ONE host pull for the whole segment's
        emitted tokens, then EOS/length bookkeeping and metrics."""
        if not pending:
            return
        em = np.asarray(jax.device_get(jnp.stack(pending)))  # (k, S[, W])
        if em.ndim == 2:
            em = em[:, :, None]   # non-speculative: window of one
        now = time.monotonic()
        seg_s = time.perf_counter() - t0
        n_steps = len(pending)
        METRICS.observe_many("serving.decode_step", [seg_s / n_steps] * n_steps)
        if self._decode_cost is not None and n_steps:
            # live utilization from the same cost_analysis() accounting
            # bench reports: flops of one dispatch / measured per-step time
            COSTS.publish_utilization(
                self._decode_cost, seg_s / n_steps,
                "serving.decode_mfu", "serving.decode_mbu")
        if self.cfg.speculative:
            # accepted-prefix length per dispatch per live slot (clipped
            # emissions at the limit count too — still useful signal)
            counts = (em >= 0).sum(axis=2)
            METRICS.observe_many(
                "serving.spec_accept_len",
                [float(c) for c in counts[counts > 0]],
                buckets=tuple(float(i)
                              for i in range(1, self.cfg.spec_k + 2)))
        delivered = 0
        for s in list(self._slots):
            sl = self._slots[s]
            req: GenerateRequest = sl.pending.request
            if req.trace_id:
                # one span per live slot per segment: all slots share the
                # wall-clock segment (they decode in the same dispatches)
                trace.record_span(
                    "serving.decode.segment", t0, seg_s,
                    trace_id=req.trace_id, parent_id=req.root_span_id,
                    request=req.id, slot=s, steps=n_steps)
            finish = None
            for t in em[:, s].reshape(-1):
                t = int(t)
                if t < 0:
                    continue
                delivered += 1
                if sl.first_token_s is None:
                    sl.first_token_s = now  # fence granularity, documented
                    METRICS.observe_time("serving.ttft",
                                         now - req.submitted_s)
                sl.delivered.append(t)
                if req.eos_id is not None and t == req.eos_id:
                    finish = "eos"
                    break
                if len(sl.delivered) >= req.max_new_tokens:
                    finish = "length"
                    break
            if finish is not None:
                self._evict(s, finish, now)
        if delivered:
            METRICS.increment("serving.tokens", delivered)
            if seg_s > 0:
                METRICS.gauge("serving.tokens_per_sec", delivered / seg_s)

    def _evict(self, s: int, finish: str, now: float) -> None:
        """Free slot ``s``: complete the caller, drop the host record,
        deactivate the row and release its K/V.  Dense: wipe the cache
        row.  Paged: decref the slot's pages — only pages whose refcount
        hits zero are wiped (an aliased prefix page stays live and
        intact for its other readers), and the block-table row parks on
        the trash page."""
        t_ev = time.perf_counter()
        with self._lock:
            sl = self._slots.pop(s)
            pages = self._slot_pages.pop(s, [])
            self._free.append(s)
            self._completed += 1
        # the freed row is reusable before these updates land only by
        # _admit, which runs on this same serve thread — no interleave
        if self.cfg.paged:
            self._state = dict(
                self._state,
                bt=self._state["bt"].at[s].set(self._num_pages),
                active=self._state["active"].at[s].set(False))
            self._wipe_pages(self._pool.decref(pages))
            self._publish_kv_gauges()
        else:
            mask = np.zeros((self.cfg.slots,), bool)
            mask[s] = True
            self._state = dict(
                self._state,
                cache=reset_cache_slots(self._state["cache"],
                                        jnp.asarray(mask)),
                active=self._state["active"].at[s].set(False))
        req = sl.pending.request
        METRICS.increment("serving.completed")
        METRICS.observe_time("serving.request_latency", now - req.submitted_s)
        if req.tenant:
            TENANTS.account("prompt_tokens", req.tenant, len(req.prompt))
            TENANTS.account("generated_tokens", req.tenant,
                            len(sl.delivered))
        sl.pending._complete(Completion(
            tokens=list(sl.delivered), finish_reason=finish,
            latency_s=now - req.submitted_s,
            ttft_s=(sl.first_token_s - req.submitted_s
                    if sl.first_token_s is not None else None),
            generation=sl.generation, loaded_step=sl.loaded_step))
        if req.trace_id:
            t_done = time.perf_counter()
            trace.record_span(
                "serving.emit", t_ev, t_done - t_ev, trace_id=req.trace_id,
                parent_id=req.root_span_id, request=req.id, finish=finish)
            # the request's root span: submit -> completion, parented to
            # the inbound traceparent (if any) so the HTTP client span
            # and the engine flame share one trace in Perfetto
            trace.record_span(
                "serving.request", req.submitted_perf,
                t_done - req.submitted_perf, trace_id=req.trace_id,
                parent_id=req.parent_span_id or None,
                span_id=req.root_span_id, request=req.id,
                tokens=len(sl.delivered), finish=finish,
                tenant=req.tenant or None)

    # ------------------------------------------------------------ hot reload
    def reload(self, step: int | None = None) -> int:
        """Hot swap to ``latest_valid_step()`` (or an explicit ``step`` —
        the online loop's rollback targets a specific previous
        generation) WITHOUT tearing any response: the new tree is
        restored off-thread and STAGED; the actual swap applies only at
        a resolve fence with every slot free (requests bound their own
        decode length, so the fence arrives within one request budget).
        While a swap is staged, admission pauses — queued requests wait
        and then decode wholly under the NEW generation; in-flight ones
        finish wholly under the OLD one, so every completion's
        ``generation``/``loaded_step`` stamp is exact.  Shapes are fixed
        by the config, so the swap hits the existing executables — no
        recompile.  With ``prefix_cache`` on, every cached chain is
        dropped atomically with the applied swap (its K/V was computed
        under the old weights); pages pinned only by the cache are wiped
        by the serve thread at its next fence before becoming
        allocatable again.  Returns the target step (applied, or staged
        for the next free fence)."""
        if self._ckpt is None:
            raise RuntimeError("no checkpoint attached — nothing to reload")
        target = step if step is not None else self._ckpt.latest_valid_step()
        if target is None:
            raise FileNotFoundError(
                f"no verified checkpoint under {self._ckpt.directory}")
        with self._lock:
            if target == self._loaded_step:
                # already serving it — and cancel any staged swap away
                # from it (a rollback racing an un-applied bad reload)
                self._staged = None
                return target
            if self._staged is not None and self._staged[2] == target:
                return target  # same target already parked for the fence
            template = self._raw_params
        with allow_transfers(), METRICS.time("serving.reload"):
            restored = self._ckpt.restore(template, step=target)
            new_params = self._maybe_quantize(restored["params"])
        with self._lock:
            self._staged = (restored["params"], new_params, target)
            applied = self._try_apply_staged_locked()
        METRICS.increment("serving.reloads")
        if applied:
            self._publish_generation_gauges()
        return target

    def _try_apply_staged_locked(self) -> bool:
        """Apply a staged swap iff NO request holds a slot (``_free`` at
        full capacity covers admitted-but-unregistered requests too: a
        slot pops off ``_free`` under this lock before its prefill ever
        reads params).  Caller holds ``self._lock``; gauge publication
        happens outside it (:meth:`_publish_generation_gauges`) to keep
        the registry lock un-nested."""
        if self._staged is None:
            return False
        if len(self._free) != self.cfg.slots:
            return False  # in-flight responses keep their generation
        raw, quantized, target = self._staged
        self._staged = None
        self._raw_params = raw
        self._params = quantized
        self._loaded_step = target
        self._generation += 1
        if self._pool is not None and self.cfg.prefix_cache:
            # same critical section as the swap: _admit's lookup (also
            # under this lock) can never see old-weight entries next to
            # the new params.  clear_prefix only QUARANTINES dead pages —
            # the serve thread wipes them at its next fence
            self._pending_wipe.extend(self._pool.clear_prefix())
        return True

    def _publish_generation_gauges(self) -> None:
        with self._lock:
            gen, step = self._generation, self._loaded_step
        METRICS.gauge("serving.generation", gen)
        if step is not None:
            METRICS.gauge("serving.loaded_step", step)

    # ------------------------------------------------- brownout actuators
    def set_speculative(self, enabled: bool) -> bool:
        """Brownout ladder level 1: turn speculative decoding off (or
        back on) at runtime.  Returns the new effective state.  Safe at
        any moment: the switch is read once per decode SEGMENT (a device
        fence), and the draft model only ever decided how many target
        tokens emit per dispatch — never which — so served tokens keep
        exact parity either way.  No-op on a plain engine."""
        if not self.cfg.speculative:
            return False
        with self._lock:
            self._spec_enabled = bool(enabled)
            now = self._spec_enabled
        METRICS.gauge("serving.speculative_enabled", 1.0 if now else 0.0)
        return now

    def set_max_new_cap(self, cap: int | None) -> None:
        """Brownout ladder level 2: clamp every future request's
        ``max_new_tokens`` to ``cap`` at admission (``None`` lifts the
        clamp).  In-flight requests keep their admitted budget."""
        if cap is not None and int(cap) < 1:
            raise ValueError(f"max_new cap must be >= 1, got {cap}")
        with self._lock:
            self._max_new_cap = int(cap) if cap is not None else None
        METRICS.gauge("serving.max_new_cap",
                      float(cap) if cap is not None else 0.0)

    def set_admission_hook(self, hook) -> None:
        """Install (or clear, with ``None``) an admission-side gate
        called with each validated :class:`GenerateRequest` BEFORE it
        enters the queue.  The hook rejects by raising a
        :class:`~.batcher.ServingRejected` subclass — the seam
        ``control/overload.py`` uses for per-tenant throttling and
        brownout shedding without serving importing control."""
        with self._lock:
            self._admission_hook = hook

    # ------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            out = {
                "slots": self.cfg.slots,
                "active": len(self._slots),
                "free": len(self._free),
                "queue_depth": self._queue.depth(),
                "admitted": self._admitted,
                "completed": self._completed,
                "loaded_step": self._loaded_step,
                "generation": self._generation,
                "reload_staged": self._staged is not None,
                "prefill_buckets": sorted(self._admit_fns),
                "running": self._thread is not None,
                "warmed": self._warmed,
                "role": self.cfg.role,
                "speculative_enabled": (self.cfg.speculative
                                        and self._spec_enabled),
                "max_new_cap": self._max_new_cap,
            }
        if self._pool is not None:
            out["kv_pages"] = self._num_pages
            out["kv_quant"] = self.cfg.kv_quant
            out["kv_page_bytes"] = self._page_bytes
            out["kv_pages_in_use"] = self._pool.in_use()
            out["prefix_entries"] = self._pool.prefix_entries()
            out["prefix_hit_rate"] = self._pool.hit_rate()
            hits, lookups = self._pool.hit_counts()
            out["prefix_hits"] = hits
            out["prefix_lookups"] = lookups
        return out


class BatchScorer:
    """Coalesce concurrent single-row score calls into padded device
    batches through any row-wise pure ``fn`` (``net.output``, a zoo
    model's jitted apply, a ``partial(forward_local, ...)``).

    Rows queue through the same bounded :class:`RequestQueue` as
    generation (shared backpressure semantics); the worker pads each
    batch up to a power-of-two bucket (repeating the first row — pad
    outputs are discarded) so ``fn``'s jit cache sees at most
    ``log2(max_batch)`` shapes.
    """

    def __init__(self, fn: Callable, max_batch: int = 64,
                 max_queue: int = 256, max_batch_delay_ms: float = 2.0):
        self.fn = fn
        self.max_batch = max_batch
        self._queue = RequestQueue(max_queue, max_batch_delay_ms)
        self._shape_lock = threading.Lock()
        self._row_shape: tuple | None = None  # guarded-by: self._shape_lock
        self._row_dtype = None                # guarded-by: self._shape_lock
        self._buckets: set[int] = set()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> "BatchScorer":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="serving-scorer")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        for p in self._queue.drain():
            p._fail(RuntimeError("scorer stopped before request ran"))

    def __enter__(self) -> "BatchScorer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def submit(self, x) -> PendingResult:
        x = np.asarray(x)
        # check-then-set must be atomic: two first submitters racing here
        # could each see None and publish different shapes
        with self._shape_lock:
            if self._row_shape is None:
                self._row_shape, self._row_dtype = x.shape, x.dtype
            elif x.shape != self._row_shape:
                raise ValueError(
                    f"row shape {x.shape} != first-seen {self._row_shape}")
        return self._queue.submit(ScoreRequest(x=x))

    def score(self, x, timeout: float = 30.0):
        """One row in, one output row out (blocking)."""
        return self.submit(x).result(timeout)

    def score_batch(self, xs, timeout: float = 30.0) -> np.ndarray:
        """Submit every row, gather in order — rows from concurrent
        callers interleave into shared device batches."""
        handles = [self.submit(x) for x in np.asarray(xs)]
        return np.stack([h.result(timeout) for h in handles])

    def _bucket(self, n: int) -> int:
        b = 1
        while b < n:
            b <<= 1
        return min(b, self.max_batch)

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self._queue.take(self.max_batch, block_s=0.05)
            if not batch:
                continue
            try:
                self._run(batch)
            except Exception as e:
                METRICS.increment("serving.score.errors")
                for p in batch:
                    p._fail(e)

    def _run(self, batch: list[PendingResult]) -> None:
        n = len(batch)
        bucket = self._bucket(n)
        xs = np.stack([p.request.x for p in batch])
        if bucket > n:
            xs = np.concatenate(
                [xs, np.broadcast_to(xs[:1], (bucket - n,) + xs.shape[1:])])
        if bucket not in self._buckets:
            self._buckets.add(bucket)
            METRICS.increment("serving.score.recompile")
        with METRICS.time("serving.score_batch"):
            ys = np.asarray(self.fn(xs))
        METRICS.observe_time("serving.score.batch_fill", n / bucket,
                             buckets=FILL_BUCKETS)
        METRICS.increment("serving.score.rows", n)
        for i, p in enumerate(batch):
            p._complete(ys[i])
