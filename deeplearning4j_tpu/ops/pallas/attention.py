"""Fused causal attention — the block-skipping generalization of flash.

Same flash-v2 schedule as ``ops/flash_attention.py`` (one query block per
program, K/V streamed through a running softmax in VMEM) with one
structural difference that matters for causal LM training: the key loop
stops at the causal frontier instead of streaming fully-masked blocks.
For causal attention that halves the streamed K/V traffic and the MXU
work (the lower-triangular half is all that exists), which is exactly
the regime the flagship decoder trains in — so this registers as a
separate ``attention`` candidate and has to beat flash AND ring through
the bench auto-pick rather than replacing either by fiat.

The loop bound is a traced value (``fori_loop`` lowers it to a while
loop, fine under both Mosaic and interpret mode); masking inside the
frontier block stays branch-free like flash.  Backward reuses flash's
``_blockwise_bwd`` jnp recompute — O(T) memory, no second kernel to
maintain.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..flash_attention import _blockwise_bwd, _VMEM

from . import registry

_NEG_INF = -1e30


def reference_attention(q, k, v, *, causal: bool = True):
    """Naive softmax attention on (B, T, H, D) — the jnp ground truth
    every attention candidate is gated against."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        t = q.shape[1]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal: bool,
                block_k: int, seq_len: int, scale: float):
    q = q_ref[0].astype(jnp.float32) * scale          # (BQ, D)
    bq = q.shape[0]
    qi = pl.program_id(1)
    q_pos = qi * bq + lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)

    n_k = seq_len // block_k
    if causal:
        # causal frontier: key blocks past the last query row of this
        # program are fully masked — skip them instead of streaming zeros
        n_k = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, n_k)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # (BQ, BK)
        if causal:
            k_pos = (j * block_k
                     + lax.broadcasted_iota(jnp.int32, (bq, block_k), 1))
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1))
        p = jnp.exp(s - m_new[:, None])
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=1)
        acc_new = acc * corr[:, None] + jnp.dot(
            p, v, preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((bq, q.shape[1]), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = lax.fori_loop(0, n_k, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0] = (m + jnp.log(l_safe))[:, None]


def _fused_fwd(q, k, v, causal, block_q, block_k, interpret):
    """q/k/v: (BH, T, D) -> (out (BH, T, D), lse (BH, T))."""
    bh, t, d = q.shape
    block_q = min(block_q, t)
    block_k = min(block_k, t)
    assert t % block_q == 0 and t % block_k == 0, (t, block_q, block_k)
    scale = d ** -0.5

    kernel = functools.partial(_fwd_kernel, causal=causal, block_k=block_k,
                               seq_len=t, scale=scale)
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    out, lse = pl.pallas_call(
        kernel,
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0), **mem),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0), **mem),
            pl.BlockSpec((1, t, d), lambda b, i: (b, 0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0), **mem),
            # trailing singleton: same Mosaic last-two-dims constraint as
            # the flash kernel's lse output
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[..., 0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _fused_bhtd(q, k, v, causal, block_q, block_k, interpret):
    out, _ = _fused_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _fused_bhtd_fwd(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _fused_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _fused_bhtd_bwd(causal, block_q, block_k, interpret, res, do):
    q, k, v, out, lse = res
    return _blockwise_bwd(q, k, v, out, lse, do, causal, block_k)


_fused_bhtd.defvjp(_fused_bhtd_fwd, _fused_bhtd_bwd)


def fused_attention(q, k, v, *, causal: bool = True, block_q: int = 128,
                    block_k: int = 128, interpret: bool | None = None):
    """Block-skipping fused attention for (B, T, H, D) tensors.

    Public API mirrors :func:`ops.flash_attention.flash_attention`;
    ``interpret=None`` auto-selects Pallas interpret mode off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, t, h, d = q.shape

    def to_bhtd(x):
        return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)

    out = _fused_bhtd(to_bhtd(q), to_bhtd(k), to_bhtd(v),
                      causal, block_q, block_k, interpret)
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _ring_single_shard(q, k, v, *, causal: bool = True, **_):
    """The XLA incumbent as a candidate: single-shard ring attention
    (lazy import — models must not load at registry import time)."""
    from ...models.transformer import ring_attention
    return ring_attention(q, k, v, n_sp=1, sp_axis=None, causal=causal,
                          t_local=q.shape[1])


registry.register(registry.KernelCandidate(
    kind="attention", name="fused", fn=fused_attention,
    reference=reference_attention,
    blocks=({"block_q": 128, "block_k": 128},
            {"block_q": 256, "block_k": 128},
            {"block_q": 128, "block_k": 256},
            {"block_q": 256, "block_k": 256}),
    # fwd/bwd max abs error vs reference_attention on the battery shapes
    # (f32; matches the flash_check gate bench has always applied)
    tolerances={"max_err": 0.05},
))

registry.register(registry.KernelCandidate(
    kind="attention", name="ring", fn=_ring_single_shard,
    reference=reference_attention, source="xla",
))
