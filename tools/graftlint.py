"""graftlint CLI — static JAX/TPU hazard analysis for this repo.

Usage (from the repo root):

    python -m tools.graftlint --check [PATHS...]     # CI gate: fail on NEW
    python -m tools.graftlint [PATHS...]             # report everything
    python -m tools.graftlint --json [PATHS...]      # machine-readable
    python -m tools.graftlint --diff HEAD~1          # only git-changed files
    python -m tools.graftlint --write-baseline       # accept current state
    python -m tools.graftlint --rules                # list every rule

Defaults: PATHS = ``deeplearning4j_tpu``, baseline =
``graftlint.baseline.json`` at the repo root.  ``--check`` exits 1 when
any finding is neither suppressed inline (``# graftlint: disable=RULE``)
nor carried in the baseline; it also exits 1 on unparseable files.
``--stale`` lists baseline entries whose finding no longer fires (fixed
hazards whose ledger entry should be deleted).  ``--diff REF`` narrows
the run to ``.py`` files changed since REF (per ``git diff``), which is
the fast local pre-commit loop; when git is unavailable or REF is
unknown it falls back to the full tree so CI semantics never weaken.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/graftlint.py` direct runs
    sys.path.insert(0, _REPO_ROOT)

from deeplearning4j_tpu.analysis import (  # noqa: E402
    Analyzer,
    Baseline,
    active,
    all_rules,
    emit_metrics,
    summarize,
    to_json,
    to_text,
)

DEFAULT_BASELINE = os.path.join(_REPO_ROOT, "graftlint.baseline.json")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based JAX/TPU hazard analyzer (HS01 host syncs, "
                    "RC01 recompiles, RNG01 key reuse, DON01 use-after-"
                    "donate, TB01 traced branches, HOT02 uninstrumented "
                    "hot loops, LK01-LK03/TH01 concurrency, SH01-SH04/NM01 "
                    "sharding + numerics; bare --rules prints the full "
                    "table)")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to analyze (default: deeplearning4j_tpu)")
    p.add_argument("--check", action="store_true",
                   help="exit 1 on any non-suppressed, non-baselined finding")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit one machine-readable JSON report on stdout")
    p.add_argument("--baseline", default=DEFAULT_BASELINE,
                   help="baseline file (default: %(default)s)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write all current active findings to the baseline "
                        "(with TODO justifications) and exit 0")
    p.add_argument("--stale", action="store_true",
                   help="also report baseline entries that no longer fire")
    p.add_argument("--all", action="store_true", dest="show_all",
                   help="text mode: show suppressed/baselined findings too")
    p.add_argument("--no-metrics", action="store_true",
                   help="skip publishing graftlint.violations.* gauges")
    p.add_argument("--rules", nargs="?", const="", default=None,
                   help="comma-separated rule ids to run (default: all); "
                        "bare --rules lists every registered rule and exits")
    p.add_argument("--diff", metavar="REF", default=None,
                   help="only lint .py files changed vs the given git ref "
                        "(falls back to the full tree if git fails)")
    return p


def _changed_files(ref: str, paths: list[str]) -> list[str] | None:
    """``.py`` files changed since ``ref`` (per git, including uncommitted
    edits), restricted to the requested ``paths``.  Returns ``None`` when
    git is unavailable or the ref does not resolve — caller falls back to
    the full-tree walk so ``--diff`` can only narrow, never miss."""
    try:
        out = subprocess.run(
            ["git", "diff", "--name-only", "-z", ref, "--"],
            cwd=_REPO_ROOT, capture_output=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if out.returncode != 0:
        return None
    changed = [os.path.join(_REPO_ROOT, p)
               for p in out.stdout.decode("utf-8", "replace").split("\0")
               if p.endswith(".py")]
    roots = [os.path.abspath(p) for p in paths]
    kept = []
    for f in changed:
        af = os.path.abspath(f)
        if any(af == r or af.startswith(r + os.sep) for r in roots):
            kept.append(f)
    return [f for f in kept if os.path.isfile(f)]


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    paths = args.paths or [os.path.join(_REPO_ROOT, "deeplearning4j_tpu")]

    if args.diff is not None:
        changed = _changed_files(args.diff, paths)
        if changed is None:
            print(f"graftlint: --diff {args.diff}: git unavailable or ref "
                  f"unknown; falling back to full tree", file=sys.stderr)
        elif not changed:
            print(f"graftlint: no .py files changed vs {args.diff}")
            return 0
        else:
            paths = changed

    if args.rules == "":          # bare --rules: print the registry
        for rid, rule in sorted(all_rules().items()):
            print(f"{rid}  {rule.title}")
        return 0

    rules = None
    if args.rules:
        wanted = {r.strip().upper() for r in args.rules.split(",")}
        registry = all_rules()
        unknown = wanted - set(registry)
        if unknown:
            print(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}; "
                  f"known: {', '.join(sorted(registry))}", file=sys.stderr)
            return 2
        rules = [registry[r] for r in sorted(wanted)]

    baseline = Baseline.load(args.baseline)
    analyzer = Analyzer(rules=rules, baseline=baseline, root=_REPO_ROOT)
    findings = analyzer.analyze_paths(paths)

    if args.write_baseline:
        Baseline.from_findings(active(findings)).save(args.baseline)
        print(f"graftlint: wrote {len(active(findings))} entries to "
              f"{args.baseline}")
        return 0

    if not args.no_metrics:
        try:
            emit_metrics(findings, skipped=analyzer.skipped_files)
        except Exception:
            pass  # metrics are best-effort; the lint verdict is the product

    new = active(findings)
    if args.as_json:
        payload = to_json(findings, errors=analyzer.errors)
        payload["visited_files"] = analyzer.visited_files
        payload["skipped_files"] = analyzer.skipped_files
        if args.stale:
            payload["stale_baseline_entries"] = baseline.stale_entries(findings)
        print(json.dumps(payload, indent=2))
    else:
        text = to_text(findings, show_all=args.show_all)
        if text:
            print(text)
        for err in analyzer.errors:
            print(f"graftlint: parse error: {err}", file=sys.stderr)
        if args.stale:
            for e in baseline.stale_entries(findings):
                print(f"graftlint: stale baseline entry "
                      f"{e['rule']} {e['path']}: {e['code']!r}")
        s = summarize(findings)
        print(f"graftlint: {s['total']} finding(s) — {s['active']} active, "
              f"{s['suppressed']} suppressed, {s['baselined']} baselined")

    if args.check and (new or analyzer.errors):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
