"""Transformer LM/encoder — the flagship model, explicit-SPMD edition.

Role in the framework (BASELINE.json north star: BERT-base fine-tune at
≥35% MFU on v5e): the reference has no attention models (SURVEY.md §5.7 —
sequence handling tops out at LSTM BPTT), but its *capability obligation* at
modern scale is long-sequence training sharded over a pod.  This module is
the TPU-first design for that: ONE train step, manually sharded with
``shard_map`` over a (dp, sp, tp) mesh, every collective explicit:

- **dp** data parallel: batch sharded; gradient `pmean` after backward.
- **tp** tensor parallel (Megatron-style): attention heads and FFN hidden
  sharded; one `psum` after the attention output projection and one after
  FFN's second matmul (forward); autodiff transposes them into the matching
  backward collectives.
- **sp** sequence/context parallel: sequence sharded; attention runs as
  **ring attention** — K/V blocks rotate around the ``sp`` ring via
  `ppermute` with a flash-style running-softmax (log-sum-exp) accumulator,
  so no device ever materializes the full (T, T) score matrix and sequence
  length scales with the ring size.

Compute is bfloat16 on the MXU with float32 params/accumulators (the
softmax statistics and loss reductions stay f32).
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover - older jax spells the flag check_rep
    from functools import wraps

    from jax.experimental.shard_map import shard_map as _sm_old

    @wraps(_sm_old)
    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _sm_old(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                       check_rep=check_vma)

from ..parallel.mesh import DP, SP, TP

Params = Any


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32768
    d_model: int = 768
    n_heads: int = 12
    n_layers: int = 12
    d_ff: int = 3072
    max_len: int = 2048
    causal: bool = True              # False = BERT-style bidirectional
    tie_embeddings: bool = True
    dtype: Any = jnp.bfloat16        # MXU compute dtype
    param_dtype: Any = jnp.float32
    remat: bool = True               # jax.checkpoint each block (HBM for FLOPs)
    attention: str = "ring"          # "ring" (default) | any registered
    #                                  ops/pallas attention candidate
    #                                  ("flash", "fused") — Pallas kernels
    #                                  are single-shard only and opt-in
    #                                  until the bench auto-pick adopts them
    fused_ln: bool = False           # fuse the mid-block residual+LN seam
    #                                  through ops/pallas/layernorm — one
    #                                  VMEM pass instead of two HBM
    #                                  round-trips; bench-gated opt-in
    xent_impl: str = "scan"          # "scan" (chunked lax.scan, default) |
    #                                  "blocked" (ops/pallas/xent streaming
    #                                  kernel for ALL chunked cases; the
    #                                  near-prime fallback always streams
    #                                  through the blocked kernel)
    xent_chunk: int = 2048           # LM-loss token-chunk size; 0 disables.
    #                                  Full (B*T, V) f32 logits are the
    #                                  biggest HBM tensor in training (4.3 GB
    #                                  at batch 64/seq 512/32k vocab);
    #                                  chunking + per-chunk remat streams
    #                                  them through VMEM-sized pieces instead
    n_kv_heads: int | None = None    # GQA/MQA: K/V heads shared by groups of
    #                                  n_heads // n_kv_heads query heads.
    #                                  None (or == n_heads) keeps today's
    #                                  full-attention layout byte-identical;
    #                                  1 is MQA.  Cache shapes (dense rows
    #                                  and page pools) are sized by this, so
    #                                  it divides serving.kv_bytes_per_slot
    #                                  directly (DESIGN.md §20)

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads

    @property
    def kv_heads(self) -> int:
        """Effective K/V head count (== n_heads without GQA)."""
        kv = self.n_kv_heads if self.n_kv_heads is not None else self.n_heads
        assert 1 <= kv <= self.n_heads and self.n_heads % kv == 0, (
            f"n_kv_heads={kv} must divide n_heads={self.n_heads}")
        return kv

    def flops_per_token(self) -> float:
        """Approximate training FLOPs per token (fwd+bwd ≈ 6*N params +
        attention term; used for MFU accounting)."""
        n_params = (self.vocab_size * self.d_model
                    + self.n_layers * (4 * self.d_model * self.d_model
                                       + 2 * self.d_model * self.d_ff)
                    + self.max_len * self.d_model)
        attn = self.n_layers * 2 * self.max_len * self.d_model  # per-token qk+av
        return 6.0 * (n_params + attn)


# --------------------------------------------------------------------------- params

def init_params(key, cfg: TransformerConfig) -> Params:
    """Scaled-normal init; qkv packed (D, 3, H, Dh), out proj (H, Dh, D).

    Under GQA (``cfg.kv_heads < n_heads``) the packed ``wqkv`` splits into
    ``wq`` (D, H, Dh) and ``wkv`` (D, 2, Kv, Dh) — a DIFFERENT tree, so
    key-presence dispatch in the forward paths is static at trace time;
    the equal-heads tree (and its RNG draws) stays byte-identical to
    every pre-GQA checkpoint."""
    pd = cfg.param_dtype
    d, h, dh, f = cfg.d_model, cfg.n_heads, cfg.head_dim, cfg.d_ff
    kv = cfg.kv_heads
    keys = jax.random.split(key, cfg.n_layers + 3)

    def norm(k, shape, scale):
        return (scale * jax.random.normal(k, shape)).astype(pd)

    layers = []
    for i in range(cfg.n_layers):
        lk = jax.random.split(keys[i], 4)
        if kv == h:
            qkv_leaves = {"wqkv": norm(lk[0], (d, 3, h, dh), d ** -0.5)}
        else:
            qk, kk = jax.random.split(lk[0])
            qkv_leaves = {"wq": norm(qk, (d, h, dh), d ** -0.5),
                          "wkv": norm(kk, (d, 2, kv, dh), d ** -0.5)}
        layers.append({
            "ln1_scale": jnp.ones((d,), pd), "ln1_bias": jnp.zeros((d,), pd),
            **qkv_leaves,
            "wo": norm(lk[1], (h, dh, d), (h * dh) ** -0.5),
            "ln2_scale": jnp.ones((d,), pd), "ln2_bias": jnp.zeros((d,), pd),
            "w1": norm(lk[2], (d, f), d ** -0.5),
            "b1": jnp.zeros((f,), pd),
            "w2": norm(lk[3], (f, d), f ** -0.5),
            "b2": jnp.zeros((d,), pd),
        })
    params = {
        "tok_embed": norm(keys[-3], (cfg.vocab_size, d), 0.02),
        "pos_embed": norm(keys[-2], (cfg.max_len, d), 0.02),
        "final_ln_scale": jnp.ones((d,), pd),
        "final_ln_bias": jnp.zeros((d,), pd),
        "layers": layers,
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = norm(keys[-1], (d, cfg.vocab_size), d ** -0.5)
    return params


def param_specs(cfg: TransformerConfig) -> Params:
    """PartitionSpecs per leaf: heads/ffn-hidden sharded over tp, the rest
    replicated (sharded-embedding variants come with the ep axis later)."""
    layer = {
        "ln1_scale": P(), "ln1_bias": P(),
        # GQA trees stay replicated: the shard-offset-aware head-group map
        # tp would need is not implemented (asserted in _block), and GQA's
        # payoff is serving-side cache bytes, not training-side tp
        **({"wqkv": P(None, None, TP, None)} if cfg.kv_heads == cfg.n_heads
           else {"wq": P(), "wkv": P()}),
        "wo": P(TP, None, None),
        "ln2_scale": P(), "ln2_bias": P(),
        "w1": P(None, TP), "b1": P(TP),
        "w2": P(TP, None), "b2": P(),
    }
    specs = {
        "tok_embed": P(), "pos_embed": P(),
        "final_ln_scale": P(), "final_ln_bias": P(),
        "layers": [dict(layer) for _ in range(cfg.n_layers)],
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P()
    return specs


# --------------------------------------------------------------------------- tp boundary ops

# Megatron-style f/g pair: explicit AD-correct boundaries for the tensor-
# parallel branch.  Under ``shard_map(check_vma=False)`` plain `psum` has an
# ambiguous transpose (replicated vs partial cotangents), so each tp branch
# is entered through ``copy_to_tp`` (identity fwd / psum bwd — collects the
# per-head/per-ffn-shard cotangent contributions exactly once) and exited
# through ``reduce_from_tp`` (psum fwd / identity bwd).  With these, local
# `jax.grad` produces full, replica-identical gradients for replicated
# params and correct shard-local gradients for tp-sharded params.

@partial(jax.custom_vjp, nondiff_argnums=(1,))
def copy_to_tp(x, axis):
    return x


def _copy_fwd(x, axis):
    return x, None


def _copy_bwd(axis, _, ct):
    return (lax.psum(ct, axis),)


copy_to_tp.defvjp(_copy_fwd, _copy_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def reduce_from_tp(x, axis):
    return lax.psum(x, axis)


def _reduce_fwd(x, axis):
    return lax.psum(x, axis), None


def _reduce_bwd(axis, _, ct):
    return (ct,)


reduce_from_tp.defvjp(_reduce_fwd, _reduce_bwd)


# --------------------------------------------------------------------------- math

def _layernorm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    y = (x32 - mu) * lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _attend_block(q, k, v, q_pos, k_pos, causal, acc, m, l):
    """One flash-style block update.

    q: (B, Tq, Hl, Dh); k/v: (B, Tk, Hl, Dh); acc: (B, Tq, Hl, Dh) f32;
    m/l: (B, Tq, Hl) running max / denominator (f32).
    """
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bqhk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        mask = q_pos[None, :, None, None] >= k_pos[None, None, None, :]
        s = jnp.where(mask, s, -jnp.inf)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # guard rows with no valid keys yet (all -inf)
    m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    p = jnp.where(jnp.isfinite(s), p, 0.0)
    corr = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
    l_new = l * corr + p.sum(axis=-1)
    acc_new = acc * corr[..., None] + jnp.einsum(
        "bqhk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    return acc_new, m_new, l_new


def ring_attention(q, k, v, *, n_sp: int, sp_axis: str | None, causal: bool,
                   t_local: int):
    """Blockwise ring attention over the sp axis (Liu et al. style).

    Inside ``shard_map``: each device holds local Q/K/V of t_local tokens;
    K/V rotate ``n_sp`` times via ``ppermute`` while a running-softmax
    accumulates — peak memory O(T_local^2) scores, full-sequence semantics.
    With n_sp == 1 this degenerates to single-block flash attention.
    """
    B, Tq, Hl, Dh = q.shape
    my = lax.axis_index(sp_axis) if sp_axis else 0
    q_pos = my * t_local + jnp.arange(t_local)

    acc = jnp.zeros((B, Tq, Hl, Dh), jnp.float32)
    m = jnp.full((B, Tq, Hl), -jnp.inf, jnp.float32)
    l = jnp.zeros((B, Tq, Hl), jnp.float32)

    def body(i, carry):
        k_blk, v_blk, acc, m, l = carry
        src = (my - i) % n_sp
        k_pos = src * t_local + jnp.arange(t_local)
        acc, m, l = _attend_block(q, k_blk, v_blk, q_pos, k_pos, causal, acc, m, l)
        if n_sp > 1:
            perm = [(j, (j + 1) % n_sp) for j in range(n_sp)]
            k_blk = lax.ppermute(k_blk, sp_axis, perm)
            v_blk = lax.ppermute(v_blk, sp_axis, perm)
        return (k_blk, v_blk, acc, m, l)

    if n_sp > 1:
        # rotate n_sp-1 times; unrolled python loop keeps ppermute count static
        carry = (k, v, acc, m, l)
        for i in range(n_sp):
            carry = body(i, carry)
        _, _, acc, m, l = carry
    else:
        _, _, acc, m, l = body(0, (k, v, acc, m, l))

    out = acc / jnp.maximum(l[..., None], 1e-30)
    return out.astype(q.dtype)


def _qkv_proj(lp, h, dt):
    """Project normed activations ``h`` (..., D) to ``(q, k, v)`` heads.

    Classic trees carry the packed ``wqkv`` and run the exact einsum the
    pre-GQA code always did (the bitwise-parity path); GQA trees carry
    ``wq``/``wkv`` and produce k/v with ``n_kv_heads`` heads.  The key
    check is static at trace time (same idiom as ``w1_q`` in ``_ffn``)."""
    if "wkv" in lp:
        q = jnp.einsum("...d,dhe->...he", h.astype(dt), lp["wq"].astype(dt))
        kv = jnp.einsum("...d,dshe->...she", h.astype(dt),
                        lp["wkv"].astype(dt))
        return q, kv[..., 0, :, :], kv[..., 1, :, :]
    qkv = jnp.einsum("...d,dshe->...she", h.astype(dt), lp["wqkv"].astype(dt))
    return qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]


def repeat_kv_heads(x, n_rep: int):
    """Head-group broadcast for GQA: repeat the K/V head axis (always
    axis -2, for both (..., T, K, Dh) caches and (..., K, Dh) tokens) so
    query head ``h`` reads shared head ``h // n_rep``.  ``n_rep == 1``
    returns ``x`` untouched — the bitwise-parity guarantee for classic
    trees."""
    if n_rep == 1:
        return x
    return jnp.repeat(x, n_rep, axis=-2)


def _ffn(lp, h, dt):
    """The FFN sublayer body on (..., D) activations — shared verbatim by
    the training ``_block`` and the incremental ``decode_step`` so the two
    paths cannot silently diverge (tp boundaries stay with the caller).

    A serving tree quantized by ``quantize_params_for_decode`` carries
    ``w1_q``/``w2_q`` (int8 + per-channel scales) instead of w1/w2; the
    key check is static at trace time, so training trees compile exactly
    the code they always did."""
    if "w1_q" in lp:
        from ..ops.pallas.matmul_int8 import int8_matmul
        u = int8_matmul(h.astype(dt), lp["w1_q"]).astype(dt)
        u = jax.nn.gelu(u + lp["b1"].astype(dt))
        return int8_matmul(u, lp["w2_q"]).astype(dt)
    u = jnp.einsum("...d,df->...f", h.astype(dt), lp["w1"].astype(dt))
    u = jax.nn.gelu(u + lp["b1"].astype(dt))
    return jnp.einsum("...f,fd->...d", u, lp["w2"].astype(dt))


def _block(params, x, cfg: TransformerConfig, n_sp, sp_axis, tp_axis, t_local):
    """One transformer block, tp/sp-aware (runs inside shard_map)."""
    dt = cfg.dtype
    h = _layernorm(x, params["ln1_scale"], params["ln1_bias"])
    if tp_axis:
        h = copy_to_tp(h, tp_axis)
    q, k, v = _qkv_proj(params, h, dt)
    if k.shape[-2] != q.shape[-2]:
        # GQA head-group broadcast before attention; under tp the local
        # query heads would need a shard-offset-aware group map — not
        # implemented, train GQA models without a tp axis
        assert tp_axis is None, "GQA (n_kv_heads < n_heads) does not shard over tp"
        k = repeat_kv_heads(k, q.shape[-2] // k.shape[-2])
        v = repeat_kv_heads(v, q.shape[-2] // v.shape[-2])
    if cfg.attention != "ring" and n_sp == 1 and t_local % 128 == 0:
        # any registered ops/pallas attention candidate ("flash", "fused",
        # ...) resolves through the kernel registry; ring keeps its direct
        # path because it is the sp-aware collective, not a candidate here
        from ..ops.pallas import registry as kernel_registry
        attn = kernel_registry.get("attention", cfg.attention).fn(
            q, k, v, causal=cfg.causal)
    else:
        attn = ring_attention(q, k, v, n_sp=n_sp, sp_axis=sp_axis,
                              causal=cfg.causal, t_local=t_local)
    proj = jnp.einsum("bthe,hed->btd", attn.astype(dt), params["wo"].astype(dt))
    if tp_axis:
        proj = reduce_from_tp(proj, tp_axis)  # partial sums over local heads
    if cfg.fused_ln and not tp_axis:
        # one VMEM pass for the mid-block residual-add + LayerNorm seam
        # (bench-gated opt-in; under tp the unfused path keeps the
        # copy_to_tp placement below untouched)
        from ..ops.pallas.layernorm import fused_residual_layernorm
        x, h2 = fused_residual_layernorm(
            x, proj.astype(x.dtype), params["ln2_scale"], params["ln2_bias"])
    else:
        x = x + proj.astype(x.dtype)
        h2 = _layernorm(x, params["ln2_scale"], params["ln2_bias"])
    if tp_axis:
        h2 = copy_to_tp(h2, tp_axis)
    down = _ffn(params, h2, dt)
    if tp_axis:
        down = reduce_from_tp(down, tp_axis)
    down = down + params["b2"].astype(dt)
    return x + down.astype(x.dtype)


def embed_local(params, tokens, cfg: TransformerConfig,
                sp_axis: str | None = None) -> jnp.ndarray:
    """Token + position embedding for the local (sp-offset) token shard —
    shared by the plain and pipelined forward paths."""
    B, T = tokens.shape
    my_sp = lax.axis_index(sp_axis) if sp_axis else 0
    pos0 = my_sp * T
    x = jnp.take(params["tok_embed"], tokens, axis=0)
    pos = lax.dynamic_slice_in_dim(params["pos_embed"], pos0, T, axis=0)
    return (x + pos[None]).astype(cfg.dtype)


def lm_head_loss(params, h, targets, cfg: TransformerConfig) -> jnp.ndarray:
    """Mean token cross entropy of final hidden states against targets
    (tied or separate head) — shared by the plain and pipelined paths.

    When ``cfg.xent_chunk`` divides the local token count, the loss is
    computed as a ``lax.scan`` over token chunks with the chunk body under
    ``jax.checkpoint``: only per-chunk logits (chunk × V) ever exist, and
    the backward recomputes them instead of reading a stored (B·T, V)
    tensor back from HBM.  One extra head matmul (~7% step FLOPs at
    BERT-base shapes) buys an order of magnitude less loss-layer HBM
    traffic — the dominant bandwidth cost of big-vocab training."""
    head = (params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"])
    hd = head.astype(cfg.dtype)
    B, T, D = h.shape
    n_tok = B * T
    chunk = cfg.xent_chunk

    def token_xent(h_flat, t_flat, w_flat):
        logits = (h_flat.astype(cfg.dtype) @ hd).astype(jnp.float32)
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, t_flat[:, None], axis=-1)[:, 0]
        return ((lse - gold) * w_flat).sum()

    h_flat = h.reshape(n_tok, D)
    t_flat = targets.reshape(n_tok)
    w_flat = jnp.ones((n_tok,), jnp.float32)
    if chunk and n_tok > chunk:
        # largest divisor of n_tok <= chunk, so odd token counts still
        # stream instead of silently falling back to full (B*T, V) logits
        div = chunk
        while n_tok % div:
            div -= 1
        if div >= cfg.xent_chunk // 4 and cfg.xent_impl != "blocked":
            chunk = div
        else:
            # Two ways here: a near-prime token count drives the divisor
            # search down to a tiny chunk (thousands of sequential
            # (chunk, V) matmuls), or ``cfg.xent_impl="blocked"`` opted
            # the whole chunked path in.  Either way the blocked-xent
            # tier streams (N, V) tile-by-tile with internal zero-weight
            # row padding — shape-independent, and on the pallas backend
            # the logits never materialize at all.
            from ..ops import losses
            return losses.blocked_token_xent(
                h_flat.astype(cfg.dtype), hd, t_flat) / n_tok

    if chunk and 1 < chunk < n_tok:
        body_fn = jax.checkpoint(token_xent)

        def body(carry, inp):
            h_c, t_c, w_c = inp
            return carry + body_fn(h_c, t_c, w_c), None

        total, _ = lax.scan(
            body, jnp.zeros((), jnp.float32),
            (h_flat.reshape(-1, chunk, D), t_flat.reshape(-1, chunk),
             w_flat.reshape(-1, chunk)))
        return total / n_tok
    return token_xent(h_flat, t_flat, w_flat) / n_tok


# --------------------------------------------------------------------------- KV-cached decode

def init_decode_cache(cfg: TransformerConfig, batch: int = 1) -> list:
    """Per-layer K/V buffers for incremental decoding: each layer caches
    ``(B, max_len, Kv, Dh)`` keys and values (``Kv = cfg.kv_heads``, ==
    n_heads without GQA); positions beyond the current one stay zero and
    are masked out of the softmax."""
    shape = (batch, cfg.max_len, cfg.kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def _decode_attend(params, x, valid, write_kv, cfg: TransformerConfig,
                   attend=None):
    """Shared per-row decode arithmetic over already-embedded queries
    ``x`` (N, D): every einsum/softmax below is byte-for-byte the op the
    single-step decode path has always run, only at a different leading
    batch size — the bitwise-parity anchor for the paged and windowed
    variants (DESIGN.md §17).  ``write_kv(layer_idx, k, v) -> (ck, cv)``
    commits the new K/V wherever the caller keeps it (dense row, page
    pool) and returns the ``(N, T, H, Dh)`` view attention reads.
    ``attend(layer_idx, q)`` optionally replaces the gather-read
    attention (the paged-attention kernel hook); numerics then carry that
    candidate's tolerance instead of bitwise parity."""
    dt = cfg.dtype
    scale = cfg.head_dim ** -0.5
    n_rep = cfg.n_heads // cfg.kv_heads
    for li, lp in enumerate(params["layers"]):
        h = _layernorm(x, lp["ln1_scale"], lp["ln1_bias"])
        q, k, v = _qkv_proj(lp, h, dt)                          # (N, H|Kv, Dh)
        ck, cv = write_kv(li, k, v)
        if attend is not None:
            att = attend(li, q)
        else:
            # GQA: broadcast the cached heads up to the query heads at the
            # READ — the cache (and its bytes) stay at n_kv_heads
            ck, cv = repeat_kv_heads(ck, n_rep), repeat_kv_heads(cv, n_rep)
            s = jnp.einsum("bhd,bthd->bht", q, ck,
                           preferred_element_type=jnp.float32) * scale
            s = jnp.where(valid[:, None, :], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            att = jnp.einsum("bht,bthd->bhd", p.astype(dt), cv,
                             preferred_element_type=jnp.float32).astype(dt)
        proj = jnp.einsum("bhe,hed->bd", att, lp["wo"].astype(dt))
        x = x + proj.astype(x.dtype)
        h2 = _layernorm(x, lp["ln2_scale"], lp["ln2_bias"])
        down = _ffn(lp, h2, dt) + lp["b2"].astype(dt)
        x = x + down.astype(x.dtype)
    h = _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])
    if "head_q" in params:
        # int8-quantized serving tree (quantize_params_for_decode): the
        # LM head streams as int8 + per-channel scales, logits f32
        from ..ops.pallas.matmul_int8 import int8_matmul
        return int8_matmul(h.astype(dt), params["head_q"])
    head = (params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"])
    return (h.astype(dt) @ head.astype(dt)).astype(jnp.float32)


def decode_step(params, cache, tokens, pos, cfg: TransformerConfig):
    """One incremental decode step: ``tokens`` (B,) are the ids at
    position ``pos`` — a traced scalar (every row at the same depth: the
    ``sample``/``beam_search`` path) or a ``(B,)`` vector of PER-ROW
    positions (the serving slot pool, where every slot decodes at its own
    depth).  Returns ``(logits (B, V) f32, new_cache)``.  O(T·D) per
    token — each layer attends the single new query against its cached
    K/V instead of recomputing the full T×T attention.  Single-device
    path (the tp/sp sharded model trains; decode serves), numerics mirror
    ``_block``: bf16 matmuls, f32 softmax/LN.  The vector-pos path runs
    the same per-row arithmetic as the scalar path (broadcast + vmapped
    row updates), so the two cannot diverge numerically."""
    dt = cfg.dtype
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), tokens.shape)  # (B,)
    x = (jnp.take(params["tok_embed"], tokens, axis=0)
         + jnp.take(params["pos_embed"], pos_b, axis=0)).astype(dt)  # (B, D)
    valid = jnp.arange(cfg.max_len)[None, :] <= pos_b[:, None]       # (B, T)
    # per-row cache write: row b's K/V lands at its OWN position pos_b[b]
    upd = jax.vmap(
        lambda c, kv, p: lax.dynamic_update_slice_in_dim(c, kv[None], p, axis=0))
    new_cache: list = []

    def write_kv(li, k, v):
        ck = upd(cache[li]["k"], k, pos_b)
        cv = upd(cache[li]["v"], v, pos_b)
        new_cache.append({"k": ck, "v": cv})
        return ck, cv

    logits = _decode_attend(params, x, valid, write_kv, cfg)
    return logits, new_cache


def reset_cache_slots(cache, slot_mask) -> list:
    """Zero the K/V rows named by ``slot_mask`` (B,) bool — the serving
    slot pool's eviction hygiene.  A newly admitted sequence's prefill
    rewrites its row before any read, so this is defense-in-depth against
    a stale-KV read ever influencing a later occupant (and makes cache
    state inspectable in tests: an evicted slot is all-zeros)."""
    def wipe(c):
        return jnp.where(slot_mask[:, None, None, None], jnp.zeros_like(c), c)
    return [{"k": wipe(c["k"]), "v": wipe(c["v"])} for c in cache]


# --------------------------------------------------------------------------- paged KV decode

def init_paged_cache(cfg: TransformerConfig, num_pages: int,
                     page_size: int) -> list:
    """Per-layer paged K/V pools: ``(num_pages, page_size, Kv, Dh)`` keys
    and values shared by ALL serving slots, addressed through per-slot
    block tables instead of a dense per-slot row (DESIGN.md §17).  The
    caller typically sizes ``num_pages`` with one extra trash page whose
    index is parked in the block-table rows of inactive slots.  For the
    int8/fp8 storage twin see ``ops.pallas.kv_quant
    .init_quantized_paged_cache`` (DESIGN.md §20)."""
    shape = (num_pages, page_size, cfg.kv_heads, cfg.head_dim)
    return [{"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}
            for _ in range(cfg.n_layers)]


def reset_cache_pages(pages, page_mask) -> list:
    """Zero the physical pages named by ``page_mask`` (P,) bool — the
    paged twin of :func:`reset_cache_slots`: eviction hygiene for pages
    whose refcount just reached zero (never for aliased pages).  A
    quantized pool (``k_scale`` present) additionally resets the wiped
    pages' absmax scales to neutral, so the monotone per-page running max
    restarts from real content for the next occupant."""
    def wipe(c):
        return jnp.where(page_mask[:, None, None, None], jnp.zeros_like(c), c)

    out = []
    for c in pages:
        d = {"k": wipe(c["k"]), "v": wipe(c["v"])}
        if "k_scale" in c:
            from ..ops.pallas import kv_quant
            s0 = jnp.float32(kv_quant.neutral_scale(c["k"].dtype))
            for sk in ("k_scale", "v_scale"):
                d[sk] = jnp.where(page_mask[:, None], s0, c[sk])
        out.append(d)
    return out


def paged_flat_index(block_table, positions, page_size: int):
    """Flatten logical positions to indices into a ``(P*page_size, ...)``
    view of the page pool: ``block_table`` (B, n_pages), ``positions``
    (B, W) → ``bt[b, t // ps] * ps + t % ps`` (B, W).  Page lookups are
    clamped to the table; callers mask out-of-range positions themselves
    (scatters use ``mode="drop"`` sentinels)."""
    n_pages = block_table.shape[1]
    page = jnp.minimum(positions // page_size, n_pages - 1)
    return (jnp.take_along_axis(block_table, page, axis=1) * page_size
            + positions % page_size)


def gather_paged_kv(c, block_table, max_len: int):
    """Materialize one logical ``(B, max_len, H, Dh)`` K/V view from the
    page pool ``c`` (P, ps, H, Dh) through ``block_table`` (B, n_pages).
    The gathered buffer has EXACTLY the dense cache's shape, so running
    ``decode_step``'s attention over it is bitwise the dense computation
    whenever the gathered content matches (the §17 parity argument —
    garbage beyond ``pos`` is masked to -inf and contributes exactly 0)."""
    ps = c.shape[1]
    B = block_table.shape[0]
    t = jnp.broadcast_to(jnp.arange(max_len, dtype=jnp.int32)[None, :],
                         (B, max_len))
    flat = paged_flat_index(block_table, t, ps)
    return c.reshape((-1,) + c.shape[2:])[flat]


def gather_paged_layer(c, block_table, max_len: int, dtype):
    """Logical ``(B, max_len, Kv, Dh)`` k and v views of ONE layer's page
    pool dict ``c`` — quant-transparent: a float pool gathers exactly as
    :func:`gather_paged_kv` always did (the §17 bitwise path), a
    quantized pool (``k_scale`` present) dequantizes through its per-page
    per-head absmax scales first.  Returns ``(k, v)`` in ``dtype``."""
    if "k_scale" in c:
        from ..ops.pallas import kv_quant
        kf = kv_quant.dequantize_pool(c["k"], c["k_scale"], dtype)
        vf = kv_quant.dequantize_pool(c["v"], c["v_scale"], dtype)
    else:
        kf, vf = c["k"], c["v"]
    return (gather_paged_kv(kf, block_table, max_len),
            gather_paged_kv(vf, block_table, max_len))


def scatter_paged_layer(c, flat, k, v) -> dict:
    """Commit token K/V rows ``k``/``v`` (N, Kv, Dh) at flat pool indices
    ``flat`` (N,) into one layer's pool dict ``c`` (out-of-range indices
    drop — the window paths' OOB sentinel).  Float pools scatter exactly
    as before; quantized pools quantize AT THE WRITE (DESIGN.md §20):
    dequantize → scatter → requantize against monotone per-page per-head
    absmax scales, so untouched pages round-trip byte-identically and
    only the written page can re-round.  This jnp path is the parity
    REFERENCE; the streamed ``paged_attention_int8`` kernel is the perf
    path behind the autopick gate."""
    if "k_scale" not in c:
        return {
            key: c[key].reshape((-1,) + c[key].shape[2:]).at[flat].set(
                val, mode="drop").reshape(c[key].shape)
            for key, val in (("k", k), ("v", v))}
    from ..ops.pallas import kv_quant
    out = {}
    for key, val in (("k", k), ("v", v)):
        skey = key + "_scale"
        f = kv_quant.dequantize_pool(c[key], c[skey], jnp.float32)
        f = f.reshape((-1,) + f.shape[2:]).at[flat].set(
            val.astype(jnp.float32), mode="drop").reshape(f.shape)
        out[key], out[skey] = kv_quant.requantize_pool(
            f, c[skey], c[key].dtype)
    return out


def decode_step_paged(params, pages, block_tables, tokens, pos,
                      cfg: TransformerConfig, attn_fn=None):
    """Paged twin of :func:`decode_step`: K/V live in the shared page
    pool and each row reads/writes through its block-table row.  The new
    K/V is scattered to page ``bt[b, pos // ps]`` BEFORE attending (same
    write-then-read order as the dense path), then attention runs over a
    gather of the row's logical ``[0, max_len)`` K/V — an exactly
    ``(B, max_len)`` buffer through :func:`_decode_attend`, so logits are
    bitwise ``decode_step``'s given equal cache content.  Quantized pools
    (``k_scale`` present) quantize-at-write and dequantize-at-read
    through :func:`scatter_paged_layer`/:func:`gather_paged_layer`;
    numerics then carry the int8-KV agreement tolerance instead of
    bitwise parity.  ``attn_fn`` optionally swaps the gather+softmax read
    for a registry candidate ``(q, k_pages, v_pages, block_tables,
    lengths) -> (B, H, Dh)`` (``(q, k_pages, v_pages, k_scale, v_scale,
    block_tables, lengths)`` for quantized pools — the bench-autopick
    perf path).  Returns ``(logits (B, V) f32, new_pages)``."""
    dt = cfg.dtype
    ps = pages[0]["k"].shape[1]
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), tokens.shape)  # (B,)
    x = (jnp.take(params["tok_embed"], tokens, axis=0)
         + jnp.take(params["pos_embed"], pos_b, axis=0)).astype(dt)
    valid = jnp.arange(cfg.max_len)[None, :] <= pos_b[:, None]
    flat = paged_flat_index(block_tables, pos_b[:, None], ps)[:, 0]      # (B,)
    new_pages: list = []

    def write_kv(li, k, v):
        c2 = scatter_paged_layer(pages[li], flat, k, v)
        new_pages.append(c2)
        if attn_fn is not None:
            return None, None  # the attend hook reads new_pages directly
        return gather_paged_layer(c2, block_tables, cfg.max_len, dt)

    attend = None
    if attn_fn is not None:
        def attend(li, q):
            c2 = new_pages[li]
            if "k_scale" in c2:
                return attn_fn(q, c2["k"], c2["v"], c2["k_scale"],
                               c2["v_scale"], block_tables,
                               pos_b + 1).astype(dt)
            return attn_fn(q, c2["k"], c2["v"], block_tables,
                           pos_b + 1).astype(dt)

    logits = _decode_attend(params, x, valid, write_kv, cfg, attend=attend)
    return logits, new_pages


def decode_window(params, cache, tokens, pos, cfg: TransformerConfig):
    """Speculative verify window: process ``tokens`` (B, W) at positions
    ``pos[b] .. pos[b]+W-1`` in ONE dispatch, returning logits for every
    window position.  Per row this is bitwise identical to W sequential
    ``decode_step`` calls: the window folds into the leading batch dim
    (N = B*W) so every matmul/softmax is the same op the single-step path
    runs (batch-size independence of those ops is what the engine's
    B=1-offline vs B=S parity already rests on), and window position w's
    validity mask admits exactly the K/V a sequential step at ``pos+w``
    would see — all W writes land before any of them is read, and a
    write at position p is masked out of every query with ``pos+w < p``.
    Positions past ``max_len-1`` become dropped scatters (never clamped
    onto a live row).  Returns ``(logits (B, W, V) f32, new_cache)``."""
    dt = cfg.dtype
    B, W = tokens.shape
    T = cfg.max_len
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    wpos = pos_b[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]   # (B, W)
    ok = wpos < T
    pos2 = jnp.minimum(wpos, T - 1).reshape(B * W)
    tok2 = tokens.reshape(B * W)
    x = (jnp.take(params["tok_embed"], tok2, axis=0)
         + jnp.take(params["pos_embed"], pos2, axis=0)).astype(dt)
    valid = jnp.arange(T)[None, :] <= pos2[:, None]                   # (N, T)
    row = jnp.arange(B, dtype=jnp.int32)[:, None]
    flat = jnp.where(ok, row * T + wpos, B * T).reshape(B * W)        # drop OOB
    new_cache: list = []

    def write_kv(li, k, v):
        c = cache[li]
        ck = c["k"].reshape((B * T,) + c["k"].shape[2:]).at[flat].set(
            k, mode="drop").reshape(c["k"].shape)
        cv = c["v"].reshape((B * T,) + c["v"].shape[2:]).at[flat].set(
            v, mode="drop").reshape(c["v"].shape)
        new_cache.append({"k": ck, "v": cv})
        ck2 = jnp.broadcast_to(ck[:, None], (B, W) + ck.shape[1:]).reshape(
            (B * W,) + ck.shape[1:])
        cv2 = jnp.broadcast_to(cv[:, None], (B, W) + cv.shape[1:]).reshape(
            (B * W,) + cv.shape[1:])
        return ck2, cv2

    logits = _decode_attend(params, x, valid, write_kv, cfg)
    return logits.reshape(B, W, -1), new_cache


def decode_window_paged(params, pages, block_tables, tokens, pos,
                        cfg: TransformerConfig):
    """Paged twin of :func:`decode_window`: the W window writes scatter
    into the page pool through the block table (out-of-range window
    positions become dropped sentinel scatters), then each window query
    attends a gather of its row's logical K/V — same shapes, same ops,
    same masks as the dense window, so the §17 parity argument carries
    over unchanged.  Returns ``(logits (B, W, V) f32, new_pages)``."""
    dt = cfg.dtype
    B, W = tokens.shape
    T = cfg.max_len
    ps = pages[0]["k"].shape[1]
    n_phys = pages[0]["k"].shape[0]
    pos_b = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (B,))
    wpos = pos_b[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]   # (B, W)
    ok = wpos < T
    pos2 = jnp.minimum(wpos, T - 1).reshape(B * W)
    tok2 = tokens.reshape(B * W)
    x = (jnp.take(params["tok_embed"], tok2, axis=0)
         + jnp.take(params["pos_embed"], pos2, axis=0)).astype(dt)
    valid = jnp.arange(T)[None, :] <= pos2[:, None]
    flat = jnp.where(ok, paged_flat_index(block_tables, wpos, ps),
                     n_phys * ps).reshape(B * W)                      # drop OOB
    new_pages: list = []

    def write_kv(li, k, v):
        c2 = scatter_paged_layer(pages[li], flat, k, v)
        new_pages.append(c2)
        ck, cv = gather_paged_layer(c2, block_tables, T, dt)
        ck2 = jnp.broadcast_to(ck[:, None], (B, W) + ck.shape[1:]).reshape(
            (B * W,) + ck.shape[1:])
        cv2 = jnp.broadcast_to(cv[:, None], (B, W) + cv.shape[1:]).reshape(
            (B * W,) + cv.shape[1:])
        return ck2, cv2

    logits = _decode_attend(params, x, valid, write_kv, cfg)
    return logits.reshape(B, W, -1), new_pages


def encode_local(params, tokens, cfg: TransformerConfig, *,
                 n_sp: int = 1, sp_axis: str | None = None,
                 tp_axis: str | None = None) -> jnp.ndarray:
    """Final hidden states (B_loc, T_loc, D) for the local token shard —
    runs inside shard_map (or standalone when all axes are trivial)."""
    T = tokens.shape[1]
    x = embed_local(params, tokens, cfg, sp_axis)

    block = _block
    if cfg.remat:
        block = jax.checkpoint(_block, static_argnums=(2, 3, 4, 5, 6))
    for lp in params["layers"]:
        x = block(lp, x, cfg, n_sp, sp_axis, tp_axis, T)

    return _layernorm(x, params["final_ln_scale"], params["final_ln_bias"])


def forward_local(params, tokens, cfg: TransformerConfig, *,
                  n_sp: int = 1, sp_axis: str | None = None,
                  tp_axis: str | None = None) -> jnp.ndarray:
    """Vocabulary logits for the local token shard."""
    x = encode_local(params, tokens, cfg, n_sp=n_sp, sp_axis=sp_axis,
                     tp_axis=tp_axis)
    head = (params["tok_embed"].T if cfg.tie_embeddings else params["lm_head"])
    logits = jnp.einsum("btd,dv->btv", x.astype(cfg.dtype), head.astype(cfg.dtype))
    return logits.astype(jnp.float32)


def lm_loss_local(params, tokens, targets, cfg: TransformerConfig, **axes):
    """Mean next-token (or MLM-style given targets) cross entropy on the
    local shard; caller pmean's across dp/sp."""
    h = encode_local(params, tokens, cfg, **axes)
    return lm_head_loss(params, h, targets, cfg)


def init_cls_head(key, cfg: TransformerConfig, n_classes: int):
    """Sequence-classification head (the BERT fine-tune north star): mean
    pooling → dense.  Mean pooling (not [CLS]) so the pooled vector is an
    sp-pmean away from correct under sequence parallelism."""
    w = (cfg.d_model ** -0.5 * jax.random.normal(
        key, (cfg.d_model, n_classes))).astype(cfg.param_dtype)
    return {"w_cls": w, "b_cls": jnp.zeros((n_classes,), cfg.param_dtype)}


def cls_head_specs():
    return {"w_cls": P(), "b_cls": P()}


def cls_loss_local(params, head, tokens, labels, cfg: TransformerConfig, *,
                   n_sp: int = 1, sp_axis: str | None = None,
                   tp_axis: str | None = None):
    """Softmax cross entropy of the pooled classifier on the local shard.

    Pooling: local mean over T_loc, then pmean over sp — equal shard sizes
    make that the exact global sequence mean."""
    x = encode_local(params, tokens, cfg, n_sp=n_sp, sp_axis=sp_axis,
                     tp_axis=tp_axis)
    pooled = x.astype(jnp.float32).mean(axis=1)
    if sp_axis:
        pooled = lax.pmean(pooled, sp_axis)
    logits = pooled @ head["w_cls"].astype(jnp.float32) + head["b_cls"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=-1))


# --------------------------------------------------------------------------- model facade

class TransformerLM:
    """Flagship trainer: explicit-SPMD train step over a (dp, sp, tp) mesh."""

    def __init__(self, cfg: TransformerConfig, mesh: Mesh | None = None):
        self.cfg = cfg
        self.mesh = mesh
        self._train_step = None
        self._fwd = None
        self._score_fn = None
        self._sample_cache: dict = {}

    # -- single-device --------------------------------------------------
    def init(self, key=None) -> Params:
        return init_params(key if key is not None else jax.random.key(0), self.cfg)

    def forward(self, params, tokens) -> jnp.ndarray:
        if self._fwd is None:
            self._fwd = jax.jit(partial(forward_local, cfg=self.cfg))
        return self._fwd(params, tokens)

    def sample(self, params, prime, length: int, temperature: float = 1.0,
               key=None, kv_cache: bool = False) -> list:
        """Temperature-sampled continuation of ``prime`` (greedy when
        ``temperature <= 0``) — the transformer counterpart of
        ``LSTMNet.sample`` (reference ``LSTM.java`` sampling seam).

        TPU-idiomatic decode: the whole loop is ONE compiled
        ``lax.fori_loop`` over a fixed ``(1, max_len)`` token buffer (no
        per-token dispatch); causality makes the unwritten suffix inert.
        Prime/generation lengths are traced int arguments, so every call
        shares one executable per (mode, kv_cache) pair.

        Two decode paths: the default recomputes the full forward per
        token (O(T²) attention — simplest, exercises the training
        graph); ``kv_cache=True`` decodes incrementally through
        :func:`decode_step` — O(T·D) per token, same numerics class (bf16
        matmuls, f32 softmax), parity-tested against the full path, and
        drawing the SAME RNG stream (the key advances only on generation
        steps, so a given key yields the same continuation either way).

        ``key=None`` defaults to ``jax.random.key(0)`` — DETERMINISTIC,
        like ``LSTMNet.sample``'s ``seed=0`` default; pass distinct keys
        to collect diverse samples."""
        cfg = self.cfg
        assert cfg.causal, "sampling needs a causal LM (cfg.causal=True)"
        P = len(prime)
        assert 1 <= P and P + length <= cfg.max_len, (P, length, cfg.max_len)
        if key is None:
            key = jax.random.key(0)
        greedy = temperature <= 0.0
        fn = self._sample_cache.get((greedy, kv_cache))
        if fn is None:
            def pick(logits, sub, temp):
                if greedy:
                    return jnp.argmax(logits).astype(jnp.int32)
                return jax.random.categorical(sub, logits / temp).astype(
                    jnp.int32)

            if kv_cache:
                def run(params, toks, key, temp, p0, n):
                    cache = init_decode_cache(cfg, 1)

                    def body(i, carry):
                        toks, cache, key = carry
                        logits, cache = decode_step(
                            params, cache, toks[:, i], i, cfg)
                        new_key, sub = jax.random.split(key)
                        # advance the RNG only on GENERATION steps, so the
                        # draw sequence matches the non-cached path (which
                        # never splits during prime prefill)
                        gen = i + 1 >= p0
                        key = jax.random.wrap_key_data(jnp.where(
                            gen, jax.random.key_data(new_key),
                            jax.random.key_data(key)))
                        nxt = pick(logits[0], sub, temp)
                        cur = toks[0, i + 1]
                        toks = toks.at[0, i + 1].set(
                            jnp.where(gen, nxt, cur))
                        return toks, cache, key

                    toks, _, _ = lax.fori_loop(0, p0 + n - 1, body,
                                               (toks, cache, key))
                    return toks
            else:
                def run(params, toks, key, temp, p0, n):
                    def body(i, carry):
                        toks, key = carry
                        pos = p0 - 1 + i
                        logits = forward_local(params, toks, cfg)[0, pos]
                        key, sub = jax.random.split(key)
                        nxt = pick(logits, sub, temp)
                        return toks.at[0, pos + 1].set(nxt), key
                    toks, _ = lax.fori_loop(0, n, body, (toks, key))
                    return toks
            fn = jax.jit(run)
            self._sample_cache[(greedy, kv_cache)] = fn
        toks0 = jnp.zeros((1, cfg.max_len), jnp.int32)
        toks0 = toks0.at[0, :P].set(jnp.asarray(prime, jnp.int32))
        toks = fn(params, toks0, key,
                  jnp.float32(temperature if not greedy else 1.0),
                  jnp.int32(P), jnp.int32(length))
        # sample() returns host tokens by contract; this is the one
        # deliberate end-of-generation pull  # graftlint: disable=HS01
        return [int(t) for t in np.asarray(toks[0, :P + length])]

    def score(self, params, tokens, targets) -> float:
        """Mean token cross entropy (model ``score`` seam, reference
        ``MultiLayerNetwork.score``); ``exp(score)`` is perplexity."""
        if self._score_fn is None:
            cfg = self.cfg
            self._score_fn = jax.jit(
                lambda p, t, y: lm_loss_local(p, t, y, cfg))
        return float(self._score_fn(params, jnp.asarray(tokens),
                                    jnp.asarray(targets)))

    def beam_search(self, params, prime, length: int, beam_width: int = 5
                    ) -> tuple[list, float]:
        """Highest-log-likelihood continuation of ``prime`` — the
        ``LSTM.java`` BeamSearch seam on the flagship.  Returns
        ``(token sequence, total log prob)``.

        The device does the O(W·T·D) work through the KV-cached
        :func:`decode_step` with the beam as the batch axis; the tiny
        top-k bookkeeping (sort W·V scores, reorder W cache rows) runs on
        host per step — beam decode is a quality tool, not a throughput
        path."""
        cfg = self.cfg
        assert cfg.causal, "beam search needs a causal LM (cfg.causal=True)"
        # more beams than vocabulary entries cannot all be distinct
        P, W = len(prime), min(beam_width, cfg.vocab_size)
        assert 1 <= P and P + length <= cfg.max_len, (P, length, cfg.max_len)
        fn = self._sample_cache.get(("beam_step", W))
        if fn is None:
            fn = jax.jit(lambda p, c, t, i: decode_step(p, c, t, i, cfg))
            self._sample_cache[("beam_step", W)] = fn

        toks = jnp.zeros((W, cfg.max_len), jnp.int32)
        toks = toks.at[:, :P].set(jnp.asarray(prime, jnp.int32)[None])
        cache = init_decode_cache(cfg, W)
        for i in range(P - 1):                       # prefill
            _, cache = fn(params, cache, toks[:, i], jnp.int32(i))

        scores = np.zeros(W)
        for i in range(P - 1, P - 1 + length):
            logits, cache = fn(params, cache, toks[:, i], jnp.int32(i))
            logp = np.asarray(jax.nn.log_softmax(logits, axis=-1))  # (W, V)
            if i == P - 1:
                # all beams are identical clones of the prime: branch the
                # top-W tokens from ONE row (else W duplicate beams)
                top = np.argsort(-logp[0])[:W]
                beam_idx, next_toks, scores = np.zeros(W, int), top, logp[0][top]
            else:
                flat = (scores[:, None] + logp).reshape(-1)
                top = np.argsort(-flat)[:W]
                beam_idx, next_toks = np.divmod(top, logp.shape[1])
                scores = flat[top]
            sel = jnp.asarray(beam_idx)
            toks = jnp.take(toks, sel, axis=0).at[:, i + 1].set(
                jnp.asarray(next_toks, jnp.int32))
            cache = jax.tree_util.tree_map(
                lambda c: jnp.take(c, sel, axis=0), cache)

        best = int(np.argmax(scores))
        return ([int(t) for t in np.asarray(toks[best, :P + length])],
                float(scores[best]))

    # -- sharded train step --------------------------------------------
    def _axes(self):
        if self.mesh is None:
            return 1, 1, 1
        s = self.mesh.shape
        return s.get(DP, 1), s.get(SP, 1), s.get(TP, 1)

    @staticmethod
    def _default_tx(lr: float):
        """SGD-with-momentum, the reference's finetune default
        (``BaseOptimizer.java:68-118`` momentum seam)."""
        from ..optimize import transforms as T
        return T.chain(T.momentum(0.9), T.sgd_lr(lr))

    def _is_finetune_tree(self, tree):
        return isinstance(tree, dict) and set(tree.keys()) == {"backbone", "head"}

    def _decay_mask(self, tree):
        """Bool pytree naming the weight-class (decayed) leaves of ``tree``.
        None = the transforms' ndim >= 2 default, which is correct for this
        class's canonical layout; layout-changing subclasses override."""
        return None

    def _specs(self):
        """Param-tree PartitionSpecs for this model's layer layout
        (subclasses with a different layout — the stacked pp pipeline —
        override, and every spec consumer routes through here)."""
        return param_specs(self.cfg)

    def init_opt(self, params, tx=None, lr: float = 1e-3, specs=None):
        """Optimizer state for ``build_train_step``/``build_finetune_step``:
        ``(step_count, tx_state)``, placed onto the mesh with tx-declared
        PartitionSpecs.  Works for both the plain param tree and the
        ``{"backbone", "head"}`` finetune tree (specs inferred; pass
        ``specs`` explicitly for custom trees)."""
        tx = tx if tx is not None else self._default_tx(lr)
        state = (jnp.zeros((), jnp.int32), tx.init(params))
        if self.mesh is None:
            return state
        if specs is None:
            specs = (self.finetune_specs() if self._is_finetune_tree(params)
                     else self._specs())
        return self.place(state, self.opt_specs(tx, specs))

    def opt_specs(self, tx, params_specs=None):
        ps = params_specs if params_specs is not None else self._specs()
        spec_fn = tx.state_spec or (lambda _: ())
        return (P(), spec_fn(ps))

    def _loss_reduce(self, loss, sp_axis):
        """Cross-replica reduction of the reported loss (subclasses with
        extra axes — e.g. the pp pipeline — extend this)."""
        loss = lax.pmean(loss, DP)
        return lax.pmean(loss, SP) if sp_axis else loss

    def _grad_sync(self, specs, sp_axis, tp_axis, include_dp: bool = True):
        """Cross-replica gradient pmean over every axis a param is
        REPLICATED on (dp+sp always; tp for tp-replicated leaves).
        ``include_dp=False`` leaves dp to the caller (the ZeRO-1 path
        reduce-scatters over dp instead)."""

        def sync(g, spec):
            if include_dp:
                g = lax.pmean(g, DP)
            if sp_axis:
                g = lax.pmean(g, SP)
            sharded_on_tp = any(ax == TP for ax in spec if ax is not None)
            if tp_axis and not sharded_on_tp:
                g = lax.pmean(g, TP)
            return g

        return lambda grads: jax.tree_util.tree_map(
            sync, grads, specs, is_leaf=lambda x: isinstance(x, P))

    # -- ZeRO-1 weight-update sharding over dp --------------------------
    #
    # Instead of pmean-ing full gradients and updating replicated optimizer
    # state on every dp rank, each rank owns 1/n_dp of every (tp-local)
    # parameter: gradients reduce-scatter over dp, the transform updates
    # only the local chunk (optimizer memory / n_dp — the XLA
    # weight-update-sharding / ZeRO-1 design), and updated params
    # all-gather back.  State leaves are encoded globally as
    # (T, n_dp * chunk) with spec P(TP|None, DP): T = n_tp for tp-sharded
    # params (their chunks differ per tp rank), else 1.

    @staticmethod
    def _z1_chunk(size: int, n_dp: int) -> int:
        return -(-size // n_dp)

    def _z1_leaf_is_tp_sharded(self, spec) -> bool:
        return any(ax == TP for ax in spec if ax is not None)

    def _z1_template_and_specs(self, params, specs):
        """(zeros template for tx.init, matching PartitionSpecs)."""
        n_dp, _, n_tp = self._axes()

        def template(p, spec):
            tp_sharded = self._z1_leaf_is_tp_sharded(spec) and n_tp > 1
            local_size = int(np.prod(p.shape))
            if tp_sharded:
                local_size //= n_tp
            k = self._z1_chunk(local_size, n_dp)
            return jnp.zeros((n_tp if tp_sharded else 1, n_dp * k), p.dtype)

        def spec_of(p, spec):
            tp_sharded = self._z1_leaf_is_tp_sharded(spec) and n_tp > 1
            return P(TP if tp_sharded else None, DP)

        is_p = lambda x: isinstance(x, P)
        tmpl = jax.tree_util.tree_map(template, params, specs, is_leaf=is_p)
        tspec = jax.tree_util.tree_map(spec_of, params, specs, is_leaf=is_p)
        return tmpl, tspec

    def init_opt_zero1(self, params, tx, specs=None):
        """Optimizer state with ZeRO-1 layout for
        ``build_train_step(..., zero1=True)``: every stateful-transform
        leaf holds only this dp-rank's parameter chunk."""
        assert self.mesh is not None, "zero1 requires a mesh"
        if specs is None:
            specs = (self.finetune_specs() if self._is_finetune_tree(params)
                     else self._specs())
        tmpl, _ = self._z1_template_and_specs(params, specs)
        state = (jnp.zeros((), jnp.int32), tx.init(tmpl))
        return self.place(state, self.opt_specs_zero1(tx, specs))

    def opt_specs_zero1(self, tx, params_specs=None, params=None):
        """Placement specs for a ZeRO-1 ``(count, tx_state)`` tree — the
        checkpoint-restore counterpart of ``init_opt_zero1`` (restore host
        arrays, then ``place(opt, model.opt_specs_zero1(tx))``).  For a
        finetune run pass the restored ``{"backbone", "head"}`` ``params``
        (or explicit ``params_specs``) so the spec tree matches."""
        if params_specs is None:
            params_specs = (self.finetune_specs()
                            if params is not None
                            and self._is_finetune_tree(params)
                            else self._specs())
        spec_fn = tx.state_spec or (lambda _: ())
        return (P(), spec_fn(self._z1_state_specs(params_specs)))

    def _z1_state_specs(self, specs):
        """ZeRO-1 state PartitionSpecs derivable from param specs alone
        (the step builder has no params in hand)."""
        n_tp = self._axes()[2]

        def spec_of(spec):
            tp_sharded = self._z1_leaf_is_tp_sharded(spec) and n_tp > 1
            return P(TP if tp_sharded else None, DP)

        return jax.tree_util.tree_map(
            spec_of, specs, is_leaf=lambda x: isinstance(x, P))

    def _z1_scatter_gather(self):
        """(scatter grads -> local chunks, slice params -> local chunks,
        gather updated chunks -> full params) closures for local_step."""
        n_dp = self._axes()[0]

        def scatter(g):
            flat = g.reshape(-1).astype(jnp.float32)
            k = self._z1_chunk(flat.size, n_dp)
            flat = jnp.pad(flat, (0, n_dp * k - flat.size))
            return lax.psum_scatter(flat, DP, scatter_dimension=0,
                                    tiled=True) / n_dp

        def pslice(p):
            flat = p.reshape(-1)
            k = self._z1_chunk(flat.size, n_dp)
            flat = jnp.pad(flat, (0, n_dp * k - flat.size))
            my = lax.axis_index(DP)
            return lax.dynamic_slice(flat, (my * k,), (k,))

        def gather(chunk, p):
            full = lax.all_gather(chunk, DP, tiled=True)
            return full[:int(np.prod(p.shape))].reshape(p.shape).astype(p.dtype)

        return scatter, pslice, gather

    def _build_step(self, tx, loss_of, specs, data_specs, zero1: bool = False):
        """Shared step builder: ``loss_of(tree, *data, axes)`` differs per
        objective; everything else (grad, cross-replica sync, transform
        chain, shard_map wrapper) is identical.  Replaces the reference's
        ``Solver``→``BaseOptimizer.optimize`` dispatch for the flagship."""
        from ..optimize import transforms as Tmod
        from ..optimize.transforms import apply_updates
        n_dp, n_sp, n_tp = self._axes()

        if self.mesh is None:
            assert not zero1, "zero1 requires a mesh with a dp axis"
            def simple(tree, opt, *data):
                count, tx_state = opt
                loss, g = jax.value_and_grad(
                    lambda t: loss_of(t, *data, axes={}))(tree)
                with Tmod.decay_mask_override(self._decay_mask(tree)):
                    updates, tx_state = tx.update(g, tx_state, tree, count)
                tree = apply_updates(tree, updates)
                return tree, (count + 1, tx_state), loss
            return jax.jit(simple, donate_argnums=(0, 1))

        sp_axis = SP if n_sp > 1 else None
        tp_axis = TP if n_tp > 1 else None
        axes = dict(n_sp=n_sp, sp_axis=sp_axis, tp_axis=tp_axis)

        if zero1:
            assert n_dp > 1, "zero1 needs a dp axis to shard state over"
            spec_fn = tx.state_spec or (lambda _: ())
            opt_spec = (P(), spec_fn(self._z1_state_specs(specs)))
            # dp is handled by reduce-scatter below; only the replication
            # axes (sp, and tp for tp-replicated leaves) pmean here
            sync = self._grad_sync(specs, sp_axis, tp_axis, include_dp=False)
            scatter, pslice, gather = self._z1_scatter_gather()
            tmap = jax.tree_util.tree_map

            def local_step(tree, opt, *data):
                count, tx_state = opt
                loss, grads = jax.value_and_grad(
                    lambda t: loss_of(t, *data, axes=axes))(tree)
                loss = self._loss_reduce(loss, sp_axis)
                grads = sync(grads)
                gch = tmap(scatter, grads)
                pch = tmap(pslice, tree)
                st = tmap(lambda s: s[0], tx_state)     # (1, k) -> (k,)
                # chunking flattened every param to 1-D, so the ndim >= 2
                # decay default would silently drop weight decay — name the
                # weight-class leaves from the UNchunked tree instead
                mask = self._decay_mask(tree)
                if mask is None:
                    mask = Tmod.decay_leaf_mask(tree)
                with Tmod.decay_mask_override(mask):
                    updates, st = tx.update(gch, st, pch, count)
                tx_state = tmap(lambda s: s[None], st)
                pch = apply_updates(pch, updates)
                tree = tmap(gather, pch, tree)
                return tree, (count + 1, tx_state), loss
        else:
            opt_spec = self.opt_specs(tx, specs)
            sync = self._grad_sync(specs, sp_axis, tp_axis)

            def local_step(tree, opt, *data):
                count, tx_state = opt
                loss, grads = jax.value_and_grad(
                    lambda t: loss_of(t, *data, axes=axes))(tree)
                loss = self._loss_reduce(loss, sp_axis)
                grads = sync(grads)
                with Tmod.decay_mask_override(self._decay_mask(tree)):
                    updates, tx_state = tx.update(grads, tx_state, tree, count)
                tree = apply_updates(tree, updates)
                return tree, (count + 1, tx_state), loss

        smapped = shard_map(
            local_step, mesh=self.mesh,
            in_specs=(specs, opt_spec) + data_specs,
            out_specs=(specs, opt_spec, P()),
            check_vma=False,
        )
        return jax.jit(smapped, donate_argnums=(0, 1))

    def build_train_step(self, tx=None, lr: float = 1e-3, zero1: bool = False):
        """LM train step over any ``GradientTransform`` (default: the
        reference's SGD+momentum).  Returns
        ``step(params, opt, tokens, targets) -> (params, opt, loss)`` where
        ``opt = (step_count, tx_state)``.  ``zero1=True`` shards optimizer
        state over dp (pair with ``init_opt_zero1``)."""
        cfg = self.cfg
        tx = tx if tx is not None else self._default_tx(lr)

        def loss_of(params, tokens, targets, axes):
            return lm_loss_local(params, tokens, targets, cfg, **axes)

        return self._build_step(tx, loss_of, self._specs(),
                                (P(DP, SP), P(DP, SP)), zero1=zero1)

    # -- BERT-style sequence-classification fine-tune -------------------
    def init_finetune(self, key, n_classes: int, params=None):
        """(backbone, head) combined tree for ``build_finetune_step``."""
        backbone = params if params is not None else self.init(key)
        head = init_cls_head(jax.random.fold_in(key, 1), self.cfg, n_classes)
        tree = {"backbone": backbone, "head": head}
        return self.place(tree, self.finetune_specs()) if self.mesh else tree

    def finetune_specs(self):
        return {"backbone": self._specs(), "head": cls_head_specs()}

    def build_finetune_step(self, tx=None, lr: float = 2e-5,
                            zero1: bool = False):
        """Classifier fine-tune step (north star: BERT-base fine-tune).
        ``step(tree, opt, tokens, labels) -> (tree, opt, loss)`` with
        ``tree = {"backbone": ..., "head": ...}``.  ``zero1=True`` shards
        optimizer state over dp (pair with ``init_opt_zero1``)."""
        cfg = self.cfg
        tx = tx if tx is not None else self._default_tx(lr)

        def loss_of(tree, tokens, labels, axes):
            return cls_loss_local(tree["backbone"], tree["head"], tokens,
                                  labels, cfg, **axes)

        return self._build_step(tx, loss_of, self.finetune_specs(),
                                (P(DP, SP), P(DP)), zero1=zero1)

    def fit(self, params, opt, batches, *, tx=None, lr: float = 1e-3,
            epochs: int = 1, finetune: bool = False,
            checkpoint_manager=None, checkpoint_every: int = 0,
            resume: bool = True):
        """Convenience training loop with auto-checkpoint/resume.

        ``batches``: list of (tokens, targets|labels) pairs.  Runs to
        ``epochs * len(batches)`` total steps counted by the optimizer's
        step counter, so a restored state continues where it left off.
        Checkpoints carry params + full transform state + data cursor
        (exceeds the reference's bare-params ``ModelSavingActor.java:75-79``).
        """
        tx = tx if tx is not None else self._default_tx(lr)
        step_fn = (self.build_finetune_step(tx) if finetune
                   else self.build_train_step(tx))
        specs = self.finetune_specs() if finetune else self._specs()

        if (checkpoint_manager is not None and resume
                and checkpoint_manager.latest_step() is not None):
            r = checkpoint_manager.restore(params, tstate_template=opt)
            params, opt = r["params"], r["tstate"]
            if self.mesh is not None:
                params = self.place(params, specs)
                opt = self.place(opt, self.opt_specs(tx, specs))

        def save():
            checkpoint_manager.save(int(opt[0]), params, tstate=opt,
                                    data_cursor=int(opt[0]))

        losses = []
        start = int(opt[0])
        total = epochs * len(batches)
        # double-buffered host->device staging: the device_put of batch k+1
        # overlaps the step on batch k (async transfers), resuming from the
        # checkpointed cursor
        from ..datasets.iterator import prefetch_to_device
        feed = (batches[k % len(batches)] for k in range(start, total))
        done = 0  # host-side mirror of opt[0]: reading it back would sync
        for a, b in prefetch_to_device(feed, size=2):
            params, opt, loss = step_fn(params, opt, a, b)
            losses.append(loss)  # stays on device; resolved once below
            done += 1
            if (checkpoint_manager is not None and checkpoint_every > 0
                    and (start + done) % checkpoint_every == 0):
                save()  # CheckpointManager.save fences params/opt itself
        losses = [float(l) for l in jax.block_until_ready(losses)]
        if checkpoint_manager is not None and losses:
            save()
        return params, opt, losses

    def place(self, tree, specs=None):
        """Device-put a pytree onto the mesh per param_specs."""
        if self.mesh is None:
            return tree
        specs = specs if specs is not None else self._specs()
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, NamedSharding(self.mesh, s)),
            tree, specs, is_leaf=lambda x: isinstance(x, P))

    def init_opt_momentum(self, params, lr: float = 1e-3):
        """Convenience: opt state for the default SGD+momentum transform."""
        return self.init_opt(params, self._default_tx(lr))
