"""Checkpoint / resume with integrity verification.

Exceeds the reference (SURVEY.md §5.4: java-serialized params only, no
optimizer state or data cursor — ``DefaultModelSaver``,
``ModelSavingActor.java:75-79``): checkpoints carry params + optimizer
(transform) state + step counter + RNG key + data cursor, with keep-last-N
rotation and atomic writes.  Storage is a directory of npz payloads + JSON
metadata — host-side, mesh-agnostic (arrays are gathered to host before
write; on restore the trainer re-places them onto its mesh).

Integrity (DESIGN.md §12): every payload file's SHA-256 lands in
``meta.json`` at save time; ``verify()`` recomputes them, and a restore
with no explicit step walks BACK from the newest checkpoint to the newest
one that verifies — a truncated or bit-flipped checkpoint is detected and
skipped (``checkpoint.corrupt_detected``), never silently loaded.  The
``checkpoint.write`` fault site corrupts the payload *after* checksums are
recorded, so the whole detection path is testable in-process.

Publish is race-free against concurrent readers (DESIGN.md §23): every
step dir materializes fully inside a unique temp dir (payloads fsync'd,
``meta.json`` written LAST) and appears under its ``ckpt_*`` name only
via one atomic ``os.replace`` — so ``all_steps()``/``latest_valid_step()``
polled from a serving process can never list a partially-written step.
Same-step republish and rotation move the old dir ASIDE (atomic rename to
a non-``ckpt_`` tombstone) before deleting, so a reader that raced the
listing sees either the complete old dir or the complete new one, never a
half-deleted tree; ``all_steps()`` additionally ignores any ``ckpt_*``
entry without a ``meta.json`` (a crashed pre-fix writer's residue).
``quarantine(step)`` is the online-rollback hook: it atomically renames a
published-but-bad step out of the ``ckpt_*`` namespace so
``latest_valid_step()`` stops offering it without destroying the evidence.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import shutil
import tempfile
import time
import warnings
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import FLIGHTREC, METRICS, trace
from ..resilience.faults import FAULTS, corrupt_file
from .mesh import DP, MeshMismatchError
from .zero import flat_padded_size, host_flat_to_natural


class CheckpointCorruptError(RuntimeError):
    """An explicitly-requested checkpoint failed checksum verification."""

    def __init__(self, step: int, directory):
        super().__init__(
            f"checkpoint step {step} under {directory} failed checksum "
            "verification — refusing to restore corrupt state")
        self.step = step


def _fsync_path(path: Path) -> None:
    """fsync a file (or a directory's entry table) — the durability half
    of the unique-tempfile + fsync + ``os.replace`` publish idiom.  Best
    effort on platforms whose filesystems refuse directory fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _flatten_with_paths(tree) -> dict[str, np.ndarray]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = {}
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        out[key] = np.asarray(leaf)
    return out


@dataclasses.dataclass
class _ReshardCtx:
    """What the restore knows about the widths on either side of the seam.

    ``saved_dp``/``zero_stage`` come from the checkpoint metadata,
    ``restore_dp`` from the caller; ``reshard`` authorizes host-side
    re-splits.  ``transformed`` records whether any leaf actually needed
    one (drives the reshard metrics/chaos accounting).
    """

    saved_dp: int | None = None
    restore_dp: int | None = None
    zero_stage: int | None = None
    reshard: bool = False
    transformed: int = 0


def _fit_leaf(key: str, arr: np.ndarray, leaf, ctx: _ReshardCtx | None):
    """Shape-guard one array leaf against its template — the fix for the
    silent failure mode where a wrong-width flat leaf flowed through
    ``jnp.asarray`` and only died (or corrupted state) later inside
    ``zero.py``.  Mismatches that flat-pad arithmetic explains are
    re-split exactly when ``reshard`` allows; everything else raises a
    named error here, never a raw reshape error downstream."""
    want = getattr(leaf, "shape", None)
    if want is None or tuple(arr.shape) == tuple(want):
        return arr
    if ctx is not None and ctx.saved_dp and arr.ndim == 1 \
            and arr.shape[0] == flat_padded_size(_size_of(want), ctx.saved_dp):
        # a flat padded P('dp') leaf from the save-side width.  Same-width
        # flat->natural is layout normalization and always allowed; a
        # CROSS-width re-split is a reshard and needs the flag.
        cross = ctx.restore_dp is not None and ctx.restore_dp != ctx.saved_dp
        if cross and not ctx.reshard:
            raise MeshMismatchError(ctx.saved_dp, ctx.restore_dp,
                                    ctx.zero_stage, detail=f"flat leaf {key}")
        ctx.transformed += 1
        return host_flat_to_natural(arr, tuple(want), ctx.saved_dp)
    saved_dp = ctx.saved_dp if ctx is not None else None
    restore_dp = ctx.restore_dp if ctx is not None else None
    stage = ctx.zero_stage if ctx is not None else None
    raise MeshMismatchError(
        saved_dp, restore_dp, stage,
        detail=f"leaf {key} has shape {tuple(arr.shape)}, template wants "
               f"{tuple(want)} and no flat-pad width explains it")


def _size_of(shape) -> int:
    return int(np.prod(shape, dtype=np.int64)) if shape else 1


def _restore_like(template, arrays: dict[str, np.ndarray],
                  ctx: _ReshardCtx | None = None):
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    used = set()
    for path, leaf in flat:
        key = "/".join(str(p) for p in path)
        if key not in arrays:
            raise KeyError(f"checkpoint missing leaf {key}")
        used.add(key)
        arr = arrays[key]
        if isinstance(leaf, (jnp.ndarray, np.ndarray, jax.ShapeDtypeStruct)):
            # abstract templates (ShapeDtypeStruct trees from eval_shape)
            # are the ZeRO restore path: the caller re-flattens and
            # re-shards the natural-layout arrays onto its CURRENT mesh,
            # so no concrete template ever needs to materialize here
            leaves.append(jnp.asarray(_fit_leaf(key, arr, leaf, ctx)))
        elif leaf is None:
            # a registered-leaf None (custom pytrees): NoneType() is not
            # callable with an argument — restore the None itself
            leaves.append(None)
        elif isinstance(leaf, (bool, np.bool_)):
            leaves.append(bool(arr.item()))
        else:
            leaves.append(type(leaf)(arr.item()))
    unused = sorted(set(arrays) - used)
    if unused:
        # template drift: the checkpoint carries leaves this template does
        # not — restoring would silently drop state, so say so loudly
        warnings.warn(
            f"checkpoint contains {len(unused)} key(s) absent from the "
            f"restore template (ignored): {unused[:5]}", stacklevel=3)
        METRICS.increment("checkpoint.unused_keys", len(unused))
    return jax.tree_util.tree_unflatten(treedef, leaves)


class CheckpointManager:
    """Keep-last-N rotating checkpoints under a directory.

    ``read_only=True`` is the serving-side open path: verify / restore /
    ``latest_valid_step`` only — the directory is never created (a typo'd
    path fails loudly instead of serving from an empty dir) and ``save``
    raises, so an inference process can never clobber the trainer's
    rotation.
    """

    def __init__(self, directory: str | Path, keep: int = 3,
                 read_only: bool = False):
        self.directory = Path(directory)
        self.read_only = read_only
        if read_only:
            if not self.directory.is_dir():
                raise FileNotFoundError(
                    f"checkpoint directory {self.directory} does not exist "
                    "(read-only manager refuses to create it)")
        else:
            self.directory.mkdir(parents=True, exist_ok=True)
        self.keep = keep

    @classmethod
    def open_read_only(cls, directory: str | Path) -> "CheckpointManager":
        """Open an EXISTING checkpoint directory for restore-only use."""
        return cls(directory, read_only=True)

    # ------------------------------------------------------------------ save
    def save(self, step: int, params, tstate=None, key=None,
             data_cursor: int = 0, extra: dict | None = None,
             dp_width: int | None = None, zero_stage: int | None = None,
             layout: str | None = None) -> Path:
        if self.read_only:
            raise RuntimeError(
                "CheckpointManager opened read-only (serving open path): "
                "save() is not allowed")
        with trace.span("checkpoint.save", step=step), \
                METRICS.time("checkpoint.save"):
            # Fence before reading: under async dispatch the caller's latest
            # step may still be executing — np.asarray on an in-flight array
            # would block leaf-by-leaf mid-flatten; one explicit barrier up
            # front snapshots a consistent state.  (The trainer additionally
            # resolves its pending-loss ring before calling save.)
            jax.block_until_ready((params, tstate))
            path = self._save(step, params, tstate, key, data_cursor, extra,
                              dp_width=dp_width, zero_stage=zero_stage,
                              layout=layout)
        METRICS.increment("checkpoint.saves")
        return path

    def _save(self, step: int, params, tstate=None, key=None,
              data_cursor: int = 0, extra: dict | None = None,
              dp_width: int | None = None, zero_stage: int | None = None,
              layout: str | None = None) -> Path:
        ckpt_dir = self.directory / f"ckpt_{step:010d}"
        tmp = Path(tempfile.mkdtemp(dir=self.directory))
        try:
            np.savez(tmp / "params.npz", **_flatten_with_paths(params))
            if tstate is not None:
                np.savez(tmp / "tstate.npz", **_flatten_with_paths(tstate))
            if key is not None:
                np.save(tmp / "key.npy", np.asarray(jax.random.key_data(key)))
            payloads = sorted(p for p in tmp.iterdir() if p.is_file())
            for p in payloads:
                _fsync_path(p)
            meta = {
                "step": step,
                "data_cursor": data_cursor,
                "has_tstate": tstate is not None,
                "has_key": key is not None,
                "extra": extra or {},
                # topology stamp: the dp width / zero stage / leaf layout
                # this checkpoint was written under — what the resharding
                # restore (and the MeshMismatchError contract) keys off.
                # ``layout`` is "natural" (gathered, width-agnostic) or
                # "flat" (padded P('dp') vectors of the save-side width).
                "topology": {DP: dp_width, "zero_stage": zero_stage,
                             "layout": layout or "natural"},
                # per-file SHA-256 manifest: verify() recomputes these; a
                # checkpoint whose payloads do not match is never restored
                "checksums": {p.name: hashlib.sha256(p.read_bytes()).hexdigest()
                              for p in payloads},
            }
            # meta.json is the publish marker: written LAST, fsync'd, so a
            # dir carrying it carries every payload its checksums name
            (tmp / "meta.json").write_text(json.dumps(meta, indent=2))
            _fsync_path(tmp / "meta.json")
            _fsync_path(tmp)
            # chaos seam: damage the payload AFTER the manifest is written,
            # exactly like a torn write / bad medium under the checksums
            spec = FAULTS.check("checkpoint.write", step)
            if spec is not None:
                corrupt_file(tmp / "params.npz", spec.kind)
            # same-step republish: the old dir moves ASIDE via atomic
            # rename (never an in-place rmtree) — a racing reader sees
            # the complete old tree, a clean miss (verify fails CLOSED on
            # the unreadable path and the walk-back retries), or the
            # complete new tree; never a half-deleted one.  The absent
            # window is bounded by two renames.
            trash = self._trash_path()
            if ckpt_dir.exists():
                os.replace(ckpt_dir, trash)
            os.replace(tmp, ckpt_dir)  # atomic publish
            _fsync_path(self.directory)
            shutil.rmtree(trash, ignore_errors=True)
        except Exception:
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._rotate()
        return ckpt_dir

    def _trash_path(self) -> Path:
        """A unique non-``ckpt_`` empty dir inside the directory — the
        rename target for dirs on their way out (``os.replace`` of a dir
        onto an empty dir is atomic on POSIX), invisible to
        ``all_steps``."""
        return Path(tempfile.mkdtemp(prefix=".trash-", dir=self.directory))

    def _rotate(self):
        ckpts = self.all_steps()
        for step in ckpts[:-self.keep] if self.keep > 0 else []:
            # rename-then-delete: mid-rmtree a concurrent lister must not
            # find a half-deleted ckpt_* dir (meta present, payloads gone)
            victim = self.directory / f"ckpt_{step:010d}"
            trash = self._trash_path()
            try:
                os.replace(victim, trash)
            except OSError:
                continue  # already gone (another writer rotated it)
            shutil.rmtree(trash, ignore_errors=True)

    # ------------------------------------------------------------------ load
    def all_steps(self) -> list[int]:
        steps = []
        for p in self.directory.glob("ckpt_*"):
            try:
                step = int(p.name.split("_")[1])
            except (IndexError, ValueError):
                continue
            # publish marker: a ckpt_* dir without meta.json is residue
            # from a crashed writer (or a reader racing one pre-atomic
            # publish) — never a listable checkpoint
            if not (p / "meta.json").is_file():
                continue
            steps.append(step)
        return sorted(steps)

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    # ------------------------------------------------------------------ verify
    def verify(self, step: int) -> bool:
        """Recompute every payload file's SHA-256 against the ``meta.json``
        manifest.  Unreadable/unparseable metadata counts as corrupt;
        pre-checksum checkpoints (no manifest) pass vacuously."""
        ckpt_dir = self.directory / f"ckpt_{step:010d}"
        try:
            meta = json.loads((ckpt_dir / "meta.json").read_text())
        except (OSError, json.JSONDecodeError):
            return False
        checksums = meta.get("checksums")
        if checksums is None:
            return True
        for name, digest in checksums.items():
            try:
                data = (ckpt_dir / name).read_bytes()
            except OSError:
                return False
            if hashlib.sha256(data).hexdigest() != digest:
                return False
        METRICS.increment("checkpoint.verifications")
        return True

    def latest_valid_step(self) -> int | None:
        """Newest step that passes :meth:`verify` (the restore target)."""
        for step in reversed(self.all_steps()):
            if self.verify(step):
                return step
        return None

    # ------------------------------------------------------------ quarantine
    def quarantine(self, step: int) -> Path:
        """Atomically retire a published-but-bad step (the online loop's
        rollback hook, DESIGN.md §23): one rename moves ``ckpt_<step>``
        to ``bad_<step>`` — outside the ``ckpt_*`` listing namespace, so
        ``latest_valid_step()`` stops offering it instantly, while the
        evidence (a checkpoint that VERIFIES but regressed serving) stays
        on disk for the flight-recorder bundle to point at.  Returns the
        quarantine path; raises ``FileNotFoundError`` if the step is not
        published."""
        if self.read_only:
            raise RuntimeError(
                "CheckpointManager opened read-only (serving open path): "
                "quarantine() is not allowed")
        src = self.directory / f"ckpt_{step:010d}"
        dst = self.directory / f"bad_{step:010d}"
        if not src.is_dir():
            raise FileNotFoundError(f"no published checkpoint {src}")
        if dst.exists():
            shutil.rmtree(dst, ignore_errors=True)
        os.replace(src, dst)
        _fsync_path(self.directory)
        METRICS.increment("checkpoint.quarantined")
        return dst

    def restore(self, params_template, tstate_template=None,
                step: int | None = None, *, reshard: bool = False,
                dp_width: int | None = None) -> dict:
        """Returns dict(step, params, tstate, key, data_cursor, extra,
        saved_dp, zero_stage, resharded).

        With ``step=None`` walks back from the newest checkpoint to the
        newest one that verifies, skipping (and counting) corrupt ones;
        an explicit ``step`` that fails verification raises
        :class:`CheckpointCorruptError` instead of loading garbage.

        ``dp_width`` declares the mesh width this restore targets.  When it
        differs from the width stamped at save time, ``reshard=True``
        re-splits the state exactly (natural-layout leaves pass through
        width-agnostic; flat padded ``P('dp')`` leaves are sliced back to
        natural host-side, no renormalization) and ``reshard=False`` raises
        :class:`MeshMismatchError` naming both widths — never a raw shape
        error deep in ``zero.py``.
        """
        with trace.span("checkpoint.restore"), \
                METRICS.time("checkpoint.restore"):
            out = self._restore(params_template, tstate_template, step,
                                reshard=reshard, dp_width=dp_width)
        METRICS.increment("checkpoint.restores")
        return out

    def _restore(self, params_template, tstate_template=None,
                 step: int | None = None, *, reshard: bool = False,
                 dp_width: int | None = None) -> dict:
        if step is not None:
            if not self.verify(step):
                METRICS.increment("checkpoint.corrupt_detected")
                FLIGHTREC.dump("checkpoint_corrupt", extra={
                    "step": int(step), "directory": str(self.directory)})
                raise CheckpointCorruptError(step, self.directory)
        else:
            steps = self.all_steps()
            if not steps:
                raise FileNotFoundError(f"no checkpoints under {self.directory}")
            for s in reversed(steps):
                if self.verify(s):
                    step = s
                    break
                METRICS.increment("checkpoint.corrupt_detected")
                warnings.warn(
                    f"checkpoint step {s} under {self.directory} failed "
                    "checksum verification — falling back to an older "
                    "checkpoint", stacklevel=4)
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self.directory} passed "
                    "verification (all corrupt)")
        ckpt_dir = self.directory / f"ckpt_{step:010d}"
        meta = json.loads((ckpt_dir / "meta.json").read_text())
        topo = meta.get("topology") or {}
        extra = meta.get("extra") or {}
        saved_dp = topo.get(DP)
        if saved_dp is None:  # pre-topology checkpoints stamped via extra
            saved_dp = extra.get("saved_dp")
        zero_stage = topo.get("zero_stage")
        if zero_stage is None:
            zero_stage = extra.get("zero_stage")
        if (dp_width is not None and saved_dp is not None
                and int(saved_dp) != int(dp_width) and not reshard):
            # the silent failure mode, made loud: cross-width restore with
            # resharding off fails HERE with both widths named, for every
            # zero stage — even when a size coincidence would have let the
            # leaves through.
            raise MeshMismatchError(int(saved_dp), int(dp_width), zero_stage)
        ctx = _ReshardCtx(
            saved_dp=int(saved_dp) if saved_dp is not None else None,
            restore_dp=int(dp_width) if dp_width is not None else None,
            zero_stage=zero_stage, reshard=reshard)
        cross_width = (ctx.saved_dp is not None and ctx.restore_dp is not None
                       and ctx.saved_dp != ctx.restore_dp)
        if cross_width:
            # chaos seam: a reshard that dies mid-flight (preempted host,
            # OOM during the re-split) — transient, retried by the
            # supervisor like any other step fault
            FAULTS.maybe_fire("checkpoint.reshard", step)
        t0 = time.monotonic()
        params_npz = np.load(ckpt_dir / "params.npz")
        params = _restore_like(params_template, dict(params_npz), ctx)
        tstate = None
        if meta["has_tstate"] and tstate_template is not None:
            tstate = _restore_like(
                tstate_template, dict(np.load(ckpt_dir / "tstate.npz")), ctx)
        key = None
        if meta["has_key"]:
            key = jax.random.wrap_key_data(jnp.asarray(np.load(ckpt_dir / "key.npy")))
        resharded = cross_width or ctx.transformed > 0
        if cross_width:
            METRICS.increment("checkpoint.reshards")
            METRICS.gauge("elastic.reshard_seconds", time.monotonic() - t0)
        return {
            "step": meta["step"],
            "params": params,
            "tstate": tstate,
            "key": key,
            "data_cursor": meta["data_cursor"],
            "extra": meta["extra"],
            "saved_dp": ctx.saved_dp,
            "zero_stage": zero_stage,
            "resharded": resharded,
        }
