"""Grouped-query / multi-query attention parity battery (DESIGN.md §20).

GQA (``n_kv_heads < n_heads``) is a CACHE-bytes technique, never a
semantics change beyond the weight tying it declares: a GQA model must
compute exactly what a full-heads model computes when that model's K/V
projections are tied group-wise.  The battery pins that down at every
layer the heads flow through: init tree compatibility, training
loss/grad vs the repeat-heads reference, dense-vs-paged decode at odd
page sizes, the windowed verify primitive, and prefix-sharing admission
in the serving engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM,
                                                   decode_step,
                                                   decode_step_paged,
                                                   decode_window,
                                                   forward_local,
                                                   init_decode_cache,
                                                   init_paged_cache,
                                                   init_params,
                                                   lm_loss_local)
from deeplearning4j_tpu.serving import InferenceEngine, ServingConfig


def gqa_cfg(**kw):
    kw.setdefault("vocab_size", 64)
    kw.setdefault("d_model", 48)
    kw.setdefault("n_heads", 6)
    kw.setdefault("n_layers", 2)
    kw.setdefault("d_ff", 64)
    kw.setdefault("max_len", 32)
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("remat", False)
    return TransformerConfig(**kw)


def _expand_to_full_heads(params, cfg):
    """Tie a full-heads tree to a GQA tree: query head ``h`` gets K/V
    projection ``h // g`` — the weight-space statement of
    ``repeat_kv_heads``.  The expanded model must match bitwise-ish."""
    g = cfg.n_heads // cfg.kv_heads
    layers = []
    for lp in params["layers"]:
        lp2 = {k: v for k, v in lp.items() if k not in ("wq", "wkv")}
        wk = jnp.repeat(lp["wkv"][:, 0], g, axis=1)     # (D, H, Dh)
        wv = jnp.repeat(lp["wkv"][:, 1], g, axis=1)
        lp2["wqkv"] = jnp.stack([lp["wq"], wk, wv], axis=1)
        layers.append(lp2)
    return dict(params, layers=layers)


# ------------------------------------------------------------------- trees
def test_default_kv_heads_tree_is_bitwise_pre_gqa():
    """``n_kv_heads=None`` and ``=n_heads`` draw the SAME RNG stream into
    the SAME packed ``wqkv`` tree — every pre-GQA checkpoint stays
    loadable and every existing test keeps its exact numbers."""
    cfg_none = gqa_cfg()
    cfg_full = gqa_cfg(n_kv_heads=6)
    p_none = init_params(jax.random.key(3), cfg_none)
    p_full = init_params(jax.random.key(3), cfg_full)
    la, lb = jax.tree.leaves(p_none), jax.tree.leaves(p_full)
    assert len(la) == len(lb)
    for a, b in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert "wqkv" in p_none["layers"][0]


def test_kv_heads_must_divide_n_heads():
    with pytest.raises(AssertionError, match="must divide"):
        gqa_cfg(n_kv_heads=4).kv_heads


@pytest.mark.parametrize("n_kv", [1, 2, 6])
def test_gqa_loss_matches_repeat_heads_reference(n_kv):
    """Forward + loss + grads of the GQA tree match the full-heads model
    whose K/V projections are tied group-wise (``n_kv == n_heads``
    exercises the packed-tree path through the same assertion)."""
    cfg = gqa_cfg(n_kv_heads=n_kv)
    cfg_full = gqa_cfg()
    params = init_params(jax.random.key(0), cfg)
    rng = np.random.default_rng(1)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    tgts = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)

    if n_kv == cfg.n_heads:
        full = params                    # same packed tree by construction
    else:
        full = _expand_to_full_heads(params, cfg)
    np.testing.assert_allclose(
        np.asarray(forward_local(params, toks, cfg)),
        np.asarray(forward_local(full, toks, cfg_full)),
        atol=1e-5, rtol=1e-5)

    loss, grads = jax.value_and_grad(
        lambda p: lm_loss_local(p, toks, tgts, cfg))(params)
    loss_f, grads_f = jax.value_and_grad(
        lambda p: lm_loss_local(p, toks, tgts, cfg_full))(full)
    np.testing.assert_allclose(float(loss), float(loss_f), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grads["tok_embed"]),
                               np.asarray(grads_f["tok_embed"]), atol=1e-5)
    np.testing.assert_allclose(np.asarray(grads["layers"][0]["w1"]),
                               np.asarray(grads_f["layers"][0]["w1"]),
                               atol=1e-5)
    if n_kv != cfg.n_heads:
        # chain rule across the tying: d/dwq is slice 0 of d/dwqkv, and
        # each shared K/V head accumulates its whole query group
        g = cfg.n_heads // n_kv
        gq = grads_f["layers"][0]["wqkv"]
        np.testing.assert_allclose(np.asarray(grads["layers"][0]["wq"]),
                                   np.asarray(gq[:, 0]), atol=1e-5)
        for s in (0, 1):
            got = np.asarray(grads["layers"][0]["wkv"][:, s])
            want = np.asarray(gq[:, s + 1].reshape(
                gq.shape[0], n_kv, g, -1).sum(axis=2))
            np.testing.assert_allclose(got, want, atol=1e-5)


@pytest.mark.parametrize("n_kv", [1, 2])
def test_gqa_training_reduces_loss(n_kv):
    cfg = gqa_cfg(n_kv_heads=n_kv)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    opt = model.init_opt(params, lr=0.05)
    toks = jax.random.randint(jax.random.key(2), (8, 16), 0, cfg.vocab_size)
    tgts = jnp.roll(toks, -1, axis=1)
    step = model.build_train_step(lr=0.05)
    loss0 = None
    for _ in range(30):
        params, opt, loss = step(params, opt, toks, tgts)
        if loss0 is None:
            loss0 = float(loss)
    assert float(loss) < loss0 * 0.7


# ------------------------------------------------------------------ decode
@pytest.mark.parametrize("n_kv,page_size", [(1, 3), (2, 5), (3, 5)])
def test_gqa_decode_step_paged_matches_dense(n_kv, page_size):
    """Dense-vs-paged single-position decode stays bitwise under GQA at
    page sizes that do not divide max_len — the K/V pools carry
    ``n_kv_heads`` heads, the broadcast happens at read time in both."""
    cfg = gqa_cfg(n_kv_heads=n_kv)
    params = init_params(jax.random.key(0), cfg)
    B = 3
    n_pages = -(-cfg.max_len // page_size)
    n_phys = B * n_pages + 1
    rng = np.random.default_rng(1)
    bt = jnp.asarray(rng.permutation(n_phys - 1)[:B * n_pages]
                     .reshape(B, n_pages), jnp.int32)
    dense = init_decode_cache(cfg, B)
    pages = init_paged_cache(cfg, n_phys, page_size)
    assert pages[0]["k"].shape[2] == n_kv     # pool bytes scale with Kv
    for i in range(10):
        tok = jnp.asarray(rng.integers(0, cfg.vocab_size, (B,)), jnp.int32)
        pos = jnp.full((B,), i, jnp.int32)
        ld, dense = decode_step(params, dense, tok, pos, cfg)
        lp, pages = decode_step_paged(params, pages, bt, tok, pos, cfg)
        np.testing.assert_array_equal(np.asarray(ld), np.asarray(lp))


def test_gqa_decode_window_matches_sequential_steps():
    """The speculative verify primitive under GQA: a (B, W) window equals
    W sequential steps — logits and cache bytes."""
    cfg = gqa_cfg(n_kv_heads=2)
    params = init_params(jax.random.key(0), cfg)
    B, W, start = 2, 4, 6
    rng = np.random.default_rng(0)
    toks = jnp.asarray(rng.integers(0, cfg.vocab_size, (B, W)), jnp.int32)
    pos = jnp.full((B,), start, jnp.int32)
    cache_a = init_decode_cache(cfg, B)
    cache_b = init_decode_cache(cfg, B)
    for i in range(start):
        tok = jnp.full((B,), (i * 7) % cfg.vocab_size, jnp.int32)
        _, cache_a = decode_step(params, cache_a, tok,
                                 jnp.full((B,), i, jnp.int32), cfg)
        _, cache_b = decode_step(params, cache_b, tok,
                                 jnp.full((B,), i, jnp.int32), cfg)
    win_logits, cache_a = decode_window(params, cache_a, toks, pos, cfg)
    for w in range(W):
        lw, cache_b = decode_step(params, cache_b, toks[:, w], pos + w, cfg)
        np.testing.assert_array_equal(np.asarray(win_logits[:, w]),
                                      np.asarray(lw))
    for ca, cb in zip(cache_a, cache_b):
        assert ca["k"].shape[2] == 2
        np.testing.assert_array_equal(np.asarray(ca["k"]), np.asarray(cb["k"]))
        np.testing.assert_array_equal(np.asarray(ca["v"]), np.asarray(cb["v"]))


def test_gqa_sample_kv_cache_matches_recompute():
    cfg = gqa_cfg(n_kv_heads=3)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(0))
    a = model.sample(params, [5, 1, 4], 8, temperature=0.0)
    b = model.sample(params, [5, 1, 4], 8, temperature=0.0, kv_cache=True)
    assert a == b


# ----------------------------------------------------------------- serving
def test_gqa_prefix_sharing_admission_unchanged():
    """Prefix admission keys on token content, not head geometry: a GQA
    engine serves shared-prefix traffic with the same bitwise parity and
    a positive hit rate — the cached pages simply hold fewer bytes."""
    cfg = gqa_cfg(n_kv_heads=2)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))
    sys_prompt = [9, 8, 7, 6, 5, 4, 3, 2, 1, 0, 11, 12]
    plans = [(sys_prompt + [t], 5, temp, seed)
             for t, temp, seed in ((1, 0.0, 5), (2, 0.9, 17), (3, 0.0, 23))]
    want = [model.sample(params, p, n, temperature=t, key=jax.random.key(s),
                         kv_cache=True)[len(p):] for p, n, t, s in plans]
    engine = InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True))
    handles = [engine.submit(p, n, temperature=t, seed=s)
               for p, n, t, s in plans]
    # cold start: 13-token prompts touch only the 16 bucket, so the
    # warmup ladder would compile graphs this test never dispatches
    with engine.start(warmup=False):
        got = [h.result(120.0).tokens for h in handles]
    assert got == want
    stats = engine.stats()
    assert stats["prefix_hit_rate"] > 0.0
    assert stats["prefix_entries"] > 0
    pinned = engine._pool.in_use()
    assert engine._pool.free_count() == engine._pool.num_pages - pinned
