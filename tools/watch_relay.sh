#!/bin/bash
# Poll the axon relay; when it opens, stabilize 60s, then run the TPU
# battery ONCE and exit. Detach with:
#   nohup bash tools/watch_relay.sh > watch_relay.log 2>&1 &
# Guard: refuses to start the battery if another instance already did
# (RELAY_BATTERY.lock) — TPU access must stay serialized.
set -u
cd "$(dirname "$0")/.."
LOCK=RELAY_BATTERY.lock

while true; do
  if python3 -c '
import socket, sys
s = socket.socket(); s.settimeout(2)
sys.exit(0 if s.connect_ex(("127.0.0.1", 8080)) == 0 else 1)'; then
    if ! mkdir "$LOCK" 2>/dev/null; then
      echo "$(date -u +%FT%TZ) relay open but lock held; exiting"
      exit 0
    fi
    echo "$(date -u +%FT%TZ) relay OPEN; stabilizing 60s"
    sleep 60
    bash tools/run_tpu_battery.sh      # writes BATTERY_r05.log itself
    echo "$(date -u +%FT%TZ) battery done"
    exit 0
  fi
  sleep 60
done
