"""Stochastic sampling ops.

TPU-native equivalent of ND4J ``Sampling.binomial`` (used by dropout /
dropconnect at ``nn/multilayer/MultiLayerNetwork.java:468`` and by RBM Gibbs
steps) and the distribution factories in
``deeplearning4j-core/.../distributions/Distributions.java``.  All samplers
are stateless: they take an explicit threefry key so they can live inside
jit/scan (SURVEY.md §7 hard-part #1).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def binomial(key, p: jnp.ndarray, n: int = 1) -> jnp.ndarray:
    """Sample Binomial(n, p) elementwise. n=1 is the Bernoulli used by RBMs."""
    if n == 1:
        return jax.random.bernoulli(key, p).astype(p.dtype)
    draws = jax.random.bernoulli(key, p[None, ...] * jnp.ones((n,) + p.shape, p.dtype))
    return jnp.sum(draws, axis=0).astype(p.dtype)


def gaussian(key, mean: jnp.ndarray, std=1.0) -> jnp.ndarray:
    return mean + std * jax.random.normal(key, mean.shape, mean.dtype)


def dropout_mask(key, shape, rate: float, dtype=jnp.float32) -> jnp.ndarray:
    """Inverted-scaling dropout mask: E[mask * x] == x.

    The reference multiplies activations by an unscaled binomial mask
    (``BaseLayer.java:139-146``); the TPU build uses the standard inverted
    scaling so inference needs no rescale.
    """
    if rate <= 0.0:
        return jnp.ones(shape, dtype)
    if rate >= 1.0:
        return jnp.zeros(shape, dtype)
    keep = 1.0 - rate
    return jax.random.bernoulli(key, keep, shape).astype(dtype) / keep


def uniform(key, shape, lo: float, hi: float, dtype=jnp.float32) -> jnp.ndarray:
    return jax.random.uniform(key, shape, dtype, lo, hi)


def normal(key, shape, mean: float = 0.0, std: float = 1.0, dtype=jnp.float32) -> jnp.ndarray:
    return mean + std * jax.random.normal(key, shape, dtype)
