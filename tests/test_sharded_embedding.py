"""Distributed embedding training tests (VERDICT missing #1).

Parity discipline: the mesh-sharded models share the single-device models'
schedule and RNG, so row-sharding the tables over `ep` must reproduce the
single-device result to float tolerance.  The scaleout row-shipping path is
checked for convergence semantics (same nearest-neighbor structure)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.text.glove import Glove
from deeplearning4j_tpu.text.sharded_embedding import (
    ShardedGlove,
    ShardedWord2Vec,
    pad_rows,
)
from deeplearning4j_tpu.text.word2vec import Word2Vec

CORPUS = [
    "the cat sat on the mat",
    "the dog sat on the rug",
    "a cat and a dog played",
    "the king ruled the land",
    "the queen ruled the kingdom",
    "a king and a queen reigned",
    "cats chase mice in the barn",
    "dogs chase cats in the yard",
] * 6


def ep_mesh(n=8):
    return make_mesh(MeshSpec(dp=1, tp=1, pp=1, sp=1, ep=n))


def test_pad_rows():
    assert pad_rows(10, 8) == 16
    assert pad_rows(16, 8) == 16
    assert pad_rows(1, 8) == 8
    assert pad_rows(0, 4) == 4


@pytest.mark.parametrize("negative,hs", [(0, True), (5, True), (5, False)])
def test_sharded_word2vec_matches_single_device(negative, hs):
    """Row-sharded tables + psum row shipping == single-device training,
    for HS, HS+NS, and NS-only modes."""
    kw = dict(layer_size=16, window=3, iterations=2, seed=11,
              negative=negative, use_hierarchic_softmax=hs, batch_size=256)
    solo = Word2Vec(CORPUS, **kw).fit()
    shard = ShardedWord2Vec(CORPUS, mesh=ep_mesh(), **kw).fit()

    np.testing.assert_allclose(shard.embeddings, solo.embeddings,
                               rtol=1e-4, atol=1e-5)
    n1 = np.asarray(solo.syn1).shape[0]
    np.testing.assert_allclose(np.asarray(shard.syn1)[:n1],
                               np.asarray(solo.syn1), rtol=1e-4, atol=1e-5)
    # query API agrees
    assert shard.words_nearest("cat", 3) == solo.words_nearest("cat", 3)


def test_sharded_word2vec_semantic_structure():
    w2v = ShardedWord2Vec(CORPUS, mesh=ep_mesh(), layer_size=24, window=3,
                          iterations=12, seed=3).fit()
    assert w2v.similarity("king", "queen") > w2v.similarity("king", "mat")


def test_sharded_glove_matches_single_device():
    kw = dict(layer_size=12, window=5, iterations=4, seed=5, batch_size=512)
    solo = Glove(CORPUS, **kw).fit()
    shard = ShardedGlove(CORPUS, mesh=ep_mesh(), **kw).fit()
    np.testing.assert_allclose(np.asarray(shard.syn0), np.asarray(solo.syn0),
                               rtol=1e-4, atol=1e-5)
    assert shard.words_nearest("cat", 3) == solo.words_nearest("cat", 3)


# --------------------------------------------------------------------------- scaleout

def _tokenized(corpus, w2v):
    fac = w2v.tokenizer_factory
    out = []
    for s in corpus:
        toks = fac.create(s).get_tokens()
        idx = np.array([i for i in (w2v.vocab.index_of(t) for t in toks)
                        if i >= 0], np.int32)
        if idx.size >= 2:
            out.append(idx)
    return out


@pytest.mark.parametrize("negative", [0, 3])
def test_scaleout_word2vec_performer(negative):
    """Row-shipping distributed Word2Vec over the scaleout SPI
    (Word2VecPerformer.java:72-137 semantics): multi-worker training
    converges to the same semantic structure as local training."""
    from deeplearning4j_tpu.parallel.scaleout import (
        DistributedRunner, HogWildWorkRouter, StateTracker)
    from deeplearning4j_tpu.text.scaleout_embeddings import (
        EmbeddingTables, RowDeltaAggregator, Word2VecJobIterator,
        Word2VecPerformer, WORDS_KEY)

    base = Word2Vec(CORPUS, layer_size=24, window=3, negative=negative,
                    use_hierarchic_softmax=(negative == 0), seed=3)
    base.build_vocab()
    base.reset_weights()
    tables = EmbeddingTables.from_model(base)
    sents = _tokenized(CORPUS, base)

    tracker = StateTracker()
    it = Word2VecJobIterator(
        sents * 12, tables, window=3, chunk=6, negative=negative,
        alpha=0.05, iterations=1, tracker=tracker)

    codes, points, lengths = base.huffman.code_arrays()

    def performer_factory(tr):
        hs = negative == 0
        return Word2VecPerformer(
            tr, window=3, negative=negative,
            codes=codes.astype(np.float32) if hs else None,
            points=points if hs else None,
            lengths=lengths if hs else None)

    runner = DistributedRunner(
        it, performer_factory, n_workers=3,
        router_cls=HogWildWorkRouter, tracker=tracker)
    runner.router.aggregator_factory = lambda: RowDeltaAggregator(tables)
    runner.run(max_wall_s=120.0)

    assert tracker.count(WORDS_KEY) > 0
    # read trained vectors through the model facade
    base.syn0 = jnp.asarray(tables.syn0)
    assert base.similarity("king", "queen") > base.similarity("king", "mat")
    assert base.similarity("cat", "dog") > base.similarity("cat", "kingdom")
