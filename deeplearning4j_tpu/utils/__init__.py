"""Shared host-side utilities (reference: ``util/*``, ``berkeley/*``)."""

from . import counters, misc, tree_math, viterbi
from .counters import Counter, CounterMap, Index
from .misc import DiskBasedQueue, SummaryStatistics
from .viterbi import Viterbi, viterbi_decode

__all__ = ["counters", "misc", "tree_math", "viterbi",
           "Counter", "CounterMap", "Index",
           "DiskBasedQueue", "SummaryStatistics",
           "Viterbi", "viterbi_decode"]
