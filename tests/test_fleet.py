"""Fleet observability plane tests (DESIGN.md §24).

Covers the federation parser as the exact inverse of
``MetricsRegistry.to_prometheus`` (including torn scrape bodies), the
``FederatedRegistry``/``FleetScraper`` rollup + staleness semantics, the
bounded ``TenantLabels`` fold, least-squares ``trend`` math, the
``ForecastEvaluator``'s crossing predictions and its fire-before-breach
ordering against the SLO evaluator, the SIGKILL'd-child scrape bound on
``ProcessReplica``, graftlint OB03's cardinality contract, and the
``metrics_dump``/``trace_report`` fleet renderings.
"""

from __future__ import annotations

import textwrap
import threading
import time
import tracemalloc

import pytest

from deeplearning4j_tpu import observability as obs
from deeplearning4j_tpu.observability import (
    FederatedRegistry,
    FleetScraper,
    FlightRecorder,
    ForecastEvaluator,
    MetricsRegistry,
    SLObjective,
    SLOEvaluator,
    TimeSeriesStore,
    parse_prometheus,
)
from deeplearning4j_tpu.observability.fleet import OTHER_TENANT, TenantLabels


# ----------------------------------------------------------- stub fleet
class StubReplica:
    """Replica double: ``body`` is a string, a callable returning one, or
    an exception instance to raise (a dead scrape)."""

    def __init__(self, name, body):
        self.name = name
        self.body = body

    def metrics_prom(self, timeout_s):
        b = self.body() if callable(self.body) else self.body
        if isinstance(b, Exception):
            raise b
        return b


class StubPool:
    """Duck-typed ``ReplicaPool`` surface the scraper needs."""

    def __init__(self, replicas, inactive=()):
        self._reps = {r.name: r for r in replicas}
        self.inactive = set(inactive)

    def names(self):
        return list(self._reps)

    def is_active(self, name):
        return name not in self.inactive

    def replica(self, name):
        return self._reps[name]


def _replica_body(tokens: float, tps: float) -> str:
    """One replica's exposition page, rendered by the real formatter."""
    reg = MetricsRegistry()
    reg.increment("serving.tokens", tokens)
    reg.gauge("serving.tokens_per_sec", tps)
    reg.gauge("serving.queue.depth", 1.0)
    return reg.to_prometheus()


# ------------------------------------------------------------ round trip
def test_prometheus_round_trip_counters_gauges_histograms():
    reg = MetricsRegistry()
    reg.increment("serving.tokens", 42)
    reg.increment("serving.requests", 7)
    reg.gauge("serving.queue.depth", 3.5)
    reg.observe_time("serving.ttft", 0.12)
    reg.observe_time("serving.ttft", 0.30)
    parsed = parse_prometheus(reg.to_prometheus())
    assert parsed["counters"]["serving_tokens"] == 42.0
    assert parsed["counters"]["serving_requests"] == 7.0
    assert parsed["gauges"]["serving_queue_depth"] == 3.5
    hist = parsed["histograms"]["serving_ttft"]
    assert hist["count"] == 2.0
    assert hist["sum"] == pytest.approx(0.42)
    assert hist["buckets"], "bucket rows must round-trip"
    # cumulative buckets end at the +Inf row carrying the full count
    les, cums = zip(*hist["buckets"])
    assert les[-1] == float("inf") and cums[-1] == 2.0
    assert list(cums) == sorted(cums)


def test_parse_tolerates_torn_bodies_and_garbage():
    reg = MetricsRegistry()
    reg.increment("serving.tokens", 9)
    reg.gauge("serving.queue.depth", 2.0)
    reg.observe_time("serving.ttft", 0.05)
    full = reg.to_prometheus()
    whole = parse_prometheus(full)
    for cut in range(0, len(full), 7):
        parsed = parse_prometheus(full[:cut])   # must never raise
        for section in ("counters", "gauges"):
            for k, v in parsed[section].items():
                assert whole[section][k] == v, "a torn prefix may only " \
                    "lose data, never invent or corrupt it"
    garbage = "##\nnot a line at all {{{\nx_total notafloat\nlone_token\n"
    assert parse_prometheus(garbage) == {
        "counters": {}, "gauges": {}, "histograms": {}}


def test_parse_classifies_bare_samples_by_suffix_convention():
    # TYPE headers lost to the tear: _total means counter, else gauge
    parsed = parse_prometheus("serving_tokens_total 3\nserving_qd 2\n")
    assert parsed["counters"] == {"serving_tokens": 3.0}
    assert parsed["gauges"] == {"serving_qd": 2.0}


# ------------------------------------------------------------ federation
def test_federated_registry_values_and_local_staleness():
    fed = FederatedRegistry()
    fed.update("a", parse_prometheus(_replica_body(10, 5.0)), t=100.0)
    fed.update("b", parse_prometheus(_replica_body(20, 7.0)), t=100.0)
    assert fed.replicas() == ["a", "b"]
    # dotted and prometheus series names both resolve
    assert fed.value("serving.tokens", "a") == 10.0
    assert fed.value("serving.tokens_per_sec", "b") == 7.0
    fed.mark_stale("b")
    assert fed.stale_replicas() == ["b"]
    assert fed.values("serving.tokens") == {"a": 10.0, "b": 20.0}
    assert fed.values("serving.tokens_per_sec",
                      include_stale=False) == {"a": 5.0}
    # staleness age is judged on the LOCAL receive clock only
    assert fed.age_s("a", now=103.0) == pytest.approx(3.0)
    fed.update("b", parse_prometheus(_replica_body(25, 6.0)))
    assert fed.stale_replicas() == []   # a good scrape clears the mark
    fed.forget("b")
    assert fed.replicas() == ["a"]


def test_scraper_rollups_spreads_and_dead_replica_degradation():
    obs.enable()
    reg = MetricsRegistry()
    scraper = FleetScraper(
        StubPool([StubReplica("r0", _replica_body(10, 5.0)),
                  StubReplica("r1", _replica_body(20, 7.0)),
                  StubReplica("r2", OSError("connection refused"))]),
        registry=reg)
    assert scraper.scrape_once() == 2
    snap = reg.snapshot()
    assert snap["counters"]["fleet.scrapes"] == 1.0
    assert snap["counters"]["fleet.scrape_errors"] == 1.0
    assert scraper.fed.stale_replicas() == ["r2"]
    # counter rollup: sum of replica counters; gauge rollup: live only
    assert snap["gauges"]["fleet.tokens_total"] == 30.0
    assert snap["gauges"]["fleet.tokens_per_sec"] == 12.0
    assert snap["gauges"]["fleet.spread.serving.tokens_per_sec.min"] == 5.0
    assert snap["gauges"]["fleet.spread.serving.tokens_per_sec.max"] == 7.0
    assert snap["gauges"]["fleet.replicas"] == 2.0
    assert snap["gauges"]["fleet.stale_replicas"] == 1.0
    assert "fleet.scrape" in snap["timers"]


def test_scraper_keeps_stale_counters_but_drops_stale_gauges():
    obs.enable()
    reg = MetricsRegistry()
    health = {"r1": _replica_body(20, 7.0)}
    pool = StubPool([StubReplica("r0", _replica_body(10, 5.0)),
                     StubReplica("r1", lambda: health["r1"])])
    scraper = FleetScraper(pool, registry=reg)
    scraper.scrape_once()
    assert reg.snapshot()["gauges"]["fleet.tokens_total"] == 30.0
    # r1 dies AFTER contributing 20 tokens: the tokens stay in the
    # counter rollup (history doesn't un-happen), its throughput leaves
    # the gauge rollup (a dead replica serves nothing)
    health["r1"] = OSError("replica died")
    scraper.scrape_once()
    snap = reg.snapshot()
    assert scraper.fed.stale_replicas() == ["r1"]
    assert snap["gauges"]["fleet.tokens_total"] == 30.0
    assert snap["gauges"]["fleet.tokens_per_sec"] == 5.0
    assert snap["counters"]["fleet.scrape_errors"] == 1.0


def test_scraper_skips_quarantined_without_counting_an_error():
    obs.enable()
    reg = MetricsRegistry()
    pool = StubPool([StubReplica("r0", _replica_body(10, 5.0)),
                     StubReplica("q", _replica_body(99, 9.0))],
                    inactive={"q"})
    scraper = FleetScraper(pool, registry=reg)
    assert scraper.scrape_once() == 1
    snap = reg.snapshot()
    assert snap["counters"].get("fleet.scrape_errors", 0.0) == 0.0
    assert scraper.fed.stale_replicas() == ["q"]
    assert snap["gauges"]["fleet.tokens_total"] == 10.0


def test_scraper_folds_empty_body_replicas_through_local_registry():
    """An in-process ``EngineReplica`` answers ``""`` — its series live
    in the scraper's own registry and are folded in exactly once."""
    obs.enable()
    reg = MetricsRegistry()
    reg.increment("serving.tokens", 4)          # the local engine's counter
    pool = StubPool([StubReplica("local", ""),
                     StubReplica("r0", _replica_body(10, 5.0))])
    scraper = FleetScraper(pool, registry=reg)
    assert scraper.scrape_once() == 1           # only r0 federates
    snap = reg.snapshot()
    assert snap["gauges"]["fleet.tokens_total"] == 14.0
    assert snap["counters"].get("fleet.scrape_errors", 0.0) == 0.0


# --------------------------------------------------------------- tenants
def test_tenant_fold_is_deterministic_and_bounded():
    obs.enable()
    reg = MetricsRegistry()
    tl = TenantLabels(registry=reg, max_tenants=2)
    assert tl.label("acme") == "acme"
    assert tl.label("globex") == "globex"
    assert tl.label("initech") == OTHER_TENANT      # cap hit: folds
    assert tl.label("umbrella") == OTHER_TENANT
    assert tl.label("acme") == "acme"               # tracked stays exact
    assert tl.tracked() == ["acme", "globex"]
    assert reg.snapshot()["counters"]["fleet.tenant_overflow"] == 2.0
    # the fold bucket itself passes through without another overflow
    assert tl.label(OTHER_TENANT) == OTHER_TENANT
    assert reg.snapshot()["counters"]["fleet.tenant_overflow"] == 2.0


def test_tenant_accounting_mints_bounded_counters_only():
    obs.enable()
    reg = MetricsRegistry()
    tl = TenantLabels(registry=reg, max_tenants=1)
    tl.account("generated_tokens", "acme", 5)
    tl.account("generated_tokens", "acme", 3)
    tl.account("generated_tokens", "globex", 7)     # folds
    tl.account("queue_wait_s", "globex", 0.25)
    tl.account("rejected", "")                      # no tenant: no-op
    counters = reg.snapshot()["counters"]
    assert counters["tenant.acme.generated_tokens"] == 8.0
    assert counters["tenant.__other__.generated_tokens"] == 7.0
    assert counters["tenant.__other__.queue_wait_s"] == 0.25
    assert not any(k.startswith("tenant.globex.") for k in counters), \
        "an untracked tenant must never mint its own series"


# ----------------------------------------------------------------- trend
def _store_with(points, name="s", t0=100.0, spacing=1.0):
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    for i, v in enumerate(points):
        reg.gauge(name, v)
        store.sample_once(t=t0 + i * spacing)
    return reg, store


def test_trend_fits_ramps_flats_and_refuses_short_history():
    _, store = _store_with([2.0 * i for i in range(10)])
    slope, r2, n = store.trend("s", 100.0)
    assert slope == pytest.approx(2.0)
    assert r2 == pytest.approx(1.0)
    assert n == 10
    _, store = _store_with([3.0] * 8)
    slope, r2, n = store.trend("s", 100.0)
    assert slope == 0.0 and r2 == 1.0       # flat: certain, not noisy
    _, store = _store_with([1.0])
    assert store.trend("s", 100.0) is None
    assert store.trend("missing", 100.0) is None


def test_trend_uses_only_the_trailing_window():
    # 10 flat samples then 5 rising: an 100 s window sees a kink, a 5 s
    # window sees the pure ramp
    _, store = _store_with([0.0] * 10 + [float(i) for i in range(1, 6)])
    slope_all = store.trend("s", 100.0)[0]
    slope_tail = store.trend("s", 4.5)[0]
    assert slope_tail == pytest.approx(1.0)
    assert 0.0 < slope_all < slope_tail


# -------------------------------------------------------------- forecast
def test_forecast_predicts_upper_crossing_within_one_sample(tmp_path):
    obs.enable()
    reg, store = _store_with([float(i) for i in range(9)])   # v = t - 100
    obj = SLObjective("ramp", "upper", "s", 10.0, windows=(8.0,))
    fore = ForecastEvaluator([obj], store, registry=reg,
                             flightrec=FlightRecorder(tmp_path),
                             horizon_s=5.0, window_s=100.0, attach=False)
    now = 108.0
    out = fore.evaluate(store, now=now)
    # v crosses 10 at t=110; last sample is (108, 8) with slope 1/s
    assert out["ramp"] == pytest.approx(2.0)
    assert now + out["ramp"] == pytest.approx(110.0, abs=1.0)
    assert reg.snapshot()["gauges"][
        "forecast.time_to_breach.ramp"] == pytest.approx(2.0)
    # ttb < horizon: one forecast_breach bundle with the fit inside
    assert len(fore.warnings) == 1
    bundles = list(tmp_path.glob("flightrec-forecast_breach-*.json"))
    assert len(bundles) == 1
    assert reg.snapshot()["counters"]["forecast.breach_warnings"] == 1.0


def test_forecast_flat_noisy_and_receding_publish_inf(tmp_path):
    obs.enable()
    rec = FlightRecorder(tmp_path)
    obj = SLObjective("o", "upper", "s", 10.0, windows=(8.0,))
    # flat well under the objective: no forecast, no warning
    reg, store = _store_with([3.0] * 8)
    fore = ForecastEvaluator([obj], store, registry=reg, flightrec=rec,
                             horizon_s=1e9, window_s=100.0, attach=False)
    assert fore.evaluate(store, now=107.0)["o"] == float("inf")
    # receding: moving AWAY from an upper bound
    reg, store = _store_with([9.0 - i for i in range(8)])
    fore = ForecastEvaluator([obj], store, registry=reg, flightrec=rec,
                             horizon_s=1e9, window_s=100.0, attach=False)
    assert fore.evaluate(store, now=107.0)["o"] == float("inf")
    # noisy (R² under the gate): an honest "no forecast"
    reg, store = _store_with([0.0, 9.0, 1.0, 8.0, 0.5, 9.5, 1.5, 7.0])
    fore = ForecastEvaluator([obj], store, registry=reg, flightrec=rec,
                             horizon_s=1e9, window_s=100.0, min_r2=0.5,
                             attach=False)
    assert fore.evaluate(store, now=107.0)["o"] == float("inf")
    # short history (< min_samples): same refusal
    reg, store = _store_with([1.0, 2.0, 3.0])
    fore = ForecastEvaluator([obj], store, registry=reg, flightrec=rec,
                             horizon_s=1e9, window_s=100.0, min_samples=4,
                             attach=False)
    assert fore.evaluate(store, now=102.0)["o"] == float("inf")


def test_forecast_already_at_threshold_is_zero(tmp_path):
    obs.enable()
    reg, store = _store_with([8.0, 9.0, 10.0, 11.0])
    obj = SLObjective("o", "upper", "s", 10.0, windows=(8.0,))
    fore = ForecastEvaluator([obj], store, registry=reg,
                             flightrec=FlightRecorder(tmp_path),
                             horizon_s=5.0, window_s=100.0, attach=False)
    assert fore.evaluate(store, now=103.0)["o"] == 0.0


def test_forecast_warning_lands_strictly_before_slo_breach(tmp_path):
    """The §24 ordering contract: on a genuine ramp the forecast bundle
    fires while the SLO evaluator still sees a healthy series, and the
    first warning instant precedes ``SLOEvaluator.breach_times``."""
    obs.enable()
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    obj = SLObjective("serving_ttft", "upper", "serving.ttft.p99", 0.5,
                      budget=0.05, windows=(8.0, 16.0))
    slo = SLOEvaluator([obj], store, registry=reg,
                       flightrec=FlightRecorder(tmp_path / "slo"),
                       breach_cooldown_s=1e9)
    fore = ForecastEvaluator([obj], store, registry=reg,
                             flightrec=FlightRecorder(tmp_path / "fc"),
                             horizon_s=30.0, window_s=8.0, min_samples=4,
                             breach_cooldown_s=1e9)
    t = 0.0
    while t <= 40.0:
        reg.gauge("serving.ttft.p99", 0.1 + 0.02 * t)   # crosses 0.5 @ t=20
        store.sample_once(t=t)
        t += 0.5
    warn_t = fore._last_warn_t.get("serving_ttft")
    breach_t = slo.breach_times.get("serving_ttft")
    assert warn_t is not None, "forecast never warned on a clean ramp"
    assert breach_t is not None, "the ramp never actually breached"
    assert warn_t < breach_t, (
        f"forecast warned at t={warn_t} but the SLO breach landed at "
        f"t={breach_t} — the leading indicator must lead")
    assert list((tmp_path / "fc").glob("flightrec-forecast_breach-*.json"))


# ------------------------------------------------------------ concurrency
@pytest.mark.lockguard
def test_scraper_and_federated_registry_survive_contention():
    """Mutator threads hammer the source registry while the scraper
    federates it and readers walk the federated view — instrumented
    locks, no deadlock, no exception, and the final quiesced scrape is
    exact."""
    obs.enable()
    source = MetricsRegistry()
    reg = MetricsRegistry()
    pool = StubPool([StubReplica("r0", source.to_prometheus),
                     StubReplica("r1", _replica_body(5, 1.0))])
    scraper = FleetScraper(pool, registry=reg)
    errors: list[str] = []
    stop = threading.Event()
    n_threads, n_iter = 4, 200

    def mutator(i):
        try:
            for k in range(n_iter):
                source.increment("serving.tokens")
                source.gauge("serving.tokens_per_sec", float(k))
                source.observe_time("serving.ttft", 0.001 * (k % 5 + 1))
        except Exception as e:              # pragma: no cover - failure path
            errors.append(repr(e))

    def reader():
        try:
            while not stop.is_set():
                scraper.fed.values("serving.tokens")
                scraper.fed.snapshot()
                scraper.fed.stale_replicas()
        except Exception as e:              # pragma: no cover - failure path
            errors.append(repr(e))

    def scrape_loop():
        try:
            while not stop.is_set():
                scraper.scrape_once()
        except Exception as e:              # pragma: no cover - failure path
            errors.append(repr(e))

    threads = ([threading.Thread(target=mutator, args=(i,))
                for i in range(n_threads)]
               + [threading.Thread(target=reader),
                  threading.Thread(target=scrape_loop)])
    for t in threads:
        t.start()
    for t in threads[:n_threads]:
        t.join(30)
    stop.set()
    for t in threads[n_threads:]:
        t.join(30)
    assert not errors
    scraper.scrape_once()                   # quiesced: must be exact now
    assert scraper.fed.value("serving.tokens", "r0") == n_threads * n_iter
    assert reg.snapshot()["gauges"]["fleet.tokens_total"] == \
        n_threads * n_iter + 5


# --------------------------------------------------------- disabled-free
def test_disabled_fleet_paths_allocate_nothing():
    """DL4J_TPU_OBS=0 contract for the whole plane: the label fold, the
    accounting, a scrape pass, a forecast pass, and a trend query all
    run allocation-free while observability is off."""
    reg = MetricsRegistry()
    store = TimeSeriesStore(registry=reg)
    tl = TenantLabels(registry=reg)
    scraper = FleetScraper(StubPool([StubReplica("r0", "x 1\n")]),
                           registry=reg)
    fore = ForecastEvaluator(
        [SLObjective("o", "upper", "s", 1.0)], store, registry=reg,
        flightrec=FlightRecorder(), attach=False)
    obs.disable()
    try:
        assert tl.label("acme") == ""
        assert scraper.scrape_once() == 0
        assert scraper.start() is False
        assert fore.evaluate(store, now=1.0) == {}
        assert store.trend("s", 5.0) is None
        # warm once, then assert the steady state allocates zero bytes
        tl.account("generated_tokens", "acme", 1.0)
        tracemalloc.start()
        base = tracemalloc.get_traced_memory()[0]
        for _ in range(50):
            tl.label("acme")
            tl.account("generated_tokens", "acme", 1.0)
            scraper.scrape_once()
            fore.evaluate(store, now=1.0)
            store.trend("s", 5.0)
        grown = tracemalloc.get_traced_memory()[0] - base
        tracemalloc.stop()
        assert grown == 0, f"disabled fleet paths allocated {grown} bytes"
        assert reg.snapshot()["counters"] == {}
    finally:
        obs.enable()


# ------------------------------------------------- process replica scrape
def test_process_replica_sigkill_scrape_raises_fast(tmp_path):
    """Satellite regression: a SIGKILL'd child must surface as
    ``ReplicaUnavailable`` within the scrape timeout — never a hang, and
    never the retry-doubled cost of the request transport."""
    from deeplearning4j_tpu.serving.router.replicas import (
        ProcessReplica, ReplicaUnavailable)

    rep = ProcessReplica(
        "pk", "deeplearning4j_tpu.serving.router.procserver:tiny_lm_factory",
        tmp_path, factory_kwargs={"max_len": 32, "slots": 2},
        env={"JAX_PLATFORMS": "cpu"}, client_timeout_s=5.0)
    try:
        body = rep.metrics_prom(timeout_s=5.0)
        assert isinstance(body, str)
        parse_prometheus(body)              # live body parses cleanly
        rep.kill()
        t0 = time.monotonic()
        with pytest.raises(ReplicaUnavailable):
            rep.metrics_prom(timeout_s=2.0)
        elapsed = time.monotonic() - t0
        assert elapsed < 2.0 + 1.0, (
            f"dead-child scrape took {elapsed:.1f}s — must be bounded by "
            "one timeout")
    finally:
        rep.close()


def test_fleet_scraper_absorbs_a_killed_replica(tmp_path):
    """The scraper-level view of the same death: errors counted, the
    dead replica stale, the live replica's rollup intact."""
    obs.enable()
    reg = MetricsRegistry()
    pool = StubPool([StubReplica("live", _replica_body(10, 5.0)),
                     StubReplica("dead", _replica_body(20, 7.0))])
    scraper = FleetScraper(pool, registry=reg, timeout_s=2.0)
    scraper.scrape_once()
    pool.replica("dead").body = OSError("SIGKILL")
    t0 = time.monotonic()
    scraper.scrape_once()
    assert time.monotonic() - t0 < 2.0 * len(pool.names()) + 1.0
    snap = reg.snapshot()
    assert snap["counters"]["fleet.scrape_errors"] == 1.0
    assert scraper.fed.stale_replicas() == ["dead"]
    assert snap["gauges"]["fleet.tokens_total"] == 30.0   # history kept
    assert snap["gauges"]["fleet.tokens_per_sec"] == 5.0  # live only


# ------------------------------------------------------------------ OB03
OB03_BAD = """
    from deeplearning4j_tpu.observability import METRICS
    def work(registry, tenant, payload, req, user_id):
        METRICS.increment(f"tenant.{tenant}.tokens")
        registry.gauge("user." + user_id + ".latency", 1.0)
        METRICS.increment(f"per.{payload.get('tenant')}.count")
        METRICS.observe_time(f"req.{req.request_id}", 0.1)
"""

OB03_GOOD = """
    from deeplearning4j_tpu.observability import METRICS, TENANTS
    def work(site, series, device_id, tenant, registry):
        METRICS.increment(f"faults.injected.{site}")
        registry.gauge("fleet.spread." + series + ".min", 1.0)
        METRICS.gauge(f"train.params_bytes.device.{device_id}", 2.0)
        TENANTS.account("generated_tokens", tenant, 5)
        METRICS.increment("serving.requests")
        name = compute_name(tenant)
        METRICS.increment(name)          # composed elsewhere: blind spot
"""


def _lint(source, path="deeplearning4j_tpu/serving/snippet.py"):
    from deeplearning4j_tpu.analysis import Analyzer, all_rules
    analyzer = Analyzer(rules=[all_rules()["OB03"]])
    findings = analyzer.analyze_source(textwrap.dedent(source), path)
    assert not analyzer.errors
    return findings


def test_ob03_fires_on_request_derived_metric_names():
    findings = _lint(OB03_BAD)
    assert len(findings) == 4
    assert {f.rule for f in findings} == {"OB03"}
    assert any("TenantLabels" in f.message for f in findings)


def test_ob03_quiet_on_bounded_interpolations_and_the_helper():
    assert not _lint(OB03_GOOD)
    # fleet.py IS the bounded helper: the one sanctioned interpolation site
    assert not _lint(OB03_BAD,
                     path="deeplearning4j_tpu/observability/fleet.py")


def test_ob03_package_tree_is_clean():
    """Zero-baseline contract: no package code interpolates
    request-derived data into metric names outside the helper."""
    import os

    from deeplearning4j_tpu.analysis import Analyzer, active, all_rules
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    analyzer = Analyzer(rules=[all_rules()["OB03"]], root=repo)
    findings = analyzer.analyze_paths(
        [os.path.join(repo, "deeplearning4j_tpu")])
    assert [f for f in active(findings)] == []


# ------------------------------------------------------------------ tools
def test_metrics_dump_renders_fleet_tenants_and_forecast_tables():
    from tools.metrics_dump import (render_fleet, render_forecast,
                                    render_tenants)

    snap = {
        "gauges": {
            "fleet.replicas": 3.0, "fleet.stale_replicas": 1.0,
            "fleet.tokens_per_sec": 12.0, "fleet.tokens_total": 137.0,
            "fleet.spread.serving.tokens_per_sec.min": 5.0,
            "fleet.spread.serving.tokens_per_sec.med": 5.0,
            "fleet.spread.serving.tokens_per_sec.max": 7.0,
            "forecast.time_to_breach.serving_ttft": float("inf"),
            "forecast.time_to_breach.serving_error_rate": 42.0,
        },
        "counters": {
            "fleet.scrapes": 4.0, "fleet.scrape_errors": 1.0,
            "fleet.tenant_overflow": 2.0,
            "tenant.acme.generated_tokens": 10.0,
            "tenant.acme.prompt_tokens": 4.0,
            "tenant.zeta.generated_tokens": 1.0,
            "tenant.__other__.generated_tokens": 2.0,
            "tenant.__other__.rejected": 3.0,
            "forecast.breach_warnings": 1.0,
        },
    }
    fleet = render_fleet(snap)
    assert "tokens_per_sec" in fleet and "scrape_errors" in fleet
    assert "spread serving.tokens_per_sec" in fleet
    tenants = render_tenants(snap)
    lines = tenants.splitlines()
    acme_i = next(i for i, ln in enumerate(lines) if "acme" in ln)
    zeta_i = next(i for i, ln in enumerate(lines) if "zeta" in ln)
    assert acme_i < zeta_i, "tenants must rank by tokens"
    assert "__other__" in tenants, "the overflow bucket must stay visible"
    forecast = render_forecast(snap)
    assert "serving_ttft" in forecast and "inf" in forecast
    assert "serving_error_rate" in forecast
    # non-fleet processes render nothing rather than empty tables
    empty = {"gauges": {"train.mfu": 0.5}, "counters": {"x": 1.0}}
    assert render_fleet(empty) is None
    assert render_tenants(empty) is None
    assert render_forecast(empty) is None


def test_trace_report_carries_the_tenant_column():
    from tools.trace_report import render, request_breakdowns

    def req(tid, tenant, ts):
        args = {"trace_id": tid, "tokens": 3}
        if tenant:
            args["tenant"] = tenant
        return [
            {"ph": "X", "name": "serving.request", "ts": ts, "dur": 5000.0,
             "args": args},
            {"ph": "X", "name": "serving.queue_wait", "ts": ts, "dur": 50.0,
             "args": {"trace_id": tid}},
            {"ph": "X", "name": "serving.prefill", "ts": ts + 100,
             "dur": 400.0, "args": {"trace_id": tid}},
        ]

    events = req("a" * 16, "acme", 0.0) + req("b" * 16, None, 10000.0)
    rows = request_breakdowns(events)
    assert [r["tenant"] for r in rows] == ["acme", None]
    out = render(rows, limit=0)
    assert "tenant" in out.splitlines()[1]
    assert "acme" in out
    # untenanted traffic renders "-" rather than "None"
    assert "None" not in out
