"""Multi-replica serving tier: ring stability, prefix-affinity routing,
spillover, breaker quarantine/re-admission, the RouterServer HTTP
surface, and procrunner-spawned process replicas (DESIGN.md §19)."""

import json
import socket
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import jax.numpy as jnp
import pytest

from deeplearning4j_tpu import observability
from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                   TransformerLM)
from deeplearning4j_tpu.observability import FLIGHTREC, METRICS, TRACER
from deeplearning4j_tpu.resilience.faults import FaultSpec, inject_faults
from deeplearning4j_tpu.serving import (EngineReplica, HashRing,
                                        InferenceEngine, PagePool,
                                        PrefixRouter, ProcessReplica,
                                        QueueFull, ReplicaPool,
                                        ReplicaUnavailable, RouterConfig,
                                        RouterServer, ServingClient,
                                        ServingConfig, ServingError,
                                        ServingRejected, prefix_chain_keys)
from deeplearning4j_tpu.serving.router.replicas import Replica


def tiny_cfg(**kw):
    base = dict(vocab_size=64, d_model=32, n_heads=4, n_layers=2, d_ff=64,
                max_len=32, dtype=jnp.float32, remat=False, xent_chunk=0)
    base.update(kw)
    return TransformerConfig(**base)


@pytest.fixture(scope="module")
def lm():
    cfg = tiny_cfg()
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))
    return model, params


def _expected(model, params, prompt, n, temp, seed):
    out = model.sample(params, prompt, n, temperature=temp,
                       key=jax.random.key(seed), kv_cache=True)
    return [int(t) for t in out[len(prompt):]]


# --------------------------------------------------------------------------- ring

def test_ring_walk_yields_every_node_once():
    ring = HashRing([f"r{i}" for i in range(5)])
    order = list(ring.walk("some-key"))
    assert sorted(order) == [f"r{i}" for i in range(5)]
    assert order == list(ring.walk("some-key"))  # deterministic


def test_ring_add_remaps_only_to_new_node():
    n = 8
    ring = HashRing([f"r{i}" for i in range(n)])
    keys = [f"key-{i}" for i in range(2000)]
    before = {k: ring.primary(k) for k in keys}
    ring.add("r-new")
    moved = {k for k in keys if ring.primary(k) != before[k]}
    # every remapped key must have moved TO the new node (consistent
    # hashing's defining property: old nodes never exchange keys) ...
    assert all(ring.primary(k) == "r-new" for k in moved)
    # ... and only ~1/(N+1) of the keyspace moves at all
    assert len(moved) / len(keys) <= 2.0 / (n + 1), (
        f"{len(moved)}/{len(keys)} keys remapped by one join")


def test_ring_remove_remaps_only_the_removed_nodes_keys():
    ring = HashRing([f"r{i}" for i in range(8)])
    keys = [f"key-{i}" for i in range(2000)]
    before = {k: ring.primary(k) for k in keys}
    ring.remove("r3")
    for k in keys:
        if before[k] != "r3":
            assert ring.primary(k) == before[k]


def test_ring_balance_under_uniform_keys():
    n = 4
    ring = HashRing([f"r{i}" for i in range(n)], vnodes=128)
    counts = {f"r{i}": 0 for i in range(n)}
    for i in range(4000):
        counts[ring.primary(f"key-{i}")] += 1
    for name, c in counts.items():
        share = c / 4000
        assert 0.10 <= share <= 0.45, (
            f"{name} owns {share:.2%} of a uniform keyspace")


# --------------------------------------------------------------------------- routing key

def test_routing_key_matches_pool_chain_hash():
    tokens = list(range(40))
    pool = PagePool(num_pages=16, page_size=4)
    assert pool.chain_keys(tokens, 39) == prefix_chain_keys(tokens, 39, 4)


def test_routing_key_affinity_prefix_stability():
    router = PrefixRouter([_StubReplica("r0")],
                          RouterConfig(page_size=4, affinity_pages=2))
    system = list(range(8))              # exactly affinity_pages full pages
    k1 = router.routing_key(system + [1, 2, 3])
    k2 = router.routing_key(system + [9, 10, 11, 12, 13])
    assert k1 == k2                      # different user tails, same key
    assert k1 in prefix_chain_keys(system + [1, 2, 3], 10, 4)
    # prompts without one full usable page fall back to a whole-prompt hash
    short = router.routing_key([1, 2])
    assert short.startswith("short:")
    assert short != router.routing_key([1, 3])


# --------------------------------------------------------------------------- breaker (stubs)

class _StubReplica(Replica):
    """A replica that answers instantly; ``fail_with`` forces errors."""

    def __init__(self, name):
        super().__init__(name)
        self.calls = 0
        self.fail_with = None

    def generate(self, payload, timeout_s):
        self.calls += 1
        if self.fail_with is not None:
            raise self.fail_with
        return {"tokens": [1], "finish_reason": "length",
                "latency_s": 0.0, "ttft_s": 0.0}

    def healthz(self, timeout_s):
        if self.fail_with is not None:
            raise self.fail_with
        return {"ok": True, "engine": {}}


def _stub_router(n=4, **cfg_kw):
    kw = dict(page_size=4, affinity_pages=2, fail_threshold=2,
              recover_threshold=1)
    kw.update(cfg_kw)
    stubs = [_StubReplica(f"r{i}") for i in range(n)]
    return PrefixRouter(stubs, RouterConfig(**kw)), stubs


@pytest.mark.lockguard
def test_spillover_on_429_preserves_availability():
    observability.enable()
    router, stubs = _stub_router()
    prompt = list(range(12))
    owner = router.route_order(router.routing_key(prompt))[0]
    stubs[int(owner[1:])].fail_with = QueueFull("shedding")
    out = router.generate(prompt, 4)
    assert out["spills"] == 1
    assert out["replica"] == router.route_order(router.routing_key(prompt))[1]
    snap = METRICS.snapshot()
    assert snap["counters"].get("router.spillover") == 1
    assert snap["counters"].get("router.prefix_affinity_hit") is None
    # 429 means alive-but-full: the breaker must NOT quarantine for it
    assert router.pool.is_active(owner)
    router.close()


@pytest.mark.lockguard
def test_quarantine_and_readmit_restore_assignment():
    observability.enable()
    router, stubs = _stub_router(fail_threshold=2)
    prompt = list(range(12))
    key = router.routing_key(prompt)
    original_order = router.route_order(key)
    owner = original_order[0]
    stub = stubs[int(owner[1:])]

    stub.fail_with = ReplicaUnavailable(f"replica {owner} wedged")
    for _ in range(2):                   # fail_threshold dispatch failures
        out = router.generate(prompt, 4)
        assert out["replica"] == original_order[1]   # drained to successor
    assert not router.pool.is_active(owner)
    # quarantined: the ring segment drains WITHOUT remapping other keys
    assert router.route_order(key) == original_order[1:]

    # a probe sweep while still down keeps it quarantined
    router.pool.probe_once()
    assert not router.pool.is_active(owner)

    # recovery: probes succeed again -> re-admitted, assignment restored
    stub.fail_with = None
    router.pool.probe_once()
    assert router.pool.is_active(owner)
    assert router.route_order(key) == original_order
    assert router.generate(prompt, 4)["replica"] == owner

    snap = METRICS.snapshot()
    assert snap["counters"].get("router.quarantines") == 1
    assert snap["counters"].get("router.readmissions") == 1
    router.close()


def test_quarantine_dumps_flightrec_bundle_naming_replica(tmp_path):
    observability.enable()
    router, stubs = _stub_router(fail_threshold=1)
    router.pool.probe_once()             # record a healthy last_probe first
    prompt = list(range(12))
    owner = router.route_order(router.routing_key(prompt))[0]
    stubs[int(owner[1:])].fail_with = ReplicaUnavailable("dead")
    router.generate(prompt, 4)
    bundles = sorted(FLIGHTREC.dump_dir.glob(
        "flightrec-router_replica_quarantine-*.json"))
    assert bundles, "quarantine left no flight-recorder bundle"
    bundle = json.loads(bundles[-1].read_text())
    assert bundle["extra"]["replica"] == owner
    assert bundle["extra"]["last_probe"], "bundle lost the last health probe"
    router.close()


def test_all_replicas_down_is_503_not_a_hang():
    router, stubs = _stub_router(n=2, fail_threshold=1)
    for s in stubs:
        s.fail_with = ReplicaUnavailable("down")
    t0 = time.monotonic()
    with pytest.raises(ServingRejected) as ei:
        router.generate(list(range(12)), 4)
    assert ei.value.status == 503
    assert time.monotonic() - t0 < 5.0
    router.close()


def test_spillover_burst_dumps_bundle():
    observability.enable()
    for _ in range(FLIGHTREC.spill_burst_n):
        path = FLIGHTREC.note_spillover("r1")
    assert path is not None and path.exists()
    bundle = json.loads(path.read_text())
    assert bundle["trigger"] == "router_spillover_burst"
    assert "r1" in bundle["extra"]["recent_replicas"]


# --------------------------------------------------------------------------- routing (engines)

@pytest.mark.lockguard
def test_affinity_and_token_parity_through_router(lm):
    model, params = lm
    observability.enable()
    engines = [InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True)) for _ in range(2)]
    for e in engines:
        e.start(warmup=False)
    reps = [EngineReplica(f"r{i}", e, own_engine=True)
            for i, e in enumerate(engines)]
    router = PrefixRouter(reps, RouterConfig(page_size=4, affinity_pages=2))
    system = [5, 9, 13, 2, 30, 41, 8, 19]          # 2 full pages shared
    served_by = set()
    for i, tail in enumerate(([3], [7, 11], [22, 1, 60])):
        prompt = system + tail
        out = router.generate(prompt, 5, temperature=0.0, seed=100 + i)
        assert out["tokens"] == _expected(model, params, prompt, 5, 0.0,
                                          100 + i)
        assert out["spills"] == 0
        served_by.add(out["replica"])
    # one tenant, one replica: that is what affinity means
    assert len(served_by) == 1
    snap = METRICS.snapshot()
    assert snap["counters"]["router.prefix_affinity_hit"] == 3
    # the pool-weighted aggregate hit-rate gauge comes from a probe sweep
    router.pool.probe_once()
    assert METRICS.snapshot()["gauges"]["router.prefix_hit_rate"] > 0.0
    router.close()


def test_spilled_requests_keep_token_parity(lm):
    model, params = lm
    observability.enable()
    engines = [InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=1, resolve_every=2, max_queue=3))
        for _ in range(2)]
    for e in engines:
        e.start(warmup=False)
    reps = [EngineReplica(f"r{i}", e, own_engine=True)
            for i, e in enumerate(engines)]
    router = PrefixRouter(reps, RouterConfig(page_size=4, affinity_pages=2))
    system = [5, 9, 13, 2, 30, 41, 8, 19]
    plans = [(system + [i], 12, 7000 + i) for i in range(6)]
    outs: dict[int, dict] = {}
    errors: list[BaseException] = []
    barrier = threading.Barrier(len(plans))

    def fire(idx, prompt, n, seed):
        barrier.wait()
        try:
            outs[idx] = router.generate(prompt, n, temperature=0.0, seed=seed)
        except BaseException as e:       # noqa: BLE001 - re-raised below
            errors.append(e)

    ts = [threading.Thread(target=fire, args=(i, *p))
          for i, p in enumerate(plans)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60.0)
    assert not errors, errors
    assert len(outs) == len(plans)
    # one tenant hammering one replica's 3-deep capacity with 6 parallel
    # requests MUST shed some onto the ring successor ...
    # the 6-burst lands before any slot pops, so the owner can absorb at
    # most max_queue of it and MUST shed the rest onto the successor
    spilled = [o for o in outs.values() if o["spills"] > 0]
    assert spilled, "no spillover under 2x oversubscription"
    assert METRICS.snapshot()["counters"]["router.spillover"] >= 1
    # ... and a spilled request's tokens are indistinguishable from the
    # affinity replica's (same params, same seed, same sampler)
    for idx, (prompt, n, seed) in enumerate(plans):
        assert outs[idx]["tokens"] == _expected(model, params, prompt, n,
                                                0.0, seed)
    router.close()


def test_chaos_replica_down_quarantine_and_readmission(lm):
    """The ISSUE's chaos plan: one of 4 replicas dies mid-workload —
    requests re-route without hanging, other replicas' tenants are
    undisturbed, and the ring re-admits the replica on recovery."""
    model, params = lm
    observability.enable()
    engines = [InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True)) for _ in range(4)]
    for e in engines:
        e.start(warmup=False)
    reps = [EngineReplica(f"r{i}", e, own_engine=True)
            for i, e in enumerate(engines)]
    router = PrefixRouter(reps, RouterConfig(
        page_size=4, affinity_pages=2, fail_threshold=1, recover_threshold=1,
        probe_interval_s=0.05)).start()

    # two tenants owned by two DIFFERENT replicas
    rng_prompts = ([5, 9, 13, 2, 30, 41, 8, 19, 3],
                   [1, 1, 2, 3, 5, 8, 13, 21, 34],
                   [60, 59, 58, 57, 56, 55, 54, 53, 2],
                   [7, 7, 7, 7, 7, 7, 7, 7, 7])
    owners = {p: router.route_order(router.routing_key(p))[0]
              for p in map(tuple, rng_prompts)}
    victim_prompt = list(rng_prompts[0])
    victim = owners[tuple(rng_prompts[0])]
    other_prompt = next(list(p) for p, o in owners.items() if o != victim)

    with inject_faults(FaultSpec("router.replica_down", probability=1.0,
                                 max_fires=0, kind=victim)):
        t0 = time.monotonic()
        out = router.generate(victim_prompt, 4, temperature=0.0, seed=11)
        # failed fast onto a successor, never hung on the dead replica
        # (spills is 1 when the dispatch raced ahead of the prober, 0
        # once the breaker had already drained the ring segment)
        assert time.monotonic() - t0 < 10.0
        assert out["replica"] != victim and out["spills"] in (0, 1)
        assert out["tokens"] == _expected(model, params, victim_prompt, 4,
                                          0.0, 11)
        assert not router.pool.is_active(victim)
        # an unrelated tenant on a healthy replica is undisturbed
        out2 = router.generate(other_prompt, 4, temperature=0.0, seed=12)
        assert out2["replica"] == owners[tuple(other_prompt)]
        assert out2["spills"] == 0

    # recovery: the fault is disarmed, probes succeed, ring re-admits
    deadline = time.monotonic() + 5.0
    while not router.pool.is_active(victim) and time.monotonic() < deadline:
        time.sleep(0.05)
    assert router.pool.is_active(victim), "replica never re-admitted"
    out3 = router.generate(victim_prompt, 4, temperature=0.0, seed=13)
    assert out3["replica"] == victim
    bundles = list(FLIGHTREC.dump_dir.glob(
        "flightrec-router_replica_quarantine-*.json"))
    assert bundles, "chaos quarantine left no evidence bundle"
    router.close()


def test_injected_route_fault_maps_to_503(lm):
    router, _ = _stub_router()
    with inject_faults(FaultSpec("router.route", probability=1.0)):
        with pytest.raises(Exception) as ei:
            router.generate(list(range(12)), 4)
    assert "router.route" in str(ei.value)
    router.close()


# --------------------------------------------------------------------------- HTTP front end

def test_router_server_http_surface(lm):
    model, params = lm
    observability.enable()
    engines = [InferenceEngine(
        model, params=params,
        cfg=ServingConfig(slots=2, resolve_every=2, paged=True, page_size=4,
                          prefix_cache=True)) for _ in range(2)]
    for e in engines:
        e.start(warmup=False)
    reps = [EngineReplica(f"r{i}", e, own_engine=True)
            for i, e in enumerate(engines)]
    router = PrefixRouter(reps, RouterConfig(page_size=4, affinity_pages=2,
                                             probe_interval_s=0.05))
    prompt = [5, 9, 13, 2, 30, 41, 8, 19, 3]
    with RouterServer(router) as server:
        client = ServingClient(port=server.port)
        from deeplearning4j_tpu.observability import trace
        with trace.span("client.generate") as sp:
            out = client.generate(prompt, 5, temperature=0.0, seed=42)
        assert out["tokens"] == _expected(model, params, prompt, 5, 0.0, 42)
        assert out["replica"] in ("r0", "r1") and out["spills"] == 0

        # the caller's trace id spans client -> router hop -> engine
        names_in_trace = {ev["name"] for ev in TRACER.to_chrome_trace()
                          ["traceEvents"]
                          if (ev.get("args") or {}).get("trace_id")
                          == sp.trace_id}
        assert {"router.request", "router.route",
                "serving.request"} <= names_in_trace

        health = client.healthz()
        assert health["ok"] and set(health["replicas"]) == {"r0", "r1"}
        assert all(v["active"] for v in health["replicas"].values())

        prom = client.metrics_prom()
        assert "router_requests_total" in prom
        assert "router_replica_state_r0" in prom

        # rejection statuses are the API: malformed prompt -> 400
        with pytest.raises(ServingError) as ei:
            client.generate([999], 4)
        assert ei.value.status == 400

        # reload passes the replica's own answer through: these engines
        # serve from in-memory params, so the 409 survives the hop
        with pytest.raises(ServingError) as ei2:
            client._json("/v1/reload", {})
        assert ei2.value.status == 409


# --------------------------------------------------------------------------- process replicas

def test_process_replica_parity_and_fail_fast(lm, tmp_path):
    model, params = lm
    observability.enable()
    rep = ProcessReplica(
        "p0", "deeplearning4j_tpu.serving.router.procserver:tiny_lm_factory",
        tmp_path, factory_kwargs={"max_len": 32, "slots": 2,
                                  "paged": True, "page_size": 4,
                                  "prefix_cache": True},
        env={"JAX_PLATFORMS": "cpu"}, client_timeout_s=30.0)
    router = PrefixRouter([rep], RouterConfig(page_size=4, affinity_pages=2,
                                              fail_threshold=1))
    try:
        prompt = [5, 9, 13, 2, 30, 41, 8, 19, 3]
        out = router.generate(prompt, 5, temperature=0.0, seed=21)
        # the child built the SAME fixed-seed model: parity across the
        # process boundary, through router + HTTP + engine
        assert out["tokens"] == _expected(model, params, prompt, 5, 0.0, 21)
        assert out["replica"] == "p0"
        health = rep.healthz(5.0)
        assert health["ok"] and health["engine"]["prefix_lookups"] >= 1

        # SIGKILL mid-service: requests fail FAST (503), never hang
        rep.kill()
        t0 = time.monotonic()
        with pytest.raises(ServingRejected) as ei:
            router.generate(prompt, 5, temperature=0.0, seed=22)
        assert ei.value.status == 503
        assert time.monotonic() - t0 < 15.0
        assert not router.pool.is_active("p0")
    finally:
        router.close()


# --------------------------------------------------------------------------- client transport

class _FlakyHandler(BaseHTTPRequestHandler):
    """Resets the first ``fail_gets`` GET connections; counts POSTs."""

    fail_gets = {"n": 1}
    posts = {"n": 0}

    def log_message(self, *a):
        pass

    def do_GET(self):
        if self.fail_gets["n"] > 0:
            self.fail_gets["n"] -= 1
            self.connection.close()      # mid-flight connection reset
            return
        body = b'{"ok": true}'
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        self.posts["n"] += 1
        self.connection.close()          # always reset: POSTs must not retry


def test_client_retries_idempotent_gets_only():
    _FlakyHandler.fail_gets["n"] = 1
    _FlakyHandler.posts["n"] = 0
    server = ThreadingHTTPServer(("127.0.0.1", 0), _FlakyHandler)
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.start()
    try:
        client = ServingClient(port=server.server_address[1], timeout_s=5.0,
                               retries=1, retry_backoff_s=0.01)
        # the first connection dies mid-flight; the single idempotent
        # retry recovers the health probe
        assert client.healthz() == {"ok": True}
        # POSTs never retry: the request may have executed server-side
        with pytest.raises(OSError):
            client.generate([1, 2, 3], 4)
        assert _FlakyHandler.posts["n"] == 1
    finally:
        server.shutdown()
        thread.join(timeout=10.0)
        server.server_close()


def test_client_timeout_is_bounded():
    # a socket that accepts and then never answers: the per-call timeout
    # must bound the probe, not hang it
    sock = socket.socket()
    sock.bind(("127.0.0.1", 0))
    sock.listen(1)
    try:
        client = ServingClient(port=sock.getsockname()[1], timeout_s=60.0,
                               retries=0)
        t0 = time.monotonic()
        with pytest.raises(OSError):
            client.healthz(timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0
    finally:
        sock.close()


# --------------------------------------------------------------------------- tooling

def test_metrics_dump_renders_router_table():
    from tools.metrics_dump import render_router

    snap = {
        "gauges": {"router.replica_state.r0": 1.0,
                   "router.replica_state.r1": 0.0,
                   "router.replica_load.r0": 2.0,
                   "router.replica_queue_depth.r0": 3.0,
                   "router.prefix_hit_rate": 0.75},
        "counters": {"router.requests": 40.0,
                     "router.prefix_affinity_hit": 36.0,
                     "router.spillover": 4.0,
                     "router.quarantines": 1.0},
    }
    table = render_router(snap)
    assert table is not None
    assert "r0" in table and "active" in table and "quarantined" in table
    assert "75.0%" in table and "spillover" in table and "90.0%" in table
    # non-router snapshots stay silent
    assert render_router({"gauges": {}, "counters": {}}) is None
