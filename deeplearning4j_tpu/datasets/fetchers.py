"""Dataset fetchers.

Capability match of ``datasets/fetchers/*`` + ``base/*`` in the reference:
``BaseDataFetcher`` cursor/batch bookkeeping (``BaseDataFetcher.java``),
``MnistDataFetcher.java:21-80`` (IDX download + binarize),
``IrisDataFetcher``, ``LFWDataFetcher``, ``CSVDataFetcher``.

Sourcing is offline-first (this environment has zero egress): Iris and the
8x8 digits corpus come from scikit-learn's bundled copies; full MNIST reads
local IDX files when present (``MnistManager``-equivalent IDX parser in
``mnist_idx.py``), else falls back to the bundled digits upscaled to 28x28 so
MNIST-shaped pipelines still run end-to-end.  Download URLs are kept for
environments with egress.
"""

from __future__ import annotations

import gzip
import os
import urllib.request
from pathlib import Path

import numpy as np

from .dataset import DataSet, to_outcome_matrix
from .mnist_idx import read_idx_images, read_idx_labels

DEFAULT_BASE_DIR = Path(os.environ.get("DL4J_TPU_DATA", Path.home() / ".dl4j_tpu"))


class BaseDataFetcher:
    """Cursor/batch bookkeeping (``BaseDataFetcher.java``): subclasses load
    arrays once; ``fetch(num)`` advances a cursor and exposes ``cur`` as a
    DataSet."""

    def __init__(self):
        self.cursor = 0
        self.num_outcomes = 0
        self.input_columns = 0
        self.total_examples_ = 0
        self.cur: DataSet | None = None
        self._features: np.ndarray | None = None
        self._labels: np.ndarray | None = None

    # subclass hook
    def _load(self) -> tuple[np.ndarray, np.ndarray]:
        raise NotImplementedError

    def _ensure_loaded(self):
        if self._features is None:
            f, l = self._load()
            self._features = np.asarray(f, dtype=np.float32)
            self._labels = np.asarray(l, dtype=np.float32)
            self.total_examples_ = self._features.shape[0]
            self.input_columns = int(np.prod(self._features.shape[1:]))
            self.num_outcomes = self._labels.shape[-1]

    def has_more(self) -> bool:
        self._ensure_loaded()
        return self.cursor < self.total_examples_

    def fetch(self, num: int) -> None:
        self._ensure_loaded()
        if not self.has_more():
            raise StopIteration("fetcher exhausted")
        end = min(self.cursor + num, self.total_examples_)
        self.cur = DataSet(self._features[self.cursor:end], self._labels[self.cursor:end])
        self.cursor = end

    def next(self) -> DataSet:
        return self.cur

    def reset(self) -> None:
        self.cursor = 0

    def total_examples(self) -> int:
        self._ensure_loaded()
        return self.total_examples_


class IrisDataFetcher(BaseDataFetcher):
    """Iris, 150 examples, 4 features, 3 classes (``IrisDataFetcher`` +
    ``base/IrisUtils.java``).  Sourced from scikit-learn's bundled copy."""

    NUM_EXAMPLES = 150

    def _load(self):
        from sklearn.datasets import load_iris
        d = load_iris()
        return d.data, to_outcome_matrix(d.target, 3)


class DigitsDataFetcher(BaseDataFetcher):
    """8x8 handwritten digits (1,797 examples, 10 classes) — the offline
    MNIST-class corpus bundled with scikit-learn; used by tests as the fast
    stand-in for full MNIST."""

    def __init__(self, binarize: bool = False, flatten: bool = True):
        super().__init__()
        self.binarize = binarize
        self.flatten = flatten

    def _load(self):
        from sklearn.datasets import load_digits
        d = load_digits()
        x = d.data / 16.0 if self.flatten else d.images[..., None] / 16.0
        if self.binarize:
            x = (x > 0.5).astype(np.float32)
        return x, to_outcome_matrix(d.target, 10)


class MnistDataFetcher(BaseDataFetcher):
    """Full MNIST via local IDX files (``MnistDataFetcher.java:21-80``,
    ``base/MnistFetcher.java:30``).

    Search order for ``train-images-idx3-ubyte[.gz]`` etc.: the vendored
    repo fixture (``datasets/fixtures/mnist`` — materialized by
    ``tools/vendor_mnist.py`` on a machine with egress), then ``data_dir``;
    attempts download when ``allow_download`` (no egress here, so default
    False).  Otherwise falls back to the bundled digits corpus upscaled to
    28x28 so MNIST-shaped pipelines still run offline — the fallback is
    LOUD: ``source`` is set to ``"digits_fallback"``, a warning is emitted,
    and ``require_real=True`` turns it into an error so a test asserting
    on real pixels can never silently pass on fake ones.
    """

    NUM_EXAMPLES = 60000
    FIXTURE_DIR = Path(__file__).parent / "fixtures" / "mnist"
    URLS = {
        "train-images-idx3-ubyte.gz": "https://ossci-datasets.s3.amazonaws.com/mnist/train-images-idx3-ubyte.gz",
        "train-labels-idx1-ubyte.gz": "https://ossci-datasets.s3.amazonaws.com/mnist/train-labels-idx1-ubyte.gz",
        "t10k-images-idx3-ubyte.gz": "https://ossci-datasets.s3.amazonaws.com/mnist/t10k-images-idx3-ubyte.gz",
        "t10k-labels-idx1-ubyte.gz": "https://ossci-datasets.s3.amazonaws.com/mnist/t10k-labels-idx1-ubyte.gz",
    }

    def __init__(self, binarize: bool = True, train: bool = True,
                 data_dir: Path | str | None = None, allow_download: bool = False,
                 flatten: bool = True, require_real: bool = False):
        super().__init__()
        self.binarize = binarize
        self.train = train
        self.data_dir = Path(data_dir) if data_dir else DEFAULT_BASE_DIR / "mnist"
        # an explicitly-passed data_dir must win over the vendored fixture
        self._search_dirs = ((self.data_dir, self.FIXTURE_DIR) if data_dir
                             else (self.FIXTURE_DIR, self.data_dir))
        self.allow_download = allow_download
        self.flatten = flatten
        self.require_real = require_real
        self.source: str | None = None   # "idx" | "digits_fallback" after load

    @classmethod
    def real_data_available(cls, data_dir: Path | str | None = None) -> bool:
        """True when real IDX files are reachable (fixture or data_dir)."""
        f = cls(train=True, data_dir=data_dir)
        return f._find("train-images-idx3-ubyte") is not None

    def _find(self, stem: str) -> Path | None:
        for base in self._search_dirs:
            for name in (stem, stem + ".gz"):
                p = base / name
                if p.exists():
                    return p
        return None

    def _maybe_download(self, stem: str) -> Path | None:
        if not self.allow_download:
            return None
        self.data_dir.mkdir(parents=True, exist_ok=True)
        url = self.URLS[stem + ".gz"]
        dest = self.data_dir / (stem + ".gz")
        try:
            urllib.request.urlretrieve(url, dest)  # noqa: S310
            return dest
        except Exception:
            return None

    def _load(self):
        img_stem = ("train-images-idx3-ubyte" if self.train else "t10k-images-idx3-ubyte")
        lbl_stem = ("train-labels-idx1-ubyte" if self.train else "t10k-labels-idx1-ubyte")
        img_path = self._find(img_stem) or self._maybe_download(img_stem)
        lbl_path = self._find(lbl_stem) or self._maybe_download(lbl_stem)
        if img_path and lbl_path:
            images = read_idx_images(img_path)  # (n, 28, 28) uint8
            labels = read_idx_labels(lbl_path)
            x = images.astype(np.float32) / 255.0
            self.source = "idx"
        else:
            if self.require_real:
                raise FileNotFoundError(
                    f"real MNIST IDX files not found (looked in "
                    f"{self.FIXTURE_DIR} and {self.data_dir}) and "
                    "require_real=True; materialize the fixture with "
                    "tools/vendor_mnist.py on a machine with egress")
            # Offline fallback: digits upscaled 8x8 -> 28x28 (nearest).
            import warnings
            warnings.warn(
                "MnistDataFetcher: real IDX files absent — falling back to "
                "sklearn 8x8 digits upscaled to 28x28 (NOT real MNIST "
                "pixels); run tools/vendor_mnist.py to vendor the fixture",
                stacklevel=2)
            self.source = "digits_fallback"
            from sklearn.datasets import load_digits
            d = load_digits()
            imgs = d.images / 16.0
            reps = 28 // 8 + 1
            x = np.repeat(np.repeat(imgs, reps, axis=1), reps, axis=2)[:, :28, :28]
            x = x.astype(np.float32)
            labels = d.target
        if self.binarize:
            x = (x > 0.5).astype(np.float32)
        if self.flatten:
            x = x.reshape(x.shape[0], -1)
        else:
            x = x[..., None]  # NHWC
        return x, to_outcome_matrix(labels, 10)


class CurvesDataFetcher(BaseDataFetcher):
    """Curves dataset (``datasets/fetchers/CurvesDataFetcher.java``): 28x28
    grayscale images of smooth random curves, the classic deep-autoencoder
    pretraining corpus.

    The reference downloads a serialized DataSet from S3
    (``CURVES_URL``); this environment has no egress, so the curves are
    synthesized directly — each image rasterizes a random cubic Bezier
    curve (4 control points, deterministic per ``seed``), which is the
    generative process behind the original corpus.  Labels are the images
    themselves (reconstruction target), matching its autoencoder use.
    """

    SIDE = 28

    def __init__(self, n_examples: int = 1000, seed: int = 0):
        super().__init__()
        self.n_examples = n_examples
        self.seed = seed

    def _load(self):
        rng = np.random.default_rng(self.seed)
        side = self.SIDE
        n_steps = 200
        t = np.linspace(0.0, 1.0, n_steps)[:, None]            # (S, 1)
        # Bernstein basis for a cubic Bezier
        basis = np.concatenate([(1 - t) ** 3, 3 * (1 - t) ** 2 * t,
                                3 * (1 - t) * t ** 2, t ** 3], axis=1)  # (S, 4)
        imgs = np.zeros((self.n_examples, side, side), np.float32)
        ctrl = rng.uniform(2, side - 3, (self.n_examples, 4, 2))  # (N, 4, 2)
        pts = np.einsum("sk,nkd->nsd", basis, ctrl)               # (N, S, 2)
        ij = np.rint(pts).astype(int)
        n_idx = np.repeat(np.arange(self.n_examples), n_steps)
        imgs[n_idx, ij[..., 1].ravel(), ij[..., 0].ravel()] = 1.0
        flat = imgs.reshape(self.n_examples, side * side)
        return flat, flat.copy()      # reconstruction corpus: labels = inputs


class LFWDataFetcher(BaseDataFetcher):
    """Labeled Faces in the Wild (``LFWDataFetcher`` + ``base/LFWLoader.java:31``).

    Uses scikit-learn's cached copy when present on disk; cannot download in
    this environment, so raises a clear error otherwise.
    """

    def __init__(self, min_faces_per_person: int = 70, resize: float = 0.4):
        super().__init__()
        self.min_faces_per_person = min_faces_per_person
        self.resize = resize

    def _load(self):
        from sklearn.datasets import fetch_lfw_people
        try:
            d = fetch_lfw_people(min_faces_per_person=self.min_faces_per_person,
                                 resize=self.resize, download_if_missing=False)
        except OSError as e:
            raise RuntimeError(
                "LFW data not cached locally and downloads are disabled in "
                "this environment; place the scikit-learn LFW cache under "
                "~/scikit_learn_data to use LFWDataFetcher") from e
        n_classes = int(d.target.max()) + 1
        return d.data / 255.0, to_outcome_matrix(d.target, n_classes)


class CSVDataFetcher(BaseDataFetcher):
    """CSV ingestion (``CSVDataFetcher``): label column index + feature
    columns; non-numeric labels are vocabulary-mapped."""

    def __init__(self, path: Path | str, label_col: int = -1, skip_header: bool = False,
                 delimiter: str = ","):
        super().__init__()
        self.path = Path(path)
        self.label_col = label_col
        self.skip_header = skip_header
        self.delimiter = delimiter

    def _load(self):
        rows = []
        with open(self.path) as f:
            lines = f.read().strip().splitlines()
        if self.skip_header:
            lines = lines[1:]
        for line in lines:
            if line.strip():
                rows.append(line.strip().split(self.delimiter))
        ncol = len(rows[0])
        lc = self.label_col % ncol
        raw_labels = [r[lc] for r in rows]
        feats = self._parse_features(lines, rows, ncol, lc)
        try:
            label_idx = np.array([int(float(v)) for v in raw_labels])
        except ValueError:
            vocab = {v: i for i, v in enumerate(sorted(set(raw_labels)))}
            label_idx = np.array([vocab[v] for v in raw_labels])
        return feats, to_outcome_matrix(label_idx, int(label_idx.max()) + 1)

    def _parse_features(self, lines, rows, ncol, lc) -> np.ndarray:
        """Feature columns as float32; native C parser fast path when the
        WHOLE grid is numeric (labels included), Python otherwise."""
        if self.delimiter == ",":
            try:
                from ..native import runtime as native_rt
                full = native_rt.parse_csv_floats("\n".join(lines) + "\n", ncol)
            except ImportError:
                full = None
            if full is not None and full.shape[0] == len(rows):
                return np.delete(full, lc, axis=1).astype(np.float32)
        return np.array([[float(v) for j, v in enumerate(r) if j != lc]
                         for r in rows], dtype=np.float32)
