"""SVMLight sparse-text record IO.

Capability match of the reference YARN path's record layer
(``deeplearning4j-scaleout/hadoop-yarn/cdh4/.../iterativereduce/runtime/io/``:
``SVMLightRecordFactory.java:44-125`` line->vector parsing,
``SVMLightDataFetcher.java:57-181`` fetch-into-DataSet,
``SVMLightHDFSDataSetIterator.java`` iterator facade,
``TextRecordParser.java`` split-aware line reading) — redesigned for the
TPU input pipeline: lines parse into *dense batched* numpy arrays up front
(the chip wants one contiguous (N, D) device_put, not a per-example vector
object stream), and byte-range splits replace HDFS input splits so a
multi-host loader can shard one file without a name node.

Format, per the reference parser: ``<label> <idx>:<val> ... # comment``
with 1-based feature indices (0-based raises, matching
``SVMLightRecordFactory.java:96-99``), out-of-range indices skipped with a
warning, and non-negative integer labels used directly as class indices
(``SVMLightDataFetcher.java:19-23``).
"""

from __future__ import annotations

import warnings
from pathlib import Path

import numpy as np

from .dataset import DataSet, to_outcome_matrix
from .fetchers import BaseDataFetcher
from .iterator import BaseDatasetIterator


class SVMLightVectorNoLabelError(ValueError):
    """A line had no parsable label (``SVMLightVectorNoLabelException.java``)."""


def parse_svmlight_line(line: str, num_features: int,
                        features_out: np.ndarray | None = None
                        ) -> tuple[np.ndarray, float]:
    """One ``label idx:val ...`` line -> (dense feature row, label).

    Mirrors ``SVMLightRecordFactory.parseFromLine`` semantics: strips
    ``#`` comments, 1-based indices (index 0 raises), indices beyond
    ``num_features`` are skipped with a warning rather than an error.
    """
    body = line.split("#", 1)[0].strip()
    if not body:
        raise SVMLightVectorNoLabelError(f"blank record line: {line!r}")
    parts = body.split()
    try:
        label = float(parts[0])
    except ValueError:
        raise SVMLightVectorNoLabelError(f"no leading label in: {line!r}")
    vec = features_out if features_out is not None else np.zeros(
        num_features, np.float32)
    vec[:] = 0.0
    for tok in parts[1:]:
        idx_s, _, val_s = tok.partition(":")
        index = int(idx_s) - 1          # svmlight text format is 1-based
        if index < 0:
            raise ValueError(
                "SVMLight does not support 0-based indexing in its text "
                f"vector formats: {tok!r}")
        if index < num_features:
            vec[index] = float(val_s)
        else:
            warnings.warn(f"svmlight feature index {index + 1} beyond "
                          f"num_features={num_features}; skipped")
    return vec, label


def load_svmlight(path: str | Path, num_features: int, num_classes: int,
                  start: int = 0, end: int | None = None
                  ) -> tuple[np.ndarray, np.ndarray]:
    """Whole file (or a byte-range split of it) -> dense ``(N, D)``
    features + ``(N, C)`` one-hot labels.

    ``start``/``end`` are byte offsets delimiting a split; like the
    reference's ``TextRecordParser``/``HDFSLineParser`` split contract, a
    split that begins mid-line skips forward to the next line boundary and
    the split containing a line's start owns the whole line — so disjoint
    byte ranges over one file partition its records exactly.
    """
    # seek-based split read: only this split's bytes are ever in memory,
    # so N hosts sharing one large file each do O(split) IO, not O(file)
    size = Path(path).stat().st_size
    if end is None:
        end = size
    raw = []
    with open(path, "rb") as f:
        if start > 0:
            f.seek(start - 1)
            f.readline()     # discard through the break; a line that starts
            #                  before `start` belongs to the previous split
        while f.tell() < end:
            line = f.readline()
            if not line:     # a line STARTING before `end` is owned whole,
                break        # even when it extends past the cut
            raw.append(line)
    data = b"".join(raw)

    try:                     # native C fast path (host_runtime.cpp)
        from ..native import runtime as native_rt
        parsed = native_rt.parse_svmlight(data, num_features)
    except ImportError:
        parsed = None
    if parsed is not None:
        feats, labs, skipped = parsed
        if skipped:
            warnings.warn(f"{skipped} svmlight feature indices beyond "
                          f"num_features={num_features}; skipped")
    else:                    # Python parser: exact reference error semantics
        lines = [l for l in data.decode("utf-8").splitlines()
                 if l.split("#", 1)[0].strip()]
        feats = np.zeros((len(lines), num_features), np.float32)
        labs = np.zeros(len(lines), np.float32)
        for i, line in enumerate(lines):
            _, labs[i] = parse_svmlight_line(line, num_features,
                                             features_out=feats[i])
    invalid = ~np.isfinite(labs) | (labs < 0) | (labs != np.floor(labs))
    if np.any(invalid):
        bad = labs[invalid][0]
        raise ValueError(
            f"only non-negative integer class labels are supported "
            f"(got {bad!r}); see SVMLightDataFetcher.java:19-23")
    return feats, to_outcome_matrix(labs.astype(np.int64), num_classes)


def save_svmlight(path: str | Path, features: np.ndarray,
                  labels: np.ndarray) -> None:
    """Write ``(N, D)`` features + labels (one-hot ``(N, C)`` or class-index
    ``(N,)``) as svmlight text — the reference only parses the format; the
    writer closes the round trip for export and for test fixtures."""
    features = np.asarray(features)
    labels = np.asarray(labels)
    classes = labels.argmax(-1) if labels.ndim == 2 else labels.astype(np.int64)
    with open(path, "w") as f:
        for row, c in zip(features, classes):
            nz = np.flatnonzero(row)
            pairs = " ".join(f"{j + 1}:{row[j]:g}" for j in nz)
            f.write(f"{int(c)}{' ' if pairs else ''}{pairs}\n")


class SVMLightDataFetcher(BaseDataFetcher):
    """Cursor/batch fetcher over an svmlight file or byte-range split of
    one (``SVMLightDataFetcher.java:57-181``).  Loads the split once into
    dense arrays; ``fetch(num)`` slices — the per-record Text shuttling of
    the HDFS original has no place in a device-feed path."""

    def __init__(self, path: str | Path, num_features: int, num_classes: int,
                 start: int = 0, end: int | None = None):
        super().__init__()
        self.path, self._nf, self._nc = Path(path), num_features, num_classes
        self._span = (start, end)

    def _load(self):
        return load_svmlight(self.path, self._nf, self._nc, *self._span)


class SVMLightDataSetIterator(BaseDatasetIterator):
    """Batched DataSet iterator over an svmlight file
    (``SVMLightHDFSDataSetIterator.java``)."""

    def __init__(self, path: str | Path, batch: int, num_features: int,
                 num_classes: int, start: int = 0, end: int | None = None,
                 num_examples: int = 0):
        super().__init__(batch, num_examples, SVMLightDataFetcher(
            path, num_features, num_classes, start, end))
