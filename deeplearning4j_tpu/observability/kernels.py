"""Kernel-tier observability: per-kernel timing histograms and the
bench auto-pick gauges.

Two thin publication shims over the global ``METRICS`` registry so the
kernel tier (``ops/pallas``) and the bench pick chain never import
histogram internals:

- ``record_kernel_time`` — one wall-clock observation per kernel call
  (``kernel.<kind>.<name>`` histogram) plus an optional bytes-moved
  gauge, fed by ``tools/kernel_smoke.py`` and any harness that times a
  dispatched kernel.
- ``publish_autopick`` — every :class:`ops.pallas.registry.Pick` lands
  as ``bench.autopick.<kind>.*`` gauges (candidates considered, dropped,
  whether a non-incumbent was adopted) and a decisions counter, so a
  dashboard shows at a glance which kernels production actually runs
  and how many candidates the gate rejected.
"""

from __future__ import annotations

from .metrics import METRICS

# kernel calls run µs-to-ms: the default request-latency buckets would
# dump everything in the first bin
KERNEL_TIME_BUCKETS = (
    1e-6, 5e-6, 1e-5, 5e-5, 1e-4, 5e-4, 1e-3, 5e-3, 1e-2, 5e-2, 1e-1,
)


def record_kernel_time(kind: str, name: str, seconds: float,
                       bytes_moved: int | None = None) -> None:
    """One timing observation for a ``(kind, name)`` kernel dispatch."""
    metric = f"kernel.{kind}.{name}"
    METRICS.observe_time(metric, seconds, buckets=KERNEL_TIME_BUCKETS)
    if bytes_moved is not None:
        METRICS.gauge(f"{metric}.bytes_per_call", bytes_moved)
        if seconds > 0:
            METRICS.gauge(f"{metric}.gbps", bytes_moved / seconds / 1e9)


def publish_autopick(pick) -> None:
    """Export one auto-pick decision (a ``registry.Pick``) as gauges."""
    base = f"bench.autopick.{pick.kind}"
    METRICS.gauge(f"{base}.candidates", pick.considered)
    METRICS.gauge(f"{base}.dropped", len(pick.dropped))
    METRICS.gauge(f"{base}.adopted", 0.0 if pick.reason.startswith("default")
                  else 1.0)
    METRICS.increment("bench.autopick.decisions")
