"""Pallas kernel tier: registered candidates behind the bench auto-pick.

This package is the TPU-native half of the framework's premise — custom
kernels where XLA's generic lowering leaves the chip idle — organized so
no kernel is ever adopted on faith:

- every kernel lives here as a *registered candidate* (``registry.py``)
  next to a pure-jnp reference implementation;
- every kernel threads an ``interpret`` flag (auto-selected off-TPU) so
  tier-1 CPU tests execute the real kernel body, not a stand-in;
- production adoption happens only through ``registry.autopick`` fed by
  TUNE battery rows: a correctness gate at documented tolerances plus a
  >2% throughput margin over the incumbent, with every dropped candidate
  logged (DESIGN.md §14).

Kinds currently registered:

- ``attention``           — ring (XLA incumbent) / flash / fused
- ``layernorm_residual``  — unfused (XLA incumbent) / fused
- ``xent``                — scan (XLA incumbent) / blocked
- ``int8_matmul``         — f32 (XLA incumbent) / pallas_int8
"""

from . import registry  # noqa: F401  (re-export the registration surface)
from .registry import (  # noqa: F401
    KernelCandidate,
    Pick,
    autopick,
    candidates,
    get,
    import_errors,
    kinds,
    register,
)
