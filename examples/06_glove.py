"""Train GloVe embeddings from a co-occurrence matrix.

The reference's second embedding family (``models/glove/Glove.java:42`` +
``CoOccurrences.java``): accumulate windowed co-occurrence counts (native
C++ fast path when built, Python otherwise), then AdaGrad weighted
least squares on the log counts.

Run:  python examples/06_glove.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from deeplearning4j_tpu.text.glove import Glove

CORPUS = [
    "the apple is a sweet fruit",
    "banana is a yellow fruit and the banana is sweet",
    "orange fruit is sweet and orange is juicy",
    "apple and banana and orange are fruit",
    "fruit salad has apple banana orange",
    "the car drives on the road",
    "a truck is a big car on the road",
    "the bus drives people on the road",
    "car truck and bus are vehicles on the road",
    "vehicles like car and bus drive fast",
] * 8


def main():
    glove = Glove(CORPUS, layer_size=32, window=5, iterations=40,
                  min_word_frequency=3, seed=11)
    glove.fit()
    print(f"final loss: {glove.losses[-1]:.4f}")

    within = glove.similarity("apple", "banana")
    cross = glove.similarity("apple", "road")
    print(f"sim(apple, banana) = {within:.3f}  (same topic)")
    print(f"sim(apple, road)   = {cross:.3f}  (cross topic)")
    assert within > cross, "within-topic similarity should beat cross-topic"


if __name__ == "__main__":
    main()
