"""Fused residual-add + LayerNorm (+ optional dropout mask) kernel.

The transformer block's mid-sublayer seam is

    x = x + proj            # residual write to HBM
    h = layernorm(x)        # read x back, write h

— two full-activation HBM round-trips that XLA does not reliably fuse
across (the LN reduction materializes its input).  This kernel computes
both outputs in one VMEM pass per row block: ``y = x + r * mask`` and
``h = LN(y) * scale + bias``, reading x/r once and writing y/h once.

Shape-independent: rows flatten to (N, D), N pads internally to the row
block (pad rows are discarded on the way out), D rides whole (a block
equal to the array dim satisfies Mosaic's last-two-dims constraint).
Backward is the standard LN gradient in plain jnp under a custom_vjp —
cheap relative to the matmuls around it, no second kernel to maintain.

Adoption is bench-gated like every candidate: opt-in via
``TransformerConfig(fused_ln=True)``, flipped by ``_pick_fused_ln`` only
on TUNE evidence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from ..flash_attention import _VMEM
from . import registry


def reference_residual_layernorm(x, r, scale, bias, *, mask=None,
                                 eps: float = 1e-5):
    """Pure-jnp ground truth: f32 compute, outputs cast to x.dtype."""
    x32 = x.astype(jnp.float32)
    r32 = r.astype(jnp.float32)
    if mask is not None:
        r32 = r32 * mask.astype(jnp.float32)
    y = x32 + r32
    mu = y.mean(-1, keepdims=True)
    var = y.var(-1, keepdims=True)
    h = (y - mu) * lax.rsqrt(var + eps)
    h = h * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(x.dtype), h.astype(x.dtype)


def _kernel(x_ref, r_ref, m_ref, s_ref, b_ref, y_ref, h_ref, *, eps: float):
    x = x_ref[...].astype(jnp.float32)                       # (BR, D)
    y = x + r_ref[...].astype(jnp.float32) * m_ref[...]
    mu = y.mean(-1, keepdims=True)
    var = ((y - mu) ** 2).mean(-1, keepdims=True)
    h = (y - mu) * lax.rsqrt(var + eps)
    h = h * s_ref[...].astype(jnp.float32) + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    h_ref[...] = h.astype(h_ref.dtype)


def _fused_call(x2, r2, m2, scale, bias, eps, block_rows, interpret):
    """x2/r2: (N, D), m2: (N, 1) f32 keep-mask, scale/bias: (1, D)."""
    n, d = x2.shape
    br = min(block_rows, n)
    pad = -n % br
    if pad:
        # zero pad rows: LN of zeros is finite (rsqrt(eps)), rows sliced off
        x2 = jnp.concatenate([x2, jnp.zeros((pad, d), x2.dtype)])
        r2 = jnp.concatenate([r2, jnp.zeros((pad, d), r2.dtype)])
        m2 = jnp.concatenate([m2, jnp.zeros((pad, 1), m2.dtype)])
    mem = {} if _VMEM is None else {"memory_space": _VMEM}
    y, h = pl.pallas_call(
        functools.partial(_kernel, eps=eps),
        grid=((n + pad) // br,),
        in_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), **mem),
            pl.BlockSpec((br, d), lambda i: (i, 0), **mem),
            pl.BlockSpec((br, 1), lambda i: (i, 0), **mem),
            pl.BlockSpec((1, d), lambda i: (0, 0), **mem),
            pl.BlockSpec((1, d), lambda i: (0, 0), **mem),
        ],
        out_specs=[
            pl.BlockSpec((br, d), lambda i: (i, 0), **mem),
            pl.BlockSpec((br, d), lambda i: (i, 0), **mem),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n + pad, d), x2.dtype),
            jax.ShapeDtypeStruct((n + pad, d), x2.dtype),
        ],
        interpret=interpret,
    )(x2, r2, m2, scale.reshape(1, d), bias.reshape(1, d))
    if pad:
        y, h = y[:n], h[:n]
    return y, h


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7))
def _fused(x2, r2, m2, scale, bias, eps, block_rows, interpret):
    return _fused_call(x2, r2, m2, scale, bias, eps, block_rows, interpret)


def _fused_fwd(x2, r2, m2, scale, bias, eps, block_rows, interpret):
    y, h = _fused_call(x2, r2, m2, scale, bias, eps, block_rows, interpret)
    return (y, h), (y, r2, m2, scale)


def _fused_bwd(eps, block_rows, interpret, res, cts):
    y, r2, m2, scale = res
    dy, dh = cts
    y32 = y.astype(jnp.float32)
    mu = y32.mean(-1, keepdims=True)
    var = y32.var(-1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    yhat = (y32 - mu) * rstd
    dh32 = dh.astype(jnp.float32)
    dscale = (dh32 * yhat).sum(0).astype(scale.dtype)
    dbias = dh32.sum(0).astype(scale.dtype)
    dyhat = dh32 * scale.astype(jnp.float32)
    g_ln = rstd * (dyhat - dyhat.mean(-1, keepdims=True)
                   - yhat * (dyhat * yhat).mean(-1, keepdims=True))
    g = dy.astype(jnp.float32) + g_ln
    dx = g.astype(y.dtype)
    dr = (g * m2).astype(r2.dtype)
    dm = (g * r2.astype(jnp.float32)).sum(-1, keepdims=True)
    return dx, dr, dm, dscale, dbias


_fused.defvjp(_fused_fwd, _fused_bwd)


def fused_residual_layernorm(x, r, scale, bias, *, mask=None,
                             eps: float = 1e-5, block_rows: int = 256,
                             interpret: bool | None = None):
    """Fused ``y = x + r*mask; h = LN(y)`` on (..., D) activations.

    Returns ``(y, h)`` in x.dtype.  ``mask`` (broadcastable to x's row
    shape) is a dropout keep-mask (pre-scaled, e.g. bernoulli/keep_prob);
    None means no masking.  ``interpret=None`` auto-selects Pallas
    interpret mode off-TPU.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, d = x.shape
    n = 1
    for s in lead:
        n *= s
    x2 = x.reshape(n, d)
    r2 = r.reshape(n, d)
    if mask is None:
        m2 = jnp.ones((n, 1), jnp.float32)
    else:
        m2 = jnp.broadcast_to(
            mask.astype(jnp.float32).reshape(n, -1)[:, :1], (n, 1))
    y, h = _fused(x2, r2, m2, scale, bias, eps, block_rows, interpret)
    return y.reshape(x.shape), h.reshape(x.shape)


def _unfused(x, r, scale, bias, *, mask=None, eps: float = 1e-5, **_):
    """The XLA incumbent: exactly the transformer's existing two-op seam
    (residual add in x.dtype, then the f32 LN)."""
    r = r * mask.astype(r.dtype) if mask is not None else r
    y = x + r.astype(x.dtype)
    y32 = y.astype(jnp.float32)
    mu = y32.mean(-1, keepdims=True)
    var = y32.var(-1, keepdims=True)
    h = (y32 - mu) * lax.rsqrt(var + eps)
    h = h * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y, h.astype(x.dtype)


registry.register(registry.KernelCandidate(
    kind="layernorm_residual", name="fused", fn=fused_residual_layernorm,
    reference=reference_residual_layernorm,
    blocks=({"block_rows": 128}, {"block_rows": 256}, {"block_rows": 512}),
    # fwd/bwd max abs error vs the f32 reference at battery shapes (f32)
    tolerances={"max_err": 1e-3},
))

registry.register(registry.KernelCandidate(
    kind="layernorm_residual", name="unfused", fn=_unfused,
    reference=reference_residual_layernorm, source="xla",
))
