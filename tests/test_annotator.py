"""Annotator pipeline tests: POS tagger, Porter stemmer, sentence
annotator, and their integration with windows + Viterbi (the reference's
UIMA pipeline roles: PoStagger.java, StemmerAnnotator.java,
SentenceAnnotator.java, TokenizerAnnotator.java)."""

import numpy as np
import pytest

from deeplearning4j_tpu.text.annotator import (
    AveragedPerceptronTagger, PorterStemmer, SentenceAnnotator,
    StemmerPreProcess, TokenizerAnnotator, load_tagged_corpus,
    pos_tag_viterbi, tagged_windows, _DATA)
from deeplearning4j_tpu.text.tokenization import DefaultTokenizerFactory


# --------------------------------------------------------------------- stemmer

@pytest.mark.parametrize("word,stem", [
    ("caresses", "caress"), ("ponies", "poni"), ("cats", "cat"),
    ("feed", "feed"), ("agreed", "agre"), ("plastered", "plaster"),
    ("motoring", "motor"), ("sing", "sing"), ("conflated", "conflat"),
    ("troubled", "troubl"), ("sized", "size"), ("hopping", "hop"),
    ("falling", "fall"), ("hissing", "hiss"), ("happy", "happi"),
    ("relational", "relat"), ("conditional", "condit"),
    ("vietnamization", "vietnam"), ("predication", "predic"),
    ("operator", "oper"), ("callousness", "callous"),
    ("formaliti", "formal"), ("triplicate", "triplic"),
    ("formative", "form"), ("formalize", "formal"),
    ("revival", "reviv"), ("allowance", "allow"), ("inference", "infer"),
    ("airliner", "airlin"), ("adjustment", "adjust"),
    ("probate", "probat"), ("rate", "rate"), ("controll", "control"),
])
def test_porter_stemmer_known_pairs(word, stem):
    assert PorterStemmer().stem(word) == stem


def test_stemmer_preprocess_plugs_into_tokenizer_factory():
    factory = DefaultTokenizerFactory(pre=StemmerPreProcess())
    toks = factory.create("The horses were running happily").get_tokens()
    assert toks == ["the", "hors", "were", "run", "happili"]


# ------------------------------------------------------------------ sentences

def test_sentence_annotator_splits_and_keeps_abbreviations():
    ann = SentenceAnnotator()
    text = ("Dr. Smith arrived at 9 a.m. sharp. He greeted Mrs. Jones "
            "warmly! Did the meeting start on time? It did.")
    sents = ann.annotate(text)
    assert len(sents) == 4
    assert sents[0].startswith("Dr. Smith")
    assert sents[1].startswith("He greeted")
    assert sents[2].endswith("time?")
    assert sents[3] == "It did."


def test_sentence_annotator_no_trailing_punctuation():
    assert SentenceAnnotator()("no punctuation here") == ["no punctuation here"]


def test_tokenizer_annotator():
    assert TokenizerAnnotator()("a b  c") == ["a", "b", "c"]


# ----------------------------------------------------------------------- tagger

@pytest.fixture(scope="module")
def corpus():
    return load_tagged_corpus(_DATA / "pos_sample.txt")


@pytest.fixture(scope="module")
def tagger(corpus):
    t = AveragedPerceptronTagger()
    t.train(corpus[:-8])                       # hold out 8 sentences
    return t


def test_tagger_heldout_accuracy(tagger, corpus):
    """Generalization across held-out sentences: overwhelmingly right."""
    right = total = 0
    for sent in corpus[-8:]:
        tags = tagger.tag([w for w, _ in sent])
        for (_, got), (_, gold) in zip(tags, sent):
            right += got == gold
            total += 1
    assert right / total >= 0.85, f"{right}/{total}"


def test_tagger_on_unseen_words_uses_suffix_features(tagger):
    # "strolls" (unseen verb, -s), "misty" (unseen adj, -y pattern via
    # suffix weights): structure should still resolve determiners/nouns
    tags = dict(tagger.tag(["the", "misty", "meadow"]))
    assert tags["the"] == "DET"
    assert tags["meadow"] == "NOUN"


def test_default_tagger_singleton_trains_offline():
    t = AveragedPerceptronTagger.default()
    tags = dict(t.tag(["the", "dog", "barks", "loudly", "."]))
    assert tags["the"] == "DET"
    assert tags["dog"] == "NOUN"
    assert tags["barks"] == "VERB"
    assert tags["loudly"] == "ADV"


def test_viterbi_smoothing_matches_greedy_on_easy_text(tagger):
    tokens = ["the", "small", "cat", "sleeps", "."]
    greedy = [t for _, t in tagger.tag(tokens)]
    smooth = [t for _, t in pos_tag_viterbi(tokens, tagger)]
    assert smooth == greedy == ["DET", "ADJ", "NOUN", "VERB", "."]


def test_emissions_are_distributions(tagger):
    probs = tagger.emissions(["the", "cat"])
    assert probs.shape == (2, len(tagger.classes))
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-6)


# ------------------------------------------------------------------ windows

def test_tagged_windows_feed_window_pipeline(tagger):
    tokens = ["the", "quick", "fox", "jumps"]
    wins = tagged_windows(tokens, tagger, window_size=3)
    assert len(wins) == len(tokens)
    (w0, label0) = wins[0]
    assert w0.focus == "the"
    assert label0 == "DET"
    (w2, label2) = wins[2]
    assert w2.focus == "fox"
    assert label2 == "NOUN"
