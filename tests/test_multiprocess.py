"""Real multi-PROCESS distributed execution (VERDICT r3 #4).

Three escalating claims, none satisfiable by threads:

1. ``initialize_multihost`` (the jax.distributed analog of the reference's
   Akka seed join, ``DeepLearning4jDistributed.java:128-187``) actually
   forms a 2-process JAX cluster on CPU, and a cross-process collective
   returns the right value in BOTH processes.
2. The scaleout SPI runs with OS-process workers over the file-backed
   state plane (``LocalFileUpdateSaver.java:20`` parity): distributed
   word count — the reference's hello-world performer — sums correctly.
3. SIGKILL a worker *process* mid-run: heartbeats stop, the master evicts
   it, re-routes the orphaned job, and the final model matches an
   uninterrupted single-worker run exactly.
"""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest

from deeplearning4j_tpu.parallel.performers import (
    VectorDeltaPerformer, WordCountRouter)
from deeplearning4j_tpu.parallel.procrunner import ProcessDistributedRunner
from deeplearning4j_tpu.parallel.procstate import (
    FileStateTracker, FileUpdateSaver, FileWorkRetriever)
from deeplearning4j_tpu.parallel.scaleout import (
    CollectionJobIterator, DistributedRunner, Job, StateTracker)

REPO = Path(__file__).resolve().parents[1]


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


_MULTIHOST_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from deeplearning4j_tpu.parallel.mesh import initialize_multihost
initialize_multihost()        # env-var driven, like the reference's conf keys
import numpy as np
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
pid = jax.process_index()
assert jax.process_count() == 2, jax.process_count()
mesh = Mesh(np.array(jax.devices()).reshape(-1), ("dp",))
local = np.full((4,), float(pid + 1), np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("dp")), local)
total = jax.jit(lambda a: jnp.sum(a),
                out_shardings=NamedSharding(mesh, P()))(arr)
print(f"RESULT proc={pid} total={float(total)}", flush=True)
"""


def test_initialize_multihost_two_processes():
    """2 OS processes form a JAX cluster; a cross-process reduction agrees
    in both.  Each process has 1 local CPU device holding full((4,), pid+1),
    so the global sum is 4*1 + 4*2 = 12."""
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
            "JAX_PROCESS_ID": str(pid),
        })
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _MULTIHOST_CHILD],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True))
    outs = []
    for p in procs:
        out, err = p.communicate(timeout=180)
        assert p.returncode == 0, f"stdout={out}\nstderr={err[-1500:]}"
        outs.append(out)
    for pid, out in enumerate(outs):
        assert f"RESULT proc={pid} total=12.0" in out, out


def test_process_runner_word_count(tmp_path):
    """The reference's distributed word-count example on OS-process workers."""
    lines = ["the quick brown fox", "the lazy dog", "the fox jumps",
             "over the lazy dog", "quick quick brown"]
    runner = ProcessDistributedRunner(
        CollectionJobIterator(lines),
        "deeplearning4j_tpu.parallel.performers:WordCountPerformer",
        state_dir=tmp_path / "state", n_workers=2,
        router_cls=WordCountRouter,
        worker_env={"JAX_PLATFORMS": "cpu"})
    result = runner.run(max_wall_s=60.0)
    from collections import Counter
    want = Counter(" ".join(lines).split())
    assert result == want
    # updates really spilled through the file plane
    assert (tmp_path / "state" / "updates").is_dir()


def test_file_state_plane_roundtrips(tmp_path):
    """FileUpdateSaver / FileWorkRetriever / FileStateTracker behave like
    their in-memory counterparts across reopens (restart survival)."""
    saver = FileUpdateSaver(tmp_path / "u")
    saver.save("w0", {"a": np.arange(3)})
    reloaded = FileUpdateSaver(tmp_path / "u").load("w0")
    np.testing.assert_array_equal(reloaded["a"], np.arange(3))

    retr = FileWorkRetriever(tmp_path / "s")
    retr.save("w0", Job(work=7.0, worker_id="w0"))
    assert FileWorkRetriever(tmp_path / "s").load("w0").work == 7.0

    t = FileStateTracker(tmp_path / "t")
    t.add_worker("w0")
    t.set_current(np.ones(2))
    t.add_job(Job(work=1.0, worker_id="w0"))
    t2 = FileStateTracker(tmp_path / "t")      # a different "process"
    assert t2.workers() == ["w0"]
    assert t2.needs_replicate("w0")
    np.testing.assert_array_equal(t2.get_current(), np.ones(2))
    assert t2.job_for("w0").work == 1.0
    t2.clear_job("w0")
    assert t.job_for("w0") is None
    assert t.load_for_worker("w0").work == 1.0  # WorkRetriever persistence


def _reference_run(jobs):
    tracker = StateTracker()
    tracker.set_current(np.zeros(VectorDeltaPerformer.dim))
    runner = DistributedRunner(
        CollectionJobIterator(jobs), VectorDeltaPerformer, n_workers=1,
        tracker=tracker)
    return np.asarray(runner.run(max_wall_s=60.0))


def test_sigkill_worker_process_recovery_parity(tmp_path):
    """Kill a worker with SIGKILL mid-run; the master evicts it by
    heartbeat staleness, re-routes the orphan from the file plane, and the
    final model matches the uninterrupted single-worker run."""
    jobs = [1.0, 2.0, 3.0, 4.0, 5.0]
    ref = _reference_run(jobs)

    state = tmp_path / "state"
    runner = ProcessDistributedRunner(
        CollectionJobIterator(jobs),
        "deeplearning4j_tpu.parallel.performers:SlowVectorDeltaPerformer",
        state_dir=state, n_workers=2, eviction_timeout_s=1.0,
        worker_env={"JAX_PLATFORMS": "cpu"})
    runner.tracker.set_current(np.zeros(VectorDeltaPerformer.dim))

    killed = {}

    import threading

    def assassin():
        # wait until worker-0 has a job in flight, then SIGKILL its process
        deadline = time.time() + 30.0
        while time.time() < deadline:
            if (state / "jobs" / "worker-0").exists() and runner.worker_processes():
                proc = runner.worker_processes()[0]
                os.kill(proc.pid, signal.SIGKILL)
                killed["pid"] = proc.pid
                return
            time.sleep(0.02)

    t = threading.Thread(target=assassin, daemon=True)
    t.start()
    result = runner.run(max_wall_s=90.0)
    t.join(timeout=5.0)

    assert "pid" in killed, "assassin never fired"
    assert "worker-0" not in runner.tracker.workers()   # evicted
    assert runner.tracker.is_done()
    np.testing.assert_allclose(np.asarray(result), ref, atol=1e-12)


def test_process_superstep_trains_from_svmlight_splits(tmp_path):
    """The IRUnit pattern end to end (IRUnitSVMLightWorkerTest analog):
    OS-process workers each train on a byte-range split of ONE svmlight
    file across parameter-averaging supersteps; the averaged model must
    classify the corpus."""
    from deeplearning4j_tpu.datasets.svmlight import load_svmlight, save_svmlight

    rng = np.random.default_rng(3)
    n, d, c = 200, 6, 2
    labels = rng.integers(0, c, n)
    feats = rng.standard_normal((n, d)).astype(np.float32)
    feats += 2.5 * labels[:, None] * np.eye(d, dtype=np.float32)[0]
    feats[:, -1] = 1.0            # bias column (the model has no intercept)
    path = tmp_path / "corpus.svmlight"
    save_svmlight(path, feats, labels)
    size = path.stat().st_size

    # 2 splits x 6 epochs of superstep jobs
    splits = [(0, size // 2), (size // 2, size)]
    jobs = [f"{path}::{s}::{e}::{d}::{c}"
            for _ in range(6) for (s, e) in splits]

    runner = ProcessDistributedRunner(
        CollectionJobIterator(jobs),
        "deeplearning4j_tpu.parallel.performers:SVMLightTrainPerformer",
        state_dir=tmp_path / "state", n_workers=2,
        worker_env={"JAX_PLATFORMS": "cpu"})
    w = np.asarray(runner.run(max_wall_s=120.0)).reshape(d, c)

    x, y = load_svmlight(path, d, c)
    acc = (np.argmax(x @ w, -1) == y.argmax(-1)).mean()
    assert acc > 0.9, f"superstep-trained softmax accuracy {acc}"
