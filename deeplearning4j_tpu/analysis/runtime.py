"""Runtime enforcement: jax.transfer_guard scopes for hot loops.

The static rules catch the *patterns*; this module catches the *behavior*:
hot loops (trainer steady state, bench legs, perf smoke) run under
``jax.transfer_guard("disallow")``, so any IMPLICIT host<->device transfer
— a numpy batch leaking into a jitted call, a Python scalar materialized
per step, a stray ``float(loss)`` on a real accelerator — raises at the
exact call site instead of silently serializing the dispatch queue.

Explicit transfers (``jax.device_put`` / ``jax.device_get``) stay allowed:
the contract is not "no transfers", it is "every transfer is spelled out"
(DESIGN.md §10's synchronization-points-are-explicit rule, now enforced).

Opt out with ``DL4J_TPU_TRANSFER_GUARD=0`` (or ``off``/``allow``), or set
it to ``log`` to trace offenders without failing.  Known backend quirk:
on the CPU backend device->host reads are free (host-addressable memory,
no transfer happens), so only host->device hazards trip the guard there —
the full contract is enforced on real devices.
"""

from __future__ import annotations

import contextlib
import os

ENV_FLAG = "DL4J_TPU_TRANSFER_GUARD"

_OFF_VALUES = {"0", "off", "false", "allow", "no", "disabled"}
_MODES = {"disallow", "log", "disallow_explicit", "log_explicit"}


def guard_mode() -> str | None:
    """The transfer-guard level for hot loops, or None when opted out."""
    raw = os.environ.get(ENV_FLAG, "").strip().lower()
    if raw in _OFF_VALUES:
        return None
    if raw in _MODES:
        return raw
    return "disallow"


@contextlib.contextmanager
def hot_loop_guard():
    """Run a hot loop under the configured transfer guard.

    No-op (and no jax import) when opted out, so host-only tooling can
    wrap loops unconditionally.
    """
    mode = guard_mode()
    if mode is None:
        yield None
        return
    import jax

    with jax.transfer_guard(mode):
        yield mode


@contextlib.contextmanager
def allow_transfers():
    """Explicit sync point inside a guarded region (checkpoint fences,
    end-of-run parameter pulls): re-allows implicit transfers for the
    scope, making 'this code is ALLOWED to sync' a visible annotation."""
    import jax

    with jax.transfer_guard("allow"):
        yield
