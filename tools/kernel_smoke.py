"""Kernel smoke: per-kernel microbench of the ops/pallas tier.

Times every registered kernel candidate at a small fixed shape (warmup
dispatch excluded, ``block_until_ready`` fences each timed call), records
µs/call and a naive bytes-moved estimate through the observability
layer's ``record_kernel_time`` (``kernel.<kind>.<name>`` histograms +
bytes/GB-s gauges), and prints one JSON line.

On CPU the kernels run in Pallas interpret mode, so the numbers are a
SANITY signal (does the kernel dispatch, is nothing pathologically
slow), NOT a perf claim — on-chip claims come only from the TUNE battery
(tools/tune_tpu.py) through the bench auto-pick gate.

Wired as a fast tier-1 test (``tests/test_kernel_smoke.py``); also
runnable standalone: ``python tools/kernel_smoke.py``.
"""

from __future__ import annotations

import json
import sys
import time

_SHAPES = {"B": 2, "T": 128, "H": 2, "D": 32, "N": 101, "V": 77, "K": 64}


def _bytes(*arrays) -> int:
    """Naive bytes-moved estimate: every input read once + output written
    once (ignores VMEM reuse — a deliberate upper-bound convention)."""
    return sum(int(a.size) * a.dtype.itemsize for a in arrays)


def _cases():
    """(kind, name, thunk, io_arrays) for one small call per candidate."""
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops.pallas import registry
    from deeplearning4j_tpu.ops.pallas.matmul_int8 import quantize

    s = _SHAPES
    k = jax.random.PRNGKey(0)
    q, kk, v = (jax.random.normal(jax.random.fold_in(k, i),
                                  (s["B"], s["T"], s["H"], s["D"]),
                                  jnp.float32) for i in range(3))
    x = jax.random.normal(jax.random.fold_in(k, 3), (s["N"], s["K"]))
    r = jax.random.normal(jax.random.fold_in(k, 4), (s["N"], s["K"]))
    scale = jnp.ones((s["K"],))
    bias = jnp.zeros((s["K"],))
    head = jax.random.normal(jax.random.fold_in(k, 5), (s["K"], s["V"])) * 0.1
    tgt = jax.random.randint(jax.random.fold_in(k, 6), (s["N"],), 0, s["V"])
    qw = quantize(jax.random.normal(jax.random.fold_in(k, 7),
                                    (s["K"], s["V"])) * 0.05)
    n_phys, ps = 9, 16                          # paged decode: T = 4 pages
    pq = jax.random.normal(jax.random.fold_in(k, 8),
                           (s["B"], s["H"], s["D"]), jnp.float32)
    pk, pv = (jax.random.normal(jax.random.fold_in(k, 9 + i),
                                (n_phys, ps, s["H"], s["D"]), jnp.float32)
              for i in range(2))
    bt = jax.random.permutation(
        jax.random.fold_in(k, 11),
        jnp.arange(n_phys, dtype=jnp.int32))[: s["B"] * 4].reshape(s["B"], 4)
    lens = jnp.asarray([ps * 4, ps * 2 + 3], jnp.int32)
    from deeplearning4j_tpu.ops.pallas import kv_quant as kvq
    s0 = jnp.full((n_phys, s["H"]), kvq.neutral_scale(jnp.int8), jnp.float32)
    pkq, pks = kvq.requantize_pool(pk, s0, jnp.int8)
    pvq, pvs = kvq.requantize_pool(pv, s0, jnp.int8)

    calls = {
        ("attention", None): (lambda fn: fn(q, kk, v, causal=True),
                              (q, kk, v, q)),
        ("layernorm_residual", None): (lambda fn: fn(x, r, scale, bias),
                                       (x, r, x, x)),
        ("xent", None): (lambda fn: fn(x, head, tgt), (x, head, tgt)),
        ("int8_matmul", None): (lambda fn: fn(x[:, :s["K"]], qw),
                                (x, qw.q, qw.scale)),
        ("paged_attention", None): (lambda fn: fn(pq, pk, pv, bt, lens),
                                    (pq, pk, pv, bt, lens, pq)),
        ("paged_attention_int8", None): (
            lambda fn: fn(pq, pkq, pvq, pks, pvs, bt, lens),
            (pq, pkq, pvq, pks, pvs, bt, lens, pq)),
    }
    for kind in registry.kinds():
        call, io = calls[(kind, None)]
        for cand in registry.candidates(kind):
            yield kind, cand.name, (lambda c=cand, call=call: call(c.fn)), io


def run() -> dict:
    import jax

    from deeplearning4j_tpu.observability.kernels import record_kernel_time

    results = {}
    for kind, name, thunk, io in _cases():
        jax.block_until_ready(thunk())          # warmup (trace + compile)
        n_iters, t0 = 3, time.perf_counter()
        for _ in range(n_iters):
            jax.block_until_ready(thunk())
        per_call = (time.perf_counter() - t0) / n_iters
        moved = _bytes(*io)
        record_kernel_time(kind, name, per_call, bytes_moved=moved)
        results[f"{kind}.{name}"] = {
            "us_per_call": round(per_call * 1e6, 1),
            "bytes_moved_est": moved,
        }
    return {
        "backend": jax.default_backend(),
        "perf_claim": False,                    # interpret-mode numbers
        "kernels": results,
    }


def main() -> int:
    out = run()
    print(json.dumps(out))
    return 0 if out["kernels"] else 1


if __name__ == "__main__":
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main())
