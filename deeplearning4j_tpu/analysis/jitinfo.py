"""Per-module JAX context: which callables are jit-compiled, which function
bodies are traced, what they donate, and what is static.

Everything here is a single-module, best-effort static approximation — the
registry resolves the idioms this codebase actually uses:

- ``@jax.jit`` / ``@partial(jax.jit, donate_argnums=...)`` decorations
- ``fn = jax.jit(step, donate_argnums=(0, 1))`` assignments
- ``return jax.jit(sm, ...)`` inside a builder function ("jit factory"),
  plus ``self._hs_fn = build_hs_step(...)`` assignments from a factory
- functions handed to ``shard_map``/``pmap`` (traced, even if the jit
  wrapper lives elsewhere)

Cross-module flow (a factory imported from another file) is out of scope:
rules that need it match on the callee's *basename* instead, which is why
suppressions exist.
"""

from __future__ import annotations

import ast
import dataclasses

from .core import (
    assigned_names,
    dotted_name,
    iter_functions,
    last_segment,
    literal_int_tuple,
)

#: canonical callables that compile/trace their function argument
_JIT_WRAPPERS = {"jax.jit", "jit"}
_TRACE_WRAPPERS = {"shard_map", "pmap", "vmap_of_jit"}  # by basename


@dataclasses.dataclass
class JitInfo:
    """What we know about one jit-compiled callable."""

    name: str                                  # dotted name it is bound to
    donate_argnums: tuple[int, ...] = ()
    static_argnums: tuple[int, ...] = ()
    line: int = 0


class ModuleInfo:
    """Parsed module + the JAX facts the rules consume."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.aliases = self._collect_aliases()
        #: dotted name -> JitInfo for every callable known to be jitted
        self.jitted: dict[str, JitInfo] = {}
        #: basenames of jitted callables (attribute-call matching)
        self.jitted_basenames: set[str] = set()
        #: function defs whose BODY is traced (jit/shard_map/pmap), with the
        #: wrapper's JitInfo when known
        self.traced_defs: dict[ast.FunctionDef, JitInfo | None] = {}
        #: local def basenames whose body calls a jitted callable (one level
        #: of propagation for hot-loop rules)
        self.dispatching_basenames: set[str] = set()
        self._factories: dict[str, JitInfo] = {}
        self._collect_jit_facts()

    # ------------------------------------------------------------------ text
    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # ------------------------------------------------------------------ names
    def _collect_aliases(self) -> dict[str, str]:
        """local name -> canonical dotted prefix (``jnp`` -> ``jax.numpy``,
        ``partial`` -> ``functools.partial``)."""
        aliases: dict[str, str] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
        return aliases

    def canonical(self, node: ast.AST) -> str | None:
        """Dotted name with the leading segment alias-resolved:
        ``jnp.arange`` -> ``jax.numpy.arange``."""
        name = dotted_name(node)
        if name is None:
            return None
        head, _, rest = name.partition(".")
        head = self.aliases.get(head, head)
        return f"{head}.{rest}" if rest else head

    # ------------------------------------------------------------------ jit facts
    def _wrapper_info(self, call: ast.Call) -> tuple[ast.AST | None, JitInfo] | None:
        """If ``call`` is a jit/trace wrapper invocation, return
        (wrapped_fn_expr_or_None, JitInfo-from-kwargs)."""
        canon = self.canonical(call.func)
        if canon is None:
            # partial(jax.jit, ...) used as a decorator factory
            return None
        base = last_segment(canon)
        is_jit = canon in _JIT_WRAPPERS or canon.endswith(".jit")
        is_trace = base in ("shard_map", "pmap") or canon.endswith(".pmap")
        if canon in ("functools.partial", "partial") and call.args:
            inner = self.canonical(call.args[0])
            if inner and (inner in _JIT_WRAPPERS or inner.endswith(".jit")):
                info = self._info_from_kwargs(call, name="")
                wrapped = call.args[1] if len(call.args) > 1 else None
                return wrapped, info
            return None
        if not (is_jit or is_trace):
            return None
        info = self._info_from_kwargs(call, name="")
        wrapped = call.args[0] if call.args else None
        return wrapped, info

    @staticmethod
    def _info_from_kwargs(call: ast.Call, name: str) -> JitInfo:
        donate = static = None
        for kw in call.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                donate = literal_int_tuple(kw.value)
            elif kw.arg in ("static_argnums", "static_argnames"):
                static = literal_int_tuple(kw.value)
        return JitInfo(name=name, donate_argnums=donate or (),
                       static_argnums=static or (),
                       line=getattr(call, "lineno", 0))

    def _register_jitted(self, name: str, info: JitInfo) -> None:
        info = dataclasses.replace(info, name=name)
        self.jitted[name] = info
        self.jitted_basenames.add(last_segment(name))

    def _collect_jit_facts(self) -> None:
        defs_by_name = {fn.name: fn for fn in iter_functions(self.tree)}

        # pass 1: decorated defs + every wrapper call in the module
        for fn in iter_functions(self.tree):
            for dec in fn.decorator_list:
                if isinstance(dec, ast.Call):
                    hit = self._wrapper_info(dec)
                    if hit is not None:
                        _, info = hit
                        self.traced_defs[fn] = info
                        self._register_jitted(fn.name, info)
                else:
                    canon = self.canonical(dec)
                    if canon and (canon in _JIT_WRAPPERS
                                  or canon.endswith(".jit")):
                        info = JitInfo(name=fn.name, line=fn.lineno)
                        self.traced_defs[fn] = info
                        self._register_jitted(fn.name, info)

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            hit = self._wrapper_info(node)
            if hit is None:
                continue
            wrapped, info = hit
            wname = dotted_name(wrapped) if wrapped is not None else None
            if wname and last_segment(wname) in defs_by_name:
                fd = defs_by_name[last_segment(wname)]
                prior = self.traced_defs.get(fd)
                # a shard_map'd fn later jitted keeps the jit's donate info
                if prior is None or (not prior.donate_argnums
                                     and info.donate_argnums):
                    self.traced_defs[fd] = dataclasses.replace(
                        prior or info,
                        donate_argnums=(info.donate_argnums
                                        or (prior.donate_argnums
                                            if prior else ())),
                        static_argnums=(info.static_argnums
                                        or (prior.static_argnums
                                            if prior else ())))

        # pass 2: assignments + jit factories (statement order matters for
        # neither: two sub-passes over the whole tree)
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                hit = self._wrapper_info(node.value)
                if hit is not None:
                    _, info = hit
                    for t in node.targets:
                        for name in assigned_names(t):
                            self._register_jitted(name, info)

        for fn in iter_functions(self.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and isinstance(
                        node.value, ast.Call):
                    hit = self._wrapper_info(node.value)
                    if hit is not None:
                        _, info = hit
                        self._factories[fn.name] = info

        # pass 3: `self._fn = build_step(...)` from a local jit factory
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                callee = dotted_name(node.value.func)
                if callee and last_segment(callee) in self._factories:
                    info = self._factories[last_segment(callee)]
                    for t in node.targets:
                        for name in assigned_names(t):
                            self._register_jitted(name, info)

        # pass 4: defs that CALL a jitted callable (device-dispatch
        # propagation for hot-loop rules)
        for fn in iter_functions(self.tree):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    if callee and self.is_jitted_call(callee):
                        self.dispatching_basenames.add(fn.name)
                        break

    # ------------------------------------------------------------------ queries
    def is_jitted_call(self, callee: str) -> bool:
        """Does a call to dotted name ``callee`` hit a known-jitted
        callable?  Exact dotted match, else basename match (covers
        ``self._hs_fn`` style attribute calls)."""
        return (callee in self.jitted
                or last_segment(callee) in self.jitted_basenames)

    def jit_info_for_call(self, callee: str) -> JitInfo | None:
        if callee in self.jitted:
            return self.jitted[callee]
        base = last_segment(callee)
        for name, info in self.jitted.items():
            if last_segment(name) == base:
                return info
        return None

    def is_dispatching_call(self, callee: str) -> bool:
        """Jitted call, or a call to a local def that itself dispatches."""
        return (self.is_jitted_call(callee)
                or last_segment(callee) in self.dispatching_basenames)
