"""Serving load generator: drive the full HTTP stack, report latency + fill.

Spins up a tiny random transformer, an :class:`InferenceEngine`, a
:class:`BatchScorer` and a :class:`ModelServer` on a free port, then fires
``--requests`` generations from ``--threads`` concurrent clients (random
prompt lengths/temperatures/budgets from ``--seed``).  Everything observable
flows through the PR-1 metrics registry — the JSON result line reports
p50/p99 request latency and queue wait, time-to-first-token, batch fill
ratio and tokens/sec exactly as a Prometheus scrape of ``/metrics.prom``
would see them, so this doubles as an end-to-end check that the serving
histograms land.

    python tools/serving_smoke.py [--requests 32] [--threads 4] [--seed 0]
                                  [--lockguard]

``--lockguard`` runs the whole smoke with instrumented threading locks
(analysis/lockguard.py): lock-order inversions and Eraser-style unguarded
shared writes observed anywhere in the engine/queue/HTTP path fail the
run, and the violation count lands in the JSON result.

Exits nonzero if any request fails, the registry is missing a serving
histogram, or lockguard saw a violation.
"""

from __future__ import annotations

import json
import random
import sys
import threading


def run(requests: int = 32, threads: int = 4, seed: int = 0,
        lockguard: bool = False) -> dict:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu import observability
    from deeplearning4j_tpu.models.transformer import (TransformerConfig,
                                                       TransformerLM)
    from deeplearning4j_tpu.observability import METRICS
    from deeplearning4j_tpu.serving import (BatchScorer, InferenceEngine,
                                            ModelServer, ServingClient,
                                            ServingConfig, ServingError)

    observability.enable()
    METRICS.reset()

    guard = None
    if lockguard:
        from deeplearning4j_tpu.analysis.lockguard import LockGuard

        guard = LockGuard().install()

    cfg = TransformerConfig(vocab_size=64, d_model=32, n_heads=4, n_layers=2,
                            d_ff=64, max_len=64, dtype=jnp.float32,
                            remat=False, xent_chunk=0)
    model = TransformerLM(cfg)
    params = model.init(jax.random.key(7))

    def score_fn(x):
        # any row-wise fn serves; use the LM's own forward as the scorer
        return model.forward(params, jnp.asarray(x, jnp.int32))[:, -1, :]

    rng = random.Random(seed)
    failures: list[str] = []
    statuses: list[int] = []
    lock = threading.Lock()

    engine = InferenceEngine(model, params=params,
                             cfg=ServingConfig(slots=4, resolve_every=4))
    scorer = BatchScorer(score_fn, max_batch=16)
    with engine, scorer, ModelServer(engine=engine, scorer=scorer) as server:
        client = ServingClient(port=server.port)
        plans = [dict(prompt=[rng.randrange(cfg.vocab_size)
                              for _ in range(rng.randint(1, 12))],
                      max_new_tokens=rng.randint(1, 10),
                      temperature=rng.choice([0.0, 0.7, 1.0]),
                      seed=rng.randrange(1 << 20))
                 for _ in range(requests)]

        def worker(mine):
            for plan in mine:
                try:
                    out = client.generate(**plan)
                    with lock:
                        statuses.append(200)
                    if len(out["tokens"]) > plan["max_new_tokens"]:
                        with lock:
                            failures.append(f"overlong answer for {plan}")
                except ServingError as e:
                    with lock:
                        statuses.append(e.status)
                        failures.append(str(e))

        ts = [threading.Thread(target=worker, args=(plans[i::threads],))
              for i in range(threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        # one scorer round-trip through HTTP as well
        rows = [[rng.randrange(cfg.vocab_size) for _ in range(4)]
                for _ in range(6)]
        outputs = client.score(rows)
        if len(outputs) != len(rows):
            failures.append("score row count mismatch")
        health = client.healthz()
        prom = client.metrics_prom()

    if guard is not None:
        guard.uninstall()
        guard.emit_metrics()
        for v in guard.violations():
            failures.append(str(v))

    snap = METRICS.snapshot()
    timers, gauges = snap["timers"], snap["gauges"]

    def pct(name):
        t = timers.get(name)
        return {"p50": t["p50_s"], "p99": t["p99_s"], "count": t["count"],
                "mean": t["mean_s"]} if t else None

    required = ["serving.request_latency", "serving.queue_wait",
                "serving.ttft", "serving.batch_fill_ratio",
                "serving.decode_step"]
    missing = [n for n in required
               if n not in timers
               or n.replace(".", "_") + "_seconds" not in prom]
    result = {
        "requests": requests,
        "threads": threads,
        "seed": seed,
        "completed": statuses.count(200),
        "rejected": len(statuses) - statuses.count(200),
        "request_latency_s": pct("serving.request_latency"),
        "queue_wait_s": pct("serving.queue_wait"),
        "ttft_s": pct("serving.ttft"),
        "batch_fill_ratio": pct("serving.batch_fill_ratio"),
        "tokens_per_sec": gauges.get("serving.tokens_per_sec"),
        "tokens_total": snap["counters"].get("serving.tokens"),
        "prefill_buckets": health["engine"]["prefill_buckets"],
        "missing_histograms": missing,
        "failures": failures[:5],
    }
    if guard is not None:
        result["lockguard_violations"] = len(guard.violations())
    assert not failures, failures[:5]
    assert not missing, f"registry missing serving histograms: {missing}"
    assert result["completed"] == requests
    return result


def main(argv: list[str]) -> int:
    def arg(flag, default, cast=int):
        return cast(argv[argv.index(flag) + 1]) if flag in argv else default

    print(json.dumps(run(requests=arg("--requests", 32),
                         threads=arg("--threads", 4),
                         seed=arg("--seed", 0),
                         lockguard="--lockguard" in argv)))
    return 0


if __name__ == "__main__":
    import os
    import pathlib

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    sys.exit(main(sys.argv[1:]))
