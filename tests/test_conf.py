"""Config serde round-trips (mirror of the reference's
NeuralNetConfigurationTest / MultiLayerNeuralNetConfigurationTest)."""

from deeplearning4j_tpu.nn.conf import (
    Configuration,
    LayerKind,
    MultiLayerConfiguration,
    NeuralNetConfiguration,
    OptimizationAlgorithm,
    list_builder,
)
from deeplearning4j_tpu.ops.losses import LossFunction


def test_neural_net_conf_json_roundtrip():
    conf = NeuralNetConfiguration(
        lr=1e-2, momentum=0.9, momentum_schedule={10: 0.5, 100: 0.99},
        l2=1e-4, use_regularization=True, n_in=4, n_out=3,
        kind=LayerKind.OUTPUT, activation="softmax", loss=LossFunction.MCXENT,
        optimization_algo=OptimizationAlgorithm.LBFGS, k=3,
        filter_size=(5, 5), stride=(2, 2),
    )
    back = NeuralNetConfiguration.from_json(conf.to_json())
    assert back == conf


def test_multilayer_conf_roundtrip_and_list_builder():
    base = NeuralNetConfiguration(n_in=4, n_out=3, kind=LayerKind.RBM)
    mlc = (list_builder(base, 3)
           .hidden_layer_sizes(10, 5)
           .override(2, kind="output", activation="softmax", loss="mcxent")
           .pretrain(True)
           .build())
    assert mlc.n_layers == 3
    assert mlc.confs[0].n_in == 4 and mlc.confs[0].n_out == 10
    assert mlc.confs[1].n_in == 10 and mlc.confs[1].n_out == 5
    assert mlc.confs[2].n_in == 5 and mlc.confs[2].n_out == 3
    assert mlc.confs[2].kind == LayerKind.OUTPUT
    back = MultiLayerConfiguration.from_json(mlc.to_json())
    assert back == mlc


def test_momentum_schedule_lookup():
    conf = NeuralNetConfiguration(momentum=0.5, momentum_schedule={10: 0.9})
    assert conf.momentum_at(0) == 0.5
    assert conf.momentum_at(10) == 0.9
    assert conf.momentum_at(500) == 0.9


def test_kv_configuration_substitution():
    c = Configuration({"root": "/tmp", "path": "${root}/data", "n": "5", "flag": "true"})
    assert c.get_str("path") == "/tmp/data"
    assert c.get_int("n") == 5
    assert c.get_bool("flag") is True
    assert c.get_bool("missing", default=True) is True
