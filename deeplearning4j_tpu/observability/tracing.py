"""Span-based structured tracing.

``with trace.span("train_step", step=i):`` opens a nestable span; nesting
propagates through a ``contextvars.ContextVar`` so spans opened on worker
threads / asyncio tasks attribute to the right parent.  Completed spans
land in a bounded in-memory buffer and (optionally) stream to a JSONL
event log.  The buffer exports as Chrome trace-event JSON — complete
("ph":"X") events with microsecond ``ts``/``dur``, ``pid`` = JAX process
index (host index on a pod slice), ``tid`` = OS thread id — loadable in
Perfetto / chrome://tracing.

Distributed identity: every span carries a ``trace_id`` (one request /
job end-to-end), a ``span_id``, and a ``parent_id``.  A span inherits
identity from its enclosing span, else from the ambient trace context
(set by ``bind(...)`` after parsing a W3C ``traceparent`` header at a
process boundary), else mints a fresh trace.  ``current_traceparent()``
renders the context for outbound HTTP; ``record_span(...)`` records a
span with *explicit* start/duration for code (like the serving engine's
single serve thread) that multiplexes many logical requests and cannot
use ``with``-nesting.

Zero-overhead contract: when observability is disabled, ``span()`` returns
the shared no-op context manager (no allocation); see ``core``.
"""

from __future__ import annotations

import contextlib
import contextvars
import json
import random
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Callable

from . import core

_EPOCH = time.perf_counter()
_MAX_EVENTS = 65536

# Innermost-open-span chain, per context (thread / task).
_current: contextvars.ContextVar["Span | None"] = contextvars.ContextVar(
    "dl4j_tpu_current_span", default=None)

# Ambient (trace_id, parent_span_id) installed by ``bind()`` at process
# boundaries (HTTP handler, scaleout worker) — consulted when no span is
# open in this context.
_trace_ctx: contextvars.ContextVar[tuple[str, str] | None] = (
    contextvars.ContextVar("dl4j_tpu_trace_ctx", default=None))

_process_index: int | None = None

# getrandbits is GIL-atomic and ~10x cheaper than os.urandom for ids that
# only need uniqueness, not cryptographic strength.
_rng = random.Random()


def new_trace_id() -> str:
    """Fresh 32-hex-char W3C trace id (non-zero)."""
    return f"{_rng.getrandbits(128) | 1:032x}"


def new_span_id() -> str:
    """Fresh 16-hex-char W3C span id (non-zero)."""
    return f"{_rng.getrandbits(64) | 1:016x}"


def current_trace_context() -> tuple[str, str] | None:
    """(trace_id, span_id) of the innermost open span, else the ambient
    bound context, else None."""
    sp = _current.get()
    if sp is not None and sp.trace_id:
        return (sp.trace_id, sp.span_id)
    return _trace_ctx.get()


def current_traceparent() -> str | None:
    """W3C ``traceparent`` header value for the current context, or None."""
    ctx = current_trace_context()
    if ctx is None:
        return None
    return f"00-{ctx[0]}-{ctx[1]}-01"


def parse_traceparent(header: str | None) -> tuple[str, str] | None:
    """Parse ``00-<32hex>-<16hex>-<2hex>`` → (trace_id, parent_span_id).

    Returns None for anything malformed (wrong field count/width, non-hex,
    all-zero ids) — a bad inbound header means "mint fresh", never an error.
    """
    if not header:
        return None
    parts = header.strip().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, _flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16:
        return None
    try:
        if int(trace_id, 16) == 0 or int(span_id, 16) == 0:
            return None
        int(version, 16)
    except ValueError:
        return None
    return (trace_id.lower(), span_id.lower())


@contextlib.contextmanager
def bind(trace_id: str | None, parent_id: str | None = None):
    """Install an ambient trace context for the dynamic extent; spans
    opened inside inherit it.  No-op when ``trace_id`` is falsy."""
    if not trace_id:
        yield
        return
    token = _trace_ctx.set((trace_id, parent_id or ""))
    try:
        yield
    finally:
        _trace_ctx.reset(token)


def _pid() -> int:
    """JAX process index (host index), lazily resolved; 0 without jax."""
    global _process_index
    if _process_index is None:
        try:
            import jax
            _process_index = int(jax.process_index())
        except Exception:
            _process_index = 0
    return _process_index


class Span:
    """One nestable timed region.  Use via ``tracer.span(...)``."""

    __slots__ = ("tracer", "name", "attrs", "parent", "depth",
                 "t0_us", "tid", "_token",
                 "trace_id", "span_id", "parent_id")

    def __init__(self, tracer: "Tracer", name: str, attrs: dict[str, Any]):
        self.tracer = tracer
        self.name = name
        self.attrs = attrs
        self.parent: Span | None = None
        self.depth = 0
        self.trace_id = ""
        self.span_id = ""
        self.parent_id = ""

    def set(self, **attrs) -> None:
        """Attach/override attributes while the span is open."""
        self.attrs.update(attrs)

    def __enter__(self) -> "Span":
        self.parent = _current.get()
        self.depth = self.parent.depth + 1 if self.parent is not None else 0
        if self.parent is not None and self.parent.trace_id:
            self.trace_id = self.parent.trace_id
            self.parent_id = self.parent.span_id
        else:
            ctx = _trace_ctx.get()
            if ctx is not None:
                self.trace_id, self.parent_id = ctx
            else:
                self.trace_id = new_trace_id()
        self.span_id = new_span_id()
        self._token = _current.set(self)
        self.tid = threading.get_ident()
        self.t0_us = (time.perf_counter() - _EPOCH) * 1e6
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_us = (time.perf_counter() - _EPOCH) * 1e6 - self.t0_us
        _current.reset(self._token)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        self.tracer._record(self, dur_us)
        return False


class Tracer:
    """Collects completed spans; exports Chrome trace JSON and JSONL."""

    def __init__(self, max_events: int = _MAX_EVENTS):
        self._lock = threading.Lock()
        self.events: deque[dict[str, Any]] = deque(maxlen=max_events)
        self.dropped = 0  # spans evicted from the bounded ring
        self._jsonl: Any = None  # open file handle when streaming
        self._listeners: list[Callable[[dict[str, Any]], None]] = []

    # ------------------------------------------------------------- record
    def span(self, name: str, **attrs):
        """Open a span context manager (no-op singleton when disabled)."""
        if not core.enabled():
            return core.NOOP_SPAN
        return Span(self, name, attrs)

    def add_listener(self, fn: Callable[[dict[str, Any]], None]) -> None:
        """Call ``fn(event)`` for every completed span (flight recorder)."""
        self._listeners.append(fn)

    def _record(self, span: Span, dur_us: float) -> None:
        ev = {
            "name": span.name,
            "ph": "X",
            "ts": span.t0_us,
            "dur": dur_us,
            "pid": _pid(),
            "tid": span.tid,
            "args": dict(span.attrs,
                         parent=span.parent.name if span.parent else None,
                         depth=span.depth,
                         trace_id=span.trace_id,
                         span_id=span.span_id,
                         parent_span_id=span.parent_id or None),
        }
        self._append(ev)

    def record_span(self, name: str, t0_s: float, dur_s: float, *,
                    trace_id: str | None = None,
                    parent_id: str | None = None,
                    span_id: str | None = None,
                    tid: int | None = None,
                    **attrs) -> str | None:
        """Record a span with explicit ``time.perf_counter()`` start and
        duration (seconds).  For code that times many interleaved logical
        requests on one thread and cannot use ``with``-nesting.  Returns
        the span id (for parenting children), or None when disabled."""
        if not core.enabled():
            return None
        sid = span_id or new_span_id()
        ev = {
            "name": name,
            "ph": "X",
            "ts": (t0_s - _EPOCH) * 1e6,
            "dur": max(dur_s, 0.0) * 1e6,
            "pid": _pid(),
            "tid": tid if tid is not None else threading.get_ident(),
            "args": dict(attrs,
                         parent=None,
                         depth=0,
                         trace_id=trace_id or new_trace_id(),
                         span_id=sid,
                         parent_span_id=parent_id or None),
        }
        self._append(ev)
        return sid

    def _append(self, ev: dict[str, Any]) -> None:
        with self._lock:
            dropped_one = len(self.events) == self.events.maxlen
            if dropped_one:
                self.dropped += 1
            self.events.append(ev)
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(ev) + "\n")
                self._jsonl.flush()
        # Outside the tracer lock: the metrics registry and flight recorder
        # take their own locks, and nesting orders would be easy to deadlock.
        if dropped_one:
            from . import metrics
            metrics.METRICS.increment("trace.dropped_events")
        for fn in self._listeners:
            try:
                fn(ev)
            except Exception:
                pass

    # ------------------------------------------------------------- export
    def to_chrome_trace(self) -> dict[str, Any]:
        """Perfetto/chrome://tracing-loadable trace object."""
        with self._lock:
            return {"traceEvents": list(self.events),
                    "displayTimeUnit": "ms",
                    "metadata": {"dropped": self.dropped}}

    def save_chrome_trace(self, path: str | Path) -> Path:
        path = Path(path)
        path.write_text(json.dumps(self.to_chrome_trace()))
        return path

    def export_jsonl(self, path: str | Path) -> Path:
        """Dump the buffered events as one JSON object per line."""
        path = Path(path)
        with self._lock:
            with open(path, "w") as f:
                for ev in self.events:
                    f.write(json.dumps(ev) + "\n")
        return path

    def stream_jsonl(self, path: str | Path) -> None:
        """Append each completed span to ``path`` as it closes (crash-safe
        event log; survives a process that never reaches export)."""
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
            self._jsonl = open(path, "a")

    def stop_stream(self) -> None:
        with self._lock:
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None

    def clear(self) -> None:
        with self._lock:
            self.events.clear()
            self.dropped = 0


TRACER = Tracer()


def span(name: str, **attrs):
    """Module-level convenience: ``with trace.span("fit", epochs=2):``."""
    return TRACER.span(name, **attrs)


def record_span(name: str, t0_s: float, dur_s: float, **kw) -> str | None:
    """Module-level convenience for ``TRACER.record_span``."""
    return TRACER.record_span(name, t0_s, dur_s, **kw)


def profiler_trace(log_dir: str):
    """Context manager: JAX profiler trace (XPlane) to ``log_dir`` — the
    XLA-level companion to the host-side spans above."""
    import jax

    class _Trace:
        def __enter__(self):
            jax.profiler.start_trace(log_dir)
            return self

        def __exit__(self, *exc):
            jax.profiler.stop_trace()

    return _Trace()
