"""L0 — tensor/math substrate (TPU-native ND4J-contract replacement).

See SURVEY.md §2.1: the reference delegates all tensor math to external
ND4J/JBLAS (JNI → Fortran BLAS).  Here the substrate is JAX/XLA: jnp arrays
are the INDArray equivalent (functional, not in-place), and these modules
provide the named contract surface the upper layers consume.
"""

from . import activations, convolution, dtypes, linalg, losses, rng, sampling
from .dtypes import DtypePolicy, get_policy, set_policy
from .losses import LossFunction
from .rng import RngStream

__all__ = [
    "activations", "convolution", "dtypes", "linalg", "losses", "rng",
    "sampling", "DtypePolicy", "get_policy", "set_policy", "LossFunction",
    "RngStream",
]
