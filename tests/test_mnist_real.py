"""Real-pixel MNIST convergence tests (VERDICT r3 #3).

Mirrors the reference's DBN-on-real-data F1 assertion pattern
(``nn/multilayer/MultiLayerTest.java:33-70``).  These tests require the
vendored real MNIST IDX fixture (``deeplearning4j_tpu/datasets/fixtures/
mnist``) and NEVER run on the upscaled-digits fallback: the fetcher is
constructed with ``require_real=True``, so fake pixels cannot silently
satisfy the assertion.

This build container has zero egress and no local MNIST copy (the
reference's own test resources ship only ``mnist2500_labels.txt`` — labels
without pixels), so here the tests SKIP with that reason; run
``tools/vendor_mnist.py`` on any machine with egress to materialize the
fixture and activate them.
"""

import jax
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import MnistDataSetIterator
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.fetchers import MnistDataFetcher
from deeplearning4j_tpu.models.zoo import lenet, mlp

requires_real_mnist = pytest.mark.skipif(
    not MnistDataFetcher.real_data_available(),
    reason="real MNIST IDX fixture absent (zero-egress container; "
           "materialize with tools/vendor_mnist.py)")


def _real_mnist(n: int, flatten: bool, train: bool = True) -> DataSet:
    f = MnistDataFetcher(binarize=False, train=train, flatten=flatten,
                         require_real=True)
    f.fetch(n)
    ds = f.next()
    assert f.source == "idx"            # fallback can never satisfy this
    return ds


@requires_real_mnist
def test_fetcher_serves_real_pixels():
    ds = _real_mnist(512, flatten=True)
    # real MNIST pixels are 256-level grayscale; the upscaled-digits
    # fallback only has 17 distinct levels — a cheap authenticity probe
    assert len(np.unique(ds.features)) > 64
    assert ds.features.shape == (512, 784)
    assert ds.labels.shape == (512, 10)


@requires_real_mnist
def test_mlp_f1_on_real_mnist():
    train = _real_mnist(2048, flatten=True)
    test = _real_mnist(512, flatten=True, train=False)
    net = mlp(784, 10, hidden=(128,), num_iterations=300)
    net.init(jax.random.key(0))
    net.fit(train)
    assert net.evaluate(test).f1() > 0.85


@requires_real_mnist
def test_lenet_f1_on_real_mnist():
    train = _real_mnist(2048, flatten=False)
    test = _real_mnist(512, flatten=False, train=False)
    net = lenet(n_classes=10, input_side=28, num_filters=6,
                num_iterations=250, lr=0.1)
    net.init(jax.random.key(0))
    net.fit(train)
    assert net.evaluate(test).f1() > 0.85


@requires_real_mnist
def test_mnist_iterator_on_real_data():
    it = MnistDataSetIterator(batch=256, binarize=False)
    b = it.next()
    assert b.features.shape[0] == 256
