"""Online learning loop: serve → capture → fine-tune → hot-reload
(DESIGN.md §23).

The one-dataflow-system composition (ROADMAP item 3, the TensorFlow
story, arXiv:1605.08695): :class:`CaptureStore` persists served traffic
durably, :class:`OnlineLoop` fine-tunes on the replayed captures through
the existing supervised training stack, publishes manifest-verified
checkpoints, hot-reloads them into live serving at generation-consistent
fences, and auto-rolls-back any canary- or SLO-failing generation.
"""

from .capture import CaptureStore
from .loop import OnlineConfig, OnlineLoop, RoundReport

__all__ = ["CaptureStore", "OnlineConfig", "OnlineLoop", "RoundReport"]
