"""Distributed-layer tests on the virtual 8-device CPU mesh — the
"distributed-without-a-cluster" pattern (SURVEY.md §4 item 4,
``BaseTestDistributed``): the REAL collectives/trainer stack in one process.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.datasets import DataSet, IrisDataSetIterator
from deeplearning4j_tpu.nn import MultiLayerNetwork
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration, list_builder
from deeplearning4j_tpu.optimize import transforms as tfm
from deeplearning4j_tpu.parallel import (
    CheckpointManager,
    DataParallelTrainer,
    MeshSpec,
    local_mesh,
    make_mesh,
)
from deeplearning4j_tpu.parallel.mesh import DP, TP, batch_sharding, replicated
from deeplearning4j_tpu.parallel import collectives as coll


def test_virtual_mesh_has_8_devices():
    assert len(jax.devices()) == 8


def test_mesh_spec_resolution():
    spec = MeshSpec(dp=-1, tp=2)
    sizes = spec.resolve(8)
    assert sizes["dp"] == 4 and sizes["tp"] == 2
    with pytest.raises(ValueError):
        MeshSpec(dp=3, tp=2).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(dp=4, tp=2))
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    assert mesh.axis_names == ("pp", "dp", "sp", "tp", "ep")


def test_collectives_via_shard_map():
    try:
        from jax import shard_map           # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = local_mesh()

    def f(x):
        return coll.pmean(x, DP), coll.psum(x, DP)

    fm = shard_map(f, mesh=mesh, in_specs=(P(DP),), out_specs=(P(DP), P(DP)))
    x = jnp.arange(8.0)
    mean, total = fm(x)
    np.testing.assert_allclose(np.asarray(mean), np.full(8, x.mean()), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(total), np.full(8, x.sum()), rtol=1e-6)


def test_ring_shift():
    try:
        from jax import shard_map           # jax >= 0.8
    except ImportError:
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    mesh = local_mesh()
    fm = shard_map(lambda x: coll.ring_shift(x, DP, 8, 1), mesh=mesh,
                   in_specs=(P(DP),), out_specs=P(DP))
    x = jnp.arange(8.0)
    shifted = fm(x)
    # ppermute (i -> i+1): value from shard i lands on shard i+1
    np.testing.assert_allclose(np.asarray(shifted), np.roll(np.arange(8.0), 1))


def _iris_net():
    base = NeuralNetConfiguration(n_in=4, n_out=3, lr=0.1, use_adagrad=True,
                                  momentum=0.9, activation="tanh")
    conf = (list_builder(base, 2).hidden_layer_sizes(10)
            .override(1, kind="output", activation="softmax", loss="mcxent")
            .pretrain(False).build())
    net = MultiLayerNetwork(conf)
    net.init(jax.random.key(0))
    return net


def _iris_data():
    return (IrisDataSetIterator(batch=150).next()
            .normalize_zero_mean_unit_variance().shuffle(seed=3))


def test_data_parallel_iterative_reduce_trains():
    """Sync DP over 8 virtual chips reaches F1>=0.9 on Iris — parity with the
    reference's parameter-averaging path, but as one pjit'd step."""
    net = _iris_net()
    ds = _iris_data()
    trainer = DataParallelTrainer(
        loss_fn=lambda p, x, y, k: net.supervised_loss(p, x, y, rng=k, train=True),
        transform=tfm.from_conf(net.layers[-1].conf),
        router="iterative_reduce")
    state = trainer.init_state(net.params)
    for _ in range(150):
        state, loss = trainer.step(state, ds.features, ds.labels)
    net.params = trainer.final_params(state)
    assert net.evaluate(ds).f1() >= 0.9


def test_data_parallel_hogwild_trains():
    """Local-SGD/periodic-averaging (HogWild approximation) also converges."""
    net = _iris_net()
    ds = _iris_data()
    trainer = DataParallelTrainer(
        loss_fn=lambda p, x, y, k: net.supervised_loss(p, x, y, rng=k, train=True),
        transform=tfm.from_conf(net.layers[-1].conf),
        router="hogwild", average_every=4)
    state = trainer.init_state(net.params)
    for _ in range(150):
        state, loss = trainer.step(state, ds.features, ds.labels)
    net.params = trainer.final_params(state)
    assert net.evaluate(ds).f1() >= 0.85


@pytest.mark.strict_dtypes
def test_sync_matches_single_device_math():
    """One sync-DP step with the full batch == one single-device step on the
    same batch (parameter averaging over equal shards ≡ full-batch gradient).
    Runs under strict dtype promotion: the parity claim is about the same
    arithmetic, so no implicit widening may sneak into either side."""
    net = _iris_net()
    ds = _iris_data()
    x, y = jnp.asarray(ds.features[:64]), jnp.asarray(ds.labels[:64])
    loss_fn = lambda p, x_, y_, k: net.supervised_loss(p, x_, y_)
    transform = tfm.sgd_lr(0.1)

    trainer = DataParallelTrainer(loss_fn, transform, router="iterative_reduce")
    state = trainer.init_state(net.params)
    state, _ = trainer.step(state, x, y)

    loss, grads = jax.value_and_grad(lambda p: net.supervised_loss(p, x, y))(net.params)
    expected = jax.tree_util.tree_map(lambda p, g: p - 0.1 * g, net.params, grads)
    got_w = np.asarray(state.params[0]["W"])
    np.testing.assert_allclose(got_w, np.asarray(expected[0]["W"]), atol=1e-5)


def test_checkpoint_roundtrip(tmp_path):
    net = _iris_net()
    transform = tfm.from_conf(net.layers[-1].conf)
    tstate = transform.init(net.params)
    mgr = CheckpointManager(tmp_path, keep=2)
    key = jax.random.key(9)
    mgr.save(5, net.params, tstate, key, data_cursor=42)
    mgr.save(10, net.params, tstate, key, data_cursor=84)
    mgr.save(15, net.params, tstate, key, data_cursor=99)
    assert mgr.all_steps() == [10, 15]  # keep=2 rotation
    restored = mgr.restore(net.params, tstate)
    assert restored["step"] == 15 and restored["data_cursor"] == 99
    np.testing.assert_allclose(np.asarray(restored["params"][0]["W"]),
                               np.asarray(net.params[0]["W"]))
    assert restored["key"] is not None
    # restored tstate drives the same update math
    assert jax.tree_util.tree_structure(restored["tstate"]) == \
        jax.tree_util.tree_structure(tstate)
