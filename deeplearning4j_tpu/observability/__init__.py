"""Observability: structured tracing + metrics + status/metrics HTTP.

The production observability layer (grown from the seed
``parallel/observe.py``; that module remains as a compat shim):

- ``trace`` (module alias) / ``span`` — nestable spans with contextvar
  propagation, Chrome-trace (Perfetto) + JSONL export (``tracing``)
- ``METRICS`` / ``MetricsRegistry`` — counters, gauges, timing histograms
  with p50/p95/p99, Prometheus text exposition (``metrics``)
- ``StatusServer`` — ``/healthz`` ``/metrics`` ``/metrics.prom`` ``/status``
- ``sample_device_memory`` — per-device HBM gauges
- ``enabled``/``enable``/``disable`` — process-global flag;
  zero-per-step-allocation when off (see ``core``)
"""

from . import tracing as trace
from .core import NOOP_SPAN, disable, enable, enabled
from .device import sample_device_memory, sample_state_bytes
from .metrics import (
    DEFAULT_TIME_BUCKETS,
    METRICS,
    Histogram,
    MetricsRegistry,
    StepTimer,
)
from .server import StatusServer
from .tracing import TRACER, Tracer, profiler_trace, span

__all__ = [
    "DEFAULT_TIME_BUCKETS", "Histogram", "METRICS", "MetricsRegistry",
    "NOOP_SPAN", "StatusServer", "StepTimer", "TRACER", "Tracer",
    "disable", "enable", "enabled", "profiler_trace",
    "sample_device_memory", "sample_state_bytes", "span", "trace",
]
