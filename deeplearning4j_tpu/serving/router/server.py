"""HTTP front end for the router tier — the same surface as
:class:`~..server.ModelServer`, so clients cannot tell one replica from
N (rejection statuses ARE the API):

- ``POST /v1/generate``  — routed continuous-batching decode; the answer
  additionally carries ``replica`` and ``spills``
- ``POST /v1/reload``    — hot swap on every active replica
- ``GET  /healthz``      — router liveness + per-replica breaker state
- ``GET  /metrics``      — JSON registry snapshot (aggregate gauges)
- ``GET  /metrics.prom`` — Prometheus text exposition (scrape target)

Error contract: 429 only when every tried replica shed (spillover
exhausted), 503 when the ring has no live node or a transient fault is
injected, 504 for deadline misses, 400 for malformed requests — exactly
the single-replica contract, because the router must be droppable in
front of an existing client without changing its retry logic.  Inbound
W3C ``traceparent`` binds the handler thread's trace context, so the
``router.request`` / ``router.route`` spans (and, through the client
hop, the replica's ``serving.*`` spans) join the caller's trace.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ...observability import METRICS, MetricsRegistry, trace
from ...resilience.faults import InjectedFault
from ..batcher import ServingRejected
from ..client import ServingError
from .router import PrefixRouter


class RouterServer:
    """REST endpoint over a :class:`PrefixRouter`."""

    def __init__(self, router: PrefixRouter,
                 registry: MetricsRegistry = METRICS,
                 host: str = "127.0.0.1", port: int = 0):
        self.router = router
        self.registry = registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, code: int, body: bytes,
                      content_type: str = "application/json") -> None:
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, code: int, payload) -> None:
                self._send(code, json.dumps(payload).encode())

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, outer._health())
                elif self.path == "/metrics":
                    self._json(200, outer.registry.snapshot())
                elif self.path == "/metrics.prom":
                    self._send(200, outer.registry.to_prometheus().encode(),
                               "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._json(404, {"error": f"no route {self.path}"})

            def do_POST(self):
                try:
                    length = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(length) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError("body must be a JSON object")
                except (ValueError, json.JSONDecodeError) as e:
                    return self._json(400, {"error": f"bad request body: {e}"})
                ctx = trace.parse_traceparent(self.headers.get("traceparent"))
                try:
                    with trace.bind(*ctx) if ctx else trace.bind(None):
                        if self.path == "/v1/generate":
                            return self._json(200, outer._generate(payload))
                        if self.path == "/v1/reload":
                            return self._json(200, outer._reload(payload))
                    return self._json(404, {"error": f"no route {self.path}"})
                except ServingRejected as e:
                    # 429 spill-exhausted / 503 no live replica / 504
                    METRICS.increment("router.http.rejected")
                    return self._json(e.status, {"error": str(e)})
                except ServingError as e:
                    # a replica's own HTTP answer, passed through verbatim
                    return self._json(e.status, {"error": e.detail})
                except InjectedFault as e:
                    return self._json(503, {"error": f"transient fault: {e}"})
                except TimeoutError as e:
                    return self._json(504, {"error": str(e)})
                except (TypeError, ValueError, KeyError) as e:
                    return self._json(400, {"error": str(e)})
                except (FileNotFoundError, RuntimeError) as e:
                    return self._json(409, {"error": str(e)})

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    # ------------------------------------------------------------ handlers
    def _generate(self, p: dict) -> dict:
        if "prompt" not in p:
            raise ValueError("missing required field 'prompt'")
        eos = p.get("eos_id")
        dl = p.get("deadline_ms")
        return self.router.generate(
            p["prompt"], int(p.get("max_new_tokens", 16)),
            temperature=float(p.get("temperature", 0.0)),
            seed=int(p.get("seed", 0)),
            eos_id=int(eos) if eos is not None else None,
            deadline_ms=float(dl) if dl is not None else None,
            tenant=str(p.get("tenant") or ""))

    def _reload(self, p: dict | None = None) -> dict:
        step = (p or {}).get("step")
        return {"steps": self.router.reload(
            step=int(step) if step is not None else None)}

    def _health(self) -> dict:
        replicas = self.router.stats()
        return {"ok": any(v["active"] for v in replicas.values()),
                "replicas": replicas}

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "RouterServer":
        self.router.start()
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever, daemon=True,
                name="router-http")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        self._server.server_close()
        self.router.close()

    def __enter__(self) -> "RouterServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
