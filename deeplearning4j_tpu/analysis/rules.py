"""The graftlint rule set — twenty-seven hazard classes from this repo's
history.

| rule  | hazard                                                           |
|-------|------------------------------------------------------------------|
| HS01  | host sync (`float`/`.item()`/`np.asarray`/`device_get`) on a     |
|       | jit-produced value in a hot path                                 |
| RC01  | recompile hazard: Python-value-dependent shapes inside a traced  |
|       | function; non-hashable literals in static arg positions          |
| RNG01 | PRNG key reuse: same key fed to two `jax.random.*` calls without |
|       | a `split`/reassignment between them                              |
| DON01 | use-after-donate: an argument at a `donate_argnums` position     |
|       | read again after the jitted call                                 |
| TB01  | Python `if`/`while` branching on a traced value inside a jitted  |
|       | function                                                         |
| HOT02 | loop dispatching device work with no `trace.span`/`METRICS`      |
|       | instrumentation anywhere in reach (bypasses the PR 1 layer)      |
| EXC01 | bare `except:` — catches SystemExit/KeyboardInterrupt, so a      |
|       | retry/supervision loop becomes unkillable and every failure      |
|       | signal is swallowed untyped                                      |
| PL01  | `pallas_call` without an `interpret=` keyword — the kernel body  |
|       | can only execute on TPU, so CPU tier-1 tests never run it        |
| ZR01  | replicated `device_put` of optimizer-state trees in ZeRO-aware   |
|       | code with no `zero_stage` gate — silently re-replicates the      |
|       | state ZeRO sharded, undoing the 1/ndp memory win                 |
| LK01  | unguarded write to a lock-guarded / thread-shared attribute      |
|       | (explicit `# guarded-by:` contract, majority-guarded inference,  |
|       | or written from two thread contexts with no lock ever held)      |
| LK02  | inconsistent lock-acquisition order: the static lock-order       |
|       | graph (nested `with` + helper-call propagation) has a cycle —    |
|       | a deadlock schedule, incl. non-reentrant self-re-acquisition     |
| LK03  | blocking call while holding a lock (`block_until_ready`,         |
|       | untimed `.wait()`/`.join()`/`.get()`, socket/HTTP I/O,           |
|       | `time.sleep`) — a convoy or deadlock under contention            |
| TH01  | `threading.Thread` created with neither `daemon=True` nor a      |
|       | visible `join()`/daemon-flag lifecycle — leaks a thread that     |
|       | can hang interpreter shutdown                                    |
| PG01  | KV page acquire (`alloc`/`incref`/`lookup_prefix` on a page      |
|       | pool, `serving/` modules) with no `decref`-style release on the  |
|       | exceptional exit paths — leaked pinned pages 429 the pool        |
| OB01  | direct `time.monotonic()`/`perf_counter()` timing of dispatch    |
|       | in `serving/`/`parallel/` with no registry/tracer call in reach  |
|       | — the measurement exists nowhere a scrape or trace can see       |
| QT01  | raw `.astype(jnp.int8)`/`.astype(jnp.float8_*)` in `serving/`    |
|       | or `models/` outside the quant helpers — an unscaled,            |
|       | unsaturated cast that silently wraps/overflows instead of going  |
|       | through `kv_quant.cast_to`/`matmul_int8.quantize`                |
| EL01  | mesh/topology construction outside the `parallel/mesh.py`        |
|       | helpers in trainer/supervisor code — a raw `Mesh(...)` or a      |
|       | `jax.devices()[<literal>]` slice hard-codes a device set the     |
|       | elastic resize path (shrink/grow/reshard) cannot rebuild         |
| OB02  | literal metric name passed to `METRICS.increment/gauge/          |
|       | observe_time/time` that is missing from the documented metrics   |
|       | tables (README.md / DESIGN.md) — undocumented names drift and    |
|       | dashboards silently scrape nothing                               |
| OB03  | request-derived data (tenant/request/session/user ids, prompt    |
|       | text) interpolated into a metric name outside the bounded        |
|       | tenant-label helper — unbounded label cardinality is a memory    |
|       | leak with a dashboard                                            |
| OL01  | non-durable file rewrite on the online-loop / checkpoint publish |
|       | path: `open("w")`/`write_text`/`write_bytes` in `online/` or     |
|       | `parallel/checkpoint.py` outside the unique-tempfile + fsync +   |
|       | `os.replace` idiom — a crash mid-write publishes a torn file     |
| SH01  | collective (`psum`/`pmean`/`all_gather`/`ppermute`/`axis_index`) |
|       | over an axis name no enclosing `shard_map`/`pmap` context binds  |
|       | (resolved through the analysis/sharding.py mesh-axis pass)       |
| SH02  | `PartitionSpec` naming an axis absent from the canonical axis    |
|       | registry (`parallel/mesh.py` `AXES`) — a typo'd axis fails the   |
|       | trace on device, or silently replicates                          |
| SH03  | `shard_map` `in_specs`/`out_specs` arity mismatch against the    |
|       | wrapped function's signature / literal-tuple returns             |
| SH04  | argument donated to a jit whose declared `in_shardings` differ   |
|       | from the sharding the caller placed it with — the implicit       |
|       | reshard copies, the donation frees the copy source, the aliasing |
|       | win is silently lost (DON01 with sharding awareness)             |
| NM01  | hand-rolled softmax/logsumexp in `ops/`/`models/` without max    |
|       | subtraction (`log(sum(exp))`, `exp/sum(exp)` shapes) — the       |
|       | blocked-xent and online-softmax kernels are the sanctioned forms |
| CT01  | raw ring/pool mutation in `control/` — the control plane must    |
|       | scale through the `ReplicaPool`/`PrefixRouter` quarantine-drain  |
|       | seams (`scale_up`/`scale_down`/`drain_replica`); touching a      |
|       | `HashRing` or the pool's internals directly skips the warmed     |
|       | gate and the drain state machine                                 |
| DG01  | page accounting or block-table write in `serving/disagg/`        |
|       | outside the `KVMigrator` export/import seams — migration's       |
|       | refcount-handoff invariant (PG01 extended across the process     |
|       | boundary) holds only because every acquire/release funnels       |
|       | through the migrator                                             |

Each rule documents its known blind spots; deliberate hits are silenced
inline with ``# graftlint: disable=<RULE>`` plus a reason, or carried in
the committed baseline with a justification.
"""

from __future__ import annotations

import ast
import pathlib
import re
from collections import Counter
from typing import Iterator

from .concurrency import _INIT_METHODS, find_cycles, module_concurrency
from .core import (
    Finding,
    Rule,
    assigned_names,
    body_statements,
    dotted_name,
    last_segment,
    literal_int_tuple,
    names_read,
    register,
    statement_targets,
)
from .jitinfo import ModuleInfo
from .sharding import axis_registry, sharding_info

#: callables whose canonical name forces a device->host read of their arg
_SYNC_CALLS = {
    "float", "int", "bool",
    "numpy.asarray", "numpy.array",
    "jax.device_get",
}
#: method names that force a device->host read of their receiver
_SYNC_METHODS = {"item", "tolist"}

#: jnp constructors whose first argument fixes an output shape
_SHAPE_CONSTRUCTORS = {
    "jax.numpy.arange", "jax.numpy.zeros", "jax.numpy.ones",
    "jax.numpy.full", "jax.numpy.empty", "jax.numpy.eye",
    "jax.numpy.linspace", "jax.numpy.tri",
}

#: attributes of a traced array that are static at trace time
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval"}

#: observability markers — any of these in reach means the loop reports
#: through the PR 1 layer
_OBS_MARKERS = ("span", "observe_time", "observe_many", "increment",
                "gauge", "time", "iteration_done", "record_span")
_OBS_BASES = ("trace", "METRICS", "TRACER", "registry", "self.registry")


def _function_loops(fn: ast.FunctionDef) -> list[ast.stmt]:
    """Top-to-bottom list of loop statements in ``fn`` (not nested defs)."""
    loops = []
    for stmt in body_statements(fn.body):
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            loops.append(stmt)
    return loops


def _calls_in(node: ast.AST) -> Iterator[ast.Call]:
    for n in ast.walk(node):
        if isinstance(n, ast.Call):
            yield n


def _is_sync_call(module: ModuleInfo, call: ast.Call) -> ast.AST | None:
    """The expression being synced to host, or None."""
    canon = module.canonical(call.func)
    if canon in _SYNC_CALLS and call.args:
        return call.args[0]
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _SYNC_METHODS and not call.args):
        return call.func.value
    return None


@register
class HostSyncRule(Rule):
    """HS01 — device->host sync of a jit-produced value in a hot path.

    Taint: names bound from a call to a known-jitted callable inside the
    same function.  A sync call (``float``/``int``/``.item()``/
    ``np.asarray``/``jax.device_get``) whose argument reads a tainted name
    fires when it happens (a) inside a loop, or (b) anywhere in a
    loop-free function — the ``_apply_step``-style per-call method whose
    *caller* is the loop.  Syncs after a loop in a loop-containing
    function are treated as deliberate fences and left alone.
    """

    id = "HS01"
    title = "host sync on jit-produced value in hot path"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(module, node)

    def _check_function(self, module: ModuleInfo,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        tainted: set[str] = set()
        for stmt in body_statements(fn.body):
            if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, (ast.Call, ast.Tuple)):
                for call in _calls_in(stmt.value):
                    callee = dotted_name(call.func)
                    if callee and module.is_jitted_call(callee):
                        for t in stmt.targets:
                            tainted.update(assigned_names(t))
                        break
        if not tainted:
            return
        has_loop = bool(_function_loops(fn))
        loop_nodes = set()
        for loop in _function_loops(fn):
            for n in ast.walk(loop):
                loop_nodes.add(id(n))
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            arg = _is_sync_call(module, node)
            if arg is None:
                continue
            hit = tainted & names_read(arg)
            # direct form: float(self._step_fn(...))
            if not hit:
                inner = [c for c in _calls_in(arg)
                         if (dotted_name(c.func)
                             and module.is_jitted_call(dotted_name(c.func)))]
                if inner:
                    hit = {dotted_name(inner[0].func)}
            if not hit:
                continue
            in_loop = id(node) in loop_nodes
            if in_loop or not has_loop:
                where = ("inside a loop" if in_loop
                         else "in a loop-free per-call function")
                yield self.finding(
                    module, node,
                    f"host sync of jit-produced value {sorted(hit)[0]!r} "
                    f"{where}: forces the async dispatch queue to drain "
                    "every call — return the device value and resolve at "
                    "the caller's fence (LazyLoss pattern, DESIGN.md §10)")


@register
class RecompileRule(Rule):
    """RC01 — shapes that depend on Python values inside traced code.

    Fires on ``jnp.arange(n)``-style constructors whose size argument
    reads a *parameter* of the traced function (``x.shape[0]`` is fine —
    static under bucketing), and on list/dict/set literals passed at a
    known ``static_argnums`` position (unhashable -> TypeError at call
    time; hashable-but-fresh objects recompile every call).
    """

    id = "RC01"
    title = "recompile hazard in traced function"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn, _info in module.traced_defs.items():
            params = {a.arg for a in (fn.args.posonlyargs + fn.args.args
                                      + fn.args.kwonlyargs)} - {"self"}
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                canon = module.canonical(node.func)
                if canon not in _SHAPE_CONSTRUCTORS or not node.args:
                    continue
                size_args = node.args[:1]
                for arg in size_args:
                    bare = _bare_param_reads(arg, params)
                    if bare:
                        yield self.finding(
                            module, node,
                            f"shape of {canon.rsplit('.', 1)[-1]}() depends "
                            f"on traced/python parameter {sorted(bare)[0]!r} "
                            "inside a jitted function — each distinct value "
                            "recompiles (or fails to trace); derive sizes "
                            "from .shape or hoist to the host")
        # static-position literal check at call sites of known jitted fns
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            info = module.jit_info_for_call(callee)
            if info is None or not info.static_argnums:
                continue
            for pos in info.static_argnums:
                if pos < len(node.args) and isinstance(
                        node.args[pos], (ast.List, ast.Dict, ast.Set)):
                    yield self.finding(
                        module, node.args[pos],
                        f"non-hashable literal at static_argnums position "
                        f"{pos} of {callee!r} — static args must be "
                        "hashable (use a tuple)")


def _bare_param_reads(node: ast.AST, params: set[str]) -> set[str]:
    """Parameter names read under ``node`` EXCLUDING reads through static
    attributes (``x.shape[0]`` does not count as a bare read of ``x``)."""
    out: set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return  # x.shape / x.ndim / x.dtype are static at trace time
        if isinstance(n, ast.Call):
            canon_last = last_segment(dotted_name(n.func) or "")
            if canon_last in ("len", "isinstance", "type", "getattr",
                              "hasattr"):
                return
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load) \
                and n.id in params:
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


@register
class KeyReuseRule(Rule):
    """RNG01 — the same PRNG key consumed twice.

    Linear scan per function: every ``jax.random.<draw>(key, ...)`` call
    consumes its key; a second consumption of the same (dotted) name with
    no reassignment in between fires.  A draw inside a loop whose key is
    never reassigned in that loop body fires too (silent reuse across
    iterations — identical "randomness" every step).
    """

    id = "RNG01"
    title = "PRNG key reuse without split"

    #: jax.random callables that CONSUME a key (split/fold_in produce
    #: fresh ones but still consume their input)
    _NON_DRAWS = {"key", "PRNGKey", "key_data", "wrap_key_data"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(module, node)

    def _key_arg(self, module: ModuleInfo, call: ast.Call) -> str | None:
        canon = module.canonical(call.func) or ""
        if not canon.startswith("jax.random."):
            return None
        if canon.rsplit(".", 1)[-1] in self._NON_DRAWS:
            return None
        if not call.args:
            return None
        return dotted_name(call.args[0])

    def _check_function(self, module: ModuleInfo,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        yield from self._scan(module, fn.body, {}, frozenset())

    @staticmethod
    def _terminates(body: list[ast.stmt]) -> bool:
        """Whether control never falls past the end of ``body``."""
        return bool(body) and isinstance(
            body[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))

    def _draws(self, module: ModuleInfo, node: ast.AST,
               used_once: dict[str, int],
               skip: frozenset) -> Iterator[Finding]:
        """Register/flag key consumptions in one expression or simple
        statement (no statement-level branching below this node)."""
        for n in ast.walk(node):
            if not isinstance(n, ast.Call):
                continue
            key = self._key_arg(module, n)
            if key is None or key in skip:
                continue
            if key in used_once:
                yield self.finding(
                    module, n,
                    f"PRNG key {key!r} already consumed at line "
                    f"{used_once[key]} with no split/reassign since — two "
                    "draws from one key produce correlated streams")
            else:
                used_once[key] = n.lineno

    def _scan(self, module: ModuleInfo, body: list[ast.stmt],
              used_once: dict[str, int],
              skip: frozenset) -> Iterator[Finding]:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                loop_assigned: set[str] = set()
                for s in body_statements(stmt.body):
                    loop_assigned.update(statement_targets(s))
                if isinstance(stmt, (ast.For, ast.AsyncFor)):
                    loop_assigned.update(assigned_names(stmt.target))
                flagged: set[str] = set()
                for s in stmt.body:
                    for n in ast.walk(s):
                        if isinstance(n, ast.Call):
                            key = self._key_arg(module, n)
                            if (key is not None and key not in loop_assigned
                                    and key not in skip
                                    and key not in flagged):
                                flagged.add(key)
                                yield self.finding(
                                    module, n,
                                    f"PRNG key {key!r} is consumed every "
                                    "loop iteration but never split/"
                                    "reassigned in the loop — identical "
                                    "random draws each step")
                # intra-iteration reuse of keys that ARE rebound per step
                yield from self._scan(module, stmt.body, {},
                                      skip | flagged)
                used_once.clear()
                continue
            if isinstance(stmt, ast.If):
                yield from self._draws(module, stmt.test, used_once, skip)
                # branches are mutually exclusive: scan each from a copy of
                # the current state, then merge the states that fall through
                states: list[dict[str, int]] = []
                for branch in (stmt.body, stmt.orelse):
                    st = dict(used_once)
                    if branch:
                        yield from self._scan(module, branch, st, skip)
                    if not branch or not self._terminates(branch):
                        states.append(st)
                used_once.clear()
                for st in states:
                    used_once.update(st)
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    yield from self._draws(module, item.context_expr,
                                           used_once, skip)
                for t in statement_targets(stmt):
                    used_once.pop(t, None)
                yield from self._scan(module, stmt.body, used_once, skip)
                continue
            if isinstance(stmt, ast.Try):
                merged = dict(used_once)
                yield from self._scan(module, stmt.body, merged, skip)
                for handler in stmt.handlers:
                    hs = dict(used_once)
                    yield from self._scan(module, handler.body, hs, skip)
                    merged.update(hs)
                if stmt.orelse:
                    yield from self._scan(module, stmt.orelse, merged, skip)
                if stmt.finalbody:
                    yield from self._scan(module, stmt.finalbody, merged,
                                          skip)
                used_once.clear()
                used_once.update(merged)
                continue
            # simple statement: uses first, then (re)binds
            yield from self._draws(module, stmt, used_once, skip)
            for t in statement_targets(stmt):
                used_once.pop(t, None)


@register
class UseAfterDonateRule(Rule):
    """DON01 — reading a buffer after donating it to a jitted call.

    For calls to callables with known ``donate_argnums``, the (dotted)
    names passed at donated positions are dead afterwards unless the same
    statement rebinds them.  A later read before a rebind fires; a call
    inside a loop whose donated names are never rebound anywhere in the
    loop body fires at the call (next iteration reuses the corpse).
    """

    id = "DON01"
    title = "use after donate_argnums donation"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(module, node)

    def _donations(self, module: ModuleInfo,
                   stmt: ast.stmt) -> list[tuple[ast.Call, str]]:
        out = []
        for call in _calls_in(stmt):
            callee = dotted_name(call.func)
            if callee is None:
                continue
            info = module.jit_info_for_call(callee)
            if info is None or not info.donate_argnums:
                continue
            for pos in info.donate_argnums:
                if pos < len(call.args):
                    arg = call.args[pos]
                    if isinstance(arg, ast.Starred):
                        continue  # *tables style: rebinding checked coarsely
                    name = dotted_name(arg)
                    if name is not None:
                        out.append((call, name))
        return out

    def _check_function(self, module: ModuleInfo,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        yield from self._scan(module, fn.body, in_loop=False)

    def _scan(self, module: ModuleInfo, body: list[ast.stmt],
              in_loop: bool) -> Iterator[Finding]:
        dead: dict[str, int] = {}       # donated name -> donation line
        for i, stmt in enumerate(body):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            donations = self._donations(module, stmt)
            rebound = statement_targets(stmt)
            # reads in this statement happen before its own donation kills
            # anything, but after PREVIOUS statements' donations
            reads = names_read(stmt)
            for name, line in list(dead.items()):
                if name in reads:
                    yield Finding(
                        rule=self.id, path=module.path, line=stmt.lineno,
                        col=stmt.col_offset + 1,
                        message=(f"{name!r} was donated to a jitted call at "
                                 f"line {line} (donate_argnums) and read "
                                 "again here — the buffer is deleted after "
                                 "donation; copy first (jnp.array) or "
                                 "rebind from the call's result"),
                        code=module.line(stmt.lineno))
                    dead.pop(name, None)
            for name in rebound:
                dead.pop(name, None)
            if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                loop_assigned: set[str] = set()
                for s in body_statements(stmt.body):
                    loop_assigned.update(statement_targets(s))
                for call, name in [d for s in body_statements(stmt.body)
                                   for d in self._donations(module, s)]:
                    if name not in loop_assigned:
                        yield self.finding(
                            module, call,
                            f"{name!r} is donated inside a loop but never "
                            "rebound in the loop body — the next iteration "
                            "passes a deleted buffer")
                yield from self._scan(module, stmt.body, in_loop=True)
                continue
            for name, line in [(n, c.lineno) for c, n in donations
                               if n not in rebound]:
                dead[name] = line
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    yield from self._scan(module, sub, in_loop)
            for handler in getattr(stmt, "handlers", []) or []:
                yield from self._scan(module, handler.body, in_loop)


@register
class TracedBranchRule(Rule):
    """TB01 — Python control flow on traced values.

    Inside a traced function body, ``if``/``while`` tests that read a
    parameter of that function concretize a tracer (ConcretizationTypeError
    at best, value-dependent retraces at worst).  ``is``/``is not`` tests,
    reads through static attributes (``x.shape``), and ``isinstance``/
    ``len`` calls are allowed — those are static at trace time.
    """

    id = "TB01"
    title = "python branch on traced value"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for fn, info in module.traced_defs.items():
            static = set(info.static_argnums) if info else set()
            ordered = [a.arg for a in (fn.args.posonlyargs + fn.args.args)]
            params = ({a for i, a in enumerate(ordered) if i not in static}
                      | {a.arg for a in fn.args.kwonlyargs}) - {"self"}
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                test = node.test
                if isinstance(test, ast.Compare) and all(
                        isinstance(op, (ast.Is, ast.IsNot))
                        for op in test.ops):
                    continue
                bare = _bare_param_reads(test, params)
                if bare:
                    kind = "while" if isinstance(node, ast.While) else "if"
                    yield self.finding(
                        module, node,
                        f"python `{kind}` on traced parameter "
                        f"{sorted(bare)[0]!r} inside a jitted function — "
                        "use jnp.where/lax.cond/lax.while_loop (or mark "
                        "the argument static)")


@register
class UninstrumentedHotLoopRule(Rule):
    """HOT02 — device-dispatching loops invisible to observability.

    A loop that calls a jitted callable (directly, or through a local
    helper that does) with no ``trace.span``/``METRICS``/timer call
    anywhere in the loop body or its enclosing function bypasses the PR 1
    metrics layer: its steps appear in no histogram, no trace, no
    ``/metrics.prom`` scrape.  One span or counter anywhere in reach —
    even per-epoch around the loop — satisfies the rule.
    """

    id = "HOT02"
    title = "uninstrumented device-dispatching loop"

    @staticmethod
    def _has_obs(node: ast.AST, module: ModuleInfo) -> bool:
        for call in _calls_in(node):
            name = dotted_name(call.func) or ""
            base, _, attr = name.rpartition(".")
            if attr in _OBS_MARKERS and (
                    last_segment(base) in ("trace", "METRICS", "TRACER",
                                           "registry")
                    or base.endswith("METRICS") or "observ" in base):
                return True
            canon = module.canonical(call.func) or ""
            if "observability" in canon or canon.endswith(".span"):
                return True
        return False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            fn_has_obs = self._has_obs(node, module)
            if fn_has_obs:
                continue
            for loop in _function_loops(node):
                dispatches = None
                for call in _calls_in(loop):
                    callee = dotted_name(call.func)
                    if callee and module.is_dispatching_call(callee):
                        dispatches = callee
                        break
                if dispatches is None:
                    continue
                yield self.finding(
                    module, loop,
                    f"loop dispatches device work ({dispatches!r}) with no "
                    "trace.span/METRICS instrumentation in reach — add a "
                    "span or counter (per-epoch is enough) so the PR 1 "
                    "observability layer sees this hot path")
                break  # one finding per function is enough signal


@register
class BareExceptRule(Rule):
    """EXC01 — bare ``except:`` clauses.

    A bare handler catches ``SystemExit``, ``KeyboardInterrupt``, and
    ``GeneratorExit`` along with everything else.  In this codebase's
    retry/supervision paths (resilience supervisor, scaleout worker
    loops) that is exactly wrong twice over: the process becomes
    unkillable under retry, and the retry policy's ``retry_on`` typing is
    bypassed — every failure looks retryable.  Catch ``Exception`` (or
    narrower) instead; if the broad catch is deliberate, re-raise the
    exit exceptions first.
    """

    id = "EXC01"
    title = "bare except swallows exit signals"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield self.finding(
                    module, node,
                    "bare `except:` catches SystemExit/KeyboardInterrupt — "
                    "a retry loop built on this cannot be killed and treats "
                    "every failure as retryable; catch Exception (or the "
                    "policy's retry_on tuple) instead")


@register
class PallasInterpretRule(Rule):
    """PL01 — ``pallas_call`` without an ``interpret`` fallback.

    The kernel tier's contract (DESIGN.md §14) is that every Pallas
    kernel runs its REAL body in tier-1 CPU tests via interpret mode —
    a ``pl.pallas_call`` with no ``interpret=`` keyword can only ever
    execute on a TPU, so its kernel body is dead code to the test suite
    and every bug in it ships untested.  Wrappers must thread an
    ``interpret`` flag (auto-selected off-TPU) down to the call.

    Blind spot: a call aliased through a variable
    (``f = pl.pallas_call; f(...)``) is not seen; none exist in-tree.
    """

    id = "PL01"
    title = "pallas_call without interpret fallback"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            name = module.canonical(node.func) or dotted_name(node.func) or ""
            if not name.endswith("pallas_call"):
                continue
            if any(kw.arg == "interpret" for kw in node.keywords):
                continue
            yield self.finding(
                module, node,
                "`pallas_call` without an `interpret=` keyword compiles "
                "only on TPU — CPU tier-1 tests can never execute the "
                "kernel body; thread an interpret flag (auto-selected "
                "off-TPU) through the wrapper")


#: identifier fragments naming an optimizer-state tree
_ZR_STATE_TOKENS = ("tstate", "opt_state")


def _mentions_token(node: ast.AST, tokens) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and any(t in n.id.lower() for t in tokens):
            return True
        if isinstance(n, ast.Attribute) \
                and any(t in n.attr.lower() for t in tokens):
            return True
    return False


@register
class ZeroReplicateRule(Rule):
    """ZR01 — un-gated replicated placement of optimizer-state trees in
    ZeRO-aware code.

    Under ``zero_stage >= 2`` the optimizer state lives shard-local
    (``NamedSharding(mesh, P('dp'))`` over the flattened layout, DESIGN.md
    §15) — a ``jax.device_put`` of a tstate/opt_state tree with a
    *replicated* sharding (``P()`` / ``NamedSharding(_, P())`` / a
    ``*rep*``-named cached sharding) silently re-materializes the full
    state on every chip, undoing the 1/ndp memory win without failing any
    numerics test.  The rule scopes itself to functions that read
    ``zero_stage`` (the code that KNOWS sharded state exists) and stays
    quiet when the placement is gated by a ``zero_stage`` conditional:
    inside any branch of an ``if``/``elif`` chain whose test mentions
    ``zero_stage``, or after a ``zero_stage`` guard that early-returns.
    Both the direct form and the ``tree_map(lambda ...: device_put(...),
    tstate)`` form are caught.

    Blind spots (documented, not accidental): placements routed through a
    helper the AST can't see into, shardings aliased to names without a
    ``rep`` fragment, and state trees not named ``*tstate*``/
    ``*opt_state*`` — naming IS the contract in this tree.
    """

    id = "ZR01"
    title = "replicated device_put of sharded optimizer state"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _mentions_token(node, ("zero_stage",)):
                    yield from self._check_function(module, node)

    # ------------------------------------------------------------- gating
    def _gated_ids(self, fn: ast.AST) -> set[int]:
        """ids of AST nodes covered by a ``zero_stage`` conditional: every
        descendant of any branch of an If whose test reads zero_stage,
        plus statements that only execute after such an If whose taken
        branch leaves the block (early return/raise/continue/break)."""
        gated: set[int] = set()

        def mark(node: ast.AST):
            for n in ast.walk(node):
                gated.add(id(n))

        # every statement list anywhere in the function is one block; a
        # zero_stage If gates its own branches, and (when its taken branch
        # leaves the block) everything after it in the same list
        for n in ast.walk(fn):
            for field in ("body", "orelse", "finalbody"):
                stmts = getattr(n, field, None)
                if isinstance(stmts, list) and stmts \
                        and all(isinstance(s, ast.stmt) for s in stmts):
                    behind = False
                    for s in stmts:
                        if behind:
                            mark(s)
                            continue
                        if isinstance(s, ast.If) and _mentions_token(
                                s.test, ("zero_stage",)):
                            for sub in s.body + s.orelse:
                                mark(sub)
                            if s.body and isinstance(
                                    s.body[-1], (ast.Return, ast.Raise,
                                                 ast.Continue, ast.Break)):
                                behind = True
        return gated

    # ------------------------------------------------------------- shardings
    def _is_replicated(self, module: ModuleInfo, node: ast.AST) -> bool:
        if isinstance(node, ast.Call):
            canon = module.canonical(node.func) or dotted_name(node.func) or ""
            seg = last_segment(canon) or canon
            if seg in ("P", "PartitionSpec") \
                    and not node.args and not node.keywords:
                return True  # bare P(): fully replicated spec
            if seg == "NamedSharding" and len(node.args) >= 2:
                return self._is_replicated(module, node.args[1])
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "replicated":
                return True
            return False
        name = dotted_name(node) or ""
        seg = (last_segment(name) or name).lower()
        return seg == "rep" or "rep_sh" in seg or "replicated" in seg

    def _check_function(self, module: ModuleInfo,
                        fn: ast.FunctionDef) -> Iterator[Finding]:
        gated = self._gated_ids(fn)
        for call in _calls_in(fn):
            if id(call) in gated:
                continue
            canon = module.canonical(call.func) or dotted_name(call.func) or ""
            seg = last_segment(canon) or canon
            if seg == "device_put" and len(call.args) >= 2:
                tree, sharding = call.args[0], call.args[1]
                if _mentions_token(tree, _ZR_STATE_TOKENS) \
                        and self._is_replicated(module, sharding):
                    yield self._fire(module, call)
            elif seg == "tree_map" and len(call.args) >= 2:
                # tree_map(lambda x: device_put(x, rep), tstate): the
                # device_put's first arg is the lambda var, so the state
                # name lives on the mapped TREE argument instead
                if not any(_mentions_token(a, _ZR_STATE_TOKENS)
                           for a in call.args[1:]):
                    continue
                for inner in _calls_in(call.args[0]):
                    iseg = last_segment(
                        module.canonical(inner.func)
                        or dotted_name(inner.func) or "") or ""
                    if iseg == "device_put" and len(inner.args) >= 2 \
                            and self._is_replicated(module, inner.args[1]):
                        yield self._fire(module, inner)

    def _fire(self, module: ModuleInfo, node: ast.AST) -> Finding:
        return self.finding(
            module, node,
            "replicated `device_put` of an optimizer-state tree in "
            "zero_stage-aware code with no `zero_stage` gate — under "
            "zero_stage >= 2 this re-materializes the full state on every "
            "chip, silently undoing the 1/ndp ZeRO memory win; branch on "
            "`zero_stage` (replicate only when it is 0) or place with the "
            "layout's dp shardings")


# ------------------------------------------------------------------ LK01-TH01

@register
class UnguardedSharedWriteRule(Rule):
    """LK01: an attribute the class treats as lock-guarded is written
    without the lock — or is written from two thread contexts with no
    lock at all.

    Three triggers, in priority order:

    1. **declared contract**: any assignment line carrying a
       ``# guarded-by: self._lock`` comment makes every later write of
       that attribute outside ``with self._lock:`` a finding;
    2. **majority inference**: when at least half of an attribute's
       non-``__init__`` writes hold some lock, the unlocked minority are
       the bug (PR 1's StepTimer race was exactly this shape);
    3. **shared-context inference**: in a class that spawns threads or
       handles HTTP, an attribute written both from a thread-entry
       context (``Thread(target=...)`` closure, ``do_GET``) and from
       caller-facing methods, with no write ever locked, is a data race
       waiting for load.  One finding per attribute, anchored at the
       first unlocked write.

    Blind spots: reads are not tracked; ``acquire()``/``release()``
    pairs are invisible (use ``with``); aliasing (``s = self.slots``)
    hides writes; happens-before edges that are real but invisible to
    the AST (warmup-before-start) need an inline suppression with the
    reason spelled out.
    """

    id = "LK01"
    title = "unguarded write to lock-guarded/thread-shared attribute"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = module_concurrency(module)
        for cls in model.classes:
            yield from self._check_class(module, cls)

    def _check_class(self, module: ModuleInfo,
                     cls) -> Iterator[Finding]:
        for attr in sorted(cls.writes):
            body = [w for w in cls.writes[attr]
                    if w.method not in _INIT_METHODS]
            if not body:
                continue
            body.sort(key=lambda w: getattr(w.node, "lineno", 0))
            lock = cls.guarded_by.get(attr)
            if lock is not None:
                for w in body:
                    if lock not in w.held:
                        yield self.finding(
                            module, w.node,
                            f"write to `self.{attr}` in `{cls.name}."
                            f"{w.method}` without holding `self.{lock}` "
                            f"(declared `# guarded-by: self.{lock}`)")
                continue
            unlocked = [w for w in body if not w.held]
            locked = [w for w in body if w.held]
            if not unlocked:
                continue
            if locked and len(locked) >= len(unlocked):
                guard = Counter(
                    l for w in locked for l in w.held).most_common(1)[0][0]
                others = ", ".join(
                    f"{w.method}:{getattr(w.node, 'lineno', '?')}"
                    for w in unlocked[1:]) or "none"
                yield self.finding(
                    module, unlocked[0].node,
                    f"`self.{attr}` is written under `self.{guard}` in "
                    f"{len(locked)} of {len(body)} sites but not in "
                    f"`{cls.name}.{unlocked[0].method}` (other unlocked "
                    f"sites: {others}) — take the lock, or annotate the "
                    f"deliberate exception with a reason")
            elif cls.threaded:
                ctxs = set()
                for w in body:
                    ctxs |= cls.contexts(w.method)
                if len(ctxs) >= 2:
                    roots = ", ".join(sorted(ctxs))
                    sites = ", ".join(sorted(
                        {f"{w.method}:{getattr(w.node, 'lineno', '?')}"
                         for w in body}))
                    yield self.finding(
                        module, unlocked[0].node,
                        f"`self.{attr}` is written from multiple thread "
                        f"contexts ({roots}; sites {sites}) with no lock "
                        f"ever held in `{cls.name}` — guard it (declare "
                        f"`# guarded-by: self._lock` and wrap writes in "
                        f"`with self._lock:`) or suppress with the "
                        f"happens-before argument spelled out")


@register
class LockOrderRule(Rule):
    """LK02: the module's static lock-order graph has a cycle.

    Nested ``with`` acquisitions and one level of ``self.m()`` helper
    propagation yield ``held -> acquired`` edges; any cycle is a
    schedule where two threads deadlock (or, for a length-1 cycle on a
    non-reentrant ``threading.Lock``, one thread deadlocks itself
    through a helper that re-takes the lock it already holds).

    Blind spots: cross-module cycles (lock identities are
    ``Class.attr``-scoped per module), ``acquire()`` call pairs, and
    locks passed as arguments.
    """

    id = "LK02"
    title = "inconsistent lock-acquisition order (deadlock schedule)"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = module_concurrency(module)
        for cyc in find_cycles(model.edges):
            e = cyc[0]
            if len(cyc) == 1 and e.held == e.acquired:
                yield self.finding(
                    module, e.node,
                    f"`{e.acquired}` is a non-reentrant Lock already held "
                    f"in `{e.func}` when it is re-acquired — guaranteed "
                    f"self-deadlock; use RLock or hoist the helper's "
                    f"locking to the caller")
                continue
            path = " -> ".join([c.held for c in cyc] + [cyc[0].held])
            where = "; ".join(
                f"{c.held}->{c.acquired} in {c.func}:"
                f"{getattr(c.node, 'lineno', '?')}" for c in cyc)
            yield self.finding(
                module, e.node,
                f"lock-order cycle {path} ({where}) — two threads taking "
                f"these paths concurrently deadlock; pick one global "
                f"order and re-nest the minority site")


@register
class BlockingUnderLockRule(Rule):
    """LK03: a call that can block indefinitely runs while a lock is
    held — every other thread needing that lock convoys behind device
    work, socket I/O, or an untimed wait (and if the blocked operation
    itself needs the lock to make progress, it is a deadlock).

    Condition-variable waits on the *same* lock being held are exempt
    (``wait`` releases its own lock); timed waits/joins/gets are exempt
    (bounded convoy).  Blind spots: blocking hidden behind helper
    functions, and ``dict.get(key)``-vs-``queue.get()`` is told apart
    only by argument count.
    """

    id = "LK03"
    title = "blocking call while holding a lock"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        model = module_concurrency(module)
        for node, why, func in model.blocking:
            yield self.finding(
                module, node,
                f"{why} while holding a lock in `{func}` — threads "
                f"contending for the lock convoy behind this call (a "
                f"deadlock if the blocked work needs the same lock); "
                f"move it outside the `with`, or bound it with a timeout")


@register
class ThreadLifecycleRule(Rule):
    """TH01: a ``threading.Thread`` is created with neither
    ``daemon=True`` nor any visible join/daemon lifecycle.

    A non-daemon thread with no ``join()`` keeps the interpreter alive
    after ``main`` returns — test runs and CLI tools hang on exit, and
    there is no orderly shutdown path.  Accepted lifecycles: a
    ``daemon=True`` kwarg, a later ``<name>.daemon = True`` assignment
    or ``setDaemon(True)`` call, or a ``.join(...)`` on the variable (or
    attribute basename) the thread was assigned to — including threads
    built in a comprehension bound to a container that is then joined
    through a loop variable (``ts = [Thread(...) ...]`` /
    ``for t in ts: t.join()``).

    Blind spots: ``Thread`` subclasses instantiated by their own name,
    and joins that live in another module.
    """

    id = "TH01"
    title = "thread without daemon flag or join lifecycle"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        joined: set[str] = set()
        daemonized: set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute):
                recv = dotted_name(node.func.value)
                if recv and node.func.attr == "join":
                    joined.add(last_segment(recv))
                if recv and node.func.attr == "setDaemon":
                    daemonized.add(last_segment(recv))
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    name = dotted_name(t)
                    if name and last_segment(name) == "daemon":
                        owner = name.rsplit(".", 2)
                        if len(owner) >= 2:
                            daemonized.add(owner[-2])
        # a container joined through a loop variable counts: the loop var
        # landed in `joined` above, so lift that onto the iterated name
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.For, ast.AsyncFor)) \
                    and isinstance(node.target, ast.Name):
                src = dotted_name(node.iter)
                if src:
                    if node.target.id in joined:
                        joined.add(last_segment(src))
                    if node.target.id in daemonized:
                        daemonized.add(last_segment(src))
        compound = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                    ast.For, ast.AsyncFor, ast.While, ast.If, ast.Try,
                    ast.With, ast.AsyncWith)
        for stmt in body_statements(module.tree.body, into_defs=True):
            if isinstance(stmt, compound):
                continue       # its simple statements are enumerated anyway
            for call, bound in self._thread_calls(module, stmt):
                kw = {k.arg: k.value for k in call.keywords}
                d = kw.get("daemon")
                if d is not None and not (
                        isinstance(d, ast.Constant) and d.value is False):
                    continue
                base = last_segment(bound) if bound else None
                if base and (base in joined or base in daemonized):
                    continue
                held = f"bound to `{bound}`" if bound else "never bound"
                yield self.finding(
                    module, call,
                    f"thread created without `daemon=True` and with no "
                    f"visible `join()`/daemon lifecycle ({held}) — it "
                    f"outlives main and hangs interpreter shutdown; pass "
                    f"`daemon=True` or join it on the shutdown path")

    @staticmethod
    def _thread_calls(module: ModuleInfo, stmt: ast.stmt):
        """(Thread(...) call, dotted name it is assigned to | None)."""
        bound_ids: dict[int, str] = {}
        if isinstance(stmt, ast.Assign):
            names = [n for t in stmt.targets for n in assigned_names(t)]
            if names and isinstance(stmt.value, ast.Call):
                bound_ids[id(stmt.value)] = names[0]
            elif names and isinstance(stmt.value, (ast.ListComp, ast.SetComp,
                                                   ast.GeneratorExp)):
                # threads built in a comprehension are "bound to" the
                # container the comprehension is assigned to
                for sub in ast.walk(stmt.value):
                    if isinstance(sub, ast.Call):
                        bound_ids[id(sub)] = names[0]
        for node in ast.walk(stmt):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)) and node is not stmt:
                continue
            if isinstance(node, ast.Call):
                canon = module.canonical(node.func) or ""
                if canon == "threading.Thread" or canon.endswith(".Thread"):
                    yield node, bound_ids.get(id(node))


#: PagePool methods that hand the caller page references it must release
_PG_ACQUIRE = {"alloc", "incref", "lookup_prefix"}
#: methods that give references back (any one on an exit path clears PG01)
#: — decref_quarantine is the off-serve-thread release (migration abort):
#: it drops the reference without making the page allocatable, which is
#: still a release for leak purposes
_PG_RELEASE = {"decref", "decref_quarantine", "free", "release", "reset"}


@register
class PageLeakRule(Rule):
    """PG01: KV pages acquired from a page pool with no release on the
    failure exit paths.

    The paged serving engine's pages are refcounted host-side
    (serving/paging.py): every ``alloc``/``lookup_prefix``/``incref``
    hands the caller references it MUST give back with ``decref`` on
    every exit path — including the exceptional ones.  A bare acquire
    that can unwind past its caller leaks pinned pages: the pool's free
    list shrinks permanently and admission starts 429ing long before the
    device pool is actually full (the refcount twin of a file-descriptor
    leak).  The engine's own discipline is acquire-inside-``try`` with
    ``decref`` in the handler or ``finally`` (see ``_admit``/``warmup``).

    Fires on an acquire-method call whose receiver looks pool-ish (its
    dotted name mentions ``pool``/``paging``) when no enclosing ``try``
    has a release call in its handlers or ``finally``.  Scoped to
    ``serving/`` modules — that is where the pool contract lives.
    ``self.<acquire>`` is exempt: those are the pool's own internals,
    whose invariants the pool lock already owns.

    Blind spots: a pool aliased to a name without ``pool`` in it; a
    release performed by a callee the handler delegates to (name the
    release in the handler, or silence with a reason).
    """

    id = "PG01"
    title = "KV page acquire without release on exit paths"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if "serving/" not in module.path.replace("\\", "/"):
            return
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _PG_ACQUIRE):
                continue
            recv = dotted_name(node.func.value) or ""
            low = recv.lower()
            if recv == "self" or not ("pool" in low or "paging" in low):
                continue
            if self._released_on_unwind(node, parents):
                continue
            yield self.finding(
                module, node,
                f"`{recv}.{node.func.attr}` acquires KV page references "
                "with no release on the exceptional exit path — an "
                "unwind here leaks pinned pages and the pool 429s "
                "forever after; wrap in try/except-or-finally that "
                "`decref`s what was acquired")

    @staticmethod
    def _released_on_unwind(call: ast.Call, parents) -> bool:
        """True when an enclosing ``try`` releases pages in a handler or
        ``finally`` (walking out stops at the enclosing function)."""
        node: ast.AST = call
        while True:
            parent = parents.get(id(node))
            if parent is None or isinstance(
                    parent, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
                return False
            if isinstance(parent, ast.Try):
                cleanup = list(parent.finalbody)
                for h in parent.handlers:
                    cleanup.extend(h.body)
                for stmt in cleanup:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call) \
                                and isinstance(sub.func, ast.Attribute) \
                                and sub.func.attr in _PG_RELEASE:
                            return True
            node = parent


@register
class UnregisteredTimingRule(Rule):
    """OB01 — hand-rolled dispatch timing that bypasses the registry.

    A function in ``serving/`` or ``parallel/`` that reads
    ``time.monotonic()``/``time.perf_counter()`` around a device-
    dispatching call but never reports through the observability layer
    (``METRICS``/``trace``/``record_span``) produces a measurement that
    exists nowhere: no histogram, no ``/metrics.prom`` scrape, no trace
    event.  PR 10's tracing/MFU accounting derives everything from
    registry observations — a private clock read next to a dispatch is
    the sign a hot path grew its own timing instead of feeding the
    registry (how the pre-PR-1 hot loops went dark).  One registry or
    tracer call anywhere in the function satisfies the rule, exactly
    like HOT02.

    Blind spots: a clock read in one function passed to a helper that
    times/dispatches in another; a dispatch hidden behind an attribute
    the jit-facts pass cannot resolve.  Silence deliberate raw timing
    with ``# graftlint: disable=OB01`` plus the reason.
    """

    id = "OB01"
    title = "dispatch timing bypasses the observability registry"

    _CLOCKS = {"time.monotonic", "time.monotonic_ns",
               "time.perf_counter", "time.perf_counter_ns"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "serving/" not in path and "parallel/" not in path:
            return
        for node in ast.walk(module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if UninstrumentedHotLoopRule._has_obs(node, module):
                continue
            clock = None
            for call in _calls_in(node):
                name = (module.canonical(call.func)
                        or dotted_name(call.func) or "")
                if name in self._CLOCKS:
                    clock = call
                    break
            if clock is None:
                continue
            dispatches = None
            for call in _calls_in(node):
                callee = dotted_name(call.func)
                if callee and module.is_dispatching_call(callee):
                    dispatches = callee
                    break
            if dispatches is None:
                continue
            yield self.finding(
                module, clock,
                f"function times device dispatch ({dispatches!r}) with a "
                "raw monotonic/perf_counter read and never reports through "
                "METRICS/trace — the measurement is invisible to scrapes "
                "and traces; record it via METRICS.observe_time/time() or "
                "trace.record_span (or silence with a reason)")


@register
class RawQuantCastRule(Rule):
    """QT01 — ad-hoc KV/weight precision casts outside the quant helpers.

    ``x.astype(jnp.int8)`` wraps on overflow (numpy semantics: 300 →
    44) and ``.astype(jnp.float8_*)`` rounds with no absmax scaling —
    neither is a quantization.  Every sound low-precision write in this
    tree goes through a helper that scales THEN saturates
    (``ops/pallas/kv_quant.cast_to`` for cache pages,
    ``ops/pallas/matmul_int8.quantize`` for weights), which is also
    where the paired scale tensor is produced.  A raw cast in
    ``serving/`` or ``models/`` means a value reached storage precision
    without a scale beside it — the bug class where a page quantizes
    fine on small activations and silently wraps on the first outlier.
    Scoped to those two trees; the helpers themselves (``ops/pallas/``)
    are the one place a raw cast is the point.

    Blind spots: a dtype smuggled through a variable
    (``dt = jnp.int8; x.astype(dt)``); ``jnp.asarray(x, jnp.int8)``.
    Silence a deliberate storage-layer cast with
    ``# graftlint: disable=QT01`` plus the reason.
    """

    id = "QT01"
    title = "raw int8/fp8 cast outside the quant helpers"

    _QUANT_DTYPES = {"jax.numpy.int8", "jnp.int8", "numpy.int8"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "serving/" not in path and "models/" not in path:
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype"):
                continue
            dtype_arg = None
            if node.args:
                dtype_arg = node.args[0]
            else:
                for kw in node.keywords:
                    if kw.arg == "dtype":
                        dtype_arg = kw.value
            if dtype_arg is None:
                continue
            name = (module.canonical(dtype_arg)
                    or dotted_name(dtype_arg) or "")
            seg = last_segment(name) or ""
            if not (name in self._QUANT_DTYPES
                    or seg.startswith("float8_")):
                continue
            yield self.finding(
                module, node,
                f"raw `.astype({seg})` — an unscaled, unsaturated cast "
                "to storage precision (int8 wraps on overflow, fp8 "
                "rounds with no absmax); quantize through "
                "`kv_quant.cast_to`/`requantize_pool` or "
                "`matmul_int8.quantize` so a scale rides beside the "
                "bytes (or silence with a reason)")


@register
class ElasticMeshConstructionRule(Rule):
    """EL01 — mesh/topology construction outside the mesh helpers.

    Elastic training (DESIGN.md §21) rebuilds the mesh at runtime: a
    device loss shrinks it, a re-registration grows it, and a resharding
    restore re-splits state onto whatever width came out.  That only
    works when every mesh in ``parallel/``/``resilience/`` flows through
    the ``parallel/mesh.py`` helpers (``make_mesh``/``local_mesh``/
    ``elastic_mesh``/``shrink_mesh``/``grow_mesh``), which keep the
    device list explicit and the axis layout canonical.  A raw
    ``jax.sharding.Mesh(...)`` call, or a ``jax.devices()`` /
    ``jax.local_devices()`` subscript with *integer-literal* bounds
    (``jax.devices()[:8]``), hard-codes a topology the resize path can
    neither rebuild nor verify — it is exactly the frozen-device-set bug
    a shrink turns into a crash.  Variable-bounded slices
    (``jax.devices()[:n]``) are fine: the width is a parameter the
    caller can re-derive after a resize.  Scoped to ``parallel/`` and
    ``resilience/`` excluding ``mesh.py`` itself (the one sanctioned
    construction site); ``NamedSharding`` over an existing mesh is not
    construction and is not flagged.

    Blind spots: a ``Mesh`` aliased through a variable
    (``M = Mesh; M(...)``), and device lists materialized in another
    module and passed in.  Silence a deliberate fixed topology with
    ``# graftlint: disable=EL01`` plus the reason.
    """

    id = "EL01"
    title = "raw mesh construction outside parallel/mesh.py helpers"

    _DEVICE_ENUMS = {"jax.devices", "jax.local_devices"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "parallel/" not in path and "resilience/" not in path:
            return
        if path.endswith("parallel/mesh.py"):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                canon = (module.canonical(node.func)
                         or dotted_name(node.func) or "")
                if (last_segment(canon) or canon) == "Mesh":
                    yield self.finding(
                        module, node,
                        "raw `Mesh(...)` constructor outside "
                        "`parallel/mesh.py` — the elastic resize path "
                        "(shrink/grow/reshard, DESIGN.md §21) can only "
                        "rebuild meshes made by the helpers; use "
                        "`make_mesh`/`local_mesh`/`elastic_mesh` (or "
                        "silence with a reason)")
            elif isinstance(node, ast.Subscript):
                v = node.value
                if not isinstance(v, ast.Call):
                    continue
                canon = (module.canonical(v.func)
                         or dotted_name(v.func) or "")
                if canon not in self._DEVICE_ENUMS:
                    continue
                if self._literal_bounds(node.slice):
                    yield self.finding(
                        module, node,
                        f"`{canon}()` subscripted with integer-literal "
                        "bounds hard-codes a device set — after a "
                        "shrink/grow the literal is stale and the slice "
                        "silently picks the wrong chips; derive the "
                        "width from the mesh (or a parameter) and build "
                        "through `elastic_mesh`/`make_mesh`")

    @staticmethod
    def _literal_bounds(sl: ast.AST) -> bool:
        """True for ``[3]`` / ``[:8]`` / ``[2:6]``; False when every
        bound is a name/expression the caller computes (``[:n]``)."""
        if isinstance(sl, ast.Constant) and isinstance(sl.value, int):
            return True
        if isinstance(sl, ast.Slice):
            return any(isinstance(b, ast.Constant)
                       and isinstance(b.value, int)
                       for b in (sl.lower, sl.upper))
        return False


@register
class UndocumentedMetricNameRule(Rule):
    """OB02 — a metric name absent from the documented metrics tables.

    Every scrape consumer (``metrics_dump``, the perf gate, the SLO
    evaluator, dashboards) binds to metric names by string; PRs 9-13
    each hand-patched a name that drifted from the docs after the fact.
    This rule closes the loop at lint time: a literal first argument to
    ``METRICS.increment/gauge/observe_time/observe_many/time`` (or the
    same mutators on a ``registry``) must appear in a metrics table row
    of ``README.md``/``DESIGN.md`` — rows shaped
    ``| `name` | counter/gauge/timer | description |``.  Documented rows
    may carry ``<placeholder>``/``{placeholder}``/``*`` suffixes
    (``faults.injected.<site>``): they match any name sharing the
    literal prefix.  F-strings and string concatenations are checked by
    their leading literal against those wildcard rows; names with no
    leading literal at all are runtime-composed and out of scope.

    Blind spots: names built through variables or ``str.join``; a
    mutator reached through a receiver not named ``METRICS``/
    ``registry``; a too-short f-string prefix that several wildcard
    rows cover.  Silence a deliberately undocumented (e.g. test-only)
    name with ``# graftlint: disable=OB02`` plus the reason.
    """

    id = "OB02"
    title = "metric name missing from the documented metrics tables"

    _MUTATORS = {"increment", "gauge", "observe_time", "observe_many",
                 "time"}
    _RECEIVERS = {"METRICS", "registry"}
    _DOC_FILES = ("README.md", "DESIGN.md")
    _ROW = re.compile(
        r"\s*\|\s*`([^`]+)`\s*\|\s*(?:counter|gauge|timer|histogram)s?\b")
    _cache: tuple[frozenset, tuple] | None = None
    _override: tuple[frozenset, tuple] | None = None

    # ------------------------------------------------------- documented set
    @classmethod
    def set_documented(cls, names) -> None:
        """Test hook: replace the parsed doc tables (None restores)."""
        cls._override = None if names is None else cls._split(names)

    @staticmethod
    def _split(names) -> tuple[frozenset, tuple]:
        exact, prefixes = set(), []
        for n in names:
            m = re.search(r"[<{*]", n)
            if m:
                prefixes.append(n[:m.start()])
            else:
                exact.add(n)
        return frozenset(exact), tuple(prefixes)

    @classmethod
    def documented(cls) -> tuple[frozenset, tuple]:
        if cls._override is not None:
            return cls._override
        if cls._cache is None:
            root = pathlib.Path(__file__).resolve().parents[2]
            names: list[str] = []
            for fn in cls._DOC_FILES:
                p = root / fn
                if p.exists():
                    for line in p.read_text().splitlines():
                        m = cls._ROW.match(line)
                        if m:
                            names.append(m.group(1))
            cls._cache = cls._split(names)
        return cls._cache

    # --------------------------------------------------------------- check
    @staticmethod
    def _literal_name(arg) -> tuple[str | None, bool]:
        """(name, is_prefix_only): a Constant is the full name; an
        f-string / ``"lit" + var`` concat yields its leading literal."""
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            return arg.value, False
        if isinstance(arg, ast.JoinedStr) and arg.values:
            head = arg.values[0]
            if isinstance(head, ast.Constant) and isinstance(head.value, str) \
                    and head.value:
                return head.value, True
        if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add) \
                and isinstance(arg.left, ast.Constant) \
                and isinstance(arg.left.value, str) and arg.left.value:
            return arg.left.value, True
        return None, False

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        exact, prefixes = self.documented()
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._MUTATORS):
                continue
            recv = dotted_name(node.func.value) or ""
            if (last_segment(recv) or recv) not in self._RECEIVERS:
                continue
            arg = node.args[0] if node.args else None
            if arg is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        arg = kw.value
            if arg is None:
                continue
            name, prefix_only = self._literal_name(arg)
            if name is None:
                continue
            if prefix_only:
                if any(name.startswith(p) or p.startswith(name)
                       for p in prefixes):
                    continue
            elif name in exact or any(name.startswith(p) for p in prefixes):
                continue
            yield self.finding(
                module, node,
                f"metric name `{name}{'…' if prefix_only else ''}` is not "
                "in the documented metrics tables (README.md/DESIGN.md) — "
                "scrape consumers bind to names by string, so undocumented "
                "names drift silently; add a "
                "`| `name` | kind | description |` row (wildcard "
                "placeholders allowed) or silence with a reason")


@register
class UnboundedMetricCardinalityRule(Rule):
    """OB03 — request-derived data interpolated into a metric name.

    The registry keys counters/gauges/histograms by name forever: a
    metric name built from a tenant id, request id, session id, or
    prompt-derived string mints one immortal series per distinct value —
    unbounded cardinality, i.e. a memory leak the dashboard renders
    proudly.  The ONE sanctioned path from request-derived strings to
    metric names is ``observability/fleet.py``'s ``TenantLabels``: it
    folds everything beyond the tracked top-K into ``__other__``, so the
    series set stays bounded by construction.  That module is exempt;
    everywhere else, an f-string or concatenation passed to
    ``METRICS.increment/gauge/observe_time/observe_many/time`` (or the
    same mutators on a ``registry``) whose interpolated parts reference
    a request-derived identifier — a name, attribute, subscript key, or
    ``.get("...")`` key in the tenant/request/session/user/prompt
    family — fails here.

    Blind spots: names composed through intermediate variables
    (``n = f"x.{tenant}"; METRICS.increment(n)``), identifiers renamed
    before interpolation (``t = req.tenant``... ``f"x.{t}"``), and
    ``str.join``/``%``/``.format`` composition.  Silence a
    deliberately-bounded interpolation (e.g. a fixed enum) with
    ``# graftlint: disable=OB03`` plus the reason.
    """

    id = "OB03"
    title = "request-derived data interpolated into a metric name"

    _MUTATORS = UndocumentedMetricNameRule._MUTATORS
    _RECEIVERS = UndocumentedMetricNameRule._RECEIVERS
    _REQUEST_DERIVED = frozenset({
        "tenant", "tenant_id", "tenants", "request_id", "req_id",
        "trace_id", "prompt", "user", "user_id", "session", "session_id"})
    _EXEMPT_SUFFIX = "observability/fleet.py"  # the bounded label helper

    @classmethod
    def _dynamic_identifiers(cls, arg) -> set[str]:
        """Lower-cased identifiers referenced by the NON-literal parts
        of an interpolated metric-name expression."""
        dyn: list[ast.AST] = []
        if isinstance(arg, ast.JoinedStr):
            dyn = [v.value for v in arg.values
                   if isinstance(v, ast.FormattedValue)]
        elif isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
            stack: list[ast.AST] = [arg]
            while stack:
                n = stack.pop()
                if isinstance(n, ast.BinOp) and isinstance(n.op, ast.Add):
                    stack.extend((n.left, n.right))
                elif not isinstance(n, ast.Constant):
                    dyn.append(n)
        out: set[str] = set()
        for d in dyn:
            for sub in ast.walk(d):
                if isinstance(sub, ast.Name):
                    out.add(sub.id.lower())
                elif isinstance(sub, ast.Attribute):
                    out.add(sub.attr.lower())
                elif isinstance(sub, ast.Subscript):
                    sl = sub.slice
                    if isinstance(sl, ast.Constant) \
                            and isinstance(sl.value, str):
                        out.add(sl.value.lower())
                elif isinstance(sub, ast.Call):
                    # payload.get("tenant") — the key names the data
                    for a in sub.args:
                        if isinstance(a, ast.Constant) \
                                and isinstance(a.value, str):
                            out.add(a.value.lower())
        return out

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        if module.path.replace("\\", "/").endswith(self._EXEMPT_SUFFIX):
            return
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self._MUTATORS):
                continue
            recv = dotted_name(node.func.value) or ""
            if (last_segment(recv) or recv) not in self._RECEIVERS:
                continue
            arg = node.args[0] if node.args else None
            if arg is None:
                for kw in node.keywords:
                    if kw.arg == "name":
                        arg = kw.value
            if arg is None:
                continue
            hits = sorted(self._dynamic_identifiers(arg)
                          & self._REQUEST_DERIVED)
            if hits:
                yield self.finding(
                    module, node,
                    f"metric name interpolates request-derived data "
                    f"({', '.join(hits)}) — every distinct value mints an "
                    "immortal registry series (unbounded cardinality); "
                    "route per-tenant accounting through "
                    "`observability.fleet.TenantLabels` (top-K exact, "
                    "`__other__` fold) instead of building the name here")


@register
class OnlineDurableWriteRule(Rule):
    """OL01 — non-durable rewrite on the online-loop / publish path.

    The online learning loop's durability story (DESIGN.md §23) has
    exactly two sanctioned write shapes: *append-only fsync'd logs* (the
    capture store — ``open(..., "a")`` plus ``os.fsync``, where a crash
    costs at most the torn tail replay already tolerates) and
    *unique-tempfile + fsync + atomic ``os.replace``* for anything
    rewritten in place (checkpoint payloads, manifests, poison/repair
    tooling).  A bare ``open(path, "w")`` / ``write_text`` /
    ``write_bytes`` on these paths is a torn-file publisher: a crash (or
    injected ``corrupt_file``) mid-write leaves a half-written file at
    the FINAL name, where a concurrent reader — the serving reload, the
    replay, ``latest_valid_step()`` — picks it up as truth.

    Fires on truncating opens (mode containing ``w`` or ``x``, incl.
    ``os.fdopen``) and ``write_text``/``write_bytes`` calls in modules
    under ``online/`` or in ``parallel/checkpoint.py``, unless the
    enclosing function visibly carries the idiom: a call to
    ``os.replace`` AND durability evidence (``os.fsync``, an
    ``*fsync*``-named helper, or a ``tempfile.mkstemp``/``mkdtemp``/
    ``NamedTemporaryFile`` unique target).  Append-mode opens are exempt
    (the log-structured contract).

    Blind spots: writers behind helpers in other modules (``np.savez``
    onto a final path — route it at a tempfile), modes built at runtime,
    and idiom halves split across functions (keep open→fsync→replace in
    ONE function so the reviewer — and this rule — can see the whole
    contract).  Silence a deliberate non-durable write with
    ``# graftlint: disable=OL01`` plus the reason.
    """

    id = "OL01"
    title = "non-durable rewrite on the online/checkpoint publish path"

    _WRITE_ATTRS = {"write_text", "write_bytes"}
    _TMP_CALLS = {"tempfile.mkstemp", "tempfile.mkdtemp",
                  "tempfile.NamedTemporaryFile", "mkstemp", "mkdtemp",
                  "NamedTemporaryFile"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "/online/" not in path and not path.startswith("online/") \
                and not path.endswith("parallel/checkpoint.py"):
            return
        parents: dict[int, ast.AST] = {}
        for node in ast.walk(module.tree):
            for child in ast.iter_child_nodes(node):
                parents[id(child)] = node
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            label = self._rewrite_label(module, node)
            if label is None:
                continue
            fn = self._enclosing_function(node, parents)
            if fn is not None and self._has_idiom(module, fn):
                continue
            yield self.finding(
                module, node,
                f"`{label}` rewrites a file on the online/checkpoint "
                "publish path without the unique-tempfile + fsync + "
                "`os.replace` idiom — a crash mid-write publishes a torn "
                "file under the final name; write to a `tempfile` "
                "sibling, fsync it, then `os.replace` onto the target "
                "(appends to fsync'd logs are the one exemption)")

    def _rewrite_label(self, module: ModuleInfo, call: ast.Call) -> str | None:
        """A display label when ``call`` truncates/rewrites a file."""
        canon = module.canonical(call.func) or dotted_name(call.func) or ""
        if canon in ("open", "os.fdopen"):
            mode = None
            if len(call.args) >= 2:
                mode = call.args[1]
            for kw in call.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if (isinstance(mode, ast.Constant) and isinstance(mode.value, str)
                    and any(c in mode.value for c in "wx")):
                return f'{canon}(..., "{mode.value}")'
            return None
        if (isinstance(call.func, ast.Attribute)
                and call.func.attr in self._WRITE_ATTRS):
            recv = dotted_name(call.func.value) or "<expr>"
            return f"{recv}.{call.func.attr}"
        return None

    @staticmethod
    def _enclosing_function(node: ast.AST, parents) -> ast.AST | None:
        while node is not None:
            node = parents.get(id(node))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def _has_idiom(self, module: ModuleInfo, fn: ast.AST) -> bool:
        """True when ``fn`` visibly replaces atomically AND shows
        durability evidence (fsync or a unique tempfile target)."""
        has_replace = False
        has_durable = False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Call):
                continue
            canon = module.canonical(sub.func) or dotted_name(sub.func) or ""
            name = last_segment(canon) or canon
            if canon == "os.replace" or name == "replace" and \
                    canon.startswith("os."):
                has_replace = True
            if canon == "os.fsync" or "fsync" in name.lower() \
                    or canon in self._TMP_CALLS:
                has_durable = True
            if has_replace and has_durable:
                return True
        return False


# ------------------------------------------------------------- sharding tier
#
# SH01-SH04 + NM01 consume the analysis/sharding.py mesh-axis pass: axis
# bindings resolved interprocedurally from Mesh construction through
# shard_map/pmap wrap sites, the canonical axis registry parsed out of
# parallel/mesh.py, and literal PartitionSpec signatures.  The runtime
# twin is analysis/shardguard.py (implicit-reshard detection on live
# executables) — same split as the concurrency tier's LK rules/lockguard.


@register
class UnboundCollectiveAxisRule(Rule):
    """SH01 — collective over an axis no enclosing mesh context binds.

    ``lax.psum(x, 'tp')`` inside a function that is only ever
    ``shard_map``-ed over a ``('dp',)`` mesh cannot succeed: the trace
    fails with an unbound axis name on device — or, when an outer
    context happens to bind a same-named axis of different extent, the
    collective silently reduces over the wrong device group.  The
    sharding pass resolves which axes each function body is bound under
    (through ``Mesh``/``make_mesh``/``local_mesh``/``elastic_mesh``,
    ``shard_map`` and ``pmap(axis_name=...)``, plus one module-internal
    call level of propagation) and this rule fires when a collective's
    literal/constant axis argument is missing from that KNOWN set.

    Deliberately confidence-ranked: an axis arriving as a function
    parameter (the ``parallel/collectives.py`` wrappers), a mesh the
    pass cannot resolve, or a function never visibly wrapped all leave
    the binding unknown and keep the rule silent — cross-module wrap
    sites are the blind spot, and why suppressions exist.
    """

    id = "SH01"
    title = "collective over an axis not bound by the enclosing mesh context"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        info = sharding_info(module)
        for call, chain in info.collective_chains.items():
            axis_arg = info.collective_axis_arg(call)
            if axis_arg is None:
                continue
            axes_named = info.resolve_axis_tuple(axis_arg)
            if axes_named is None:
                continue
            bound = info.axes_for_chain(chain)
            if bound is None:
                continue
            missing = [a for a in axes_named if a not in bound]
            if missing:
                op = last_segment(module.canonical(call.func) or "") or "?"
                yield self.finding(
                    module, call,
                    f"collective `{op}` over axis {missing[0]!r} but the "
                    f"enclosing shard_map/pmap context only binds "
                    f"{sorted(bound)} — an unbound axis name fails the "
                    "trace on device (or reduces over the wrong device "
                    "group); bind the axis in the mesh or fix the name")


@register
class UnknownAxisNameRule(Rule):
    """SH02 — ``PartitionSpec`` naming an axis outside the registry.

    Every axis name in this repo comes from ONE table —
    ``parallel/mesh.py``'s ``DP/TP/PP/SP/EP`` constants and the ``AXES``
    tuple — which the sharding pass parses directly, so the linter and
    the runtime can never disagree about which axes exist.  A literal
    axis string in a ``PartitionSpec``/``P(...)`` call that is not in
    that table is a typo ('dpx'), a stale rename, or an axis the mesh
    builder will never create: placement either fails the trace or
    silently replicates where the author meant to shard.

    The fix for a true finding is the registry hoist: import the
    constant (``P(DP)``) instead of repeating the string.  Blind spots:
    names built at runtime, specs threaded through variables, and
    constants shadowed locally with non-registry values.
    """

    id = "SH02"
    title = "PartitionSpec axis name absent from the canonical axis registry"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        registry = axis_registry()
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            canon = module.canonical(node.func) or ""
            if last_segment(canon) != "PartitionSpec":
                continue
            for arg in node.args:
                elts = (arg.elts if isinstance(arg, (ast.Tuple, ast.List))
                        else [arg])
                for elt in elts:
                    if not (isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)):
                        continue
                    if elt.value not in registry:
                        yield self.finding(
                            module, node,
                            f"PartitionSpec axis {elt.value!r} is not in "
                            "the canonical axis registry "
                            f"({', '.join(sorted(registry))}; "
                            "parallel/mesh.py AXES) — no mesh builder "
                            "creates this axis, so placement fails the "
                            "trace or silently replicates; use the mesh.py "
                            "constants instead of string literals")


@register
class ShardMapSpecArityRule(Rule):
    """SH03 — ``shard_map`` specs that cannot match the wrapped function.

    ``in_specs`` is zipped positionally against the wrapped function's
    arguments and ``out_specs`` against its returned tuple; an arity
    mismatch is a guaranteed trace-time pytree error — but one that only
    surfaces when the wrap site finally executes, typically deep inside
    a trainer build.  When the wrapped callable is a module-local def or
    lambda and the specs are literal tuples, both arities are checkable
    at lint time; functions with ``*args``, specs threaded through
    variables, and cross-module targets stay out of scope.  The return
    check only fires when every return statement is a literal tuple of
    one consistent length (anything else — a returned variable, a
    single-value return — is unknowable statically).
    """

    id = "SH03"
    title = "shard_map in_specs/out_specs arity mismatch with wrapped fn"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        info = sharding_info(module)
        for site in info.shard_map_sites:
            target = site.target
            if target is None:
                continue
            args = target.args
            if args.vararg is not None:
                continue
            params = [a.arg for a in (args.posonlyargs + args.args)]
            if params and params[0] == "self":
                params = params[1:]
            name = getattr(target, "name", "<lambda>")
            if isinstance(site.in_specs, (ast.Tuple, ast.List)):
                n_in = len(site.in_specs.elts)
                lo = len(params) - len(args.defaults or [])
                if not (lo <= n_in <= len(params)):
                    yield self.finding(
                        module, site.call,
                        f"shard_map in_specs has {n_in} entries but "
                        f"`{name}` takes {len(params)} positional "
                        "argument(s) — the spec/argument zip fails at "
                        "trace time; make the arities match")
            if isinstance(site.out_specs, (ast.Tuple, ast.List)) \
                    and isinstance(target, (ast.FunctionDef,
                                            ast.AsyncFunctionDef)):
                lens = set()
                literal = True
                returns = [s for s in body_statements(target.body)
                           if isinstance(s, ast.Return) and s.value is not None]
                for ret in returns:
                    if isinstance(ret.value, ast.Tuple):
                        lens.add(len(ret.value.elts))
                    else:
                        literal = False
                        break
                if literal and len(lens) == 1:
                    n_ret = lens.pop()
                    if n_ret != len(site.out_specs.elts):
                        yield self.finding(
                            module, site.call,
                            f"shard_map out_specs has "
                            f"{len(site.out_specs.elts)} entries but "
                            f"`{name}` returns a {n_ret}-tuple — the "
                            "output pytree/spec zip fails at trace time")


@register
class DonatedReshardRule(Rule):
    """SH04 — donation through a sharding mismatch (DON01, shard-aware).

    When an argument reaches a ``donate_argnums`` position of a jit
    whose declared ``in_shardings`` differ from the sharding the caller
    placed the array with (``jax.device_put(x, NamedSharding(...))``),
    XLA inserts an implicit reshard copy at the boundary: the donation
    then aliases the *copy*, the caller's original buffer is still freed
    — so the memory win the donation promised is silently lost on every
    step, and any post-call read of the name is use-after-free exactly
    as in DON01.  Fires at the call site when both shardings are
    statically literal (``NamedSharding(mesh, P(...))`` placement in the
    same function, literal ``in_shardings`` tuple on the jit); either
    side arriving through a variable keeps the rule silent.
    """

    id = "SH04"
    title = "donated argument placed with a sharding the jit reshards"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        info = sharding_info(module)
        jits = self._declared_jits(module, info)
        if not jits:
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield from self._check_function(module, info, jits, fn)

    def _declared_jits(self, module: ModuleInfo, info) -> dict:
        """basename -> (donate positions, tuple of spec signatures)."""
        out: dict[str, tuple] = {}
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Assign)
                    and isinstance(node.value, ast.Call)):
                continue
            call = node.value
            canon = module.canonical(call.func) or ""
            if not (canon in ("jit", "jax.jit") or canon.endswith(".jit")):
                continue
            donate = in_sh = None
            for kw in call.keywords:
                if kw.arg in ("donate_argnums", "donate_argnames"):
                    donate = literal_int_tuple(kw.value)
                elif kw.arg == "in_shardings":
                    in_sh = kw.value
            if not donate or not isinstance(in_sh, (ast.Tuple, ast.List)):
                continue
            sigs = tuple(info.spec_signature(e) for e in in_sh.elts)
            for target in node.targets:
                tname = dotted_name(target)
                if tname is not None:
                    out[last_segment(tname)] = (donate, sigs)
        return out

    def _check_function(self, module: ModuleInfo, info, jits,
                        fn) -> Iterator[Finding]:
        placed: dict[str, tuple] = {}     # name -> placed spec signature
        for stmt in body_statements(fn.body):
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Call):
                sig = self._device_put_sig(module, info, stmt.value)
                for target in stmt.targets:
                    tname = dotted_name(target)
                    if tname is None:
                        continue
                    if sig is not None:
                        placed[tname] = sig
                    else:
                        placed.pop(tname, None)   # rebound: stale signature
            for call in _calls_in(stmt):
                callee = dotted_name(call.func)
                if callee is None:
                    continue
                hit = jits.get(last_segment(callee))
                if hit is None:
                    continue
                donate, sigs = hit
                for pos in donate:
                    if pos >= len(call.args):
                        continue
                    aname = dotted_name(call.args[pos])
                    if aname is None:
                        continue
                    declared = sigs[pos] if pos < len(sigs) else None
                    got = placed.get(aname)
                    if declared is not None and got is not None \
                            and declared != got:
                        yield self.finding(
                            module, call,
                            f"{aname!r} was placed with sharding "
                            f"P{got!r} but is donated at position {pos} "
                            f"of a jit declaring in_shardings P"
                            f"{declared!r} — the implicit reshard copies "
                            "and the donation frees the original without "
                            "aliasing it: the memory win is lost and any "
                            "later read is use-after-free; place with the "
                            "jit's sharding (or fix the declaration)")

    def _device_put_sig(self, module: ModuleInfo, info, call: ast.Call):
        canon = module.canonical(call.func) or ""
        if last_segment(canon) != "device_put":
            return None
        sh = call.args[1] if len(call.args) > 1 else None
        for kw in call.keywords:
            if kw.arg in ("device", "sharding"):
                sh = kw.value
        return None if sh is None else info.spec_signature(sh)


@register
class UnstableReductionRule(Rule):
    """NM01 — hand-rolled softmax/logsumexp without max subtraction.

    ``log(sum(exp(x)))`` and ``exp(x)/sum(exp(x))`` overflow to inf the
    moment one logit exceeds ~88 (f32) or ~11 (bf16) — which real logits
    do.  The sanctioned implementations in this tree are the blocked-
    xent kernel (``ops/pallas/xent.py``), the online-softmax attention
    kernels (``ops/flash_attention.py``, ``ops/pallas/attention.py``)
    and ``jax.scipy.special.logsumexp`` / ``jax.nn.softmax`` — all of
    which subtract a running or global max first.  Scoped to ``ops/``
    and ``models/``, the rule fires on three shapes: direct
    ``log(...sum(exp(...))...)`` nesting, a division whose numerator
    holds ``exp`` and denominator a ``sum`` of ``exp``, and a ``log``/
    division of a local name bound from a sum-of-exp — in each case
    only when the enclosing function shows NO max/clip evidence at all
    (any ``max``/``maximum``/``clip``/``logsumexp``/``softmax`` call
    quiets it, which is what makes the online-softmax kernels pass).

    Blind spots: guards living in a helper the function calls, and
    reductions split across functions.  A deliberately unguarded form
    (inputs bounded by construction) gets ``# graftlint: disable=NM01``
    with the bound stated.
    """

    id = "NM01"
    title = "numerically unstable reduction (softmax/logsumexp w/o max)"

    _GUARDS = {"max", "maximum", "clip", "pmax", "logsumexp", "softmax",
               "log_softmax", "amax", "nanmax"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if not any(seg in path for seg in ("ops/", "models/")):
            return
        for fn in ast.walk(module.tree):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._has_guard(module, fn):
                    continue
                yield from self._check_function(module, fn)

    def _has_guard(self, module: ModuleInfo, fn) -> bool:
        for call in _calls_in(fn):
            canon = module.canonical(call.func) or dotted_name(call.func) or ""
            base = (last_segment(canon) or canon).lstrip("_")
            if base in self._GUARDS:
                return True
        return False

    def _is_exp(self, module: ModuleInfo, call: ast.Call) -> bool:
        canon = module.canonical(call.func) or ""
        return last_segment(canon) == "exp"

    def _contains_exp(self, module: ModuleInfo, node: ast.AST) -> bool:
        return any(self._is_exp(module, c) for c in _calls_in(node))

    def _is_sum_of_exp(self, module: ModuleInfo, node: ast.AST,
                       exp_names=frozenset()) -> bool:
        """``sum(..exp..)`` call or ``(..exp..).sum()`` method call,
        where "exp" is a literal exp call or a name bound from one."""
        if not isinstance(node, ast.Call):
            return False
        canon = module.canonical(node.func) or ""
        if last_segment(canon) != "sum":
            return False

        def exppy(n: ast.AST) -> bool:
            return (self._contains_exp(module, n)
                    or bool(names_read(n) & exp_names))

        scope = (node.func.value if isinstance(node.func, ast.Attribute)
                 else None)
        return any(exppy(a) for a in node.args) \
            or (scope is not None and exppy(scope))

    def _contains_sum_of_exp(self, module: ModuleInfo, node: ast.AST,
                             exp_names=frozenset()) -> bool:
        return any(self._is_sum_of_exp(module, n, exp_names)
                   for n in ast.walk(node) if isinstance(n, ast.Call))

    def _check_function(self, module: ModuleInfo, fn) -> Iterator[Finding]:
        # names bound (in this function) to an exp / to a sum-of-exp
        exp_names: set[str] = set()
        sumexp_names: set[str] = set()
        for stmt in body_statements(fn.body):
            if not isinstance(stmt, ast.Assign):
                continue
            if self._contains_sum_of_exp(module, stmt.value, exp_names):
                for target in stmt.targets:
                    sumexp_names.update(assigned_names(target))
            elif self._contains_exp(module, stmt.value):
                for target in stmt.targets:
                    exp_names.update(assigned_names(target))

        def holds_exp(node: ast.AST) -> bool:
            return (self._contains_exp(module, node)
                    or bool(names_read(node) & exp_names))

        def holds_sumexp(node: ast.AST) -> bool:
            return (self._contains_sum_of_exp(module, node, exp_names)
                    or bool(names_read(node) & sumexp_names))

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                canon = module.canonical(node.func) or ""
                if last_segment(canon) == "log" and node.args \
                        and holds_sumexp(node.args[0]):
                    yield self.finding(
                        module, node,
                        "hand-rolled logsumexp: log of a sum of exp "
                        "with no max subtraction in reach — overflows "
                        "to inf on realistic logits; use "
                        "jax.scipy.special.logsumexp (or subtract the "
                        "max first, like the blocked-xent kernel)")
            elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Div):
                if holds_sumexp(node.right) and holds_exp(node.left):
                    yield self.finding(
                        module, node,
                        "hand-rolled softmax: exp(x) divided by a sum of "
                        "exp with no max subtraction in reach — overflows "
                        "to inf on realistic logits; use jax.nn.softmax "
                        "(or the online-softmax kernels in ops/)")


@register
class ControlSeamRule(Rule):
    """CT01 — raw ring/pool mutation in the control plane.

    The autoscaler's correctness argument (DESIGN.md §26) rests on
    every scale action being all-or-nothing THROUGH the serving seams:
    ``PrefixRouter.scale_up`` gates ring admission on the warmed flag,
    ``scale_down`` drains via the quarantine state machine and refuses
    to detach a replica with requests in flight, and the pool publishes
    whole rings atomically.  A control module that calls
    ``ring.add``/``ring.remove``, builds a ``HashRing`` itself, assigns
    ``router.ring``, or reaches into ``pool._replicas``/``pool._state``
    re-creates exactly the failure modes those seams were built to make
    unrepresentable: a cold replica on the ring (compile-storm TTFT), a
    half-drained replica, or a reader observing a mid-mutation ring.

    Fires, in modules under ``control/``, on: calls whose dotted target
    ends in ``.ring.add`` / ``.ring.remove`` / ``.ring.rebuild``; any
    ``HashRing(...)`` construction; assignment to an attribute ending
    ``.ring``; and any read/write of a ``._replicas`` / ``._state``
    attribute reached through another object (pool internals).

    Blind spots: mutation behind an alias (``r = router.ring`` then
    ``r.add(...)`` — only the aliasing assignment's reads escape), and
    helpers outside ``control/`` that mutate on control's behalf (the
    review catches those; keep actuators in serving).  Silence a
    deliberate hit with ``# graftlint: disable=CT01`` plus the reason.
    """

    id = "CT01"
    title = "control plane bypasses the ReplicaPool/PrefixRouter seams"

    _RING_CALL_SUFFIXES = (".ring.add", ".ring.remove", ".ring.rebuild")
    _INTERNAL_ATTRS = {"_replicas", "_state"}

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "/control/" not in path and not path.startswith("control/"):
            return
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                canon = module.canonical(node.func) \
                    or dotted_name(node.func) or ""
                if any(canon.endswith(s) for s in self._RING_CALL_SUFFIXES):
                    yield self.finding(
                        module, node,
                        f"`{canon}` mutates a hash ring directly from the "
                        "control plane — scale through "
                        "`PrefixRouter.scale_up`/`scale_down` (warmed "
                        "gate + quarantine drain + atomic ring swap)")
                elif last_segment(canon) == "HashRing":
                    yield self.finding(
                        module, node,
                        "control code builds a `HashRing` itself — ring "
                        "construction belongs to the router's atomic "
                        "swap; act through `PrefixRouter.scale_up`/"
                        "`scale_down` instead")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    name = dotted_name(t) or ""
                    if name.endswith(".ring") and "." in name:
                        yield self.finding(
                            module, t,
                            f"assignment to `{name}` swaps a router's "
                            "ring from the control plane — only the "
                            "router publishes rings (atomically, under "
                            "its own lock)")
            elif isinstance(node, ast.Attribute):
                if node.attr in self._INTERNAL_ATTRS \
                        and isinstance(node.value, ast.Attribute):
                    owner = dotted_name(node.value) or "<pool>"
                    yield self.finding(
                        module, node,
                        f"`{owner}.{node.attr}` reaches into pool "
                        "internals from the control plane — use the "
                        "pool's public membership seams "
                        "(`add_replica`/`drain_replica`/"
                        "`remove_replica`/`inflight`)")


#: page-accounting calls that move refcounts or hand pages across tiers —
#: inside serving/disagg/ these belong to the KVMigrator seams only
_DG_ACCOUNTING = {
    "alloc", "incref", "decref", "decref_quarantine", "lookup_prefix",
    "insert_prefix", "clear_prefix", "requeue", "queue_wipe",
    "admit_from_pages",
}


@register
class DisaggSeamRule(Rule):
    """DG01: page accounting or block-table writes in ``serving/disagg/``
    outside the ``KVMigrator`` export/import seams.

    The disagg tier's whole correctness story is one invariant: a
    migrated request's page refcounts hand off ATOMICALLY — the decode
    pool's claims plus fresh allocations transfer to the engine in the
    same step that queues the request, and every abort path releases
    exactly what it acquired (the chaos legs assert refcounts balance to
    zero leaked pages).  That invariant is auditable only because every
    pool acquire/release and block-table mutation in the package funnels
    through the :class:`~..serving.disagg.migrate.KVMigrator`'s seams —
    PG01's acquire/release discipline extended across the process
    boundary.  A scheduler (or future tier code) that increfs a page or
    pokes a block table itself reintroduces the scattered-refcount bug
    class the seam exists to kill.

    Fires, in modules whose path contains ``serving/disagg``, on (a) any
    call whose attribute is a page-accounting method (``alloc``,
    ``incref``, ``decref``, ``decref_quarantine``, ``lookup_prefix``,
    ``insert_prefix``, ``clear_prefix``, ``requeue``, ``queue_wipe``,
    ``admit_from_pages``) and (b) any assignment whose target mentions a
    block table (``bt`` / ``block_table``) — when the enclosing class is
    not ``KVMigrator``.

    Blind spots: accounting reached through a helper defined outside the
    package (the helper's own module gets PG01 instead), and a pool
    aliased into a collection.  Silence a deliberate hit with
    ``# graftlint: disable=DG01`` plus the reason.
    """

    id = "DG01"
    title = "disagg page accounting outside the KVMigrator seams"

    def check(self, module: ModuleInfo) -> Iterator[Finding]:
        path = module.path.replace("\\", "/")
        if "serving/disagg" not in path:
            return
        # map every node to its enclosing class, one walk
        owner: dict[int, str] = {}

        def _mark(node: ast.AST, cls: str | None) -> None:
            if isinstance(node, ast.ClassDef):
                cls = node.name
            for child in ast.iter_child_nodes(node):
                if cls is not None:
                    owner[id(child)] = cls
                _mark(child, cls)

        _mark(module.tree, None)
        for node in ast.walk(module.tree):
            if owner.get(id(node)) == "KVMigrator":
                continue
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr in _DG_ACCOUNTING:
                recv = dotted_name(node.func.value) or "<expr>"
                yield self.finding(
                    module, node,
                    f"`{recv}.{node.func.attr}` moves page references "
                    "outside the KVMigrator export/import seams — route "
                    "it through the migrator so the refcount handoff "
                    "stays atomic and auditable (DESIGN.md §27)")
            elif isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    name = (dotted_name(t) or "").lower()
                    seg = name.rsplit(".", 1)[-1]
                    if seg == "bt" or "block_table" in name:
                        yield self.finding(
                            module, t,
                            f"assignment to `{dotted_name(t)}` writes a "
                            "block table outside the KVMigrator seams — "
                            "block-table rows are installed only by the "
                            "engine's admit path on the migrator's "
                            "behalf (DESIGN.md §27)")
