#!/usr/bin/env python
"""Fetch /metrics + /status from a running job's StatusServer and render a
human-readable table.

Usage:
    python tools/metrics_dump.py --port 8787 [--host 127.0.0.1]
    python tools/metrics_dump.py --url http://10.0.0.3:8787
    python tools/metrics_dump.py --port 8787 --prom   # raw Prometheus text
    python tools/metrics_dump.py --timeline ts_dir/   # offline: sparklines
                                                      # from TimeSeriesStore
                                                      # JSONL exports

``--timeline`` takes a ``timeseries-*.jsonl`` file (or a directory of
them, as written under ``DL4J_TPU_TS_DIR``) and needs no live server:
each series renders as min/last/max plus a unicode sparkline of its
recent samples.  ``--series SUBSTR`` filters the set.

No dependencies beyond stdlib: talks to the endpoints
``deeplearning4j_tpu.observability.StatusServer`` serves, and reads the
``TimeSeriesStore`` JSONL format directly (torn final lines from a
killed process are skipped, matching ``timeseries.read_back``).
"""

from __future__ import annotations

import argparse
import json
import sys
import urllib.error
import urllib.request


def _fetch(base: str, path: str, timeout: float):
    try:
        with urllib.request.urlopen(base + path, timeout=timeout) as r:
            body = r.read()
    except (urllib.error.URLError, OSError) as e:
        return None, f"{path}: {e}"
    if path.endswith(".prom"):
        return body.decode(), None
    return json.loads(body), None


def _rows(title: str, rows: list[tuple], headers: tuple) -> str:
    """Plain-text table: header + aligned columns."""
    out = [title]
    if not rows:
        out.append("  (none)")
        return "\n".join(out)
    cells = [tuple(str(c) for c in r) for r in rows]
    widths = [max(len(h), *(len(r[i]) for r in cells))
              for i, h in enumerate(headers)]
    fmt = "  " + "  ".join(f"{{:<{w}}}" for w in widths)
    out.append(fmt.format(*headers))
    out.append("  " + "-" * (sum(widths) + 2 * (len(widths) - 1)))
    out.extend(fmt.format(*r) for r in cells)
    return "\n".join(out)


def _fmt_s(v: float) -> str:
    if v != v:
        return "nan"
    if v >= 1.0:
        return f"{v:.3f}s"
    return f"{v * 1e3:.2f}ms"


def _fmt_bytes(v: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if v < 1024 or unit == "GiB":
            return f"{v:.0f}{unit}" if unit == "B" else f"{v:.2f}{unit}"
        v /= 1024
    return f"{v:.2f}GiB"


def render_state_memory(snap: dict) -> str | None:
    """Per-device params / optimizer-state footprint table, from the
    ``train.params_bytes`` / ``train.opt_state_bytes`` gauges the trainer
    publishes at init and after restore.  Under ZeRO (zero_stage >= 1)
    the opt-state column shows the ~1/ndp shrink directly.  Returns None
    when the job published no state gauges (pre-ZeRO jobs)."""
    gauges = snap.get("gauges", {})
    devs: dict[str, dict[str, float]] = {}
    for prefix, col in (("train.params_bytes.device.", "params"),
                        ("train.opt_state_bytes.device.", "opt_state")):
        for k, v in gauges.items():
            if k.startswith(prefix):
                devs.setdefault(k[len(prefix):], {})[col] = v
    if not devs:
        return None
    rows = [(d, _fmt_bytes(c.get("params", 0.0)),
             _fmt_bytes(c.get("opt_state", 0.0)))
            for d, c in sorted(devs.items(), key=lambda kv: kv[0])]
    return _rows("state memory (per device)", rows,
                 ("device", "params", "opt_state"))


def render_serving(snap: dict) -> str | None:
    """Paged-KV / prefix-cache / speculative serving gauges (PR-9), plus
    the speculative accepted-prefix histogram.  Returns None when the job
    published none of them (non-serving jobs, dense engines)."""
    gauges = snap.get("gauges", {})
    rows = []
    if "serving.kv_pages_in_use" in gauges:
        rows.append(("kv_pages_in_use", f"{gauges['serving.kv_pages_in_use']:.0f}"))
    if "serving.prefix_hit_rate" in gauges:
        rows.append(("prefix_hit_rate",
                     f"{gauges['serving.prefix_hit_rate'] * 100:.1f}%"))
    if "serving.kv_bytes_per_slot" in gauges:
        rows.append(("kv_bytes_per_slot",
                     _fmt_bytes(gauges["serving.kv_bytes_per_slot"])))
    accept = snap.get("timers", {}).get("serving.spec_accept_len")
    if accept:
        rows.append(("spec_accept_len(mean)",
                     f"{accept['mean_s']:.2f} tok over {accept['count']} windows"))
    if not rows:
        return None
    return _rows("serving (paged KV / prefix cache / speculative)", rows,
                 ("metric", "value"))


def render_kv_capacity(snap: dict) -> str | None:
    """The users-per-chip ledger (ISSUE 12): derive how many concurrent
    slots the KV page pool can hold from the gauges the engine publishes
    — ``serving.kv_page_bytes`` x ``kv_pages_total`` is the pool's byte
    budget, ``kv_bytes_per_slot`` is one user's share at the current
    sequence budget, and their ratio is the capacity the quant mode +
    head layout bought.  Returns None without a paged engine's gauges."""
    gauges = snap.get("gauges", {})
    page_bytes = gauges.get("serving.kv_page_bytes")
    pages_total = gauges.get("serving.kv_pages_total")
    per_slot = gauges.get("serving.kv_bytes_per_slot")
    if page_bytes is None or pages_total is None:
        return None
    pool_bytes = page_bytes * pages_total
    bits = gauges.get("serving.kv_quant_bits")
    rows = [
        ("kv_storage_bits", "?" if bits is None else f"{bits:.0f}"),
        ("pool_pages", f"{pages_total:.0f}"),
        ("page_bytes", _fmt_bytes(page_bytes)),
        ("pool_bytes", _fmt_bytes(pool_bytes)),
    ]
    if "serving.kv_pages_in_use" in gauges:
        used = gauges["serving.kv_pages_in_use"]
        rows.append(("pages_in_use",
                     f"{used:.0f} ({used / max(pages_total, 1) * 100:.1f}%)"))
    if per_slot:
        rows.append(("bytes_per_slot", _fmt_bytes(per_slot)))
        rows.append(("slots_per_pool (users/chip)",
                     f"{pool_bytes / per_slot:.1f}"))
    return _rows("kv capacity (users per page pool)", rows,
                 ("metric", "value"))


def render_router(snap: dict) -> str | None:
    """Multi-replica router tier (PR 11): per-replica breaker state /
    in-flight load / queue depth, plus the aggregate affinity, spillover
    and quarantine story.  Returns None when the job published no
    ``router.*`` gauges (single-replica or non-serving jobs)."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    names: set[str] = set()
    for prefix in ("router.replica_state.", "router.replica_load.",
                   "router.replica_queue_depth."):
        for k in gauges:
            if k.startswith(prefix):
                names.add(k[len(prefix):])
    if not names and not any(k.startswith("router.") for k in counters):
        return None
    rows = []
    for n in sorted(names):
        state = gauges.get(f"router.replica_state.{n}")
        rows.append((
            n,
            "?" if state is None else ("active" if state else "quarantined"),
            f"{gauges.get(f'router.replica_load.{n}', 0.0):.0f}",
            f"{gauges.get(f'router.replica_queue_depth.{n}', 0.0):.0f}"))
    out = [_rows("router (per replica)", rows,
                 ("replica", "state", "inflight", "queue_depth"))]
    summary = []
    reqs = counters.get("router.requests")
    if reqs:
        summary.append(("requests", f"{reqs:.0f}"))
        hits = counters.get("router.prefix_affinity_hit", 0.0)
        summary.append(("prefix_affinity", f"{hits / reqs * 100:.1f}%"))
    if "router.prefix_hit_rate" in gauges:
        summary.append(("aggregate_prefix_hit_rate",
                        f"{gauges['router.prefix_hit_rate'] * 100:.1f}%"))
    for name in ("router.spillover", "router.quarantines",
                 "router.readmissions", "router.replica_errors"):
        if name in counters:
            summary.append((name.split(".", 1)[1],
                            f"{counters[name]:.0f}"))
    if summary:
        out.append(_rows("router (aggregate)", summary, ("metric", "value")))
    return "\n\n".join(out)


def render_elasticity(snap: dict) -> str | None:
    """Elastic-training tier (ISSUE 13): current mesh width, topology
    resize count, last reshard wall-clock, scaleout wave size, and the
    injected shrink/grow chaos fires that exercised them.  Returns None
    when the job published no ``elastic.*`` gauges (fixed-topology jobs)."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    rows = []
    if "elastic.mesh_size" in gauges:
        rows.append(("mesh_size", f"{gauges['elastic.mesh_size']:.0f} chips"))
    if "elastic.wave_size" in gauges:
        rows.append(("wave_size", f"{gauges['elastic.wave_size']:.0f} workers"))
    if "elastic.resizes_total" in gauges:
        rows.append(("resizes_total", f"{gauges['elastic.resizes_total']:.0f}"))
    if "elastic.reshard_seconds" in gauges:
        rows.append(("reshard_seconds", _fmt_s(gauges["elastic.reshard_seconds"])))
    for name, label in (("checkpoint.reshards", "reshard_restores"),
                        ("resilience.device_losses", "device_losses"),
                        ("scaleout.wave_shrinks", "wave_shrinks"),
                        ("scaleout.wave_grows", "wave_grows"),
                        ("faults.injected.mesh.shrink", "injected mesh.shrink"),
                        ("faults.injected.mesh.grow", "injected mesh.grow")):
        if name in counters:
            rows.append((label, f"{counters[name]:.0f}"))
    if not rows:
        return None
    return _rows("elasticity (topology changes)", rows, ("metric", "value"))


def render_online(snap: dict) -> str | None:
    """Online learning loop tier (ISSUE 15): live weight generation,
    reload/rollback counts, captured-traffic volume, and the last hot
    reload's wall-clock.  Returns None when the process published no
    ``online.*`` state (offline-only jobs)."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    rows = []
    if "online.generation" in gauges:
        rows.append(("generation", f"{gauges['online.generation']:.0f}"))
    for name, label in (("online.reloads", "reloads"),
                        ("online.rollbacks", "rollbacks"),
                        ("online.rounds", "rounds"),
                        ("online.captured_records", "captured_records"),
                        ("capture.corrupt_records", "corrupt_records"),
                        ("checkpoint.quarantined", "quarantined_ckpts")):
        if name in counters:
            rows.append((label, f"{counters[name]:.0f}"))
    if "capture.bytes" in gauges:
        rows.append(("capture.bytes", f"{gauges['capture.bytes']:.0f} B"))
    if "online.reload_seconds" in gauges:
        rows.append(("reload_seconds", _fmt_s(gauges["online.reload_seconds"])))
    if "online.canary_loss" in gauges:
        rows.append(("canary_loss", f"{gauges['online.canary_loss']:.4f}"))
    if not rows:
        return None
    return _rows("online loop (serve → capture → fine-tune → reload)",
                 rows, ("metric", "value"))


def render_fleet(snap: dict) -> str | None:
    """Fleet federation plane (ISSUE 16): the ``fleet.*`` rollups the
    scraper publishes — fleet-wide sums plus the per-replica min/med/max
    spread of each rolled-up series, and the scrape health counters.
    Returns None when no :class:`FleetScraper` ran in this process."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    if not any(k.startswith("fleet.") for k in gauges) and \
            not any(k.startswith("fleet.") for k in counters):
        return None
    rows = []
    for name, label in (("fleet.replicas", "replicas"),
                        ("fleet.stale_replicas", "stale_replicas"),
                        ("fleet.tokens_per_sec", "tokens_per_sec"),
                        ("fleet.kv_pages_in_use", "kv_pages_in_use"),
                        ("fleet.queue_depth", "queue_depth"),
                        ("fleet.tokens_total", "tokens_total")):
        if name in gauges:
            rows.append((label, f"{gauges[name]:.6g}", "", "", ""))
    # spread rows: fleet.spread.<series>.{min,med,max}
    spreads: dict[str, dict[str, float]] = {}
    prefix = "fleet.spread."
    for k, v in gauges.items():
        if k.startswith(prefix):
            base, _, stat = k[len(prefix):].rpartition(".")
            if stat in ("min", "med", "max"):
                spreads.setdefault(base, {})[stat] = v
    for base, s in sorted(spreads.items()):
        rows.append((f"spread {base}", "",
                     f"{s.get('min', 0.0):.6g}", f"{s.get('med', 0.0):.6g}",
                     f"{s.get('max', 0.0):.6g}"))
    for name, label in (("fleet.scrapes", "scrapes"),
                        ("fleet.scrape_errors", "scrape_errors"),
                        ("fleet.tenant_overflow", "tenant_overflow")):
        if name in counters:
            rows.append((label, f"{counters[name]:.0f}", "", "", ""))
    if not rows:
        return None
    return _rows("fleet (federated rollups + spread)", rows,
                 ("metric", "value", "min", "med", "max"))


def render_control(snap: dict) -> str | None:
    """Control plane (DESIGN.md §26): autoscaler liveness + actions,
    brownout level and what it currently costs callers, overload
    verdicts.  Returns None when no controller ran in this process."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    if not any(k.startswith("control.") for k in gauges) and \
            not any(k.startswith("control.") for k in counters):
        return None
    rows = []
    for name, label in (("control.autoscaler_alive", "autoscaler_alive"),
                        ("control.pool_size", "pool_size"),
                        ("control.brownout_level", "brownout_level"),
                        ("serving.speculative_enabled", "speculative_enabled"),
                        ("serving.max_new_cap", "max_new_cap")):
        if name in gauges:
            rows.append((label, f"{gauges[name]:.6g}"))
    for name, label in (("control.scale_up", "scale_ups"),
                        ("control.scale_down", "scale_downs"),
                        ("control.scale_errors", "scale_errors"),
                        ("control.errors", "loop_errors"),
                        ("control.autoscaler_killed", "autoscaler_killed"),
                        ("control.brownout_transitions",
                         "brownout_transitions"),
                        ("control.throttled", "throttled"),
                        ("control.shed", "background_shed"),
                        ("serving.preempted", "preempted"),
                        ("serving.max_new_clamped", "max_new_clamped")):
        if name in counters:
            rows.append((label, f"{counters[name]:.0f}"))
    if not rows:
        return None
    return _rows("control (autoscaler + overload)", rows, ("metric", "value"))


def render_tenants(snap: dict, top_k: int = 10) -> str | None:
    """Per-tenant accounting (ISSUE 16): the ``tenant.<label>.*``
    counters fed through the bounded :class:`TenantLabels` fold, ranked
    by tokens generated; the ``__other__`` overflow bucket renders like
    any tenant so folded traffic stays visible.  Returns None when no
    tenant traffic was accounted."""
    counters = snap.get("counters", {})
    tenants: dict[str, dict[str, float]] = {}
    for k, v in counters.items():
        if not k.startswith("tenant."):
            continue
        label, _, field = k[len("tenant."):].rpartition(".")
        if label:
            tenants.setdefault(label, {})[field] = v
    if not tenants:
        return None
    ranked = sorted(tenants.items(),
                    key=lambda kv: -(kv[1].get("generated_tokens", 0.0) +
                                     kv[1].get("prompt_tokens", 0.0)))
    rows = []
    for label, c in ranked[:top_k]:
        rows.append((label,
                     f"{c.get('prompt_tokens', 0.0):.0f}",
                     f"{c.get('generated_tokens', 0.0):.0f}",
                     _fmt_s(c.get("queue_wait_s", 0.0)),
                     f"{c.get('rejected', 0.0):.0f}",
                     f"{c.get('deadline_dropped', 0.0):.0f}"))
    title = f"tenants (top {min(top_k, len(ranked))} of {len(ranked)} by tokens)"
    if len(ranked) > top_k:
        title += f" [+{len(ranked) - top_k} not shown]"
    return _rows(title, rows,
                 ("tenant", "prompt_tok", "gen_tok", "queue_wait",
                  "rejected", "deadline_dropped"))


def render_forecast(snap: dict) -> str | None:
    """Trend forecasts (ISSUE 16): predicted seconds until each SLO
    objective breaches (``+Inf`` = flat/receding/noisy — no forecast),
    plus the warning count.  Returns None without a ForecastEvaluator."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    prefix = "forecast.time_to_breach."
    rows = [(k[len(prefix):],
             "inf" if v == float("inf") else _fmt_s(v))
            for k, v in sorted(gauges.items()) if k.startswith(prefix)]
    if "forecast.breach_warnings" in counters:
        rows.append(("breach_warnings",
                     f"{counters['forecast.breach_warnings']:.0f}"))
    if not rows:
        return None
    return _rows("forecast (time to SLO breach)", rows,
                 ("objective", "time_to_breach"))


def render_utilization(snap: dict) -> str | None:
    """MFU / memory-bandwidth gauges from the analytic cost model
    (``observability.cost``): published by the trainer, the decode loop
    and bench.py from the same ``cost_analysis()``-derived FLOPs."""
    gauges = snap.get("gauges", {})
    rows = [(name, f"{gauges[name] * 100:.2f}%")
            for name in ("train.mfu", "train.mbu",
                         "serving.decode_mfu", "serving.decode_mbu")
            if name in gauges]
    if not rows:
        return None
    return _rows("utilization (analytic cost model)", rows,
                 ("gauge", "value"))


def render_goodput(snap: dict) -> str | None:
    """Goodput accounting + SLO burn rates (ISSUE 14): the wall-clock
    split a finished ``GoodputTracker`` published, each state as seconds
    and share-of-wall, plus every ``slo.burn_rate.*`` gauge the SLO
    evaluator keeps live (>= 1.0 means the error budget burns faster
    than the objective allows).  Returns None when the job published
    neither (unsupervised or pre-goodput jobs)."""
    gauges = snap.get("gauges", {})
    counters = snap.get("counters", {})
    out = []
    wall = gauges.get("goodput.wall_seconds")
    if wall is not None:
        rows = [("fraction", f"{gauges.get('goodput.fraction', 0.0) * 100:.1f}%"),
                ("wall", _fmt_s(wall))]
        prefix = "goodput.seconds."
        for k, v in sorted(gauges.items()):
            if k.startswith(prefix):
                share = v / wall * 100 if wall else 0.0
                rows.append((k[len(prefix):], f"{_fmt_s(v)} ({share:.1f}%)"))
        out.append(_rows("goodput (wall-clock accounting)", rows,
                         ("state", "value")))
    slo_rows = [(k[len("slo.burn_rate."):],
                 f"{v:.2f}x" + ("  << BURNING" if v >= 1.0 else ""))
                for k, v in sorted(gauges.items())
                if k.startswith("slo.burn_rate.")]
    if "slo.breaches" in counters:
        slo_rows.append(("breaches", f"{counters['slo.breaches']:.0f}"))
    if slo_rows:
        out.append(_rows("slo (error-budget burn rates)", slo_rows,
                         ("objective", "burn")))
    return "\n\n".join(out) if out else None


def render_metrics(snap: dict) -> str:
    parts = []
    state_mem = render_state_memory(snap)
    if state_mem is not None:
        parts.append(state_mem)
    for section in (render_serving(snap), render_kv_capacity(snap),
                    render_router(snap), render_fleet(snap),
                    render_control(snap),
                    render_tenants(snap), render_elasticity(snap),
                    render_online(snap), render_goodput(snap),
                    render_forecast(snap), render_utilization(snap)):
        if section is not None:
            parts.append(section)
    parts.append(_rows(
        "counters", sorted(snap.get("counters", {}).items()),
        ("name", "value")))
    parts.append(_rows(
        "gauges",
        [(k, f"{v:.6g}") for k, v in sorted(snap.get("gauges", {}).items())],
        ("name", "value")))
    timer_rows = [
        (name, s["count"], _fmt_s(s["mean_s"]), _fmt_s(s["p50_s"]),
         _fmt_s(s["p95_s"]), _fmt_s(s["p99_s"]), _fmt_s(s["total_s"]))
        for name, s in sorted(snap.get("timers", {}).items())]
    parts.append(_rows(
        "timers", timer_rows,
        ("name", "count", "mean", "p50", "p95", "p99", "total")))
    return "\n\n".join(parts)


def render_status(status: dict) -> str:
    if not status:
        return "status: (no tracker attached)"
    lines = ["status"]
    hb = status.get("heartbeats_age_s", {})
    enabled = status.get("enabled", {})
    worker_rows = [(w, enabled.get(w, "?"), hb.get(w, "?"))
                   for w in status.get("workers", [])]
    lines.append(_rows("  workers", worker_rows,
                       ("worker", "enabled", "heartbeat_age_s")))
    for k in ("current_jobs", "pending_updates", "done"):
        if k in status:
            lines.append(f"  {k}: {status[k]}")
    counters = status.get("counters", {})
    if counters:
        lines.append(_rows("  tracker counters", sorted(counters.items()),
                           ("name", "value")))
    for e in status.get("errors", []):
        lines.append(f"  partial: {e}")
    return "\n".join(lines)


_SPARK = " ▁▂▃▄▅▆▇█"


def _sparkline(values: list[float], width: int = 40) -> str:
    vals = [v for v in values if v == v][-width:]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    if hi <= lo:
        return _SPARK[4] * len(vals)
    return "".join(
        _SPARK[min(8, int((v - lo) / (hi - lo) * 8.999))] for v in vals)


def _read_timeline(path: str) -> dict[str, list[tuple[float, float]]]:
    """Merge ``timeseries-*.jsonl`` exports (one file or a directory)
    into ``{series: [(t, value), ...]}``, skipping torn/partial lines —
    the stdlib twin of ``timeseries.read_back_series``."""
    import os
    paths = ([os.path.join(path, f) for f in sorted(os.listdir(path))
              if f.endswith(".jsonl")] if os.path.isdir(path) else [path])
    series: dict[str, list[tuple[float, float]]] = {}
    for p in paths:
        with open(p, errors="replace") as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue            # torn tail from a killed process
                if not isinstance(rec, dict) or "series" not in rec:
                    continue
                t = float(rec.get("t", 0.0))
                for name, v in rec["series"].items():
                    series.setdefault(name, []).append((t, float(v)))
    for rows in series.values():
        rows.sort(key=lambda tv: tv[0])
    return series


def render_timeline(path: str, pattern: str = "") -> str:
    series = _read_timeline(path)
    names = sorted(n for n in series if pattern in n)
    if not names:
        return f"timeline: no series matching {pattern!r} in {path}"
    rows = []
    for name in names:
        vals = [v for _, v in series[name]]
        rows.append((name, len(vals), f"{min(vals):.6g}", f"{vals[-1]:.6g}",
                     f"{max(vals):.6g}", _sparkline(vals)))
    span = max(t for rs in series.values() for t, _ in rs) - \
        min(t for rs in series.values() for t, _ in rs)
    return _rows(f"timeline ({len(names)} series over {span:.1f}s)", rows,
                 ("series", "n", "min", "last", "max", "recent"))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int)
    ap.add_argument("--url", help="full base URL (overrides --host/--port)")
    ap.add_argument("--prom", action="store_true",
                    help="dump raw Prometheus text exposition instead")
    ap.add_argument("--timeline", metavar="PATH",
                    help="render TimeSeriesStore JSONL (file or dir) "
                         "offline instead of scraping a server")
    ap.add_argument("--series", default="",
                    help="substring filter for --timeline series names")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    if args.timeline:
        print(render_timeline(args.timeline, args.series))
        return 0
    if args.url:
        base = args.url.rstrip("/")
    elif args.port:
        base = f"http://{args.host}:{args.port}"
    else:
        ap.error("need --port or --url")

    if args.prom:
        body, err = _fetch(base, "/metrics.prom", args.timeout)
        if err:
            print(err, file=sys.stderr)
            return 1
        print(body, end="")
        return 0

    snap, err = _fetch(base, "/metrics", args.timeout)
    if err:
        print(err, file=sys.stderr)
        return 1
    print(render_metrics(snap))
    status, err = _fetch(base, "/status", args.timeout)
    print()
    if err:
        print(f"status unavailable ({err})")
    else:
        print(render_status(status))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
