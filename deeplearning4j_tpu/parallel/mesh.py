"""Device-mesh construction and multi-host bootstrap.

TPU-native replacement for the reference's cluster formation
(``DeepLearning4jDistributed.java:128-187`` Akka seed join +
``BaseHazelCastStateTracker.java:454-539`` embedded-vs-client): topology is a
`jax.sharding.Mesh` with named axes, and multi-host bootstrap is
``jax.distributed.initialize`` (the JAX coordination service) — one program,
no master/worker asymmetry.

Axis convention (used across trainers/models):
``dp`` data, ``tp`` tensor/model, ``pp`` pipeline stages, ``sp`` sequence
(ring attention / context parallel), ``ep`` expert.
"""

from __future__ import annotations

import dataclasses
import math
import os
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP, TP, PP, SP, EP = "dp", "tp", "pp", "sp", "ep"

#: Canonical axis-name registry — THE one table every PartitionSpec,
#: shard_map and collective in this repo draws axis names from.  The
#: static sharding pass (analysis/sharding.py) parses this module for
#: exactly these assignments, so an axis name that is not here fails
#: lint (SH02) before it fails a trace on device.
AXES: tuple[str, ...] = (DP, TP, PP, SP, EP)

#: role of each axis, for error messages and operator docs
AXIS_ROLES: dict[str, str] = {
    DP: "data",
    TP: "tensor/model",
    PP: "pipeline stages",
    SP: "sequence (ring attention / context parallel)",
    EP: "expert",
}


class MeshMismatchError(RuntimeError):
    """A checkpoint written at one dp width met a mesh of another width
    without ``reshard=True`` (or with shapes that no reshard can explain).

    Carries both widths and the zero_stage so the operator can tell at a
    glance whether to pass ``reshard=True`` or fix the mesh spec.
    """

    def __init__(self, saved_dp, restore_dp, zero_stage, detail: str = ""):
        self.saved_dp = saved_dp
        self.restore_dp = restore_dp
        self.zero_stage = zero_stage
        msg = (f"checkpoint written at dp={saved_dp} cannot restore onto "
               f"dp={restore_dp} (zero_stage={zero_stage}) without "
               f"reshard=True")
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named mesh shape; -1 on one axis means 'absorb remaining devices'."""

    dp: int = -1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1

    def resolve(self, n_devices: int) -> dict[str, int]:
        sizes = {DP: self.dp, TP: self.tp, PP: self.pp, SP: self.sp, EP: self.ep}
        wild = [k for k, v in sizes.items() if v == -1]
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(f"{n_devices} devices not divisible by {fixed}")
            sizes[wild[0]] = n_devices // fixed
        if math.prod(sizes.values()) != n_devices:
            raise ValueError(f"mesh {sizes} != {n_devices} devices")
        return sizes


def make_mesh(spec: MeshSpec | None = None, devices: Sequence | None = None) -> Mesh:
    """Build a named mesh over the given (default: all) devices.

    Axis order is (pp, dp, sp, tp, ep) — tp innermost so tensor-parallel
    collectives ride the fastest ICI links; pp outermost so pipeline stages
    can span slower (DCN) boundaries.
    """
    spec = spec or MeshSpec()
    devices = list(devices if devices is not None else jax.devices())
    sizes = spec.resolve(len(devices))
    order = (PP, DP, SP, TP, EP)
    shape = tuple(sizes[a] for a in order)
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, order)


def local_mesh(n: int | None = None, axis: str = DP) -> Mesh:
    """1-axis mesh over local devices (the common data-parallel case)."""
    devices = jax.devices()[: (n or len(jax.devices()))]
    return Mesh(np.array(devices), (axis,))


def mesh_devices(mesh: Mesh) -> list:
    """Flat device list of a mesh, in axis order."""
    return list(mesh.devices.flat)


def dp_width(mesh: Mesh) -> int:
    """Data-parallel width of a mesh (1 when it has no dp axis)."""
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get(DP, 1))


def elastic_mesh(devices: Sequence, axis: str = DP) -> Mesh:
    """1-axis mesh over an explicit device list — the topology-change
    primitive: every shrink/grow rebuild goes through here so elastic code
    never hardcodes a device count (graftlint EL01)."""
    devices = list(devices)
    if not devices:
        raise ValueError("elastic_mesh: no surviving devices")
    return Mesh(np.array(devices), (axis,))


def shrink_mesh(mesh: Mesh, lost: Sequence) -> Mesh:
    """Rebuild a dp mesh from the survivors after losing ``lost`` devices.

    Elasticity is dp-only: multi-axis meshes (tp/pp/...) have rigid
    collective schedules and must be rebuilt from a MeshSpec instead.
    """
    if set(mesh.axis_names) - {DP}:
        sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
        if any(v != 1 for a, v in sizes.items() if a != DP):
            raise ValueError(f"shrink_mesh: only dp meshes are elastic, got {sizes}")
    lost_ids = {id(d) for d in lost} | {getattr(d, "id", None) for d in lost}
    survivors = [d for d in mesh_devices(mesh)
                 if id(d) not in lost_ids and getattr(d, "id", None) not in lost_ids]
    if len(survivors) == len(mesh_devices(mesh)):
        raise ValueError("shrink_mesh: no listed device is in the mesh")
    return elastic_mesh(survivors)


def grow_mesh(mesh: Mesh, regained: Sequence) -> Mesh:
    """Rebuild a dp mesh with ``regained`` devices appended (devices already
    present are ignored, so re-registration is idempotent)."""
    current = mesh_devices(mesh)
    have = {getattr(d, "id", id(d)) for d in current}
    added = [d for d in regained if getattr(d, "id", id(d)) not in have]
    return elastic_mesh(current + added)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharding(mesh: Mesh, axis: str = DP, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over ``axis``, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def initialize_multihost(coordinator: str | None = None,
                         num_processes: int | None = None,
                         process_id: int | None = None) -> None:
    """Multi-host bootstrap (replaces Akka-seed/ZooKeeper discovery).

    No-op when single-process.  Env-var driven (JAX_COORDINATOR_ADDRESS,
    JAX_NUM_PROCESSES, JAX_PROCESS_ID) like the reference's
    Hadoop-style ``Configuration`` keys.
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes or os.environ.get("JAX_NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("JAX_PROCESS_ID", 0)),
    )
