"""Train skip-gram word embeddings and query nearest neighbors.

The reference workflow (``models/word2vec/Word2Vec.java:42``): build vocab,
Huffman-code it, train hierarchical-softmax skip-gram, then probe with
similarity queries and save in the Google text format
(``WordVectorSerializer``-compatible round trip).

Run:  python examples/02_word2vec.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import tempfile

from deeplearning4j_tpu.text.serializer import load_txt, save_txt
from deeplearning4j_tpu.text.word2vec import Word2Vec

CORPUS = [
    "the apple is a sweet fruit",
    "banana is a yellow fruit and the banana is sweet",
    "orange fruit is sweet and orange is juicy",
    "apple and banana and orange are fruit",
    "fruit salad has apple banana orange",
    "the car drives on the road",
    "a truck is a big car on the road",
    "the bus drives people on the road",
    "car truck and bus are vehicles on the road",
    "vehicles like car and bus drive fast",
] * 8


def main():
    w2v = Word2Vec(CORPUS, layer_size=32, window=3, iterations=8,
                   min_word_frequency=3, seed=7)
    w2v.fit()

    print(f"nearest to 'car': {w2v.words_nearest('car', 4)}")
    within = w2v.similarity("apple", "banana")
    cross = w2v.similarity("apple", "road")
    print(f"sim(apple, banana) = {within:.3f}  (same topic)")
    print(f"sim(apple, road)   = {cross:.3f}  (cross topic)")
    assert within > cross, "within-topic similarity should beat cross-topic"

    with tempfile.NamedTemporaryFile(suffix=".txt") as f:
        words = [w2v.vocab.word_at(i) for i in range(len(w2v.vocab))]
        save_txt(words, w2v.syn0, f.name)
        words2, _ = load_txt(f.name)
        print(f"round-tripped {len(words2)} vectors through Google txt format")


if __name__ == "__main__":
    main()
