"""Resilience subsystem: fault injection, self-healing supervision,
checkpoint integrity, and scaleout hardening (DESIGN.md §12).

The acceptance chain for this tier lives in
``test_supervised_chaos_parity``: transient step failure + corrupted
checkpoint write + data-pipeline failure in ONE run, and the supervisor
still finishes with the exact parameters of a fault-free run — resuming
from the newest checkpoint that passes checksum verification, never from
the corrupted one.  Everything else here pins the parts: deterministic
injection, checksum verify/fallback, scalar-leaf restore, NaN rollback
with batch-window skip, preemption resume, job retry/quarantine, run
timeouts, and the FileModelSaver tmp-file race.
"""

import json
import threading
import time
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deeplearning4j_tpu.observability import METRICS
from deeplearning4j_tpu.optimize import transforms as T
from deeplearning4j_tpu.parallel.checkpoint import (
    CheckpointCorruptError, CheckpointManager)
from deeplearning4j_tpu.parallel.mesh import MeshSpec, make_mesh
from deeplearning4j_tpu.parallel.scaleout import (
    CollectionJobIterator, DistributedRunner, FileModelSaver, ScaleoutTimeout,
    StateTracker)
from deeplearning4j_tpu.parallel.trainer import DataParallelTrainer
from deeplearning4j_tpu.resilience import (
    FAULTS, DataIteratorFault, FaultInjector, FaultSpec, RetryPolicy,
    TrainingSupervisor, TransientStepFault, corrupt_file, inject_faults,
    parse_fault_env)


@pytest.fixture(autouse=True)
def _disarm_faults():
    FAULTS.disarm()
    yield
    FAULTS.disarm()


# --------------------------------------------------------------------------- injector

def test_injector_probability_is_deterministic_per_seed():
    def fire_indices(seed):
        inj = FaultInjector()
        inj.arm([FaultSpec("train.step", probability=0.3, max_fires=0)],
                seed=seed)
        return [i for i in range(100) if inj.check("train.step") is not None]

    a, b = fire_indices(7), fire_indices(7)
    assert a == b and a                      # same plan + seed -> same schedule
    assert fire_indices(8) != a              # seed actually matters


def test_injector_at_step_and_max_fires():
    inj = FaultInjector()
    inj.arm([FaultSpec("train.step", at_step=3)], seed=0)
    fired = [s for s in range(1, 10) if inj.check("train.step", s) is not None]
    assert fired == [3]                      # max_fires=1: transient by default
    assert inj.fire_count("train.step") == 1


def test_maybe_fire_raises_mapped_exception():
    with inject_faults(FaultSpec("train.step", at_step=1)):
        with pytest.raises(TransientStepFault):
            FAULTS.maybe_fire("train.step", 1)
    assert FAULTS.check("train.step", 1) is None     # disarmed on exit


def test_parse_fault_env():
    specs = parse_fault_env(
        "train.step:at=5;checkpoint.write:kind=truncate,p=0.5,max=2;preempt")
    by_site = {s.site: s for s in specs}
    assert by_site["train.step"].at_step == 5
    assert by_site["checkpoint.write"].kind == "truncate"
    assert by_site["checkpoint.write"].probability == 0.5
    assert by_site["checkpoint.write"].max_fires == 2
    assert by_site["preempt"].probability == 1.0     # bare site fires
    with pytest.raises(ValueError):
        parse_fault_env("train.step:bogus=1")


def test_env_arming_is_lazy(monkeypatch):
    monkeypatch.setenv("DL4J_TPU_FAULTS", "train.step:at=2")
    monkeypatch.setenv("DL4J_TPU_FAULTS_SEED", "9")
    FAULTS.disarm()                          # re-allow env pickup
    assert FAULTS.check("train.step", 1) is None
    assert FAULTS.check("train.step", 2) is not None


# --------------------------------------------------------------------------- checkpoint integrity

def _save_steps(mgr, steps):
    for s in steps:
        mgr.save(s, {"w": jnp.full(4, float(s))})


def test_checksum_verify_detects_corruption(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=10)
    _save_steps(mgr, [1, 2, 3])
    assert all(mgr.verify(s) for s in (1, 2, 3))
    for kind in ("truncate", "bitflip"):
        corrupt_file(tmp_path / "ckpt_0000000003" / "params.npz", kind)
        assert not mgr.verify(3)
    assert mgr.latest_valid_step() == 2


def test_restore_falls_back_to_newest_valid(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=10)
    _save_steps(mgr, [1, 2, 3])
    corrupt_file(tmp_path / "ckpt_0000000003" / "params.npz", "bitflip")
    before = METRICS.snapshot()["counters"].get("checkpoint.corrupt_detected", 0)
    with pytest.warns(UserWarning, match="failed checksum"):
        out = mgr.restore({"w": jnp.zeros(4)})
    assert out["step"] == 2
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]), np.full(4, 2.0))
    after = METRICS.snapshot()["counters"]["checkpoint.corrupt_detected"]
    assert after == before + 1


def test_explicit_corrupt_step_raises(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=10)
    _save_steps(mgr, [1])
    corrupt_file(tmp_path / "ckpt_0000000001" / "params.npz", "truncate")
    with pytest.raises(CheckpointCorruptError):
        mgr.restore({"w": jnp.zeros(4)}, step=1)
    with pytest.raises(FileNotFoundError, match="all corrupt"):
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            mgr.restore({"w": jnp.zeros(4)})


def test_checkpoint_write_fault_site_corrupts_published_payload(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=10)
    with inject_faults(FaultSpec("checkpoint.write", at_step=2, kind="bitflip")):
        _save_steps(mgr, [1, 2])
    assert mgr.verify(1) and not mgr.verify(2)
    assert mgr.latest_valid_step() == 1


def test_restore_like_scalar_and_bool_leaves(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    params = {"w": jnp.ones(4), "count": 3, "rate": 0.5, "flag": True}
    mgr.save(1, params)
    out = mgr.restore({"w": jnp.zeros(4), "count": 0, "rate": 0.0,
                       "flag": False})
    assert out["params"]["count"] == 3 and type(out["params"]["count"]) is int
    assert out["params"]["rate"] == 0.5
    assert out["params"]["flag"] is True


def test_restore_warns_on_unused_checkpoint_keys(tmp_path):
    mgr = CheckpointManager(tmp_path, keep=3)
    mgr.save(1, {"w": jnp.ones(4), "extra": jnp.zeros(2)})
    with pytest.warns(UserWarning, match="absent from the restore template"):
        out = mgr.restore({"w": jnp.zeros(4)})
    assert "extra" not in out["params"]
    assert METRICS.snapshot()["counters"]["checkpoint.unused_keys"] == 1


# --------------------------------------------------------------------------- supervised training

def _toy_problem():
    w_true = jnp.asarray([1.0, -2.0, 0.5])
    x = jax.random.normal(jax.random.key(3), (64, 3))
    y = x @ w_true
    params = {"w": jnp.zeros(3)}

    def loss_fn(p, xb, yb, key=None):
        return jnp.mean(((xb @ p["w"]) - yb) ** 2)

    return params, loss_fn, x, y


class _Batch:
    def __init__(self, x, y):
        self.features, self.labels = x, y


def _batches(x, y, n=8, bs=8):
    return [_Batch(x[i * bs:(i + 1) * bs], y[i * bs:(i + 1) * bs])
            for i in range(n)]


def _new_trainer(loss_fn):
    mesh = make_mesh(MeshSpec(dp=8), devices=jax.devices()[:8])
    return DataParallelTrainer(loss_fn, T.chain(T.momentum(0.9),
                                                T.sgd_lr(5e-2)), mesh=mesh)


@pytest.mark.lockguard
def test_supervised_chaos_parity(tmp_path):
    """The acceptance chain: transient step failure + corrupted checkpoint
    write + data-pipeline failure in one run — the supervisor completes,
    resumes from the newest VALID checkpoint (the corrupted one is
    detected by checksum and skipped), and the final parameters are
    bitwise identical to a fault-free run."""
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)

    t_ref = _new_trainer(loss_fn)
    s_ref, ref_losses = t_ref.fit(t_ref.init_state(params), data, epochs=1)

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    with inject_faults(FaultSpec("checkpoint.write", at_step=4, kind="bitflip"),
                       FaultSpec("train.step", at_step=5),
                       FaultSpec("data.next", at_step=7), seed=11):
        sup = TrainingSupervisor(
            mgr, RetryPolicy(max_attempts=4, backoff_base_s=0.01))
        t = _new_trainer(loss_fn)
        state, losses = sup.fit(t, params, data, epochs=1, checkpoint_every=2)

    assert state.step == s_ref.step
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # losses resolved after the last resume match the reference exactly
    for step_loss, ref_loss in zip(losses[::-1], ref_losses[::-1]):
        assert step_loss == ref_loss
    assert sup.report.retries >= 1
    counters = METRICS.snapshot()["counters"]
    assert counters["checkpoint.corrupt_detected"] >= 1
    assert counters["resilience.retries"] >= 1
    assert counters["faults.injected.train.step"] == 1
    # recovery events are scrapeable, not just in-process
    prom = METRICS.to_prometheus()
    assert "resilience" in prom and "corrupt_detected" in prom


def test_nan_guard_rolls_back_and_skips_window(tmp_path):
    """A batch with non-finite labels diverges the loss at step 5: the
    supervisor rolls back to the last checkpoint and skips the poisoned
    batch window, finishing the remaining stream."""
    params, loss_fn, x, y = _toy_problem()
    y = np.array(y)                          # writable host copy
    y[4 * 8:5 * 8] = np.nan                  # batch index 4 -> step 5
    data = _batches(x, jnp.asarray(y))

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    sup = TrainingSupervisor(mgr, RetryPolicy(max_attempts=3,
                                              backoff_base_s=0.01))
    t = _new_trainer(loss_fn)
    state, losses = sup.fit(t, params, data, epochs=1, checkpoint_every=1)

    assert sup.report.rollbacks == 1
    assert sup.report.skipped_steps == 1
    # 8 batches, one skipped -> 7 steps, and nothing non-finite survived
    assert state.step == 7
    assert all(np.isfinite(v) for v in losses)
    counters = METRICS.snapshot()["counters"]
    assert counters["resilience.nan_detected"] == 1
    assert counters["resilience.rollbacks"] == 1


def test_nan_guard_gives_up_after_max_rollbacks(tmp_path):
    params, loss_fn, x, y = _toy_problem()
    y = np.array(y)                          # writable host copy
    y[:] = np.nan                            # every batch diverges
    data = _batches(x, jnp.asarray(y))

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    sup = TrainingSupervisor(mgr, max_rollbacks=2)
    t = _new_trainer(loss_fn)
    from deeplearning4j_tpu.resilience import DivergenceError
    with pytest.raises(DivergenceError):
        sup.fit(t, params, data, epochs=1, checkpoint_every=1)
    assert sup.report.rollbacks == 3         # budget exhausted
    assert METRICS.snapshot()["counters"]["resilience.gave_up"] == 1


@pytest.mark.lockguard
def test_injected_preemption_checkpoints_and_resumes(tmp_path):
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)

    t_ref = _new_trainer(loss_fn)
    s_ref, _ = t_ref.fit(t_ref.init_state(params), data, epochs=1)

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    with inject_faults(FaultSpec("preempt", at_step=3), seed=0):
        sup = TrainingSupervisor(mgr)
        t = _new_trainer(loss_fn)
        state, losses = sup.fit(t, params, data, epochs=1, checkpoint_every=4)

    assert sup.report.preemptions == 1
    assert 3 in sup.report.resumed_from      # emergency checkpoint at step 3
    assert state.step == s_ref.step
    for a, b in zip(jax.tree_util.tree_leaves(s_ref.params),
                    jax.tree_util.tree_leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    counters = METRICS.snapshot()["counters"]
    assert counters["resilience.emergency_checkpoints"] >= 1


def test_fit_trains_from_scratch_when_all_checkpoints_corrupt(tmp_path):
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)
    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    t = _new_trainer(loss_fn)
    t.fit(t.init_state(params), data, epochs=1, checkpoint_manager=mgr)
    for s in mgr.all_steps():
        corrupt_file(tmp_path / "ckpt" / f"ckpt_{s:010d}" / "params.npz",
                     "truncate")
    t2 = _new_trainer(loss_fn)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state, losses = t2.fit(t2.init_state(params), data, epochs=1,
                               checkpoint_manager=mgr, resume=True)
    assert state.step == len(data)           # full run, not a corrupt resume
    assert METRICS.snapshot()["counters"]["checkpoint.no_valid_restore"] == 1


def test_data_iterator_fault_is_retryable(tmp_path):
    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)
    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    with inject_faults(FaultSpec("data.next", at_step=5), seed=0):
        sup = TrainingSupervisor(mgr, RetryPolicy(max_attempts=3,
                                                  backoff_base_s=0.01))
        t = _new_trainer(loss_fn)
        state, _ = sup.fit(t, params, data, epochs=1, checkpoint_every=2)
    assert state.step == len(data)
    assert sup.report.retries == 1


def test_supervise_generic_retry_and_give_up():
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise TransientStepFault("boom")
        return "ok"

    sup = TrainingSupervisor(policy=RetryPolicy(max_attempts=3,
                                                backoff_base_s=0.0))
    assert sup.supervise(flaky) == "ok"
    assert calls["n"] == 3

    def doomed():
        raise TransientStepFault("always")

    sup2 = TrainingSupervisor(policy=RetryPolicy(max_attempts=2,
                                                 backoff_base_s=0.0))
    with pytest.raises(TransientStepFault):
        sup2.supervise(doomed)


# --------------------------------------------------------------------------- scaleout hardening

class _DeltaPerformer:
    """Order-free: final model == init + sum(job deltas) iff each job ran
    at least once and results were aggregated exactly once each."""

    def __init__(self, tracker):
        self.tracker = tracker

    def perform(self, job):
        current = self.tracker.get_current()
        base = np.zeros(4) if current is None else np.asarray(current)
        job.result = base + np.full(4, float(job.work))

    def update(self, *args):
        pass


def _run_jobs(performer_factory, jobs, n_workers, **kw):
    tracker = StateTracker()
    tracker.set_current(np.zeros(4))
    runner = DistributedRunner(
        CollectionJobIterator(jobs), performer_factory,
        n_workers=n_workers, tracker=tracker, **kw)
    result = runner.run(max_wall_s=60.0)
    return np.asarray(result), tracker, runner


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_injected_worker_kill_recovers_with_parity():
    """Chaos flavor of the elastic test: the injected ``scaleout.worker``
    site kills one worker silently (no failure report — heartbeats stop),
    eviction re-routes its job, and the survivor finishes with the same
    model as the single-worker fault-free run (the dead worker never
    reports an update, so no two-worker wave ever averages)."""
    jobs = [1.0, 2.0, 3.0, 4.0, 5.0]
    ref, _, _ = _run_jobs(_DeltaPerformer, jobs, n_workers=1)
    with inject_faults(FaultSpec("scaleout.worker", at_step=1), seed=0):
        got, tracker, _ = _run_jobs(_DeltaPerformer, jobs, n_workers=2,
                                    eviction_timeout_s=0.5)
    np.testing.assert_allclose(got, ref, atol=1e-12)
    assert tracker.is_done()
    counters = METRICS.snapshot()["counters"]
    assert counters["faults.injected.scaleout.worker"] == 1
    assert counters["scaleout.workers_evicted"] >= 1


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_transient_perform_failure_requeues_promptly():
    jobs = [1.0, 2.0, 3.0]
    ref, _, _ = _run_jobs(_DeltaPerformer, jobs, n_workers=1)
    with inject_faults(FaultSpec("scaleout.perform", at_step=1), seed=0):
        got, tracker, _ = _run_jobs(_DeltaPerformer, jobs, n_workers=2)
    np.testing.assert_allclose(got, ref, atol=1e-12)
    counters = METRICS.snapshot()["counters"]
    assert counters["scaleout.job_failures"] == 1
    assert counters["scaleout.jobs_requeued"] == 1
    assert not tracker.quarantined()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_poison_job_is_quarantined_not_fatal():
    """A job that fails every attempt exhausts its retry budget and is
    quarantined; every worker it kills is respawned (opt-in budget) and
    each healthy job still executes exactly once."""
    executed = []
    exec_lock = threading.Lock()

    class Poisoned(_DeltaPerformer):
        def perform(self, job):
            if float(job.work) == 3.0:
                raise RuntimeError("poison")
            super().perform(job)
            with exec_lock:
                executed.append(float(job.work))

    jobs = [1.0, 2.0, 3.0, 4.0]
    _, tracker, _ = _run_jobs(Poisoned, jobs, n_workers=2,
                              max_job_attempts=2, max_respawns=4)
    assert tracker.is_done()
    assert sorted(executed) == [1.0, 2.0, 4.0]
    quarantined = tracker.quarantined()
    assert [float(j.work) for j in quarantined] == [3.0]
    assert quarantined[0].attempts == 2
    assert "poison" in quarantined[0].last_error
    counters = METRICS.snapshot()["counters"]
    assert counters["scaleout.jobs_quarantined"] == 1
    assert counters["scaleout.workers_respawned"] >= 1


def test_run_timeout_raises_with_partial():
    class Slow(_DeltaPerformer):
        def perform(self, job):
            time.sleep(1.0)
            super().perform(job)

    tracker = StateTracker()
    tracker.set_current(np.zeros(4))
    runner = DistributedRunner(CollectionJobIterator([1.0, 2.0, 3.0]), Slow,
                               n_workers=1, tracker=tracker)
    with pytest.raises(ScaleoutTimeout) as ei:
        runner.run(max_wall_s=0.3)
    assert ei.value.partial is not None
    assert METRICS.snapshot()["counters"]["scaleout.run_timeouts"] == 1

    tracker2 = StateTracker()
    tracker2.set_current(np.zeros(4))
    runner2 = DistributedRunner(CollectionJobIterator([1.0]), Slow,
                                n_workers=1, tracker=tracker2,
                                on_timeout="return")
    out = runner2.run(max_wall_s=0.2)        # opt-in best-effort return
    assert out is not None


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_job_timeout_reroutes_wedged_worker():
    """With ``job_timeout_s`` armed, a worker stuck in perform is treated
    like a dead one: removed, its job re-routed, the run completes."""
    stuck = {"done": False}

    class WedgeOnce(_DeltaPerformer):
        _lock = threading.Lock()

        def perform(self, job):
            with WedgeOnce._lock:
                first = not stuck["done"]
                stuck["done"] = True
            if first:
                time.sleep(3.0)              # well past job_timeout_s
                return                        # result discarded: worker was removed
            super().perform(job)

    jobs = [1.0, 2.0]
    ref, _, _ = _run_jobs(_DeltaPerformer, jobs, n_workers=1)
    got, _, _ = _run_jobs(WedgeOnce, jobs, n_workers=2, job_timeout_s=0.3,
                          eviction_timeout_s=10.0)
    np.testing.assert_allclose(got, ref, atol=1e-12)
    assert METRICS.snapshot()["counters"]["scaleout.job_timeouts"] == 1


def test_file_model_saver_concurrent_saves_never_tear(tmp_path):
    """Two savers hammering one path (the old shared-``.tmp`` collision):
    every published file must be one complete pickle."""
    saver = FileModelSaver(tmp_path / "model.bin")
    payloads = [np.full(256, float(i)) for i in range(4)]
    errors = []

    def hammer(p):
        try:
            for _ in range(40):
                saver.save(p)
        except Exception as e:               # pragma: no cover - the bug
            errors.append(e)

    threads = [threading.Thread(target=hammer, args=(p,)) for p in payloads]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    loaded = saver.load()                    # parses -> not torn
    assert any(np.array_equal(loaded, p) for p in payloads)
    assert list(tmp_path.glob("*.tmp")) == []   # no leaked temp files


# --------------------------------------------------------------------------- chaos smoke wiring

def _load_chaos_smoke():
    import importlib.util
    import pathlib
    tools = pathlib.Path(__file__).resolve().parent.parent / "tools"
    spec = importlib.util.spec_from_file_location(
        "chaos_smoke", tools / "chaos_smoke.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.filterwarnings("ignore::UserWarning")
def test_chaos_smoke_fixed_seed():
    """Tier-1 wiring for ``tools/chaos_smoke.py`` with a pinned seed: the
    randomized tool must itself keep passing on a known draw."""
    cs = _load_chaos_smoke()
    result = cs.run(seed=0)
    # run() asserts the invariants; pin the headline ones against refactor
    assert result["params_bitwise_equal"]
    assert result["loss_parity"]
    assert result["final_step"] == result["ref_step"]
    assert result["faults_injected"]


# ------------------------------------------------------------ flight recorder

def test_injected_step_fault_dumps_flight_bundle(tmp_path):
    """PR 10 acceptance: killing a supervised run via an injected
    ``train.step`` fault writes a flight-recorder bundle holding the
    fault's chaos site, the last spans, and the step-keyed loss tail."""
    from deeplearning4j_tpu.observability import FLIGHTREC

    params, loss_fn, x, y = _toy_problem()
    data = _batches(x, y)
    FLIGHTREC.clear()
    FLIGHTREC.dump_dir = tmp_path / "flight"

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    with inject_faults(FaultSpec("train.step", at_step=5), seed=3):
        sup = TrainingSupervisor(
            mgr, RetryPolicy(max_attempts=4, backoff_base_s=0.01))
        t = _new_trainer(loss_fn)
        state, losses = sup.fit(t, params, data, epochs=1,
                                checkpoint_every=2)

    assert state.step == 8                     # recovered and finished
    bundles = sorted((tmp_path / "flight").glob(
        "flightrec-supervisor_retry-*.json"))
    assert bundles, "supervisor retry produced no flight bundle"
    bundle = json.loads(bundles[0].read_text())
    # the chaos fire that killed the attempt is on record
    assert any(f["site"] == "train.step" for f in bundle["faults"])
    assert "injected fault" in bundle["extra"]["error"]
    # recent spans from before the crash survived it
    assert bundle["spans"], "span ring empty at dump time"
    span_names = {s["name"] for s in bundle["spans"]}
    assert any(n.startswith(("train", "checkpoint", "resilience"))
               for n in span_names), span_names
    # the loss tail is keyed by STEP (string keys after JSON round-trip)
    tail = bundle["extra"]["losses_tail"]
    assert all(k.isdigit() for k in tail)
    assert all(np.isfinite(v) for v in tail.values())
    # the full metrics snapshot rides along with the fault counter in it
    assert bundle["metrics"]["counters"]["faults.injected.train.step"] == 1


def test_divergence_dump_carries_step_and_loss_tail(tmp_path):
    """The NaN-guard path dumps too, with the diverging step identified."""
    from deeplearning4j_tpu.observability import FLIGHTREC

    params, loss_fn, x, y = _toy_problem()
    y = np.array(y)
    y[4 * 8:5 * 8] = np.nan                  # batch index 4 -> step 5
    data = _batches(x, jnp.asarray(y))
    FLIGHTREC.clear()
    FLIGHTREC.dump_dir = tmp_path / "flight"

    mgr = CheckpointManager(tmp_path / "ckpt", keep=10)
    sup = TrainingSupervisor(mgr, RetryPolicy(max_attempts=3,
                                              backoff_base_s=0.01))
    t = _new_trainer(loss_fn)
    sup.fit(t, params, data, epochs=1, checkpoint_every=1)

    bundles = sorted((tmp_path / "flight").glob(
        "flightrec-divergence-*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["extra"]["step"] == 5
    assert bundle["extra"]["rollbacks"] == 1
    assert bundle["metrics"]["counters"]["resilience.nan_detected"] == 1


def test_explicit_corrupt_restore_dumps_bundle(tmp_path):
    """CheckpointCorruptError (explicit-step restore) triggers a dump."""
    from deeplearning4j_tpu.observability import FLIGHTREC

    FLIGHTREC.clear()
    FLIGHTREC.dump_dir = tmp_path / "flight"
    mgr = CheckpointManager(tmp_path / "ckpt", keep=5)
    mgr.save(1, {"w": jnp.zeros(3)})
    # flip bits under the checksums
    payload = tmp_path / "ckpt" / "ckpt_0000000001" / "params.npz"
    raw = bytearray(payload.read_bytes())
    raw[len(raw) // 2] ^= 0xFF
    payload.write_bytes(bytes(raw))

    with pytest.raises(CheckpointCorruptError):
        mgr.restore({"w": jnp.zeros(3)}, step=1)
    bundles = list((tmp_path / "flight").glob(
        "flightrec-checkpoint_corrupt-*.json"))
    assert len(bundles) == 1
    bundle = json.loads(bundles[0].read_text())
    assert bundle["extra"]["step"] == 1
    assert str(tmp_path / "ckpt") in bundle["extra"]["directory"]
