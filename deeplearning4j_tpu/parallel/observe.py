"""Observability: metrics registry + REST status endpoint.

Capability match of the reference's observability surface (SURVEY.md §5.5):
SLF4J logging ≡ stdlib logging; distributed counters ≡ ``StateTracker``
counters; the dropwizard REST resource exposing tracker state
(``StateTracker.startRestApi``, ``StateTrackerDropWizardResource.java:28``)
≡ a stdlib ThreadingHTTPServer serving JSON; plus a step-timer/profiler
hook (``jax.profiler`` trace toggles) the reference lacks.
"""

from __future__ import annotations

import json
import threading
import time
from collections import defaultdict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any


class MetricsRegistry:
    """Process-wide named counters/gauges/timers."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: dict[str, float] = defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.timers: dict[str, list[float]] = defaultdict(list)

    def increment(self, name: str, by: float = 1.0) -> None:
        with self._lock:
            self.counters[name] += by

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self.gauges[name] = value

    def time(self, name: str):
        registry = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                with registry._lock:
                    registry.timers[name].append(time.perf_counter() - self.t0)

        return _Timer()

    def snapshot(self) -> dict[str, Any]:
        with self._lock:
            return {
                "counters": dict(self.counters),
                "gauges": dict(self.gauges),
                "timers": {k: {"count": len(v), "mean_s": sum(v) / len(v),
                               "total_s": sum(v)}
                           for k, v in self.timers.items() if v},
            }


METRICS = MetricsRegistry()


class StepTimer:
    """IterationListener that records per-iteration wall time and score into
    the metrics registry (profiler hook, SURVEY.md §5.1 obligation)."""

    def __init__(self, registry: MetricsRegistry = METRICS, name: str = "train_step"):
        self.registry = registry
        self.name = name
        self._last = None

    def iteration_done(self, model, iteration: int) -> None:
        now = time.perf_counter()
        if self._last is not None:
            self.registry.timers[self.name].append(now - self._last)
        self._last = now
        self.registry.increment(f"{self.name}.iterations")
        if hasattr(model, "score"):
            try:
                self.registry.gauge(f"{self.name}.score", float(model.score()))
            except Exception:
                pass


def profiler_trace(log_dir: str):
    """Context manager: JAX profiler trace (XPlane) to ``log_dir``."""
    import jax

    class _Trace:
        def __enter__(self):
            jax.profiler.start_trace(log_dir)
            return self

        def __exit__(self, *exc):
            jax.profiler.stop_trace()

    return _Trace()


class StatusServer:
    """REST endpoint: /status (tracker state), /metrics (registry),
    /healthz.  Read-only, JSON; replaces the dropwizard resource."""

    def __init__(self, tracker=None, registry: MetricsRegistry = METRICS,
                 host: str = "127.0.0.1", port: int = 0):
        self.tracker = tracker
        self.registry = registry
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                if self.path == "/healthz":
                    payload = {"ok": True}
                elif self.path == "/metrics":
                    payload = outer.registry.snapshot()
                elif self.path == "/status":
                    payload = outer._tracker_state()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = json.dumps(payload).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    def _tracker_state(self) -> dict:
        t = self.tracker
        if t is None:
            return {}
        return {
            "workers": t.workers(),
            "enabled": {w: t.is_enabled(w) for w in t.workers()},
            "heartbeats_age_s": {w: round(time.time() - t.last_heartbeat(w), 3)
                                 for w in t.workers()},
            "current_jobs": len(t.current_jobs()),
            "pending_updates": sorted(t.updates().keys()),
            # in-memory tracker exposes its counter dict; the file-backed
            # tracker has no cheap enumerate — omit rather than scan disk
            "counters": dict(getattr(t, "_counters", {})),
            "done": t.is_done(),
        }

    def start(self) -> "StatusServer":
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
